"""Elastic world resize (ISSUE 8): survive scale-down/scale-up
restarts with reshard-on-load checkpoints.

Acceptance pins:

1. **Shrink drill e2e** — a 2-process gloo spawn with
   ``shrink:rank1@step12`` under ``elastic=True, min_world=1``
   completes training at world 1; final metrics match an uninjected
   run at the surviving world size (same preserved global batch);
   ``goodput.json`` attributes the resize downtime separately from
   restart downtime (slow tier — real spawned worlds). Same drill
   green for ``--parallel zero`` (flat buckets re-bucket on restore).
2. **ZeRO elastic restore unit pin** — a zero checkpoint saved at
   world 2 re-buckets and restores at world 1 bit-identically to a
   fresh shard of the merged state (and the reverse, with zero pad);
   zero1 moments saved data=2 restore data=1 (template resharding).
3. **Exactly one run_start metrics record per generation** carries the
   restart count and the old/new world sizes.
4. The shard math preserves the global batch exactly: one step's
   sample window is identical at any divisor world size.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ddp_tpu.runtime.chaos import ChaosEvent, format_chaos, parse_chaos
from ddp_tpu.runtime.launch import (
    GROW_EXIT_CODE,
    SHRINK_EXIT_CODE,
    classify_exit,
    spawn,
)
from ddp_tpu.runtime.mesh import MeshSpec, live_world_spec, make_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- chaos grammar ---------------------------------------------------


def test_shrink_grow_grammar_roundtrip():
    spec = "shrink:rank1@step12,grow:+1@epoch2,shrink:rank0@epoch1"
    ev = parse_chaos(spec)
    assert [e.kind for e in ev] == ["shrink", "grow", "shrink"]
    assert ev[0] == ChaosEvent(kind="shrink", rank=1, step=12)
    assert ev[1] == ChaosEvent(kind="grow", epoch=2)
    assert format_chaos(ev) == spec
    for bad in (
        "shrink:rank1",     # no trigger point
        "shrink@step3",     # no rank
        "grow:+2@epoch1",   # only +1 exists
        "grow:rank1@step3",  # grow takes no rank
    ):
        with pytest.raises(ValueError):
            parse_chaos(bad)


def test_classify_exit_elastic_codes():
    assert "shrink" in classify_exit(SHRINK_EXIT_CODE)
    assert "grow" in classify_exit(GROW_EXIT_CODE)


# ---- mesh re-derivation ----------------------------------------------


def test_live_world_spec_rederives_data_axis():
    spec = live_world_spec(MeshSpec(), 3)
    assert spec.data == 3
    spec = live_world_spec(MeshSpec(model=2), 6)
    assert spec.data == 3 and spec.model == 2
    # mapping form works too (the MeshSpec(**dict) path)
    spec = live_world_spec({"model": 2}, 4)
    assert spec.data == 2
    with pytest.raises(ValueError, match="elastic resize"):
        live_world_spec(MeshSpec(model=4), 2)  # fixed axes don't fit
    with pytest.raises(ValueError, match="elastic resize"):
        live_world_spec(MeshSpec(model=2), 3)  # indivisible
    with pytest.raises(ValueError, match="data axis may be"):
        live_world_spec(MeshSpec(model=-1), 4)  # only data is derived


# ---- shard math: global batch preserved exactly ----------------------


def test_rescale_per_shard_batch_math():
    from ddp_tpu.data.sampler import rescale_per_shard_batch

    assert rescale_per_shard_batch(8, 1) == 8
    assert rescale_per_shard_batch(8, 2) == 4
    assert rescale_per_shard_batch(8, 2, grad_accum_steps=2) == 2
    with pytest.raises(ValueError, match="global batch 8"):
        rescale_per_shard_batch(8, 3)
    with pytest.raises(ValueError, match="global batch"):
        rescale_per_shard_batch(2, 2, grad_accum_steps=2)  # < 1/shard


def test_step_sample_windows_identical_across_worlds():
    """The claim the batch rescale rests on: shard r of N takes
    ``indices[r::N]``, so one step's union of per-shard slices is the
    SAME window of the global permutation at any divisor world."""
    from ddp_tpu.data.sampler import ShardSampler

    n, G = 64, 8
    for epoch in (0, 1, 5):
        one = ShardSampler(
            num_examples=n, num_shards=1, shard_id=0, seed=3
        ).shard_indices(epoch)
        for world in (2, 4):
            b = G // world
            shards = [
                ShardSampler(
                    num_examples=n, num_shards=world, shard_id=r, seed=3
                ).shard_indices(epoch)
                for r in range(world)
            ]
            for k in range(n // G):
                window = set(one[k * G : (k + 1) * G].tolist())
                union = set()
                for s in shards:
                    union |= set(s[k * b : (k + 1) * b].tolist())
                assert union == window


# ---- goodput: resize vs restart downtime attribution -----------------


def test_goodput_resize_vs_restart_attribution(tmp_path):
    from ddp_tpu.obs.goodput import GoodputAccountant

    path = str(tmp_path / "goodput.json")
    t = {"now": 1000.0}

    def clock():
        return t["now"]

    a = GoodputAccountant(path, clock=clock)
    a.start_run(world_size=2)
    assert a.restarts == 0 and a.prev_world is None
    a.add_productive(5.0)
    t["now"] = 1010.0
    a.flush()

    # same-world relaunch 3 s later → restart downtime
    t["now"] = 1013.0
    b = GoodputAccountant(path, clock=clock)
    b.start_run(world_size=2)
    assert b.restarts == 1 and b.resizes == 0 and b.prev_world == 2
    assert b.restart_downtime_s == pytest.approx(3.0)
    t["now"] = 1014.0
    b.flush()

    # RESIZED relaunch 6 s later → resize downtime, separately
    t["now"] = 1020.0
    c = GoodputAccountant(path, clock=clock)
    c.start_run(world_size=1)
    assert c.restarts == 2 and c.resizes == 1 and c.prev_world == 2
    assert c.resize_downtime_s == pytest.approx(6.0)
    assert c.restart_downtime_s == pytest.approx(3.0)
    snap = c.snapshot()
    assert snap["resizes"] == 1
    assert snap["resize_downtime_s"] == pytest.approx(6.0)
    c.flush()
    side = json.loads((tmp_path / "goodput.json").read_text())
    assert side["world_size"] == 1 and side["resizes"] == 1


def test_goodput_legacy_sidecar_still_loads(tmp_path):
    """Pre-elastic sidecars (no world/flush fields) resume without
    inventing downtime."""
    from ddp_tpu.obs.goodput import GoodputAccountant

    path = tmp_path / "goodput.json"
    path.write_text(
        json.dumps(
            {"first_launch_unix": 100.0, "productive_s": 7.0, "restarts": 2}
        )
    )
    a = GoodputAccountant(str(path))
    a.start_run(world_size=4)
    assert a.restarts == 3 and a.prev_world is None
    assert a.restart_downtime_s == 0.0 and a.resize_downtime_s == 0.0


# ---- elastic contract sidecar ----------------------------------------


def test_elastic_contract_write_once(tmp_path):
    from ddp_tpu.train.checkpoint import (
        load_elastic_contract,
        save_elastic_contract,
    )

    d = str(tmp_path / "ck")
    assert load_elastic_contract(d) == {}
    p = save_elastic_contract(d, global_batch_size=8, world_size=2)
    assert p is not None
    assert load_elastic_contract(d)["global_batch_size"] == 8
    # write-once: a later (resized) generation must not overwrite the
    # run's contract
    assert save_elastic_contract(d, global_batch_size=4, world_size=1) is None
    assert load_elastic_contract(d)["global_batch_size"] == 8
    assert load_elastic_contract(d)["world_size"] == 2


# ---- supervisor validation (no processes spawned) --------------------


def test_spawn_validates_min_world():
    def worker(rank, world):  # pragma: no cover — never launched
        pass

    with pytest.raises(ValueError, match="min_world"):
        spawn(worker, 2, min_world=0)
    with pytest.raises(ValueError, match="min_world"):
        spawn(worker, 2, min_world=3)


def test_cli_elastic_guards(tmp_path):
    sys.path.insert(0, REPO)
    import train as train_cli

    with pytest.raises(ValueError, match="min_world"):
        train_cli.main(["--min_world", "2"])
    with pytest.raises(ValueError, match="min_world"):
        train_cli.main(
            ["--spawn", "2", "--elastic", "--min_world", "3"]
        )


def test_trainer_elastic_rejects_pipe(tmp_path):
    from ddp_tpu.train.config import TrainConfig
    from ddp_tpu.train.trainer import Trainer

    with pytest.raises(ValueError, match="elastic"):
        Trainer(
            TrainConfig(
                model="pipe_vit", mesh_pipe=2, elastic=True,
                epochs=1, batch_size=8,
                checkpoint_dir=str(tmp_path / "ck"),
                data_root=str(tmp_path / "data"),
                synthetic_data=True, synthetic_size=64,
            )
        )


# ---- ZeRO elastic restore: re-bucket on world change -----------------


def _odd_params():
    """Leaves totalling 17 elements: padded is 18 at world 2 but 17 at
    world 1 — the shape mismatch resharding cannot bridge."""
    import jax.numpy as jnp

    return {
        "a": jnp.arange(7, dtype=jnp.float32),
        "b": jnp.arange(10, dtype=jnp.float32) * 0.5,
    }


def _zero_fixture(mesh, world, params, tx, *, moment_bias=0.0):
    import jax

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddp_tpu.parallel.zero import build_layout, create_zero_opt_state

    rep = NamedSharding(mesh, P())
    p = jax.tree.map(lambda x: jax.device_put(x, rep), params)
    layout = build_layout(params, world, bucket_mb=4.0)
    opt = create_zero_opt_state(p, tx, mesh, layout)
    if moment_bias:
        opt = jax.tree.map(
            lambda x: x + moment_bias if getattr(x, "ndim", 0) else x,
            opt,
        )
    return p, layout, opt


def test_zero_rebucket_world2_to_world1_bit_identical(tmp_path, devices):
    """The satellite pin: a zero checkpoint saved at world 2 re-buckets
    and restores at world 1 bit-identically to a fresh shard of the
    merged state (values untouched, old pad stripped)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ddp_tpu.parallel.ddp import TrainState
    from ddp_tpu.parallel.zero import ZeroElasticReshaper
    from ddp_tpu.train.checkpoint import CheckpointManager
    from jax.sharding import NamedSharding, PartitionSpec as P

    params = _odd_params()
    tx = optax.adam(1e-3)
    mesh2 = make_mesh(MeshSpec(data=2), devices=devices[:2])
    mesh1 = make_mesh(MeshSpec(data=1), devices=devices[:1])
    p2, lay2, opt2 = _zero_fixture(mesh2, 2, params, tx, moment_bias=0.25)
    assert [b.padded for b in lay2.buckets] == [18]

    d = str(tmp_path / "ck")
    mgr = CheckpointManager(d, async_save=False)
    mgr.save(0, TrainState(jnp.zeros((), jnp.int32), p2, opt2, {}))
    mgr.wait()

    p1, lay1, opt1 = _zero_fixture(mesh1, 1, params, tx)
    assert [b.padded for b in lay1.buckets] == [17]
    rep1 = NamedSharding(mesh1, P())
    tpl = TrainState(
        jax.device_put(jnp.zeros((), jnp.int32), rep1), p1, opt1, {}
    )
    restored, epoch = mgr.restore(
        tpl, opt_reshape=ZeroElasticReshaper(tx, lay1, mesh1)
    )
    mgr.close()
    assert epoch == 0

    def leaves(t):
        return jax.tree_util.tree_flatten_with_path(t)[0]

    tot = lay1.buckets[0].total
    for (_, got), (_, want) in zip(
        leaves(restored.opt_state), leaves(opt2)
    ):
        got, want = np.asarray(got), np.asarray(want)
        if got.ndim:
            assert got.shape == (17,)
            np.testing.assert_array_equal(got[:tot], want[:tot])
        else:
            np.testing.assert_array_equal(got, want)
    # restored flats actually rest sharded over the live data axis
    flat = next(
        l for _, l in leaves(restored.opt_state) if getattr(l, "ndim", 0)
    )
    from jax.sharding import PartitionSpec

    assert flat.sharding.spec == PartitionSpec("data")
    # ... and the params resharded onto the 1-device mesh by templating
    leaf = jax.tree_util.tree_leaves(restored.params)[0]
    assert set(leaf.sharding.device_set) <= set(devices[:1])


def test_zero_rebucket_world1_to_world2_pads_zeros(tmp_path, devices):
    """Scale-UP: the re-pad region is zeros (zero grads → zero moments
    — the Bucket contract the update math relies on)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ddp_tpu.parallel.ddp import TrainState
    from ddp_tpu.parallel.zero import ZeroElasticReshaper
    from ddp_tpu.train.checkpoint import CheckpointManager
    from jax.sharding import NamedSharding, PartitionSpec as P

    params = _odd_params()
    tx = optax.sgd(1e-2, momentum=0.9)  # trace state: one flat per bucket
    mesh1 = make_mesh(MeshSpec(data=1), devices=devices[:1])
    mesh2 = make_mesh(MeshSpec(data=2), devices=devices[:2])
    p1, lay1, opt1 = _zero_fixture(mesh1, 1, params, tx, moment_bias=0.5)

    d = str(tmp_path / "ck")
    mgr = CheckpointManager(d, async_save=False)
    mgr.save(2, TrainState(jnp.zeros((), jnp.int32), p1, opt1, {}))
    mgr.wait()

    p2, lay2, opt2 = _zero_fixture(mesh2, 2, params, tx)
    rep2 = NamedSharding(mesh2, P())
    tpl = TrainState(
        jax.device_put(jnp.zeros((), jnp.int32), rep2), p2, opt2, {}
    )
    restored, epoch = mgr.restore(
        tpl, opt_reshape=ZeroElasticReshaper(tx, lay2, mesh2)
    )
    mgr.close()
    assert epoch == 2

    def leaves(t):
        return jax.tree_util.tree_flatten_with_path(t)[0]

    tot = lay2.buckets[0].total
    for (_, got), (_, want) in zip(
        leaves(restored.opt_state), leaves(opt1)
    ):
        got, want = np.asarray(got), np.asarray(want)
        if got.ndim:
            assert got.shape == (18,)
            np.testing.assert_array_equal(got[:tot], want[:tot])
            np.testing.assert_array_equal(got[tot:], np.zeros(18 - tot))


def test_zero_rebucket_rejects_structure_change(devices):
    """A bucket-STRUCTURE mismatch (bucket_mb changed, not the world)
    is a recipe change — refuse instead of reinterpreting."""
    import optax

    from ddp_tpu.parallel.zero import (
        ZeroElasticReshaper,
        _opt_template,
        build_layout,
    )

    params = {
        "a": np.zeros((40,), np.float32),
        "b": np.zeros((40,), np.float32),
    }
    tx = optax.adam(1e-3)
    one_bucket = build_layout(params, 2, bucket_mb=4.0)
    # tiny target → one bucket per leaf (leaf >= target gets its own)
    two_buckets = build_layout(params, 2, bucket_mb=1e-4)
    assert len(one_bucket.buckets) != len(two_buckets.buckets)
    mesh2 = make_mesh(MeshSpec(data=2), devices=devices[:2])
    reshaper = ZeroElasticReshaper(tx, one_bucket, mesh2)
    with pytest.raises(ValueError, match="STRUCTURE"):
        reshaper.plan(_opt_template(tx, two_buckets))


def test_zero_rebucket_plan_noop_when_shapes_match(devices):
    import optax

    from ddp_tpu.parallel.zero import (
        ZeroElasticReshaper,
        _opt_template,
        build_layout,
    )

    params = _odd_params()
    tx = optax.adam(1e-3)
    lay = build_layout(params, 2, bucket_mb=4.0)
    mesh2 = make_mesh(MeshSpec(data=2), devices=devices[:2])
    reshaper = ZeroElasticReshaper(tx, lay, mesh2)
    assert reshaper.plan(_opt_template(tx, lay)) is None
    # non-bucketed metadata (a plain tree-shaped opt state) is a no-op
    # too: nothing to re-bucket, the templated restore handles it
    assert reshaper.plan({"mu": np.zeros((3, 3), np.float32)}) is None


def test_zero1_moments_reshard_data2_to_data1(tmp_path, devices):
    """The other half of the satellite pin: zero1 (tree-shaped,
    data-sharded moments) needs NO re-bucketing — Orbax reshards on
    load via the live template (the test_elastic_shard mechanism),
    data=2 → data=1."""
    import jax
    import jax.numpy as jnp
    import optax

    from ddp_tpu.models import get_model
    from ddp_tpu.parallel.ddp import TrainState
    from ddp_tpu.parallel.spmd import create_spmd_state, make_spmd_train_step
    from ddp_tpu.train.checkpoint import CheckpointManager

    model = get_model("simple_cnn")
    tx = optax.adam(1e-3)
    sample = jnp.zeros((1, 28, 28, 1))
    mesh2 = make_mesh(MeshSpec(data=2), devices=devices[:2])
    st2 = create_spmd_state(model, tx, sample, mesh2, seed=0, zero1=True)
    step = make_spmd_train_step(model, tx, mesh2, zero1=True, donate=False)
    rng = np.random.default_rng(0)
    images = jnp.asarray(
        rng.integers(0, 256, size=(8, 28, 28, 1), dtype=np.uint8)
    )
    labels = jnp.asarray(rng.integers(0, 10, size=(8,)), jnp.int32)
    st2, _ = step(st2, images, labels)

    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(0, TrainState(st2.step, st2.params, st2.opt_state, {}))
    mgr.wait()

    mesh1 = make_mesh(MeshSpec(data=1), devices=devices[:1])
    st1 = create_spmd_state(model, tx, sample, mesh1, seed=7, zero1=True)
    restored, epoch = mgr.restore(
        TrainState(st1.step, st1.params, st1.opt_state, {})
    )
    mgr.close()
    assert epoch == 0
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        restored.opt_state,
        st2.opt_state,
    )
    leaf = jax.tree_util.tree_leaves(restored.opt_state)[0]
    assert set(leaf.sharding.device_set) <= set(devices[:1])


# ---- single-process device-count resize (subprocess: own device
# ---- count) + the run_start exactly-once pin -------------------------


def _run_cli(args, cwd=REPO, timeout=420):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "train.py"), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=cwd,
    )


@pytest.mark.slow
def test_device_resize_preserves_global_batch_and_run_start(tmp_path):
    """Single-process elastic resize (2 emulated devices → 1): the
    recorded global batch is preserved (same steps/epoch), downtime is
    attributed as RESIZE, and each generation writes EXACTLY ONE
    run_start metrics record carrying the restart count and the
    old/new world shapes."""
    ck = str(tmp_path / "ck")
    metrics = str(tmp_path / "m.jsonl")
    base = [
        "--batch_size", "4", "--synthetic_data", "--synthetic_size",
        "64", "--eval_every", "0", "--log_interval", "4",
        "--checkpoint_dir", ck, "--data_root", str(tmp_path / "data"),
        "--metrics_file", metrics, "--elastic",
    ]
    p1 = _run_cli(["--epochs", "1", "--emulate_devices", "2", *base])
    assert p1.returncode == 0, p1.stderr[-2000:]
    p2 = _run_cli(["--epochs", "2", "--emulate_devices", "1", *base])
    assert p2.returncode == 0, p2.stderr[-2000:]

    records = [json.loads(l) for l in open(metrics) if l.strip()]
    epochs = [r for r in records if r["kind"] == "epoch"]
    # global batch preserved → SAME steps/epoch at both worlds
    assert [e["batches"] for e in epochs] == [8, 8]
    starts = [r for r in records if r["kind"] == "run_start"]
    assert len(starts) == 2  # exactly one per generation
    assert [s["restarts"] for s in starts] == [0, 1]
    assert [s["data_shards"] for s in starts] == [2, 1]
    assert starts[1]["prev_data_shards"] == 2
    assert all(s["global_batch_size"] == 8 for s in starts)
    contract = json.loads(
        open(os.path.join(ck, "elastic.json")).read()
    )
    assert contract["global_batch_size"] == 8
    side = json.loads(open(os.path.join(ck, "goodput.json")).read())
    assert side["resizes"] == 1
    assert side["resize_downtime_s"] > 0
    assert side["restart_downtime_s"] == 0.0


# ---- spawned-world drills (slow tier) --------------------------------


def _read(out_dir, ranks):
    out = []
    for rank in ranks:
        with open(os.path.join(out_dir, f"rank{rank}.json")) as f:
            out.append(json.load(f))
    return out


def _elastic_train_worker(
    rank, world, ckpt, data, out_dir, chaos_spec, parallel, epochs
):
    from ddp_tpu.runtime import dist
    from ddp_tpu.train.config import TrainConfig
    from ddp_tpu.train.trainer import Trainer

    # batch_size stays 4 in EVERY generation (same argv on relaunch);
    # the elastic contract is what rescales the per-shard batch.
    config = TrainConfig(
        epochs=epochs, batch_size=4,
        checkpoint_dir=ckpt, data_root=data,
        synthetic_data=True, synthetic_size=64,
        log_interval=4, eval_every=0,
        chaos=chaos_spec, elastic=True,
        parallel=parallel,
        optimizer="adam" if parallel == "zero" else "sgd",
        metrics_file=os.path.join(out_dir, "metrics.jsonl"),
    )
    trainer = Trainer(config, ctx=dist.current())
    try:
        summary = trainer.train()
    finally:
        trainer.close()
    with open(os.path.join(out_dir, f"rank{rank}.json"), "w") as f:
        json.dump(
            {
                "world": world,
                "epochs_run": summary["epochs_run"],
                "acc": summary["final_accuracy"],
                "loss": summary["final_loss"],
                "step": int(trainer.state.step),
                "global_batch": trainer.global_batch_size,
                "per_shard": trainer.per_shard_batch,
            },
            f,
        )


def _reference_world1(tmp_path, parallel):
    """Uninjected run at the SURVIVING world size (1), same preserved
    global batch (8) — in-process, single data shard."""
    from ddp_tpu.train.config import TrainConfig
    from ddp_tpu.train.trainer import Trainer

    config = TrainConfig(
        epochs=2, batch_size=8, num_devices=1,
        checkpoint_dir=str(tmp_path / "ref_ck"),
        data_root=str(tmp_path / "data"),
        synthetic_data=True, synthetic_size=64,
        log_interval=4, eval_every=0,
        parallel=parallel,
        optimizer="adam" if parallel == "zero" else "sgd",
    )
    trainer = Trainer(config)
    try:
        summary = trainer.train()
    finally:
        trainer.close()
    return summary


@pytest.mark.multihost
@pytest.mark.parametrize("parallel", ["auto", "zero"])
def test_spawn_shrink_drill_completes_at_world1(tmp_path, parallel):
    """THE acceptance drill: 2-process gloo spawn, rank 1 permanently
    lost mid-epoch-1 (``shrink:rank1@step12``), ``elastic`` +
    ``min_world=1``. The supervisor resizes (consuming NO restart
    budget), the survivor resumes from the epoch-0 checkpoint at the
    preserved global batch, training completes at world 1 with final
    metrics matching an uninjected world-1 run, and goodput.json
    attributes the resize downtime separately from restart downtime.
    ``parallel='zero'`` additionally exercises the bucket re-bucket on
    the restore path (world-2 padded flats → world-1 layout)."""
    out = tmp_path / "out"
    out.mkdir()
    ck = str(tmp_path / "ck")
    events = []
    restarts = spawn(
        _elastic_train_worker, 2,
        (ck, str(tmp_path / "data"), str(out), "shrink:rank1@step12",
         parallel, 2),
        timeout=900, grace=5.0,
        max_restarts=0,  # resizes must not need a restart budget
        restart_backoff=0.1,
        elastic=True, min_world=1, events_out=events,
    )
    assert restarts == 0
    assert [e["kind"] for e in events] == ["resize"]
    assert events[0]["old_world"] == 2 and events[0]["new_world"] == 1
    assert events[0]["shrunk_ranks"] == [1]

    # only the surviving world's rank 0 completes
    results = _read(str(out), [0])
    assert results[0]["world"] == 1
    assert results[0]["step"] == 16  # 2 epochs × 8 steps, none lost
    assert results[0]["global_batch"] == 8  # preserved
    assert results[0]["per_shard"] == 8  # rescaled 4 → 8
    assert np.isfinite(results[0]["loss"])

    # parity with an uninjected run at the surviving world size: the
    # replayed epoch-1 reproduces the lost work at world 1, and epoch 0
    # differed only in gradient summation structure (mean-of-shard-
    # means vs full-batch mean) — tight float tolerance, not bitwise.
    ref = _reference_world1(tmp_path, parallel)
    assert np.isclose(results[0]["acc"], ref["final_accuracy"], atol=1e-3)
    assert np.isclose(results[0]["loss"], ref["final_loss"], rtol=1e-3)

    side = json.loads((tmp_path / "ck" / "goodput.json").read_text())
    assert side["restarts"] == 1  # one relaunch happened...
    assert side["resizes"] == 1  # ...and it was a resize
    assert side["resize_downtime_s"] > 0
    assert side["restart_downtime_s"] == 0.0

    # one run_start metrics record per generation, old/new worlds on it
    starts = [
        json.loads(l)
        for l in open(os.path.join(str(out), "metrics.jsonl"))
        if '"run_start"' in l
    ]
    assert len(starts) == 2
    assert [s["data_shards"] for s in starts] == [2, 1]
    assert starts[1]["prev_data_shards"] == 2
    assert [s["restarts"] for s in starts] == [0, 1]

    # the ledger stopped a second shrink
    ledger = json.loads(
        (tmp_path / "ck" / "chaos_ledger.rank1.json").read_text()
    )
    assert ledger["fired"] == ["shrink:rank1@step12"]


@pytest.mark.multihost
def test_spawn_shrink_then_grow_restores_world(tmp_path):
    """Scale-up drill: shrink to 1 mid-epoch-0, then ``grow:+1`` at the
    top of epoch 1 restores world 2 — the run finishes with BOTH ranks
    live, per-shard batch back at 4, and the goodput sidecar counting
    two resizes."""
    out = tmp_path / "out"
    out.mkdir()
    ck = str(tmp_path / "ck")
    events = []
    restarts = spawn(
        _elastic_train_worker, 2,
        (ck, str(tmp_path / "data"), str(out),
         "shrink:rank1@step4,grow:+1@epoch1", "auto", 2),
        timeout=900, grace=5.0,
        max_restarts=0, restart_backoff=0.1,
        elastic=True, min_world=1, events_out=events,
    )
    assert restarts == 0
    assert [(e["old_world"], e["new_world"]) for e in events] == [
        (2, 1), (1, 2),
    ]
    results = _read(str(out), [0, 1])
    assert all(r["world"] == 2 for r in results)
    assert all(r["step"] == 16 for r in results)
    assert all(r["per_shard"] == 4 for r in results)  # grown back
    side = json.loads((tmp_path / "ck" / "goodput.json").read_text())
    assert side["resizes"] == 2

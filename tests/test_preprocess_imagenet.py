"""ImageFolder → mmap-array preprocessing (scripts/preprocess_imagenet.py):
the one-time job that feeds --dataset imagenet (data/imagenet.py)."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), os.pardir, "scripts")
)

from ddp_tpu.data import imagenet


def _make_tree(root, split, classes, per_class, side=40):
    from PIL import Image

    rng = np.random.default_rng(0)
    for cls in classes:
        d = root / split / cls
        d.mkdir(parents=True)
        for i in range(per_class):
            arr = rng.integers(0, 256, (side, side + 8, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"img{i}.png")


def test_convert_and_load_roundtrip(tmp_path):
    import preprocess_imagenet as pp

    src, out = tmp_path / "src", tmp_path / "out"
    classes = ["n01", "n02", "n03"]
    _make_tree(src, "train", classes, per_class=4)
    _make_tree(src, "val", classes, per_class=2)

    rc = pp.main(
        ["--src", str(src), "--out", str(out), "--size", "32",
         "--resize", "36", "--workers", "2"]
    )
    assert rc == 0
    assert not list(out.glob("*.part*"))  # temp names atomically renamed

    train = imagenet.load(str(out), "train")
    test = imagenet.load(str(out), "test")
    assert train.images.shape == (12, 32, 32, 3)
    assert test.images.shape == (6, 32, 32, 3)
    assert train.images.dtype == np.uint8
    # sorted-directory label order, like torchvision ImageFolder
    mapping = json.loads((out / "imagenet_classes.json").read_text())
    assert mapping == {"n01": 0, "n02": 1, "n03": 2}
    assert sorted(set(train.labels.tolist())) == [0, 1, 2]
    # 4 images per class, grouped by sorted class dir
    assert train.labels.tolist() == sorted(train.labels.tolist())


def test_decode_resize_center_crop(tmp_path):
    from PIL import Image

    import preprocess_imagenet as pp

    arr = np.zeros((60, 100, 3), np.uint8)
    arr[:, 40:60] = 255  # white vertical band in the center
    p = tmp_path / "x.png"
    Image.fromarray(arr).save(p)
    out = pp.decode(str(p), resize=36, size=32)
    assert out.shape == (32, 32, 3)
    # center crop keeps the central band bright
    assert out[:, 12:20].mean() > 200


def test_empty_split_raises(tmp_path):
    import preprocess_imagenet as pp

    (tmp_path / "src" / "train" / "n01").mkdir(parents=True)
    with pytest.raises(SystemExit, match="no images"):
        pp.main(["--src", str(tmp_path / "src"), "--out", str(tmp_path / "o")])


def test_unknown_val_class_is_hard_error(tmp_path):
    import preprocess_imagenet as pp

    src = tmp_path / "src"
    _make_tree(src, "train", ["n01", "n02"], per_class=1)
    _make_tree(src, "val", ["n01", "n03"], per_class=1)  # n03 not in train
    with pytest.raises(SystemExit, match="not present in the train split"):
        pp.main(["--src", str(src), "--out", str(tmp_path / "o"),
                 "--size", "32", "--resize", "36"])


def test_val_and_test_both_present_rejected(tmp_path):
    import preprocess_imagenet as pp

    src = tmp_path / "src"
    _make_tree(src, "train", ["n01"], per_class=1)
    _make_tree(src, "val", ["n01"], per_class=1)
    _make_tree(src, "test", ["n01"], per_class=1)
    with pytest.raises(SystemExit, match="BOTH val/ and test/"):
        pp.main(["--src", str(src), "--out", str(tmp_path / "o")])

"""On-device augmentation (data/augment.py): shape/range invariants,
determinism, and integration with the compiled train step."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddp_tpu.data.augment import (
    get_augmentation,
    random_crop_flip,
    random_flip,
)
from ddp_tpu.models import get_model
from ddp_tpu.parallel.ddp import (
    create_train_state,
    make_train_step,
    replicate_state,
)
from ddp_tpu.runtime.mesh import data_axes
from ddp_tpu.train.config import TrainConfig


def _images(n=16, side=32, c=3, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.random(size=(n, side, side, c)).astype(np.float32)
    )


class TestOps:
    def test_crop_flip_shape_and_range(self):
        x = _images()
        y = random_crop_flip(jax.random.key(0), x)
        assert y.shape == x.shape
        assert float(y.min()) >= 0.0 and float(y.max()) <= 1.0

    def test_deterministic_in_rng(self):
        x = _images()
        a = random_crop_flip(jax.random.key(7), x)
        b = random_crop_flip(jax.random.key(7), x)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c = random_crop_flip(jax.random.key(8), x)
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_flip_is_flip_or_identity(self):
        x = _images(n=64)
        y = np.asarray(random_flip(jax.random.key(1), x))
        xn = np.asarray(x)
        flipped = 0
        for i in range(len(xn)):
            if np.array_equal(y[i], xn[i]):
                continue
            np.testing.assert_array_equal(y[i], xn[i, :, ::-1, :])
            flipped += 1
        assert 10 < flipped < 54  # ~Binomial(64, 0.5)

    def test_registry(self):
        assert get_augmentation(None) is None
        assert get_augmentation("none") is None
        assert get_augmentation("crop_flip") is random_crop_flip
        with pytest.raises(KeyError):
            get_augmentation("cutmix")


class TestIntegration:
    def test_train_step_with_augmentation_learns(self, mesh8):
        from jax.sharding import NamedSharding, PartitionSpec as P

        model = get_model("simple_cnn")
        tx = optax.sgd(0.05)
        state = replicate_state(
            create_train_state(model, tx, jnp.zeros((1, 28, 28, 1)), seed=0),
            mesh8,
        )
        step = make_train_step(
            model, tx, mesh8, augment_fn=random_crop_flip
        )
        sh = NamedSharding(mesh8, P(data_axes(mesh8)))
        rng = np.random.default_rng(0)
        images = jax.device_put(
            rng.integers(0, 256, size=(32, 28, 28, 1), dtype=np.uint8), sh
        )
        labels = jax.device_put(
            rng.integers(0, 10, size=(32,)).astype(np.int32), sh
        )
        losses = []
        for _ in range(6):
            state, m = step(state, images, labels)
            losses.append(float(m.loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_cli_flag(self):
        cfg = TrainConfig.from_args(["--augment", "crop_flip"])
        assert cfg.augment == "crop_flip"

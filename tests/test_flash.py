"""Pallas flash-attention kernel == dense attention (values and grads).

Runs the kernel in interpreter mode on CPU — the same program the TPU
compiles. Exactness vs. the dense reference is the contract, including
under causal masking and through the custom-VJP backward.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ddp_tpu.ops.attention import dot_product_attention
from ddp_tpu.ops.flash import flash_attention, make_flash_attention


def _qkv(B, T, H, D, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
        for _ in range(3)
    )


def test_flash_matches_dense():
    q, k, v = _qkv(2, 64, 3, 16)
    out = flash_attention(q, k, v, False, 16, 16, True)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_single_block():
    """Block size ≥ T: one block, still exact."""
    q, k, v = _qkv(1, 32, 2, 8, seed=1)
    out = flash_attention(q, k, v, False, 128, 128, True)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_causal():
    q, k, v = _qkv(1, 32, 2, 8, seed=2)
    out = flash_attention(q, k, v, True, 8, 8, True)
    # dense causal reference
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    mask = jnp.tril(jnp.ones((32, 32), bool))
    logits = jnp.where(mask, logits, -jnp.inf)
    w = jax.nn.softmax(logits, axis=-1)
    ref = jnp.einsum("bhts,bshd->bthd", w, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_causal_rectangular():
    """T != S (KV-cache decode shape): mask anchored at the sequence end."""
    q, _, _ = _qkv(1, 4, 2, 8, seed=5)
    _, k, v = _qkv(1, 16, 2, 8, seed=6)
    out = flash_attention(q, k, v, True, 4, 8, True)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    mask = jnp.tril(jnp.ones((4, 16), bool), k=16 - 4)
    logits = jnp.where(mask, logits, -jnp.inf)
    ref = jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(logits, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_grads_match_dense():
    q, k, v = _qkv(1, 32, 2, 8, seed=3)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, False, 16, 16, True) ** 2).mean()

    def loss_dense(q, k, v):
        return (dot_product_attention(q, k, v) ** 2).mean()

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_flash_in_vit():
    """The kernel slots into the model family via attention_fn."""
    from ddp_tpu.models.vit import ViT

    model = ViT(
        num_classes=10, patch_size=7, embed_dim=32, depth=1, num_heads=4,
        attention_fn=make_flash_attention(block_q=16, block_k=16, interpret=True),
    )
    x = jnp.zeros((2, 28, 28, 1), jnp.float32)
    params = model.init(jax.random.key(0), x)["params"]
    logits = model.apply({"params": params}, x)
    assert logits.shape == (2, 10)
    assert np.isfinite(np.asarray(logits)).all()


def _dense_causal(q, k, v):
    """Dense reference with the same end-anchored mask as the kernel."""
    T, S = q.shape[1], k.shape[1]
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    mask = jnp.tril(jnp.ones((T, S), bool), k=S - T)
    logits = jnp.where(mask, logits, -jnp.inf)
    return jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(logits, -1), v)


def test_flash_grads_match_dense_causal():
    """The Pallas backward (dq/dkv kernels) under the causal mask."""
    q, k, v = _qkv(2, 64, 2, 8, seed=4)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, True, 16, 16, True) ** 2).mean()

    def loss_dense(q, k, v):
        return (_dense_causal(q, k, v) ** 2).mean()

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_flash_grads_causal_rectangular():
    """Backward with T != S (decode shape), end-anchored causal mask."""
    q, _, _ = _qkv(1, 8, 2, 8, seed=7)
    _, k, v = _qkv(1, 32, 2, 8, seed=8)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, True, 4, 8, True) ** 2).mean()

    def loss_dense(q, k, v):
        return (_dense_causal(q, k, v) ** 2).mean()

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_flash_lse_matches_logsumexp():
    from ddp_tpu.ops.flash import flash_attention_with_lse

    q, k, v = _qkv(2, 32, 2, 8, seed=9)
    _, lse = flash_attention_with_lse(q, k, v, False, 16, 16, True)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    ref = jax.nn.logsumexp(logits, axis=-1).transpose(0, 2, 1)  # [B, T, H]
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref), atol=2e-5)


def test_flash_lse_combine_identity():
    """(out, lse) halves over split keys combine to full attention —
    the ring-attention hop primitive."""
    from ddp_tpu.ops.flash import flash_attention_with_lse
    from ddp_tpu.parallel.ring import combine_attention_partials

    q, k, v = _qkv(1, 32, 2, 8, seed=10)
    o1, l1 = flash_attention_with_lse(q, k[:, :16], v[:, :16], False, 16, 16, True)
    o2, l2 = flash_attention_with_lse(q, k[:, 16:], v[:, 16:], False, 16, 16, True)
    o, _ = combine_attention_partials(o1, l1, o2, l2)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=2e-5)


def test_flash_lse_combine_grads():
    """Gradients flow through the lse cotangent (the delta − dlse fold)."""
    from ddp_tpu.ops.flash import flash_attention_with_lse
    from ddp_tpu.parallel.ring import combine_attention_partials

    q, k, v = _qkv(1, 32, 2, 8, seed=11)

    def loss_split(q, k, v):
        o1, l1 = flash_attention_with_lse(
            q, k[:, :16], v[:, :16], False, 16, 16, True
        )
        o2, l2 = flash_attention_with_lse(
            q, k[:, 16:], v[:, 16:], False, 16, 16, True
        )
        o, _ = combine_attention_partials(o1, l1, o2, l2)
        return (o**2).mean()

    def loss_dense(q, k, v):
        return (dot_product_attention(q, k, v) ** 2).mean()

    g_s = jax.grad(loss_split, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_s, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_flash_backward_memory_is_linear():
    """The whole VJP at long T compiles with O(T·D) temporaries — no
    [T, S] tensor anywhere (the round-1 backward recomputed through a
    dense O(T²) reference; VERDICT.md missing #1)."""
    T, D = 4096, 64
    shapes = jax.ShapeDtypeStruct((1, T, 1, D), jnp.float32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, False, 128, 128, True) ** 2).mean()

    def loss_dense(q, k, v):
        return (dot_product_attention(q, k, v) ** 2).mean()

    def peak(fn):
        lowered = jax.jit(
            lambda q, k, v: jax.grad(fn, argnums=(0, 1, 2))(q, k, v)
        ).lower(shapes, shapes, shapes)
        return lowered.compile().memory_analysis().temp_size_in_bytes

    flash_mem, dense_mem = peak(loss_flash), peak(loss_dense)
    # Dense saves the [B, H, T, S] softmax (≥ T²·4 bytes ≈ 67 MB);
    # flash residuals are q/k/v/out/lse ≈ 5·T·D·4 ≈ 5 MB.
    assert dense_mem > T * T * 4, dense_mem
    assert flash_mem < dense_mem / 4, (flash_mem, dense_mem)


def test_flash_bf16_finite():
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(1, 64, 2, 16, seed=12))

    def loss(q, k, v):
        return (flash_attention(q, k, v, True, 32, 32, True).astype(jnp.float32) ** 2).mean()

    val, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert np.isfinite(float(val))
    for g in grads:
        assert g.dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(g, dtype=np.float32)).all()

"""Pallas flash-attention kernel == dense attention (values and grads).

Runs the kernel in interpreter mode on CPU — the same program the TPU
compiles. Exactness vs. the dense reference is the contract, including
under causal masking and through the custom-VJP backward.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ddp_tpu.ops.attention import dot_product_attention
from ddp_tpu.ops.flash import flash_attention, make_flash_attention


def _qkv(B, T, H, D, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
        for _ in range(3)
    )


def test_flash_matches_dense():
    q, k, v = _qkv(2, 64, 3, 16)
    out = flash_attention(q, k, v, False, 16, 16, True)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_single_block():
    """Block size ≥ T: one block, still exact."""
    q, k, v = _qkv(1, 32, 2, 8, seed=1)
    out = flash_attention(q, k, v, False, 128, 128, True)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_causal():
    q, k, v = _qkv(1, 32, 2, 8, seed=2)
    out = flash_attention(q, k, v, True, 8, 8, True)
    # dense causal reference
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    mask = jnp.tril(jnp.ones((32, 32), bool))
    logits = jnp.where(mask, logits, -jnp.inf)
    w = jax.nn.softmax(logits, axis=-1)
    ref = jnp.einsum("bhts,bshd->bthd", w, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_causal_rectangular():
    """T != S (KV-cache decode shape): mask anchored at the sequence end."""
    q, _, _ = _qkv(1, 4, 2, 8, seed=5)
    _, k, v = _qkv(1, 16, 2, 8, seed=6)
    out = flash_attention(q, k, v, True, 4, 8, True)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    mask = jnp.tril(jnp.ones((4, 16), bool), k=16 - 4)
    logits = jnp.where(mask, logits, -jnp.inf)
    ref = jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(logits, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_grads_match_dense():
    q, k, v = _qkv(1, 32, 2, 8, seed=3)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, False, 16, 16, True) ** 2).mean()

    def loss_dense(q, k, v):
        return (dot_product_attention(q, k, v) ** 2).mean()

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_flash_in_vit():
    """The kernel slots into the model family via attention_fn."""
    from ddp_tpu.models.vit import ViT

    model = ViT(
        num_classes=10, patch_size=7, embed_dim=32, depth=1, num_heads=4,
        attention_fn=make_flash_attention(block_q=16, block_k=16, interpret=True),
    )
    x = jnp.zeros((2, 28, 28, 1), jnp.float32)
    params = model.init(jax.random.key(0), x)["params"]
    logits = model.apply({"params": params}, x)
    assert logits.shape == (2, 10)
    assert np.isfinite(np.asarray(logits)).all()

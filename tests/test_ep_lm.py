"""Expert-parallel MoE-LM (models/moe.py MoEMLP all-to-all path).

VERDICT round-2 "do this" #3: shard the LM's expert weights over the
``expert`` mesh axis inside the seq shard_map step, with all-to-all
token dispatch — the explicit shard_map analogue of what GSPMD derives
for the annotated image family. Contract:

- EXACT parity with the replicated-experts step under the same batch
  split (``expert`` is a batch axis, so (data=1, expert=2) routes
  identically to (data=2) — the all_to_all pair is mathematically the
  identity around the expert FFN);
- per-device expert memory drops by the axis size (asserted on the
  addressable shard);
- composes with seq (ring attention), fsdp (dim-1 shards of wi/wo),
  and bf16;
- clear construction-time errors from the trainer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from ddp_tpu.models.lm import (
    LMSpec,
    create_lm_train_state,
    init_lm,
    make_lm_train_step,
)
from ddp_tpu.runtime.mesh import MeshSpec, make_mesh

SPEC = LMSpec(
    vocab_size=64, total_len=32, d_model=32, depth=2, num_heads=4,
    num_experts=4, moe_every=2,
)


def _mesh(n, **axes):
    return make_mesh(MeshSpec(**axes), devices=jax.devices()[:n])


def _run(mesh, *, steps=3, dtype=jnp.float32):
    tx = optax.adam(1e-3)
    state = create_lm_train_state(SPEC, tx, mesh, seed=0)
    step = make_lm_train_step(SPEC, tx, mesh, donate=False,
                              compute_dtype=dtype)
    toks = jax.random.randint(jax.random.key(7), (4, 32), 0, 64)
    out = []
    for _ in range(steps):
        state, m = step(state, toks)
        out.append(float(m.loss))
    return np.array(out), state


def test_ep_exact_parity_with_replicated():
    """(data=1, expert=2) == (data=2): same batch split, same local
    routing — the experts merely live on their owners."""
    ref, _ = _run(_mesh(2, data=2))
    ep, _ = _run(_mesh(2, data=1, expert=2))
    np.testing.assert_array_equal(ep, ref)


def test_ep4_parity_with_dp4():
    """4-way splits agree exactly whatever axis provides them (ep>2:
    the all-to-all exchanges more than a neighbor swap)."""
    ref, _ = _run(_mesh(4, data=4))
    ep, _ = _run(_mesh(4, data=1, expert=4))
    np.testing.assert_array_equal(ep, ref)


# (The former dp×ep×sp finite-only composition smoke is subsumed by
# test_full_stack_gqa_moe_tp_ep_sp below, which pins ep×sp — plus tp
# and GQA — to float-tolerance PARITY, not just finiteness.)


def test_ep_expert_memory_shards():
    """wi rests 1/ep per device; with fsdp, dim 1 halves too. Adam
    moments inherit both placements."""
    mesh = _mesh(4, data=1, expert=2, fsdp=2)
    _, state = _run(mesh, steps=1)
    wi = state.params["block2"]["moe"]["wi"]
    E, d, f = SPEC.num_experts, 32, 32 * 4
    assert wi.shape == (E, d, f)
    assert wi.addressable_shards[0].data.shape == (E // 2, d // 2, f)
    mu_wi = state.opt_state[0].mu["block2"]["moe"]["wi"]
    assert mu_wi.addressable_shards[0].data.shape == (E // 2, d // 2, f)
    # Router weights replicate over expert (identical routing on every
    # member); fallback fsdp dim-0 rule still applies.
    router = state.params["block2"]["moe"]["router"]["kernel"]
    assert "expert" not in jax.tree_util.tree_leaves(
        [router.sharding.spec]
    )


def test_ep_specs_assignment():
    from ddp_tpu.parallel.tp import seq_param_specs

    mesh = _mesh(4, data=1, expert=2, fsdp=2)
    specs = seq_param_specs(init_lm(SPEC, seed=0), mesh)
    moe = specs["block2"]["moe"]
    assert moe["wi"] == P("expert", "fsdp")
    assert moe["wo"] == P("expert", "fsdp")
    assert moe["bi"] == P("expert")  # dim 1 is 1: expert only
    assert moe["bo"] == P("expert")
    # Dense block 1 keeps the plain fsdp rule.
    assert specs["block1"]["mlp1"]["kernel"] == P("fsdp")


def test_ep_bf16_runs():
    losses, _ = _run(_mesh(2, data=1, expert=2), dtype=jnp.bfloat16)
    assert np.all(np.isfinite(losses))


def test_ep_indivisible_experts_rejected():
    from ddp_tpu.parallel.tp import seq_param_specs

    spec3 = SPEC._replace(num_experts=3)
    with pytest.raises(ValueError, match="not divisible"):
        seq_param_specs(
            init_lm(spec3, seed=0), _mesh(2, data=1, expert=2)
        )


def test_trainer_ep_guards():
    from ddp_tpu.train.config import TrainConfig
    from ddp_tpu.train.trainer import Trainer

    base = dict(
        model="causal_lm", model_dim=32, num_heads=4, seq_len=32,
        vocab_size=64, epochs=1, batch_size=4,
    )
    with pytest.raises(ValueError, match="--moe_experts"):
        Trainer(TrainConfig(mesh_expert=2, **base))
    with pytest.raises(ValueError, match="not divisible"):
        Trainer(TrainConfig(mesh_expert=2, moe_experts=3, **base))
    # TP×MoE composes since round 5 (Megatron-MoE: attention heads
    # over ``model``, experts over ``expert``) — construction passes.
    Trainer(TrainConfig(mesh_model=2, moe_experts=4, **base)).close()


def test_tp_moe_replicated_experts_matches_dp():
    """TP×MoE with FULLY REPLICATED experts (no expert axis, data>1)
    — a distinct MoEMLP path from the full-stack test below (no
    all-to-all; expert/router leaves replicate across BOTH data and
    model, the layout where a transpose double-count would bite).
    SGD for scaling sensitivity (see the full-stack docstring)."""
    tx = optax.sgd(0.1)
    toks = jax.random.randint(jax.random.key(7), (4, 32), 0, 64)

    def run(mesh):
        state = create_lm_train_state(SPEC, tx, mesh, seed=0)
        step = make_lm_train_step(SPEC, tx, mesh, donate=False)
        out = []
        for _ in range(2):
            state, m = step(state, toks)
            out.append(float(m.loss))
        return np.array(out)

    ref = run(_mesh(2, data=2))
    tp = run(_mesh(4, data=2, model=2))
    np.testing.assert_allclose(tp, ref, atol=2e-6)


def test_full_stack_gqa_moe_tp_ep_sp():
    """TP×MoE (round 5 — the Megatron-MoE layout) at full stack:
    every LM axis at once — GQA attention, routed MLPs, Megatron TP
    over ``model`` (attention heads shard inside routed blocks too),
    expert parallelism over ``expert``, ring attention over ``seq`` —
    equals the dp×sp run with the same batch/token split (GShard
    groups match) to float tolerance. SGD on purpose: adam's m/√v
    update is nearly invariant to uniform gradient scaling, so it
    could not catch a tp×-double-counted gradient on the replicated
    expert/router leaves — the exact failure mode this composition
    risks."""
    spec = SPEC._replace(num_kv_heads=2, total_len=32)
    toks = jax.random.randint(jax.random.key(9), (4, 32), 0, 64)
    tx = optax.sgd(0.1)  # scaling-sensitive — see the docstring

    def run(mesh):
        state = create_lm_train_state(spec, tx, mesh, seed=0)
        step = make_lm_train_step(spec, tx, mesh, donate=False)
        out = []
        for _ in range(2):
            state, m = step(state, toks)
            out.append(float(m.loss))
        return np.array(out), state

    ref, _ = run(_mesh(4, data=2, seq=2))
    full, state = run(_mesh(8, model=2, expert=2, seq=2))
    np.testing.assert_allclose(full, ref, atol=2e-6)
    wi = state.params["block2"]["moe"]["wi"]
    assert wi.sharding.spec == P("expert")
    assert wi.addressable_shards[0].data.shape[0] == wi.shape[0] // 2
    # MoE-block attention rests column-sharded over ``model``.
    qkv = state.params["block2"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.spec == P(None, "model")

"""CIFAR binary parsing + synthetic fallback (offline box: no download)."""

import numpy as np
import pytest

from ddp_tpu.data import cifar


def _records_cifar10(n):
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 10, n, dtype=np.uint8)
    pixels = rng.integers(0, 256, (n, 3072), dtype=np.uint8)
    raw = np.concatenate([labels[:, None], pixels], axis=1).tobytes()
    return raw, labels, pixels


def test_parse_cifar10_records():
    raw, labels, pixels = _records_cifar10(7)
    split = cifar.parse_records(raw, name="cifar10")
    assert split.images.shape == (7, 32, 32, 3)
    assert split.images.dtype == np.uint8
    np.testing.assert_array_equal(split.labels, labels.astype(np.int32))
    # CHW-planar → HWC: red plane is the first 1024 bytes
    np.testing.assert_array_equal(
        split.images[0, :, :, 0].ravel(), pixels[0, :1024]
    )


def test_parse_cifar100_records_picks_fine_label():
    rng = np.random.default_rng(1)
    coarse = rng.integers(0, 20, 5, dtype=np.uint8)
    fine = rng.integers(0, 100, 5, dtype=np.uint8)
    pixels = rng.integers(0, 256, (5, 3072), dtype=np.uint8)
    raw = np.concatenate(
        [coarse[:, None], fine[:, None], pixels], axis=1
    ).tobytes()
    split = cifar.parse_records(raw, name="cifar100")
    np.testing.assert_array_equal(split.labels, fine.astype(np.int32))


def test_parse_rejects_truncated():
    raw, _, _ = _records_cifar10(3)
    with pytest.raises(ValueError):
        cifar.parse_records(raw[:-1], name="cifar10")


def test_synthetic_fallback_offline(tmp_path):
    split = cifar.load(
        str(tmp_path), "train", name="cifar10",
        allow_synthetic=True, synthetic_size=256,
    )
    assert split.images.shape == (256, 32, 32, 3)
    assert split.labels.min() >= 0 and split.labels.max() < 10
    # deterministic
    again = cifar.load(
        str(tmp_path), "train", name="cifar10",
        allow_synthetic=True, synthetic_size=256,
    )
    np.testing.assert_array_equal(split.images, again.images)


def test_no_silent_fallback(tmp_path):
    with pytest.raises((RuntimeError, OSError)):
        cifar.load(str(tmp_path), "train", name="cifar10", allow_synthetic=False)


def test_corrupt_cached_tar_falls_back_and_is_removed(tmp_path):
    """A corrupt cached tarball must not wedge load() forever: the bad
    file is deleted (so a future call re-downloads) and allow_synthetic
    still yields data."""
    bad = tmp_path / "cifar-10-binary.tar.gz"
    bad.write_bytes(b"<html>totally not a tarball</html>")
    split = cifar.load(
        str(tmp_path), "train", name="cifar10",
        allow_synthetic=True, synthetic_size=64,
    )
    assert split.images.shape == (64, 32, 32, 3)
    assert not bad.exists()

"""GSPMD (tensor/FSDP-parallel) step == pure-DDP step, numerically.

The contract that makes sharding rules safe to use: for the same seed,
data, and optimizer, the tensor-parallel/FSDP-sharded train step must
trace the same loss curve and produce the same params as the plain
data-parallel shard_map step — the mesh is an execution detail, not a
semantics change.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ddp_tpu.models import get_model
from ddp_tpu.parallel.ddp import create_train_state, make_train_step, replicate_state
from ddp_tpu.parallel.spmd import (
    ShardingRules,
    batch_spec,
    create_spmd_state,
    make_spmd_train_step,
    param_specs,
)
from ddp_tpu.runtime.mesh import MeshSpec, make_mesh


def _vit(num_classes=10):
    from ddp_tpu.models.vit import ViT

    return ViT(
        num_classes=num_classes, patch_size=7, embed_dim=32, depth=2,
        num_heads=4, dropout_rate=0.0,
    )


def _batches(n_steps, bs, seed=0):
    rng = np.random.default_rng(seed)
    return [
        (
            rng.integers(0, 256, size=(bs, 28, 28, 1), dtype=np.uint8),
            rng.integers(0, 10, size=(bs,)).astype(np.int32),
        )
        for _ in range(n_steps)
    ]


def test_param_specs_follow_rules(devices):
    mesh = make_mesh(MeshSpec(data=2, model=2, fsdp=2), devices=devices)
    model = _vit()
    params = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros((1, 28, 28, 1)))
    )["params"]
    specs = param_specs(params, mesh, ShardingRules())
    b1 = specs["block1"]
    # column kernels: output dim on model; row kernels: input dim on
    # model; fsdp may co-shard the other dim when the param is big.
    assert tuple(b1["attn"]["qkv"]["kernel"])[-1] == "model"
    assert tuple(b1["attn"]["proj"]["kernel"])[0] == "model"
    assert tuple(b1["mlp1"]["kernel"])[-1] == "model"
    assert tuple(b1["mlp2"]["kernel"])[0] == "model"
    # big non-TP param picks up fsdp on its largest divisible dim
    assert "fsdp" in tuple(specs["pos_embed"]) or _small(params["pos_embed"])


def _small(x):
    return x.size < ShardingRules().fsdp_min_size


def test_spmd_state_is_sharded(devices):
    mesh = make_mesh(MeshSpec(data=2, model=2, fsdp=2), devices=devices)
    model = _vit()
    state = create_spmd_state(
        model, optax.sgd(0.1, momentum=0.9), jnp.zeros((1, 28, 28, 1)), mesh
    )
    qkv = state.params["block1"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.spec == P(None, "model")
    # momentum (optax trace) inherited the param sharding via GSPMD
    mom = state.opt_state[0].trace["block1"]["attn"]["qkv"]["kernel"]
    assert mom.sharding.spec == P(None, "model")


def test_tp_fsdp_matches_ddp(devices):
    """3 steps of momentum-SGD: TP×FSDP×DP == pure DP, same numbers."""
    model = _vit()
    tx = optax.sgd(0.05, momentum=0.9)
    batches = _batches(3, 16)

    # pure-DDP reference on a 1-D data mesh
    mesh_dp = make_mesh(MeshSpec(data=8), devices=devices)
    state_dp = replicate_state(
        create_train_state(model, tx, jnp.zeros((1, 28, 28, 1)), seed=0), mesh_dp
    )
    step_dp = make_train_step(model, tx, mesh_dp, donate=False)
    dp_losses = []
    for img, lbl in batches:
        sh = NamedSharding(mesh_dp, P(("data",)))
        state_dp, m = step_dp(
            state_dp, jax.device_put(img, sh), jax.device_put(lbl, sh)
        )
        dp_losses.append(float(m.loss))

    # GSPMD on data=2 × model=2 × fsdp=2
    mesh = make_mesh(MeshSpec(data=2, model=2, fsdp=2), devices=devices)
    state = create_spmd_state(model, tx, jnp.zeros((1, 28, 28, 1)), mesh, seed=0)
    step = make_spmd_train_step(model, tx, mesh, donate=False)
    sh = NamedSharding(mesh, batch_spec(mesh))
    losses = []
    for img, lbl in batches:
        state, m = step(state, jax.device_put(img, sh), jax.device_put(lbl, sh))
        losses.append(float(m.loss))

    np.testing.assert_allclose(losses, dp_losses, rtol=1e-4)
    flat_dp = jax.tree.leaves(jax.device_get(state_dp.params))
    flat_sp = jax.tree.leaves(jax.device_get(state.params))
    for a, b in zip(flat_sp, flat_dp):
        np.testing.assert_allclose(a, b, atol=1e-4)


def test_tp_only_mesh(devices):
    """Pure tensor parallelism (no data axis) also runs and learns."""
    mesh = make_mesh(MeshSpec(data=1, model=4), devices=devices[:4])
    model = _vit()
    tx = optax.sgd(0.05)
    state = create_spmd_state(model, tx, jnp.zeros((1, 28, 28, 1)), mesh, seed=0)
    step = make_spmd_train_step(model, tx, mesh, donate=False)
    (img, lbl) = _batches(1, 8)[0]
    state, m = step(state, jnp.asarray(img), jnp.asarray(lbl))
    assert np.isfinite(float(m.loss))
    assert int(state.step) == 1

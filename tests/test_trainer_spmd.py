"""Trainer-level multi-strategy meshes (tp / fsdp / expert via CLI).

The reference's trainer knows exactly one strategy (DDP, SURVEY.md
§2c). Here the same Trainer drives the GSPMD step when the configured
mesh has non-data axes: params come up sharded, checkpoints round-trip
sharded, and resume works — all through the ordinary config surface.
"""

import jax
import numpy as np

from ddp_tpu.train.config import TrainConfig
from ddp_tpu.train.trainer import Trainer


def make_config(tmp_path, **kw):
    defaults = dict(
        epochs=1,
        batch_size=8,
        checkpoint_dir=str(tmp_path / "checkpoints"),
        data_root=str(tmp_path / "data"),
        synthetic_data=True,
        synthetic_size=512,
        log_interval=8,
        eval_every=0,
    )
    defaults.update(kw)
    return TrainConfig(**defaults)


def test_tp_fsdp_trainer_trains_and_resumes(tmp_path):
    cfg = make_config(
        tmp_path,
        model="vit_micro",
        num_classes=10,
        mesh_model=2,
        mesh_fsdp=2,
        optimizer="adam",
        lr=1e-3,
    )
    t = Trainer(cfg)
    assert t.use_spmd
    assert dict(t.mesh.shape)["model"] == 2
    assert dict(t.mesh.shape)["fsdp"] == 2
    assert dict(t.mesh.shape)["data"] == 2
    # a genuinely sharded parameter exists
    sharded = [
        p
        for p in jax.tree.leaves(t.state.params)
        if any(s is not None for s in p.sharding.spec)
    ]
    assert sharded, "no parameter is sharded on a tp/fsdp mesh"
    summary = t.train()
    t.close()
    assert summary["epochs_run"] == 1
    assert np.isfinite(summary["final_accuracy"])

    # resume with the sharded state
    t2 = Trainer(make_config(
        tmp_path,
        model="vit_micro",
        num_classes=10,
        mesh_model=2,
        mesh_fsdp=2,
        optimizer="adam",
        lr=1e-3,
        epochs=2,
    ))
    summary2 = t2.train()
    t2.close()
    assert summary2["epochs_run"] == 1
    assert summary2["history"][0]["epoch"] == 1


def test_expert_parallel_trainer(tmp_path):
    cfg = make_config(
        tmp_path,
        model="vit_moe_micro",
        num_classes=10,
        mesh_expert=2,
        mesh_model=2,
        optimizer="adam",
        lr=1e-3,
    )
    t = Trainer(cfg)
    assert t.use_spmd
    wi = t.state.params["block2"]["moe"]["wi"]
    assert wi.sharding.spec[0] == "expert"
    summary = t.train()
    t.close()
    assert summary["epochs_run"] == 1
    assert np.isfinite(summary["final_accuracy"])


def test_cli_mesh_flags():
    cfg = TrainConfig.from_args(["--mesh_model", "2", "--mesh_fsdp", "4"])
    assert cfg.mesh_model == 2 and cfg.mesh_fsdp == 4


def test_attention_free_model_under_gspmd(tmp_path, devices):
    """simple_cnn (no attention_fn parameter) under a GSPMD config:
    the trainer's dense-attention pin must fall back cleanly rather
    than crash at construction (half the zoo is attention-free)."""
    from ddp_tpu.train.config import TrainConfig
    from ddp_tpu.train.trainer import Trainer

    cfg = TrainConfig(
        epochs=1,
        batch_size=4,
        model="simple_cnn",
        zero1=True,
        optimizer="adam",
        lr=1e-3,
        checkpoint_dir=str(tmp_path / "ck"),
        data_root=str(tmp_path / "data"),
        synthetic_data=True,
        synthetic_size=64,
        log_interval=4,
        eval_every=0,
    )
    t = Trainer(cfg)
    summary = t.train()
    t.close()
    assert summary["epochs_run"] == 1

#!/usr/bin/env python
"""Headline benchmark: MNIST DDP training throughput, images/sec/chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Baseline is the driver's north-star target of 50,000 images/sec/chip on
TPU (BASELINE.json) — the reference itself publishes no numbers
(/root/reference/README.md has only a quickstart; see BASELINE.md).

Measures the compiled-epoch fast path (ddp_tpu/train/fast.py): dataset
device-resident as uint8, per-epoch shuffle on device, ``lax.scan`` over
per-batch DDP steps — one dispatch per epoch. This is the framework's
answer to the reference's hot loop (train_ddp.py:195-202), which pays a
Python→C++ crossing per op and a collective sync per batch.
"""

from __future__ import annotations

import json
import os
import time

BASELINE_IMAGES_PER_SEC_PER_CHIP = 50_000.0


def _bench_trace_path(name: str) -> str:
    """Where a bench's span trace lands (ddp_tpu.obs tracer export).

    Default ./bench_traces beside the BENCH_*.json records;
    DDP_TPU_BENCH_TRACE_DIR relocates (e.g. CI artifact dirs).
    """
    d = os.environ.get("DDP_TPU_BENCH_TRACE_DIR", "./bench_traces")
    return os.path.abspath(os.path.join(d, f"{name}.trace.json"))


def _lint_clean() -> bool:
    """Self-lint verdict stamped on headline records (never raises —
    a linter crash reads as not-clean, loudly, not as a dead bench)."""
    try:
        from ddp_tpu.analysis import self_lint_clean

        return self_lint_clean()
    except Exception:
        return False


def _env_fields() -> dict:
    """Capture provenance every record carries: platform, backend, and
    an explicit ``cpu_fallback`` flag.

    The r02-r05 captures fell back to CPU when the TPU tunnel was
    unreachable (ROADMAP perf-trajectory note) and their records were
    only distinguishable by correlating ``platform`` — the flag makes
    "never compare an on-chip trajectory point against a CPU one"
    greppable in one field, in every entry, not just the headline.
    """
    import jax

    platform = jax.devices()[0].platform
    return {
        "platform": platform,
        "backend": jax.default_backend(),
        "cpu_fallback": platform == "cpu",
    }


def _assert_provenance(fields: dict) -> None:
    """Pin a record's published provenance to the LIVE backend.

    ``_env_fields`` output asserted against a fresh read of jax at
    publish time: a stale dict captured before a backend flip, copied
    from another record, or mutated downstream fails loudly here
    instead of poisoning the perf trajectory (the r02-r05 stale-capture
    lesson — a CPU record that claims otherwise is worse than no
    record).
    """
    import jax

    live = jax.devices()[0].platform
    assert (
        fields["platform"] == live
        and fields["backend"] == jax.default_backend()
        and fields["cpu_fallback"] == (live == "cpu")
    ), (fields, live)


def run_bench(
    *,
    global_batch_size: int = 16384,
    warmup_epochs: int = 2,
    timed_epochs: int = 10,
) -> dict:
    # Defaults from a sweep on the v4 chip (2026-07): 16384 beat 4096
    # (419k) and 32768 (430k) at 462k images/sec/chip; 10 timed epochs
    # amortize dispatch/timer noise that dominates sub-second windows.
    # Profiled (xprof op_profile, 2026-07): >50% of device time is the
    # conv2 fwd/grad fusions at ~7% MXU util — the 16384×28×28×32
    # bf16 activations (~0.8 GB/tensor) make the step HBM-bandwidth
    # bound, so batch size and kernel tweaks move it little; the
    # remaining headroom would need an architecture change, not
    # scheduling.
    import jax
    import jax.numpy as jnp
    import optax

    from ddp_tpu.data import mnist
    from ddp_tpu.models import get_model
    from ddp_tpu.parallel.ddp import create_train_state, replicate_state
    from ddp_tpu.runtime.mesh import MeshSpec, make_mesh
    from ddp_tpu.train.fast import device_put_dataset, make_epoch_runner

    devices = jax.devices()
    platform = devices[0].platform
    if platform != "tpu":
        # Off-TPU this bench is a smoke/fallback record, not a perf
        # claim — shrink the workload so a CPU run (e.g. the tunnel-
        # outage fallback in __main__) finishes in minutes, not an
        # hour. The record's ``platform`` field marks it.
        global_batch_size = min(global_batch_size, 256)
        warmup_epochs = min(warmup_epochs, 1)
        timed_epochs = min(timed_epochs, 2)
    mesh = make_mesh(MeshSpec(data=len(devices)), devices=devices)

    train = mnist.load("./data", "train", allow_synthetic=True)
    if platform != "tpu":
        train = train._replace(
            images=train.images[:2048], labels=train.labels[:2048]
        )
    n = (train.images.shape[0] // global_batch_size) * global_batch_size
    images, labels = device_put_dataset(
        train.images[:n], train.labels[:n], mesh
    )

    model = get_model("simple_cnn")
    tx = optax.sgd(0.01)
    compute_dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32
    state = replicate_state(
        create_train_state(model, tx, jnp.zeros((1, 28, 28, 1)), seed=0), mesh
    )
    # Compiled-program introspection for the headline (obs/xprof.py):
    # the CPU path instruments the raw jit step (full AOT ledger —
    # real compile seconds, XLA FLOPs, memory); the TPU fast path is
    # an epoch-runner closure, so its ledger entry is observe-only
    # (first-dispatch wall time, flagged ``fallback``). Either way the
    # record carries compile_time_s and the HBM high-water.
    from ddp_tpu.obs.xprof import DeviceMemorySampler, Xprof

    xprof = Xprof(enabled=True)
    hbm = DeviceMemorySampler(enabled=True)
    if platform == "tpu":
        runner = xprof.instrument(
            make_epoch_runner(
                model,
                tx,
                mesh,
                images,
                labels,
                global_batch_size,
                compute_dtype=compute_dtype,
                seed=0,
            ),
            "bench_epoch",
        )
    else:
        # XLA:CPU compiles the conv step ~200× slower INSIDE lax.scan
        # than the identical step standalone (measured round 4:
        # 3.4 s/step scanned vs 15 ms/step at B=32 — the r03 fallback's
        # absurd 8.7 img/s was this artifact, not the framework). The
        # fallback record therefore measures the per-step path; the
        # scanned fast path stays the TPU measurement.
        from ddp_tpu.parallel.ddp import make_train_step

        step_fn = xprof.instrument(
            make_train_step(
                model, tx, mesh, donate=False,
                compute_dtype=compute_dtype, seed=0,
            ),
            "bench_step",
        )
        n_imgs = images.shape[0]
        steps = n_imgs // global_batch_size
        if steps == 0:
            raise ValueError(
                f"train split ({n_imgs} images) smaller than "
                f"global_batch_size ({global_batch_size}) — zero steps "
                "per epoch (make_lm_epoch_runner guards the same case)"
            )

        def runner(state, e):
            perm = jax.random.permutation(jax.random.key(e), n_imgs)
            metrics = None
            for b in range(steps):
                sel = perm[b * global_batch_size:(b + 1) * global_batch_size]
                state, metrics = step_fn(state, images[sel], labels[sel])
            # Match the epoch runner's stacked-loss contract ([-1]).
            return state, metrics._replace(loss=metrics.loss[None])

        runner.steps_per_epoch = steps
    images_per_epoch = runner.steps_per_epoch * global_batch_size

    from ddp_tpu.obs.goodput import cnn_train_flops, peak_flops_per_chip
    from ddp_tpu.obs.tracer import Tracer

    tracer = Tracer(enabled=True, ring_events=4096)
    for e in range(warmup_epochs):  # compile + stabilize clocks
        with tracer.span("bench.warmup_epoch", {"epoch": e}):
            state, metrics = runner(state, e)
            jax.block_until_ready(metrics.loss)
    hbm.sample()  # post-compile steady state

    t0 = time.perf_counter()
    for e in range(warmup_epochs, warmup_epochs + timed_epochs):
        with tracer.span("bench.epoch", {"epoch": e}):
            state, metrics = runner(state, e)
    jax.block_until_ready(metrics.loss)
    seconds = time.perf_counter() - t0
    hbm.sample()

    total_images = images_per_epoch * timed_epochs
    per_chip = total_images / seconds / len(devices)
    # MFU vs the chip's peak (off-TPU: the nominal fallback peak —
    # a trend line, not a hardware claim; `platform` disambiguates).
    flops_per_image = cnn_train_flops((28, 28, 1), 10)
    mfu = per_chip * flops_per_image / peak_flops_per_chip(devices[0])
    try:
        trace = tracer.export(_bench_trace_path("mnist_ddp"))
    except OSError:
        trace = None  # read-only checkout: the record survives
    env = _env_fields()
    # Stale-trajectory guard (ISSUE 10 satellite): the headline's
    # provenance fields are what makes the next TPU-reachable capture
    # comparable against BENCH_LKG.json — assert they are present and
    # self-consistent before the record is published (_finalize embeds
    # the last on-chip record whenever cpu_fallback is True).
    _assert_provenance(env)
    return {
        "metric": "mnist_ddp_train_throughput",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3),
        **env,
        "num_chips": len(devices),
        "global_batch_size": global_batch_size,
        "timed_epochs": timed_epochs,
        "final_loss": round(float(metrics.loss[-1]), 4),
        "seconds": round(seconds, 3),
        "mfu": round(mfu, 6),
        "trace": trace,
        # Compiled-program ledger (obs/xprof.py): what this number
        # paid in XLA builds, and the device-memory high-water of the
        # measured loop (memory_stats on TPU, live-buffer accounting
        # on CPU — never null either way). compile_measured says what
        # compile_time_s IS: "aot" = real lower().compile() seconds
        # (the CPU per-step path); "first_call" = the observe-only
        # fallback's whole first dispatch (compile + one epoch of
        # steps — the TPU epoch-runner closure can't lower), which
        # must never be compared against an aot number.
        "compile_time_s": round(xprof.total_compile_s, 3),
        "compile_measured": (
            "first_call"
            if any(r.get("fallback") for r in xprof.ledger_records())
            else "aot"
        ),
        "compiled_programs": xprof.program_count,
        "hbm_high_water_bytes": hbm.high_water_bytes,
        # How many times this measurement was respawned before a
        # record landed (the supervisor overwrites with the real
        # count): a nonzero value in the trajectory means the headline
        # paid restart overhead and is not comparing like with like.
        "restarts": 0,
        # Self-lint status of the measured tree (scripts/lint.py
        # --self, ddp_tpu.analysis): False means this number was
        # captured on a tree with unsuppressed distributed-JAX hazard
        # findings — a lint regression shows up in the perf-trajectory
        # sidecars next to the throughput it might be corrupting.
        "lint_clean": _lint_clean(),
    }


# --- MXU-bound side benchmarks (VERDICT.md round-1 "do this" #2) -----
#
# The headline MNIST number is HBM-bound (see run_bench notes); these
# measure the models where the TPU-first design actually pays — the
# attention path in bf16 with the Pallas flash kernel — and report an
# MFU estimate. Results go to BENCH_EXTRA.json + stderr; stdout stays
# the single headline JSON line (the driver contract).

# bf16 peak FLOP/s per chip by device kind: one table, owned by the
# observability subsystem (ddp_tpu/obs/goodput.py) so bench and the
# trainer's MFU accounting cannot drift.


def _bf16_peak(device) -> float | None:
    """Spec-sheet peak, or None off-TPU (the ``estimated_mfu`` fields
    stay honest-None there; the ``mfu`` fields use the nominal
    fallback peak via peak_flops_per_chip for a populated trend line).
    """
    from ddp_tpu.obs.goodput import TPU_BF16_PEAK

    kind = getattr(device, "device_kind", "")
    for prefix, peak in TPU_BF16_PEAK.items():
        if kind.startswith(prefix):
            return peak
    return None


def _timed_device_loop(run, state, *, repeats: int = 3):
    """Time ``run(state, seed)`` — one dispatch scanning ``nsteps``
    training steps on device — syncing on the returned scalar.

    Best of ``repeats`` timed dispatches: the device work is
    deterministic per seed, so the spread between repeats is tunnel /
    host scheduling noise (measured ±8% on the v5e link), and the
    minimum is the measurement closest to the device's own rate.
    """
    import time

    loss = float(run(state, 1))  # compile + warm (full sync via float)
    seconds = float("inf")
    for r in range(repeats):
        t0 = time.perf_counter()
        loss = float(run(state, 2 + r))
        seconds = min(seconds, time.perf_counter() - t0)
    return loss, seconds


def _profile_op_split(run, state) -> dict | None:
    """One profiled dispatch → {hlo_category: fraction of device time}.

    Captures a ``jax.profiler`` trace of ``run(state, 3)`` and
    aggregates leaf HLO events on the TPU track by the category the
    profiler assigns ('convolution fusion' = the MXU matmuls, 'data
    formatting'/'copy-done' = layout copies, …), skipping the 'while'
    loop container (it double-counts its body). Returns None off-TPU
    or if anything about the trace format surprises us — the split is
    evidence, never a reason to fail the bench.
    """
    import collections
    import glob
    import gzip
    import tempfile

    import jax

    if jax.devices()[0].platform != "tpu":
        return None  # the pid filter below only knows TPU tracks
    try:
        with tempfile.TemporaryDirectory() as td:
            with jax.profiler.trace(td):
                float(run(state, 3))
            files = glob.glob(td + "/**/*.trace.json.gz", recursive=True)
            if not files:
                return None
            tr = json.load(gzip.open(sorted(files)[-1]))
            evs = tr["traceEvents"]
            procs = {
                e["pid"]: e["args"].get("name", "")
                for e in evs
                if e.get("ph") == "M" and e.get("name") == "process_name"
            }
            dev = {p for p, n in procs.items() if "TPU" in n}
            agg = collections.Counter()
            tot = 0.0
            for e in evs:
                if e.get("ph") != "X" or e.get("pid") not in dev:
                    continue
                cat = (e.get("args") or {}).get("hlo_category")
                if not cat or cat == "while":
                    continue
                agg[cat] += e.get("dur", 0)
                tot += e.get("dur", 0)
            if not tot:
                return None
            return {k: round(v / tot, 3) for k, v in agg.most_common(6)}
    except Exception:
        return None


def run_vit_bench(
    *, batch: int = 256, nsteps: int = 30, use_cls_token: bool = True
) -> dict:
    """ViT-Tiny bf16 training throughput (images/sec/chip + MFU est).

    CIFAR-100-shaped synthetic data generated on device; one jitted
    dispatch scans ``nsteps`` full train steps (fwd+bwd+SGD), so tunnel
    latency and per-call dispatch cost cannot pollute the timing. The
    attention hot op is the Pallas flash kernel (ops/flash.py) via the
    model-zoo default.

    ``use_cls_token=False`` is the round-4 layout-tax experiment
    (round-3 verdict weak #5): T drops from 65 to 64 — a whole tile
    multiple — by mean-pooling instead of a cls token, attacking the
    measured ~30% of step time in 'data formatting'/'copy-done' that
    the T=65 padding forces. Published as the ``vit_t64`` entry so the
    two op-time splits sit side by side.
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax

    from ddp_tpu.models import get_model
    from ddp_tpu.obs.goodput import peak_flops_per_chip

    device = jax.devices()[0]
    if use_cls_token:
        model = get_model("vit_tiny", num_classes=100)
    else:
        from ddp_tpu.models.vit import ViT

        model = ViT(
            num_classes=100, patch_size=4, embed_dim=192, depth=12,
            num_heads=3, use_cls_token=False,
        )
    tx = optax.sgd(0.01, momentum=0.9)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3), jnp.float32)
    )["params"]
    opt_state = tx.init(params)

    def step(carry, key):
        params, opt_state = carry
        # One key per consumer (self-lint DDP005): sharing `key`
        # between normal() and randint() draws labels CORRELATED with
        # the images — a synthetic batch the model can partially read
        # the answer from.
        k_img, k_lbl = jax.random.split(key)
        images = jax.random.normal(k_img, (batch, 32, 32, 3), jnp.bfloat16)
        labels = jax.random.randint(k_lbl, (batch,), 0, 100)

        def loss_fn(p):
            pb = jax.tree.map(lambda x: x.astype(jnp.bfloat16), p)
            logits = model.apply({"params": pb}, images.astype(jnp.bfloat16))
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state), loss

    @jax.jit
    def run(state, seed):
        keys = jax.random.split(jax.random.key(seed), nsteps)
        (params, opt_state), losses = lax.scan(step, state, keys)
        return losses[-1]

    loss, seconds = _timed_device_loop(run, (params, opt_state))
    images_per_sec = batch * nsteps / seconds

    # Analytic train FLOPs/image (fwd ≈ blocks' matmuls + attention;
    # backward ≈ 2× forward). T = 64 patches (8×8) + optional cls.
    d, depth = 192, 12
    T = (32 // 4) ** 2 + (1 if use_cls_token else 0)
    fwd = depth * (24 * T * d * d + 4 * T * T * d)
    train_flops_per_image = 3 * fwd
    peak = _bf16_peak(device)
    mfu = images_per_sec * train_flops_per_image / peak if peak else None
    # The MFU ceiling story (round-2 verdict weak #2), backed by a
    # live per-category profile of this exact dispatch: ViT-Tiny's
    # shapes are tiling-limited on the MXU — K=d=192 contractions fill
    # 1.5 of 2 padded 128-lanes (≤75% per-matmul ceiling), T=65
    # attention pads to 128 rows, and the [B,65,H,3,D] qkv tensors
    # force data-formatting relayouts worth ~1/3 of device time
    # (measured; qkv-slice layout variants and a reshape-matmul patch
    # embed were benchmarked at parity or worse — the copies follow
    # from the shapes, not the op choice). Dividing est. MFU by the
    # matmul share of device time gives ~0.5 MXU-busy efficiency —
    # in line with the LM bench at MXU-friendly shapes (d=1024).
    split = _profile_op_split(run, (params, opt_state))
    note = (
        f"tiling-limited at T={T}/d=192: see op_time_split — matmuls "
        "('convolution fusion') vs layout copies ('data formatting', "
        "'copy-done'); est_mfu / matmul_share ≈ MXU-busy efficiency"
    ) if split is not None else None
    return {
        "metric": "vit_tiny_bf16_train_throughput",
        "value": round(images_per_sec, 1),
        "unit": "images/sec/chip",
        **_env_fields(),
        "tokens": T,
        "use_cls_token": use_cls_token,
        "batch": batch,
        "nsteps": nsteps,
        "final_loss": round(loss, 4),
        "train_flops_per_image": train_flops_per_image,
        "estimated_mfu": round(mfu, 4) if mfu is not None else None,
        "mfu": round(
            images_per_sec * train_flops_per_image
            / peak_flops_per_chip(device),
            6,
        ),
        "device_kind": getattr(device, "device_kind", "unknown"),
        "op_time_split": split,
        "profile_note": note,
    }


def run_lm_bench(
    *, batch: int = 8, seq_len: int = 2048, nsteps: int = 10
) -> dict:
    """Causal-LM training throughput (tokens/sec/chip + MFU est).

    A real MXU workload: d_model 1024, depth 8, heads 8 (head_dim 128
    — wider contractions fill the MXU; measured ~0.48-0.51 estimated MFU
    across runs on the v5e at this config vs 0.39 at d_model 512), T 2048, causal
    flash attention (Pallas) by model-zoo default, bf16 compute.
    Driven through the SHIPPED compiled-epoch path the trainer's
    ``--fast_epoch`` uses (train/fast.py make_lm_epoch_runner — the
    round-3 ask #9 lift): a device-resident token dataset, per-epoch
    on-device shuffle, one dispatch per epoch of ``nsteps`` steps of
    the same raw make_lm_train_step. 1×1 data×seq mesh.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ddp_tpu.models.lm import LMSpec, create_lm_train_state
    from ddp_tpu.obs.goodput import peak_flops_per_chip
    from ddp_tpu.runtime.mesh import MeshSpec, make_mesh
    from ddp_tpu.train.fast import (
        device_put_replicated,
        make_lm_epoch_runner,
    )

    device = jax.devices()[0]
    vocab, d, depth, heads = 8192, 1024, 8, 8
    mesh = make_mesh(MeshSpec(data=1, seq=1), devices=[device])
    spec = LMSpec(
        vocab_size=vocab, total_len=seq_len, d_model=d, depth=depth,
        num_heads=heads,
    )
    tx = optax.adam(3e-4)
    state = create_lm_train_state(spec, tx, mesh, seed=0)
    rng = np.random.default_rng(0)
    tokens = device_put_replicated(
        rng.integers(0, vocab, (batch * nsteps, seq_len), dtype=np.int32),
        mesh,
    )
    runner = make_lm_epoch_runner(
        spec, tx, mesh, tokens, batch,
        compute_dtype=jnp.bfloat16, donate=False,
    )
    assert runner.steps_per_epoch == nsteps

    def run(state, epoch):
        _, metrics = runner(state, epoch)
        return metrics.loss[-1]

    loss, seconds = _timed_device_loop(run, state)
    tokens_per_sec = batch * seq_len * nsteps / seconds

    # PaLM-style estimate: 6·N per token (fwd+bwd matmuls) + causal
    # attention 3.5 × 2 matmuls × T/2 keys × d.
    n_params = depth * 12 * d * d + vocab * d  # tied embedding
    attn = 3.5 * 2 * 2 * (seq_len / 2) * d * depth
    train_flops_per_token = 6 * n_params + attn
    peak = _bf16_peak(device)
    mfu = tokens_per_sec * train_flops_per_token / peak if peak else None
    return {
        "metric": "causal_lm_train_throughput",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        **_env_fields(),
        "batch": batch,
        "seq_len": seq_len,
        "nsteps": nsteps,
        "d_model": d,
        "depth": depth,
        "final_loss": round(loss, 4),
        "train_flops_per_token": round(train_flops_per_token),
        "estimated_mfu": round(mfu, 4) if mfu is not None else None,
        "mfu": round(
            tokens_per_sec * train_flops_per_token
            / peak_flops_per_chip(device),
            6,
        ),
        "device_kind": getattr(device, "device_kind", "unknown"),
    }


def run_lm_long_bench(*, batch: int = 2, seq_len: int = 8192) -> dict:
    """Long-context causal-LM training at T=8192 (flash attention).

    Same model family and step path as run_lm_bench but in the regime
    the flash kernel exists for: O(T) attention memory where dense
    attention would materialize [B, H, T, T] fp32 logits — 2·8·8192²
    = 4 GiB per materialization, several of which coexist across the
    fwd+bwd of 8 layers on a 16 GiB chip. Demonstrates long-context
    training on one chip is real, not extrapolated.
    """
    return {
        **run_lm_bench(batch=batch, seq_len=seq_len, nsteps=4),
        "metric": "causal_lm_long_context_train_throughput",
    }


def run_decode_bench(
    *, batch: int = 8, prompt_len: int = 128, new_tokens: int = 256,
    num_kv_heads: int = 0, num_experts: int = 0,
) -> dict:
    """Generation (serving-path) throughput: KV-cache greedy decode.

    Prefill runs (jitted) OUTSIDE the timed window; the measurement is
    one jitted ``lax.scan`` of decode steps (models/generate.py) on
    the bench LM config — the latency-bound regime (matmuls are
    [B, 1, d]-thin, HBM-bandwidth dominated), the complement of the
    training benches' throughput regime. ``num_kv_heads`` benches the
    GQA variant: the compact cache cuts per-step KV reads by the
    group factor (the ``decode_gqa`` entry records the effect).
    ``num_experts`` benches the round-5 MoE serving path (generate.py
    ``_moe_mlp``: dense E-way expert compute, top-k combine) — the
    ``decode_moe`` entry records routed-decode cost vs dense.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ddp_tpu.models.generate import decode_step, prefill
    from ddp_tpu.models.lm import LMSpec, init_lm

    device = jax.devices()[0]
    vocab, d, depth, heads = 8192, 1024, 8, 8
    spec = LMSpec(
        vocab_size=vocab, total_len=prompt_len + new_tokens, d_model=d,
        depth=depth, num_heads=heads, num_kv_heads=num_kv_heads,
        num_experts=num_experts,
    )
    params = init_lm(spec, seed=0)
    prompt = jnp.zeros((batch, prompt_len), jnp.int32)

    @jax.jit
    def do_prefill(p, pr):
        return prefill(spec, p, pr)

    @jax.jit
    def do_decode(p, logits, cache):
        def step(carry, _):
            logits, cache = carry
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits, cache = decode_step(spec, p, cache, tok)
            return (logits, cache), tok

        (logits, _), toks_out = lax.scan(
            step, (logits, cache), None, length=new_tokens
        )
        return toks_out[-1, 0]

    logits, cache = do_prefill(params, prompt)
    _, best = _timed_device_loop(
        lambda s, _seed: do_decode(*s), (params, logits, cache)
    )
    toks = batch * new_tokens
    # Decode MFU: forward FLOPs/token (train estimate ÷ 3) over peak —
    # the latency-bound regime's honest MXU number (it is SUPPOSED to
    # be low; HBM bandwidth is the binding resource here).
    from ddp_tpu.obs.goodput import (
        lm_train_flops_per_token,
        peak_flops_per_chip,
    )

    fwd_per_token = lm_train_flops_per_token(
        vocab_size=vocab, total_len=spec.total_len, d_model=d,
        depth=depth, num_heads=heads, num_kv_heads=num_kv_heads,
        num_experts=num_experts,
    ) / 3.0
    return {
        "metric": "kv_cache_decode_throughput",
        "value": round(toks / best, 1),
        **_env_fields(),
        "mfu": round(
            (toks / best) * fwd_per_token / peak_flops_per_chip(device), 6
        ),
        "unit": "tokens/sec/chip",
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "d_model": d,
        "depth": depth,
        "num_heads": heads,
        "num_kv_heads": num_kv_heads or heads,
        "num_experts": num_experts,
        "per_token_ms": round(best / new_tokens * 1000, 3),
        "device_kind": getattr(device, "device_kind", "unknown"),
    }


def run_serve_bench(
    *,
    slots: int = 8,
    prefill_len: int = 128,
    new_tokens: int = 128,
    n_requests: int = 48,
    seed: int = 0,
) -> dict:
    """Serving-engine throughput under an open-loop arrival process.

    The ddp_tpu.serve regime: the continuous-batching engine
    (fixed-slot SlotCache, serve/engine.py) fed by Poisson arrivals
    whose rate is INDEPENDENT of service progress (open loop — the
    honest serving measurement; closed-loop clients hide queueing).
    Mixed prompt/output lengths exercise refill churn. The arrival
    rate is sized ~1.5× the engine's slot-seconds so the queue
    genuinely builds and drains — TTFT then includes queueing delay,
    which is the point: this entry reports what a user would see, not
    what a drained batch can do.

    Complements run_decode_bench: that measures the raw decode scan
    (one batch, no arrivals); this measures the whole data plane —
    admission, bucketed chunked prefill co-scheduled with the fused
    decode+sample step, device-resident token handoff, retirement —
    as sustained decode tokens/s, TTFT percentiles and per-step
    latency percentiles (p50/p99: chunk co-scheduling exists exactly
    to keep the p99 step near the p50 — a monolithic prefill would
    show up as a fat tail). The steady-state compile-count budget
    (buckets + decode) is asserted so shape-explosion regressions
    fail the bench fast. Serving metrics stream through
    utils/metrics.MetricsWriter the same way a real deployment's
    would (here: discarded; scripts/serve.py wires --metrics_file).
    """
    import time

    import jax
    import numpy as np

    from ddp_tpu.models.lm import LMSpec, init_lm
    from ddp_tpu.obs.goodput import (
        lm_train_flops_per_token,
        peak_flops_per_chip,
    )
    from ddp_tpu.obs.tracer import Tracer
    from ddp_tpu.serve.engine import ServeEngine

    device = jax.devices()[0]
    vocab, d, depth, heads = 8192, 1024, 8, 8
    if device.platform != "tpu":
        # Fallback shape: the engine logic is platform-free; keep the
        # CPU record minutes-cheap like the other benches' fallbacks.
        vocab, d, depth, heads = 512, 128, 2, 4
        slots, prefill_len = min(slots, 4), min(prefill_len, 32)
        new_tokens, n_requests = min(new_tokens, 32), min(n_requests, 12)
    spec = LMSpec(
        vocab_size=vocab, total_len=prefill_len + new_tokens,
        d_model=d, depth=depth, num_heads=heads,
    )
    params = init_lm(spec, seed=0)
    tracer = Tracer(enabled=True, ring_events=16384)
    # Request-level tracing + SLO evaluation over the bench run
    # (ISSUE 11): every request's admit→retire timeline reconstructs
    # from the exported trace (causally validated below), and the
    # record carries user-facing latency objectives evaluated over
    # the same traffic — recorded, never asserted (a CPU-fallback
    # capture legitimately breaches latency bounds sized for chips).
    from ddp_tpu.obs.slo import SLOEngine

    slo = SLOEngine(
        "ttft_p99<2s,tpot_p50<250ms,availability>0.999",
        min_eval_interval_s=0.0,
    )
    engine = ServeEngine(
        spec, params, slots=slots, prefill_len=prefill_len,
        max_queue=max(16, n_requests), tracer=tracer,
        reqtrace=True, trace_seed=seed, slo=slo,
        # The coverage assert below needs every retired trace still
        # resident at emit time (the timed window runs untraced, so
        # nothing is emitted at retire) — size the retained ring to
        # the run, or a big-capture n_requests would evict the oldest
        # traces and fail the assert spuriously.
        reqtrace_keep=max(512, n_requests + slots),
    )

    rng = np.random.default_rng(seed)
    prompt_lens = rng.integers(8, prefill_len + 1, n_requests)
    budgets = rng.integers(new_tokens // 2, new_tokens + 1, n_requests)
    prompts = [
        rng.integers(0, vocab, int(n)).tolist() for n in prompt_lens
    ]

    # Warmup: eagerly compile the WHOLE bounded program set (one
    # first-chunk + one continuation-chunk program per bucket width,
    # plus the fused decode+sample step) outside the timed window —
    # and assert the compile-count BUDGET: a shape explosion
    # (per-length prefill, per-config decode) fails the bench before
    # it pollutes a published record.
    compile_counts = engine.warmup()
    compile_budget = engine.compile_budget()
    assert sum(compile_counts.values()) <= compile_budget, (
        f"engine program set {compile_counts} exceeds its budget of "
        f"2 x {len(engine.buckets)} chunk buckets + 1 decode program"
    )

    # Open-loop schedule: estimate per-step latency from a short
    # drive, then set the Poisson rate to ~1.5× service capacity.
    t0 = time.perf_counter()
    engine.submit(prompts[0], 8)
    engine.run()
    step_s = max(1e-4, (time.perf_counter() - t0) / 9)
    # Warmup/calibration TTFTs span XLA compilation — reset the
    # engine's latency summaries so the published percentiles reflect
    # the timed open-loop phase only ("what a user would see").
    from ddp_tpu.utils.metrics import StatSummary

    engine.ttft = StatSummary()
    engine.decode_rate = StatSummary()
    engine.step_latency = StatSummary()
    engine.queue_wait = StatSummary()
    engine.tpot = StatSummary()
    # The timed window runs UNTRACED: with tracing on, every dispatch
    # blocks until ready for span fidelity, which disables the
    # dispatch/retire overlap this bench exists to measure. The
    # exported trace keeps the warmup/calibration spans.
    tracer.enabled = False
    service_rate = slots / (step_s * float(np.mean(budgets)))
    arrival_rate = 1.5 * service_rate
    arrivals = np.cumsum(
        rng.exponential(1.0 / arrival_rate, n_requests)
    )

    t_start = time.perf_counter()
    rejected = 0
    max_queue_depth = 0
    timed_rids = []
    i = 0
    while i < n_requests or engine.pending:
        now = time.perf_counter() - t_start
        while i < n_requests and arrivals[i] <= now:
            adm = engine.submit(prompts[i], int(budgets[i]))
            if adm.accepted:
                timed_rids.append(adm.request.rid)
            else:
                rejected += 1
            i += 1
        max_queue_depth = max(max_queue_depth, engine.scheduler.depth)
        if engine.pending:
            engine.step()
        elif i < n_requests:
            time.sleep(min(0.005, max(0.0, arrivals[i] - now)))
    wall = time.perf_counter() - t_start
    tracer.enabled = True
    # The engine records its own per-step latency (reset above so the
    # summary covers exactly the timed open-loop window).
    step_lat = engine.step_latency

    total_tokens = sum(
        len(engine.result(r).tokens)
        for r in timed_rids
        if engine.result(r) is not None
    )
    assert engine.compile_counts() == compile_counts, (
        "serve bench recompiled after warmup — static-shape invariant "
        f"broken: {compile_counts} -> {engine.compile_counts()}"
    )
    # The /metricsz exposition must stay scrapeable under a real
    # traffic mix: render the live engine counters and run the lint
    # (obs/promtext.py) so a renderer regression fails the bench too,
    # not just the smoke tier.
    from ddp_tpu.obs.promtext import render_serve, validate_promtext

    promtext_samples = validate_promtext(
        render_serve(engine.stats(), up=True)
    )
    fwd_per_token = lm_train_flops_per_token(
        vocab_size=vocab, total_len=spec.total_len, d_model=d,
        depth=depth, num_heads=heads,
    ) / 3.0
    # Per-request timeline acceptance (ISSUE 11): the timed window ran
    # with measuring mode off (overlap preserved), so emit the retired
    # request spans retroactively, then require that EVERY completion
    # reconstructs to a complete, causally-ordered admit→retire
    # timeline from the trace — a broken lifecycle event fails the
    # bench, not just a test.
    from ddp_tpu.obs.reqtrace import (
        reconstruct_requests,
        validate_request_timeline,
    )

    engine.emit_request_spans()
    timelines = reconstruct_requests(
        tracer.trace_document()["traceEvents"]
    )
    for tid, timeline in timelines.items():
        validate_request_timeline(timeline)  # raises naming the hole
    assert len(timelines) == len(engine._completed), (
        f"request-trace coverage broken: {len(timelines)} timelines "
        f"for {len(engine._completed)} completions"
    )
    try:
        trace = tracer.export(_bench_trace_path("serve_decode"))
    except OSError:
        trace = None

    # ---- decode-path variants (ISSUE 10) ----------------------------
    # Same model, same traffic, four engine configs: the PR-3 baseline
    # (jnp reference attention, fp32 cache), flash-decode (the engine's
    # auto selection: Pallas kernel on TPU, the bit-identical reference
    # off-TPU — forcing the interpreter here would measure the
    # interpreter, not the kernel), +speculative (γ=4 greedy drafts
    # from a truncated-depth draft sharing the target's weights — the
    # zero-training draft; --draft_checkpoint_dir wires a real one),
    # and +int8 KV (quantize-on-write cache). Each sub-record carries
    # steady-state step-latency p50/p99, tokens/s, acceptance, cache
    # bytes/slot, and the PR-9 provenance fields so a CPU-fallback
    # capture can never be compared against an on-chip one.
    from ddp_tpu.utils.metrics import StatSummary as _SS

    def _variant(name: str, **ekw) -> dict:
        v_eng = ServeEngine(
            spec, params, slots=slots, prefill_len=prefill_len,
            max_queue=4 * slots, **ekw,
        )
        counts = v_eng.warmup()
        assert sum(counts.values()) <= v_eng.compile_budget(), (
            f"variant {name} program set {counts} exceeds its budget "
            f"{v_eng.compile_budget()}"
        )
        v_rng = np.random.default_rng(seed + 1)  # same traffic per variant
        for _ in range(2 * slots):
            plen = int(v_rng.integers(8, max(9, prefill_len // 2 + 1)))
            v_eng.submit(
                v_rng.integers(0, vocab, plen).tolist(), new_tokens
            )
        v_eng.step()  # settle admission/prefill before timing
        v_eng.step_latency = _SS()
        v0 = time.perf_counter()
        while v_eng.pending:
            v_eng.step()
        v_wall = time.perf_counter() - v0
        v_tokens = sum(
            len(c.tokens) for c in v_eng._completed.values()
        )
        lat = v_eng.step_latency
        assert v_eng.compile_counts() == counts, (
            f"variant {name} recompiled after warmup"
        )
        return {
            "attn_impl": v_eng.decode_attn,
            "kv_dtype": v_eng.kv_dtype,
            "spec_tokens": v_eng.spec_tokens,
            "step_latency_s": {
                "count": lat.count,
                "p50": round(lat.percentile(50), 6) if lat.count else None,
                "p99": round(lat.percentile(99), 6) if lat.count else None,
            },
            "tokens_per_s": round(v_tokens / v_wall, 1),
            "total_tokens": v_tokens,
            "acceptance_rate": v_eng.spec_acceptance_rate(),
            "cache_bytes_per_slot": v_eng.cache_bytes_per_slot(),
            "compile_programs": sum(counts.values()),
            "compile_budget": v_eng.compile_budget(),
            # Paged-KV pool/prefix gauges (PR 12) — absent on the
            # fixed-lane variants, same gate as /metricsz.
            **(
                {"paged": v_eng.page_stats()} if v_eng.paged else {}
            ),
            **_env_fields(),
        }

    # Truncated-depth draft sharing the target's weights: the cheapest
    # "small draft LM from models/lm.py" that exists without a second
    # training run. On random init its proposals barely correlate with
    # the target (acceptance is reported, not assumed); a trained
    # draft checkpoint slots into the same machinery via
    # scripts/serve.py --draft_checkpoint_dir.
    draft_spec = spec._replace(depth=max(1, depth // 2))
    draft_params = {
        k: params[k]
        for k in ["embed", "pos_embed", "ln_final"]
        + [f"block{i + 1}" for i in range(draft_spec.depth)]
    }
    variants = {
        "baseline": _variant("baseline", decode_attn="reference"),
        "flash_decode": _variant("flash_decode", decode_attn="auto"),
        "spec": _variant(
            "spec", decode_attn="auto",
            draft_spec=draft_spec, draft_params=draft_params,
            spec_tokens=4,
        ),
        # Perfectly-aligned draft (the target itself): acceptance-1.0
        # ceiling — measures the verify-round mechanics (γ tokens per
        # target step) with the draft-quality variable removed.
        "spec_selfdraft": _variant(
            "spec_selfdraft", decode_attn="auto",
            draft_spec=spec, draft_params=params, spec_tokens=4,
        ),
        "int8_kv": _variant("int8_kv", decode_attn="auto",
                            kv_dtype="int8"),
        # Paged KV (PR 12): page-pool cache + radix prefix index at
        # the capacity-neutral pool size. This traffic has no shared
        # prefixes, so the record measures the paged layout's pure
        # overhead (gather/scatter through the table); the reuse win
        # is serve_prefix's job.
        "paged_kv": _variant("paged_kv", decode_attn="auto",
                             page_size=16),
    }
    base_bytes = variants["baseline"]["cache_bytes_per_slot"]
    int8_bytes = variants["int8_kv"]["cache_bytes_per_slot"]
    assert int8_bytes <= 0.55 * base_bytes, (
        f"int8 KV cache bytes/slot {int8_bytes} did not halve the "
        f"fp32 layout {base_bytes}"
    )

    env = _env_fields()
    # Satellite 6 (stale on-chip trajectory): the provenance fields
    # are load-bearing for the next TPU-reachable capture — assert
    # they exist and agree before publishing, and say loudly when
    # this record is a CPU fallback.
    _assert_provenance(env)
    return {
        "metric": "serve_decode_throughput",
        "value": round(total_tokens / wall, 1),
        **env,
        **(
            {
                "note": "CPU-fallback capture: decode-path variant "
                "latencies are CPU-bound (flash-decode auto-selects "
                "the reference path off-TPU); compare on-chip records "
                "only against BENCH_LKG.json"
            }
            if env["cpu_fallback"]
            else {}
        ),
        "variants": variants,
        "flash_p50_vs_baseline": (
            round(
                variants["flash_decode"]["step_latency_s"]["p50"]
                / variants["baseline"]["step_latency_s"]["p50"],
                3,
            )
            if variants["baseline"]["step_latency_s"]["p50"]
            else None
        ),
        "int8_cache_bytes_ratio": round(int8_bytes / base_bytes, 3),
        # How many int8 lanes fit in the HBM one fp32 lane occupies —
        # the slots-per-chip capacity story.
        "int8_slots_capacity_gain": round(base_bytes / int8_bytes, 2),
        "mfu": round(
            (total_tokens / wall) * fwd_per_token
            / peak_flops_per_chip(device),
            6,
        ),
        "trace": trace,
        "engine_goodput": engine.goodput(),
        "unit": "tokens/sec/chip",
        "slots": slots,
        "prefill_len": prefill_len,
        "prefill_chunk": engine.prefill_chunk,
        "prefill_buckets": list(engine.buckets),
        "step_token_budget": engine.step_token_budget,
        "n_requests": n_requests,
        "rejected": rejected,
        "max_queue_depth": max_queue_depth,
        "arrival_rate_req_per_s": round(float(arrival_rate), 2),
        "ttft_s": engine.ttft.snapshot(),
        # User-facing latency percentiles (ISSUE 11): the perf
        # trajectory records what a user would see, not just step
        # latency — TTFT tail, median time-per-output-token, and the
        # queueing-delay tail the open-loop arrivals exist to build.
        "ttft_p99": (
            round(engine.ttft.percentile(99), 4)
            if engine.ttft.count else None
        ),
        "tpot_p50": (
            round(engine.tpot.percentile(50), 6)
            if engine.tpot.count else None
        ),
        "queue_s_p99": (
            round(engine.queue_wait.percentile(99), 4)
            if engine.queue_wait.count else None
        ),
        # Objectives evaluated over this run's traffic (recorded, not
        # asserted — see the SLOEngine note above) + request-trace
        # coverage: every completion reconstructed causally-ordered.
        "slo": slo.state(),
        "reqtrace": {
            "requests": len(timelines),
            "causal_ok": len(timelines),
        },
        "decode_tokens_per_s_per_req": engine.decode_rate.snapshot(),
        "step_latency_s": {
            "count": step_lat.count,
            "p50": (
                round(step_lat.percentile(50), 6)
                if step_lat.count else None
            ),
            "p99": (
                round(step_lat.percentile(99), 6)
                if step_lat.count else None
            ),
            "mean": (
                round(step_lat.snapshot(ndigits=6).get("mean", 0.0), 6)
                if step_lat.count
                else None
            ),
        },
        "compile_counts": compile_counts,
        "compile_budget": compile_budget,
        "promtext_samples": promtext_samples,
        "wall_s": round(wall, 3),
        "d_model": d,
        "depth": depth,
        "device_kind": getattr(device, "device_kind", "unknown"),
    }


def run_serve_prefix_bench(
    *,
    slots: int = 8,
    page_size: int = 16,
    prefix_tokens: int = 96,
    tail_tokens: int = 16,
    new_tokens: int = 32,
    n_requests: int = 24,
    seed: int = 0,
) -> dict:
    """Shared-prefix serving: the paged KV + radix index win (PR 12).

    The serve_decode entry's traffic shares nothing, so it measures
    the paged layout's overhead; THIS entry measures what the layout
    exists for. Open-loop traffic where every request shares one
    system prompt (``prefix_tokens``) and differs only in a short
    user tail — the fleet-routing regime PAPERS.md #1 identifies as
    where TPU serving loses to GPU baselines today. One seed request
    publishes the prefix pages; the rest fork them copy-free. The
    record carries:

    - token-level **prefix-hit rate** (matched prompt tokens /
      admitted prompt tokens) — the chunked prefill never runs for
      matched tokens, so this is the prefill-compute discount;
    - the **effective-slots multiplier**: peak Σ per-lane page
      mappings over unique mapped pages — how many lane-copies of
      residency the pool is serving per physical page (1.0 = the
      fixed-lane baseline, > 1 = the int8-compounding capacity win);
    - **TTFT p50/p99 split hit vs miss** — what reuse buys the user;
    - throughput vs a fixed-lane engine over the identical traffic
      (honest CPU nulls: off-TPU the gather/scatter overhead and the
      skipped prefill compute both land on the same cores).

    Both hit-rate and multiplier floors are asserted (>= 0.5 and
    > 1.5): they are scheduling facts, not timing facts, so a miss is
    a regression in the radix index, not noise.
    """
    import time

    import jax
    import numpy as np

    from ddp_tpu.models.lm import LMSpec, init_lm
    from ddp_tpu.serve.engine import ServeEngine
    from ddp_tpu.utils.metrics import StatSummary

    device = jax.devices()[0]
    vocab, d, depth, heads = 8192, 1024, 8, 8
    if device.platform != "tpu":
        # CPU fallback shape (the serve_decode convention): the
        # engine/index logic is platform-free; keep it minutes-cheap.
        vocab, d, depth, heads = 512, 128, 2, 4
        slots = min(slots, 4)
        prefix_tokens, tail_tokens = min(prefix_tokens, 48), 8
        new_tokens, n_requests = min(new_tokens, 16), min(n_requests, 12)
    prompt_len = prefix_tokens + tail_tokens
    total_len = prompt_len + new_tokens
    if total_len % page_size:
        total_len += page_size - total_len % page_size
    spec = LMSpec(
        vocab_size=vocab, total_len=total_len, d_model=d,
        depth=depth, num_heads=heads,
    )
    params = init_lm(spec, seed=0)
    rng = np.random.default_rng(seed)
    prefix = rng.integers(0, vocab, prefix_tokens).tolist()
    prompts = [
        prefix + rng.integers(0, vocab, tail_tokens).tolist()
        for _ in range(n_requests)
    ]

    def _drive(eng) -> dict:
        """Identical traffic shape per engine: the first request runs
        alone (on the paged engine it publishes the prefix), then the
        rest arrive as a burst — concurrent lanes really fork."""
        eng.warmup()
        counts = eng.compile_counts()
        t0 = time.perf_counter()
        rids = [eng.submit(prompts[0], new_tokens).request.rid]
        eng.run()
        eff_peak = None
        for p in prompts[1:]:
            adm = eng.submit(p, new_tokens)
            assert adm.accepted, adm.reason
            rids.append(adm.request.rid)
        while eng.pending:
            eng.step()
            ps = eng.page_stats()
            if ps and ps["effective_slots_multiplier"] is not None:
                eff_peak = max(
                    eff_peak or 0.0, ps["effective_slots_multiplier"]
                )
        wall = time.perf_counter() - t0
        assert eng.compile_counts() == counts, (
            "serve_prefix recompiled after warmup"
        )
        hit_ttft, miss_ttft = StatSummary(), StatSummary()
        tokens = 0
        for r in rids:
            c = eng.result(r)
            assert c is not None and c.status == "complete", (
                r, None if c is None else c.status
            )
            tokens += len(c.tokens)
            if c.ttft is None:
                continue
            # Fixed-lane completions carry prefix_hit_tokens=None —
            # no prefix cache means EVERY request pays the miss path,
            # so the control's TTFTs all land in the miss summary
            # (ttft_hit_s stays count-0 there by construction).
            if c.prefix_hit_tokens:
                hit_ttft.add(c.ttft)
            else:
                miss_ttft.add(c.ttft)

        def pct(s, q):
            return round(s.percentile(q), 4) if s.count else None

        return {
            "tokens_per_s": round(tokens / wall, 1),
            "total_tokens": tokens,
            "wall_s": round(wall, 3),
            "ttft_hit_s": {
                "count": hit_ttft.count,
                "p50": pct(hit_ttft, 50), "p99": pct(hit_ttft, 99),
            },
            "ttft_miss_s": {
                "count": miss_ttft.count,
                "p50": pct(miss_ttft, 50), "p99": pct(miss_ttft, 99),
            },
            "effective_slots_multiplier_peak": eff_peak,
            **(
                {"paged": eng.page_stats()} if eng.paged else {}
            ),
        }

    paged_eng = ServeEngine(
        spec, params, slots=slots, prefill_len=prompt_len,
        max_queue=max(16, n_requests), page_size=page_size,
    )
    paged = _drive(paged_eng)
    baseline = _drive(
        ServeEngine(
            spec, params, slots=slots, prefill_len=prompt_len,
            max_queue=max(16, n_requests),
        )
    )
    hit_rate = paged["paged"]["prefix_hit_rate"]
    eff = paged["effective_slots_multiplier_peak"]
    # Scheduling facts, not timing facts (see docstring) — assert.
    assert hit_rate is not None and hit_rate >= 0.5, (
        f"prefix hit rate {hit_rate} below the 0.5 floor on a "
        "shared-prefix workload: radix matching is broken"
    )
    assert eff is not None and eff > 1.5, (
        f"effective-slots multiplier {eff} never exceeded 1.5 with "
        f"{slots} lanes forking a {prefix_tokens}-token prefix: page "
        "sharing is broken"
    )
    env = _env_fields()
    _assert_provenance(env)
    return {
        "metric": "serve_prefix_hit_rate",
        "value": hit_rate,
        **env,
        **(
            {
                "note": "CPU-fallback capture: wall-clock numbers are "
                "honest CPU nulls (skipped prefill compute and table "
                "gather overhead share the same cores); hit rate and "
                "effective-slots multiplier are platform-free facts"
            }
            if env["cpu_fallback"]
            else {}
        ),
        "effective_slots_multiplier_peak": eff,
        "paged_vs_baseline_tokens_per_s": (
            round(paged["tokens_per_s"] / baseline["tokens_per_s"], 3)
            if baseline["tokens_per_s"]
            else None
        ),
        "paged_kv": paged,
        "fixed_lane_baseline": baseline,
        "unit": "hit fraction",
        "slots": slots,
        "page_size": page_size,
        "kv_pages": paged_eng.kv_pages,
        "prefix_tokens": prefix_tokens,
        "tail_tokens": tail_tokens,
        "new_tokens": new_tokens,
        "n_requests": n_requests,
        "total_len": total_len,
        "device_kind": getattr(device, "device_kind", "unknown"),
    }


def run_serve_fleet_bench(
    *,
    n_replicas: int = 3,
    slots: int = 4,
    page_size: int = 16,
    prefix_tokens: int = 48,
    tail_tokens: int = 8,
    new_tokens: int = 8,
    groups: int = 4,
    per_group: int = 6,
    kill_at: int = 12,
) -> dict:
    """Fleet serving (ISSUE 14): a REAL ≥3-replica CPU fleet —
    subprocess ``scripts/serve.py --init_demo`` engines behind the
    serve/fleet.py router — under open-loop shared-prefix traffic.

    Three phases over one fleet (distinct prefix sets, so the radix
    caches never cross-pollinate):

    1. **random dispatch** (the control): per-replica prefix-hit
       rates when traffic sprays everywhere;
    2. **prefix affinity**: the same traffic shape routed by the
       prompt-hash → preferred-replica map — the AFFINITY hit rate
       MUST beat the random one (asserted: it is a routing fact, not
       a timing fact), plus aggregate tokens/s and p99 TTFT;
    3. **kill drill**: ``kill:replica1@request<kill_at>`` mid-burst —
       ALL submitted requests complete (zero dropped, ASSERTED), no
       completion is delivered twice (fleet trace-id uniqueness,
       ASSERTED), exactly one replica restart (ASSERTED), replayed
       requests recorded, and recovery time measured from the
       SIGKILL to the first completion the restarted replica serves.

    Disaggregation phases (PR 16) over the same fleet:

    4. **prefill:decode ratio sweep** — 1:2, 1:1 and 2:1 tier splits
       (roles are router-side, so the sweep re-labels the live
       replicas) vs the homogeneous hybrid control, all under the
       same long-prompt traffic shape: aggregate tokens/s and p99
       TTFT per ratio (honest CPU nulls — replicas share cores),
       page-migration latency p50/p99 from the router's own summary,
       and zero dropped requests per ratio (ASSERTED);
    5. **migration token identity** — the same prompts asked of the
       1:2 disagg fleet and of the hybrid control must stream
       IDENTICAL tokens (ASSERTED; greedy over identical weights —
       disaggregation is a placement change, not a numerics change),
       with every disagg response served by the decode tier
       (ASSERTED);
    6. **directory vs affinity under churn** — seed shared prefixes,
       kill the affinity home of at least one group, serve through
       the outage, then burst after the restart: the fleet-wide
       prefix-hit rate with the prefix directory on must be >= the
       affinity-only control's (ASSERTED — the directory re-warms
       the restarted replica by pulling pages from the replica that
       served during the outage; affinity alone restarts cold).
    """
    import tempfile
    import threading
    import time
    import urllib.request

    import numpy as np

    from ddp_tpu.serve.fleet import (
        ROLE_DECODE,
        ROLE_HYBRID,
        ROLE_PREFILL,
        FleetChaos,
        ReplicaManager,
        Router,
        RouterConfig,
        affinity_key,
    )

    rng = np.random.default_rng(0)
    vocab, seq_len = 256, 128
    n_requests = groups * per_group

    def make_prompts(phase_seed):
        prng = np.random.default_rng(phase_seed)
        prefixes = [
            prng.integers(0, vocab, prefix_tokens).tolist()
            for _ in range(groups)
        ]
        return [
            prefixes[g] + prng.integers(0, vocab, tail_tokens).tolist()
            for g in range(groups)
            for _ in range(per_group)
        ]

    def paged_counts(url):
        with urllib.request.urlopen(url + "/statusz", timeout=10) as r:
            pg = json.loads(r.read()).get("stats", {}).get("paged") or {}
        return (
            int(pg.get("prefix_hits") or 0),
            int(pg.get("prefix_misses") or 0),
            pg.get("prefix_hit_rate"),
        )

    def drive(router, prompts):
        """Per-group seeding request first (publishes the prefix),
        then the open-loop burst — the serve_prefix traffic shape at
        fleet scale."""
        results: list[dict] = []
        lock = threading.Lock()

        def one(i):
            status, payload = router.dispatch(
                {
                    "prompt_tokens": prompts[i],
                    "max_new_tokens": new_tokens,
                }
            )
            with lock:
                # http_status is OURS; the payload's own "status" is
                # the completion status ("complete"/"timeout_...").
                results.append(
                    {"i": i, "http_status": status, **payload}
                )

        t0 = time.perf_counter()
        seed_threads = [
            threading.Thread(target=one, args=(g * per_group,))
            for g in range(groups)
        ]
        for t in seed_threads:
            t.start()
        for t in seed_threads:
            t.join()
        rest = [
            i for i in range(len(prompts)) if i % per_group != 0
        ]
        threads = [
            threading.Thread(target=one, args=(i,)) for i in rest
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        return results, wall

    def phase_summary(results, wall):
        from ddp_tpu.utils.metrics import StatSummary

        ttft = StatSummary()
        tokens = 0
        for r in results:
            tokens += len(r.get("tokens") or [])
            if r.get("ttft_s") is not None:
                ttft.add(r["ttft_s"])
        return {
            "completed": sum(
                1 for r in results if r["http_status"] == 200
            ),
            "tokens_per_s": round(tokens / wall, 2) if wall else None,
            "total_tokens": tokens,
            "wall_s": round(wall, 3),
            "ttft_p50_s": (
                round(ttft.percentile(50), 4) if ttft.count else None
            ),
            "ttft_p99_s": (
                round(ttft.percentile(99), 4) if ttft.count else None
            ),
        }

    workdir = tempfile.mkdtemp(prefix="ddp_tpu_fleet_bench_")
    mgr = ReplicaManager(
        n_replicas,
        [
            "--init_demo",
            "--slots", str(slots),
            "--page_size", str(page_size),
            "--vocab_size", str(vocab),
            "--seq_len", str(seq_len),
        ],
        workdir=workdir,
        # Budget for the kill drill (1 restart) plus one kill per
        # churn trial in phase 6, even if they all land on replica 1.
        max_restarts=4,
        restart_backoff=0.2,
    )
    record: dict = {"metric": "serve_fleet_affinity_hit_rate"}
    try:
        mgr.start()
        assert mgr.wait_healthy(420), "fleet never became healthy"
        urls = [r.url for r in mgr.replicas]

        def hit_deltas(before):
            # Re-read replica URLs: a restarted replica (phase 6
            # churn) rebinds a fresh port, so the startup list goes
            # stale the moment a kill drill fires.
            after = [
                paged_counts(r.url) for r in mgr.replicas
            ]
            per_replica = []
            hits = misses = 0
            for (h0, m0, _), (h1, m1, rate) in zip(before, after):
                dh, dm = h1 - h0, m1 - m0
                hits += dh
                misses += dm
                per_replica.append(
                    {
                        "hits": dh, "misses": dm,
                        "hit_rate": (
                            round(dh / (dh + dm), 4)
                            if dh + dm
                            else None
                        ),
                        "lifetime_hit_rate": rate,
                    }
                )
            total = hits + misses
            return (
                round(hits / total, 4) if total else None,
                per_replica,
                after,
            )

        # Phase 1: random dispatch (the control the affinity claim
        # is measured against).
        base = [paged_counts(u) for u in urls]
        router = mgr.attach_router(
            Router(
                mgr.replicas,
                RouterConfig(affinity=False, trace_seed=1),
            )
        )
        results_r, wall_r = drive(router, make_prompts(101))
        random_rate, random_per_replica, base = hit_deltas(base)

        # Phase 2: prefix affinity (distinct prefixes — no help from
        # phase 1's published pages).
        router = mgr.attach_router(
            Router(
                mgr.replicas,
                RouterConfig(
                    affinity=True,
                    affinity_page=page_size,
                    trace_seed=2,
                ),
            )
        )
        results_a, wall_a = drive(router, make_prompts(202))
        affinity_rate, affinity_per_replica, base = hit_deltas(base)

        # Phase 3: the kill drill.
        chaos = FleetChaos(f"kill:replica1@request{kill_at}", mgr)
        kill_time = [None]
        orig_kill = mgr.kill_replica

        def timed_kill(index):
            kill_time[0] = time.perf_counter()
            orig_kill(index)

        mgr.kill_replica = timed_kill
        router = mgr.attach_router(
            Router(
                mgr.replicas,
                RouterConfig(
                    affinity=True,
                    affinity_page=page_size,
                    retry_backoff_s=0.02,
                    trace_seed=3,
                ),
                on_dispatch=chaos.on_dispatch,
            )
        )
        results_k, wall_k = drive(router, make_prompts(303))
        assert mgr.chaos_kills == 1, "the drill never fired"
        # zero dropped, zero duplicated — ASSERTED
        dropped = [
            r for r in results_k if r["http_status"] != 200
        ]
        assert not dropped, f"kill drill dropped {len(dropped)} requests"
        tids = [r["router"]["trace_id"] for r in results_k]
        assert len(set(tids)) == len(results_k), (
            "duplicate completion delivered (trace-id collision)"
        )
        # The non-vacuous half of zero-dup: (replica, replica-rid)
        # names the REPLICA-SIDE completion each response came from —
        # a collision would mean one engine completion was delivered
        # to two clients (a replayed/hedged response double-served).
        served = [
            (r["router"]["replica"], r.get("rid")) for r in results_k
        ]
        assert len(set(served)) == len(results_k), (
            "one replica completion was delivered twice"
        )
        # exactly one replica restart
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if mgr.restarts_total == 1 and all(
                r.state == "healthy" for r in mgr.replicas
            ):
                break
            time.sleep(0.25)
        assert mgr.restarts_total == 1, (
            f"expected exactly one restart, saw {mgr.restarts_total}"
        )
        # recovery time: SIGKILL → first completion the RESTARTED
        # replica serves (trickle until the router hands it one).
        recovery_s = None
        probe_deadline = time.monotonic() + 120
        while time.monotonic() < probe_deadline:
            status, payload = router.dispatch(
                {
                    "prompt_tokens": rng.integers(
                        0, vocab, page_size
                    ).tolist(),
                    "max_new_tokens": 2,
                }
            )
            if (
                status == 200
                and payload["router"]["replica"] == 1
            ):
                recovery_s = time.perf_counter() - kill_time[0]
                break
            time.sleep(0.2)
        kill_drill = {
            **phase_summary(results_k, wall_k),
            "killed_replica": 1,
            "kill_at_request": kill_at,
            "replays_total": router.replays_total,
            "retries_total": router.retries_total,
            "restarts": mgr.restarts_total,
            "recovery_s": (
                round(recovery_s, 3) if recovery_s else None
            ),
            "dropped": 0,
            "duplicated": 0,
        }
        # Phase 3 was the last chaos-wrapped phase; phase 6 kills
        # replicas directly.
        mgr.kill_replica = orig_kill

        # Phase 4: prefill:decode ratio sweep vs the hybrid control.
        # Roles are ROUTER-side placement over identical replica
        # processes, so the sweep re-labels the live fleet — the same
        # assignment `scripts/fleet.py --roles` makes at spawn time.
        # saturation_depth is raised because the decode tier shrinks
        # to 1-2 replicas: excess burst queues on the replicas
        # instead of tripping the router's spill/503 path.
        cutoff = 2 * page_size  # prefix traffic classifies prefill

        def set_roles(roles):
            for rep in mgr.replicas:
                rep.role = ROLE_HYBRID
            for rep, role in zip(mgr.replicas, roles):
                rep.role = role

        def disagg_counters(router):
            ms = router.migration_seconds
            return {
                "prefill_handoffs": router.prefill_handoffs_total,
                "migrations": router.migrations_total,
                "migration_failures": (
                    router.migration_failures_total
                ),
                "pages_migrated": router.pages_migrated_total,
                "migration_p50_s": (
                    round(ms.percentile(50), 4) if ms.count else None
                ),
                "migration_p99_s": (
                    round(ms.percentile(99), 4) if ms.count else None
                ),
            }

        ratio_sweep = {}
        for label, roles, seed in (
            ("1:2", [ROLE_PREFILL, ROLE_DECODE, ROLE_DECODE], 404),
            ("1:1", [ROLE_PREFILL, ROLE_DECODE], 414),
            ("2:1", [ROLE_PREFILL, ROLE_PREFILL, ROLE_DECODE], 424),
        ):
            set_roles(roles)
            subset = mgr.replicas[: len(roles)]
            router = mgr.attach_router(
                Router(
                    subset,
                    RouterConfig(
                        affinity=True,
                        affinity_page=page_size,
                        saturation_depth=64,
                        retry_max=5,
                        disagg=True,
                        prefill_cutoff_tokens=cutoff,
                        trace_seed=seed,
                    ),
                )
            )
            results, wall = drive(router, make_prompts(seed))
            dropped = sum(
                1 for r in results if r["http_status"] != 200
            )
            assert not dropped, (
                f"ratio {label} dropped {dropped} requests"
            )
            prefill_idx = {
                r.index for r in subset if r.role == ROLE_PREFILL
            }
            assert all(
                r["router"]["replica"] not in prefill_idx
                for r in results
            ), f"ratio {label}: client stream on the prefill tier"
            ratio_sweep[label] = {
                **phase_summary(results, wall),
                "roles": roles,
                **disagg_counters(router),
            }
        # Homogeneous control: same traffic shape, no tiers.
        set_roles([])
        router = mgr.attach_router(
            Router(
                mgr.replicas,
                RouterConfig(
                    affinity=True,
                    affinity_page=page_size,
                    saturation_depth=64,
                    retry_max=5,
                    trace_seed=434,
                ),
            )
        )
        results_h, wall_h = drive(router, make_prompts(434))
        assert all(r["http_status"] == 200 for r in results_h)
        hybrid_control = phase_summary(results_h, wall_h)

        # Phase 5: migration token identity — the SAME prompts asked
        # of the 1:2 disagg split and of the hybrid control must
        # stream identical tokens (greedy over identical weights).
        probe = make_prompts(606)[::per_group]
        set_roles([ROLE_PREFILL, ROLE_DECODE, ROLE_DECODE])
        router = mgr.attach_router(
            Router(
                mgr.replicas,
                RouterConfig(
                    affinity=True,
                    affinity_page=page_size,
                    disagg=True,
                    prefill_cutoff_tokens=cutoff,
                    trace_seed=606,
                ),
            )
        )
        disagg_streams = []
        for p in probe:
            status, payload = router.dispatch(
                {"prompt_tokens": p, "max_new_tokens": new_tokens}
            )
            assert status == 200, payload
            assert payload["router"]["replica"] != 0, (
                "identity probe served by the prefill tier"
            )
            disagg_streams.append(payload["tokens"])
        identity_counters = disagg_counters(router)
        assert identity_counters["migrations"] >= 1, (
            "identity probes never migrated pages"
        )
        set_roles([])
        router = mgr.attach_router(
            Router(
                mgr.replicas,
                RouterConfig(
                    affinity=True,
                    affinity_page=page_size,
                    trace_seed=616,
                ),
            )
        )
        for p, want in zip(probe, disagg_streams):
            status, payload = router.dispatch(
                {"prompt_tokens": p, "max_new_tokens": new_tokens}
            )
            assert status == 200, payload
            assert payload["tokens"] == want, (
                "migrated stream diverged from the hybrid stream"
            )

        # Phase 6: prefix directory vs affinity-only under churn.
        # Both trials: seed each group's prefix on its affinity home,
        # SIGKILL a home replica, serve through the outage (with the
        # directory on, completions re-home each prefix to whoever
        # served it), then burst once the victim restarts COLD.
        def churn_trial(seed, use_directory):
            prompts = make_prompts(seed)
            router = mgr.attach_router(
                Router(
                    mgr.replicas,
                    RouterConfig(
                        affinity=True,
                        affinity_page=page_size,
                        retry_backoff_s=0.02,
                        directory=use_directory,
                        trace_seed=seed,
                    ),
                )
            )
            leaders = list(range(0, len(prompts), per_group))
            for i in leaders:
                status, payload = router.dispatch(
                    {
                        "prompt_tokens": prompts[i],
                        "max_new_tokens": new_tokens,
                    }
                )
                assert status == 200, payload
            # Kill a replica that IS a group's affinity home, so the
            # burst below actually exercises the cold-restart case.
            homes = {
                affinity_key(prompts[i], page_size)
                % len(mgr.replicas)
                for i in leaders
            }
            victim = min(homes)
            r0 = mgr.restarts_total
            mgr.kill_replica(victim)
            deadline = time.monotonic() + 60
            while (
                mgr.replicas[victim].state == "healthy"
                and time.monotonic() < deadline
            ):
                time.sleep(0.05)
            for i in leaders:
                status, payload = router.dispatch(
                    {
                        "prompt_tokens": prompts[i],
                        "max_new_tokens": new_tokens,
                    }
                )
                assert status == 200, payload
            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                if mgr.restarts_total > r0 and all(
                    r.state == "healthy" for r in mgr.replicas
                ):
                    break
                time.sleep(0.25)
            assert (
                mgr.replicas[victim].state == "healthy"
            ), "churn victim never came back"
            base = [paged_counts(r.url) for r in mgr.replicas]
            results, wall = drive(router, prompts)
            assert all(
                r["http_status"] == 200 for r in results
            ), "churn burst dropped requests"
            rate, per_rep, _ = hit_deltas(base)
            out = {
                **phase_summary(results, wall),
                "victim": victim,
                "post_churn_hit_rate": rate,
                "per_replica": per_rep,
            }
            if use_directory:
                pulls = router.directory_pulls_total
                hits = router.directory_pull_hits_total
                out["directory_pulls"] = pulls
                out["directory_pull_hits"] = hits
                out["directory_pull_hit_rate"] = (
                    round(hits / pulls, 4) if pulls else None
                )
            return out

        churn_affinity = churn_trial(808, use_directory=False)
        churn_directory = churn_trial(909, use_directory=True)
        assert (
            churn_directory["post_churn_hit_rate"] is not None
            and churn_affinity["post_churn_hit_rate"] is not None
        )
        assert (
            churn_directory["post_churn_hit_rate"]
            >= churn_affinity["post_churn_hit_rate"]
        ), (
            f"directory hit rate "
            f"{churn_directory['post_churn_hit_rate']} under churn "
            f"fell below the affinity-only control "
            f"{churn_affinity['post_churn_hit_rate']}: the prefix "
            "tier is not re-warming restarted replicas"
        )

        # Phase 7: fleet-wide distributed tracing (ISSUE 19) — a
        # FRESH 3-process disagg fleet launched with trace_dir, so
        # every replica exports its request spans on shutdown and the
        # router records a span per hop. The merged router+replica
        # trace dirs must reconstruct ONE causally-valid timeline per
        # request under a single trace id, prefill handoff and
        # /pages migration hops included (the acceptance gate).
        import glob as _glob
        import subprocess
        import sys as _sys

        from ddp_tpu.obs.reqtrace import (
            reconstruct_fleet,
            validate_fleet_timeline,
        )
        from ddp_tpu.obs.tracer import Tracer

        trace_root = os.path.join(workdir, "fleet_trace")
        tmgr = ReplicaManager(
            n_replicas,
            [
                "--init_demo",
                "--slots", str(slots),
                "--page_size", str(page_size),
                "--vocab_size", str(vocab),
                "--seq_len", str(seq_len),
            ],
            workdir=os.path.join(workdir, "trace_fleet"),
            max_restarts=1,
            restart_backoff=0.2,
            roles=[ROLE_PREFILL, ROLE_DECODE, ROLE_DECODE],
            trace_dir=trace_root,
        )
        fleet_tracer = Tracer(enabled=True)
        tprobe = make_prompts(707)[::per_group]
        try:
            tmgr.start()
            assert tmgr.wait_healthy(420), (
                "trace fleet never became healthy"
            )
            trouter = tmgr.attach_router(
                Router(
                    tmgr.replicas,
                    RouterConfig(
                        affinity=True,
                        affinity_page=page_size,
                        disagg=True,
                        prefill_cutoff_tokens=cutoff,
                        trace_seed=707,
                    ),
                    tracer=fleet_tracer,
                )
            )
            traced = []
            for p in tprobe:
                status, payload = trouter.dispatch(
                    {"prompt_tokens": p, "max_new_tokens": new_tokens}
                )
                assert status == 200, payload
                traced.append(payload["router"])
            # Per-hop seconds on the router digest — queue/dispatch on
            # every request, handoff/migrate on at least one.
            for d in traced:
                hops = d.get("hops") or {}
                assert "queue_s" in hops and "dispatch_s" in hops, d
            migrated_digests = [
                d for d in traced if "migrate_s" in d.get("hops", {})
            ]
            assert migrated_digests, (
                "trace phase never migrated pages — no migration hop "
                "to validate"
            )
            tstate = trouter.state()
            assert (
                tstate.get("trace_propagated_total") == len(tprobe)
            ), tstate
            assert "dispatch" in (tstate.get("hop_seconds") or {}), (
                tstate
            )
        finally:
            # Graceful drain, not the default 0.1s SIGKILL: each
            # replica exports its trace file on the SIGTERM path, and
            # a killed process exports nothing.
            tmgr.stop(drain_timeout=60)
        fleet_tracer.export_to_dir(os.path.join(trace_root, "router"))
        trace_dirs = [os.path.join(trace_root, "router")] + sorted(
            _glob.glob(os.path.join(trace_root, "replica*"))
        )
        assert len(trace_dirs) == n_replicas + 1, trace_dirs
        merged_path = os.path.join(trace_root, "merged.trace.json")
        proc = subprocess.run(
            [
                _sys.executable,
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "scripts", "trace_merge.py",
                ),
                *trace_dirs, "-o", merged_path,
            ],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        with open(merged_path) as f:
            merged_doc = json.load(f)
        fleet_side = merged_doc["ddp_tpu"].get("fleet") or {}
        assert fleet_side.get("count") == len(tprobe), fleet_side
        assert fleet_side.get("causal_ok") == len(tprobe), fleet_side
        assert fleet_side.get("migrated", 0) >= 1, fleet_side
        # The single-trace-id gate, re-derived from raw events: the
        # migrated request's router hop chain and its replica
        # admit→retire timeline reconstruct under ONE id and pass
        # causal validation (dispatch before admit, export before
        # install, exactly one winning decode path).
        fleet_map = reconstruct_fleet(merged_doc["traceEvents"])
        mig_tid = migrated_digests[0]["trace_id"]
        assert mig_tid in fleet_map, (mig_tid, sorted(fleet_map))
        mig_summary = validate_fleet_timeline(fleet_map[mig_tid])
        assert mig_summary["migrated"], mig_summary
        fleet_trace = {
            "requests": len(tprobe),
            "causal_ok": fleet_side["causal_ok"],
            "migrated": fleet_side["migrated"],
            "hop_p99_s": fleet_side.get("hop_p99_s"),
            "validated_trace_id": mig_tid,
            "winner_replica": mig_summary["winner_replica"],
        }

        # The headline assert: affinity must beat random dispatch on
        # per-replica prefix-hit rate — the reason the router hashes
        # prompts at all. A routing fact, not a timing fact.
        assert affinity_rate is not None and random_rate is not None
        assert affinity_rate > random_rate, (
            f"affinity hit rate {affinity_rate} does not beat random "
            f"{random_rate}: prefix affinity is not keeping replica "
            "caches warm"
        )
        env = _env_fields()
        _assert_provenance(env)
        record.update(
            value=affinity_rate,
            **env,
            unit="hit fraction",
            random_dispatch_hit_rate=random_rate,
            affinity_hit_rate=affinity_rate,
            per_replica_random=random_per_replica,
            per_replica_affinity=affinity_per_replica,
            random_dispatch=phase_summary(results_r, wall_r),
            affinity=phase_summary(results_a, wall_a),
            kill_drill=kill_drill,
            disagg_ratio_sweep=ratio_sweep,
            disagg_hybrid_control=hybrid_control,
            disagg_prefill_cutoff_tokens=cutoff,
            disagg_token_identity={
                "prompts": len(probe),
                "identical": True,
                **identity_counters,
            },
            churn_affinity_only=churn_affinity,
            churn_directory=churn_directory,
            fleet_trace=fleet_trace,
            n_replicas=n_replicas,
            slots=slots,
            page_size=page_size,
            prefix_tokens=prefix_tokens,
            tail_tokens=tail_tokens,
            new_tokens=new_tokens,
            n_requests_per_phase=n_requests,
            **(
                {
                    "note": "CPU-fallback capture: throughput/TTFT "
                    "(ratio sweep included) are honest CPU nulls "
                    "(replicas share cores); hit rates, "
                    "replay/restart accounting, zero-drop/zero-dup, "
                    "migration token identity and the "
                    "directory-vs-affinity churn ordering are "
                    "platform-free facts"
                }
                if env["cpu_fallback"]
                else {}
            ),
        )
    finally:
        mgr.stop()
    return record


def run_serve_reload_bench(
    *,
    n_replicas: int = 3,
    slots: int = 4,
    inflight: int = 8,
    new_tokens: int = 16,
) -> dict:
    """Model lifecycle (ISSUE 20): verified atomic hot-swap vs the
    only pre-lifecycle upgrade path (``/rollz`` process churn), plus
    the streaming-restore TTFT claim — all against REAL
    ``scripts/serve.py`` subprocesses.

    1. **in-flight across a swap** — a burst straddles a single
       replica's ``POST /reload`` to a different checkpoint: EVERY
       request completes (zero dropped, ASSERTED — admission pauses,
       it never sheds) and the swap's verify/load/swap timings come
       from the reload response itself.
    2. **cold vs streaming TTFT** — the same non-trivial checkpoint
       served twice from fresh processes: spawn → first generated
       token, cold (restore THEN warmup, serial) vs
       ``--streaming_restore`` (restore I/O behind the XLA warmup
       compiles). Streaming MUST reach its first token sooner
       (ASSERTED: the overlap is min(restore, warmup) of real work,
       not a timing coin-flip).
    3. **fleet swap vs ``/rollz``** — the same 3-replica fleet
       upgraded both ways: ``reload_fleet`` (in-place swaps, zero
       process churn — respawns == 0 ASSERTED, every replica
       converges on the target version, ASSERTED) must beat a
       rolling restart's wall-clock (ASSERTED: a swap restores one
       checkpoint; a roll pays full process + jax + warmup per
       replica).
    """
    import socket
    import subprocess
    import sys
    import tempfile
    import threading
    import time
    import urllib.error
    import urllib.request

    import jax.numpy as jnp
    import optax

    from ddp_tpu.models.lm import LMSpec, init_lm
    from ddp_tpu.parallel.ddp import TrainState
    from ddp_tpu.serve.fleet import ReplicaManager
    from ddp_tpu.serve.lifecycle import model_version_token
    from ddp_tpu.train.checkpoint import CheckpointManager, save_lm_spec

    env = _env_fields()
    record: dict = {"metric": "serve_reload_swap_vs_roll", **env}
    workdir = tempfile.mkdtemp(prefix="ddp_tpu_reload_bench_")
    serve_py = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts", "serve.py"
    )

    def save_ckpt(directory, spec, seed):
        params = init_lm(spec, seed=seed)
        tx = optax.sgd(0.01)
        state = TrainState(
            step=jnp.zeros((), jnp.int32), params=params,
            opt_state=tx.init(params), model_state={},
        )
        mgr = CheckpointManager(directory, async_save=False)
        mgr.save(0, state)
        mgr.close()
        save_lm_spec(directory, spec)

    def post(url, path, body, timeout=120.0):
        req = urllib.request.Request(
            url + path,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    def spawn_serve(ckpt, port, extra=()):
        """scripts/serve.py subprocess → (proc, url, ready_s, lines).

        ``lines`` keeps draining stdout in the background so the
        streaming milestone JSON is parseable after the fact."""
        t0 = time.perf_counter()
        proc = subprocess.Popen(
            [
                sys.executable, serve_py,
                "--checkpoint_dir", ckpt,
                "--slots", str(slots),
                "--port", str(port),
                *extra,
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            cwd=os.path.dirname(serve_py) + "/..",
        )
        ready = [None]
        lines: list[dict] = []
        started = threading.Event()

        def drain():
            for line in proc.stdout:
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                lines.append(obj)
                if "serving" in obj:
                    ready[0] = time.perf_counter() - t0
                    started.set()

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        assert started.wait(600), "serve.py never printed startup JSON"
        return proc, f"http://127.0.0.1:{port}", t0, ready[0], lines

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    # The engine-logic spec (phases 1+3): minutes-cheap on CPU.
    small = LMSpec(
        vocab_size=512, total_len=128, d_model=128, depth=2, num_heads=4,
    )
    ckpt_a = os.path.join(workdir, "ckpt_a")
    ckpt_b = os.path.join(workdir, "ckpt_b")
    save_ckpt(ckpt_a, small, seed=0)
    save_ckpt(ckpt_b, small, seed=1)

    # Phase 1: in-flight burst straddling a single-replica hot-swap.
    proc, url, _, _, _ = spawn_serve(ckpt_a, free_port())
    try:
        results: list[tuple[int, dict]] = []
        lock = threading.Lock()

        def one(i):
            status, payload = post(
                url, "/generate",
                {
                    "prompt_tokens": [(7 * i + j) % 512 for j in range(16)],
                    "max_new_tokens": new_tokens,
                },
            )
            with lock:
                results.append((status, payload))

        threads = [
            threading.Thread(target=one, args=(i,))
            for i in range(inflight)
        ]
        for t in threads:
            t.start()
        time.sleep(0.15)  # land the reload mid-burst
        status, payload = post(
            url, "/reload", {"checkpoint_dir": ckpt_b}
        )
        for t in threads:
            t.join()
        assert status == 200 and payload.get("reloaded"), payload
        assert payload["model_version"] == model_version_token(ckpt_b, 0)
        completed = sum(1 for s, _ in results if s == 200)
        assert completed == inflight, (
            f"swap dropped {inflight - completed}/{inflight} "
            f"in-flight requests"
        )
        record["inflight_across_swap"] = {
            "submitted": inflight,
            "completed": completed,
            "completion_rate": 1.0,
            "verify_s": payload.get("verify_s"),
            "load_s": payload.get("load_s"),
            "swap_s": payload.get("swap_s"),
        }
    finally:
        proc.kill()
        proc.wait()

    # Phase 2: cold vs streaming TTFT on a checkpoint whose restore
    # is real work (tens of MB), from fresh processes — both pay the
    # same interpreter + jax import; the delta is the overlap.
    big = LMSpec(
        vocab_size=4096, total_len=160, d_model=512, depth=6,
        num_heads=8,
    )
    ckpt_big = os.path.join(workdir, "ckpt_big")
    save_ckpt(ckpt_big, big, seed=0)
    ttft = {}
    for mode, extra in [
        ("cold", ()),
        ("streaming", ("--streaming_restore", "--stream_layers", "1")),
    ]:
        proc, url, t0, ready_s, lines = spawn_serve(
            ckpt_big, free_port(), extra
        )
        try:
            status, payload = post(
                url, "/generate",
                {"prompt_tokens": [1, 2, 3, 4], "max_new_tokens": 1},
                timeout=600.0,
            )
            first_token_s = time.perf_counter() - t0
            assert status == 200 and payload.get("tokens"), payload
            ttft[mode] = {
                "ready_s": round(ready_s, 3),
                "first_token_s": round(first_token_s, 3),
            }
            if mode == "streaming":
                ms = [ln for ln in lines if ln.get("streamed")]
                if ms:
                    ttft[mode]["admission_ready_s"] = round(
                        ms[-1]["admission_ready_s"], 3
                    )
                    ttft[mode]["complete_s"] = round(
                        ms[-1]["complete_s"], 3
                    )
        finally:
            proc.kill()
            proc.wait()
    assert (
        ttft["streaming"]["first_token_s"] < ttft["cold"]["first_token_s"]
    ), f"streaming restore won nothing: {ttft}"
    record["ttft"] = ttft

    # Phase 3: the same fleet upgraded both ways — in-place swaps
    # (zero process churn) vs the PR-13 rolling restart.
    mgr = ReplicaManager(
        n_replicas,
        [
            "--checkpoint_dir", ckpt_a,
            "--slots", str(slots),
        ],
        workdir=os.path.join(workdir, "fleet"),
        max_restarts=2,
        restart_backoff=0.2,
    )
    try:
        mgr.start()
        assert mgr.wait_healthy(420), "fleet never became healthy"
        restarts_before = mgr.restarts_total
        t0 = time.perf_counter()
        out = mgr.reload_fleet(ckpt_b)
        swap_wall = time.perf_counter() - t0
        assert out["ok"], out
        assert out["respawns"] == 0, out
        assert mgr.restarts_total == restarts_before, (
            "hot-swap respawned a process"
        )
        target = model_version_token(ckpt_b, 0)
        # /healthz advertises the serving version via the poll loop —
        # give it a couple of poll intervals to observe the swap.
        deadline = time.monotonic() + 30
        versions = {r.model_version for r in mgr.replicas}
        while versions != {target} and time.monotonic() < deadline:
            time.sleep(0.25)
            versions = {r.model_version for r in mgr.replicas}
        assert versions == {target}, (
            f"fleet did not converge: {versions} != {{{target}}}"
        )
        t0 = time.perf_counter()
        roll = mgr.rolling_restart()
        roll_wall = time.perf_counter() - t0
        assert roll.get("ok", True), roll
        assert swap_wall < roll_wall, (
            f"hot-swap ({swap_wall:.2f}s) not faster than /rollz "
            f"({roll_wall:.2f}s)"
        )
        record["fleet_upgrade"] = {
            "replicas": n_replicas,
            "swap_wall_s": round(swap_wall, 3),
            "roll_wall_s": round(roll_wall, 3),
            "speedup": round(roll_wall / swap_wall, 2),
            "swap_respawns": 0,
            "converged_version": target,
        }
    finally:
        mgr.stop()

    _assert_provenance(env)
    record.update(
        **(
            {
                "null_result_note":
                    "CPU capture: wall-clocks are not chip numbers, "
                    "but zero-dropped, zero-respawn, version "
                    "convergence and the swap<roll / streaming<cold "
                    "orderings are platform-free facts"
            }
            if env["cpu_fallback"]
            else {}
        ),
    )
    return record


def run_loader_bench(
    *, n: int = 4096, side: int = 96, batch: int = 256, epochs: int = 3
) -> dict:
    """Native C++ worker pool vs single-thread Python gather.

    Two measurements (round-3 verdict weak #3 — "win or retire"):

    1. **Raw assembly race** — host-side batch gather only, no device
       work. On a 1-core host the pool LOSES this by construction
       (its ring adds a handoff on the same core that does the
       gather); that measurement is what sets the loader's
       auto-disable policy (data/loader.py POOL_MIN_BATCH_BYTES +
       the >1-core requirement).
    2. **Overlap regime** (TPU only — the pool's actual purpose): a
       training loop where the device computes step t while the host
       assembles batch t+1. The C++ workers release the GIL, so even
       on one host core they overlap the Python thread's blocking
       device wait — the reference's ``num_workers=2`` rationale
       (data.py:21-25). Reported as ``overlap_native_s`` vs
       ``overlap_python_s`` wall-clock for the same step count.

    ImageNet-shaped uint8 rows in both.
    """
    import time

    import numpy as np

    from ddp_tpu import native

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(n, side, side, 3), dtype=np.uint8)
    labels = rng.integers(0, 1000, size=(n,)).astype(np.int32)
    idx = rng.permutation(n)
    steps = n // batch

    def python_gather():
        t0 = time.perf_counter()
        for _ in range(epochs):
            for b in range(steps):
                sel = idx[b * batch : (b + 1) * batch]
                _ = images[sel], labels[sel]
        return epochs * steps / (time.perf_counter() - t0)

    import os

    from ddp_tpu.data.loader import ShardedLoader

    batch_bytes = batch * side * side * 3
    pool_engaged = ShardedLoader.pool_would_engage(batch_bytes)
    result = {
        "metric": "loader_batch_assembly",
        **_env_fields(),
        "shape": [batch, side, side, 3],
        "python_batches_per_sec": round(python_gather(), 1),
        "native_available": native.available(),
        # The pool's win conditions are (a) >1 host core and (b)
        # overlap with device compute; a raw assembly race on a 1-core
        # box measures its ring overhead instead. Record the context
        # and what ShardedLoader's gate (bytes >= POOL_MIN_BATCH_BYTES
        # AND >1 core) would decide for this shape on this host.
        "cpu_count": os.cpu_count(),
        "pool_gate_would_engage": pool_engaged,
    }
    if native.available():
        pre = native.NativePrefetcher(images, labels, batch, num_workers=2)
        try:
            t0 = time.perf_counter()
            for _ in range(epochs):
                for _ in pre.epoch(idx):
                    pass
            result["native_batches_per_sec"] = round(
                epochs * steps / (time.perf_counter() - t0), 1
            )
            result["native_speedup"] = round(
                result["native_batches_per_sec"]
                / result["python_batches_per_sec"],
                2,
            )
        finally:
            pre.close()
    result.update(_loader_overlap_bench(images, labels, idx, batch))
    return result


def _loader_overlap_bench(images, labels, idx, batch, *, steps=24) -> dict:
    """Host-assembly ↔ device-compute overlap: the pool's real regime.

    Runs a small conv train step on the DEVICE while the host prepares
    the next batch — python gather vs the C++ ring. TPU only: on a CPU
    backend the 'device' computes on the same core as the loader, so
    there is no idle host time to overlap into and the measurement
    would just re-state the raw assembly race above.
    """
    import time

    import jax
    import jax.numpy as jnp
    import optax

    from ddp_tpu import native

    if jax.devices()[0].platform != "tpu" or not native.available():
        return {}
    # SimpleCNN is MNIST-shaped; a small generic conv step serves here.
    import flax.linen as nn

    side = images.shape[1]

    class TinyConv(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Conv(32, (3, 3))(x)
            x = nn.relu(x)
            x = nn.Conv(64, (3, 3), strides=(2, 2))(x)
            x = nn.relu(x).mean(axis=(1, 2))
            return nn.Dense(1000)(x)

    model = TinyConv()
    tx = optax.sgd(0.01)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, side, side, 3), jnp.float32)
    )["params"]
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, xb, yb):
        def loss_fn(p):
            logits = model.apply(
                {"params": p}, xb.astype(jnp.float32) / 255.0
            )
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb
            ).mean()

        loss, g = jax.value_and_grad(loss_fn)(params)
        up, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, up), opt, loss

    def python_loop():
        p, o = params, opt
        t0 = time.perf_counter()
        for b in range(steps):
            sel = idx[(b * batch) % len(idx) : (b * batch) % len(idx) + batch]
            if len(sel) < batch:
                sel = idx[:batch]
            p, o, loss = step(p, o, jnp.asarray(images[sel]),
                              jnp.asarray(labels[sel]))
        jax.block_until_ready(loss)
        return time.perf_counter() - t0

    def native_loop():
        pre = native.NativePrefetcher(images, labels, batch, num_workers=2)
        try:
            p, o = params, opt
            t0 = time.perf_counter()
            done = 0
            while done < steps:
                for xb, yb in pre.epoch(idx):
                    p, o, loss = step(p, o, jnp.asarray(xb), jnp.asarray(yb))
                    done += 1
                    if done >= steps:
                        break
            jax.block_until_ready(loss)
            return time.perf_counter() - t0
        finally:
            pre.close()

    # Warm the compile outside both timed windows.
    _ = step(params, opt, jnp.asarray(images[idx[:batch]]),
             jnp.asarray(labels[idx[:batch]]))
    py_s = python_loop()
    nat_s = native_loop()
    return {
        "overlap_steps": steps,
        "overlap_python_s": round(py_s, 3),
        "overlap_native_s": round(nat_s, 3),
        "overlap_native_speedup": round(py_s / nat_s, 2),
    }


def _zero_bench_impl(
    *, batch_per_shard: int = 32, warmup_steps: int = 3,
    timed_steps: int = 20, bucket_mb: float = 0.05,
) -> dict:
    """ZeRO weight-update sharding vs the ddp baseline, world ≥ 2.

    Three step variants over identical data on the full device mesh:
    the ddp all-reduce step, the zero step (bucketed psum_scatter /
    1/N update / all_gather — scheduler free to overlap), and the
    zero step with its no-overlap control (optimization_barrier fence
    after backward + serial collective chain). Reports step-time p50,
    the analytic per-step collective payload (comm_bytes — the zero
    path's all_reduce term is ZERO, the headline claim), the
    optimizer-state memory high-water per device (live-buffer
    accounting over the real shardings — strictly 1/N for zero), and
    the MEASURED overlap fraction: the share of the serialized step
    time the scheduler hid by overlapping the bucketed collectives
    with compute, plus the obs/steptime dispatch-vs-compute split of
    one representative step of each variant. On a CPU backend the
    collectives share cores with compute, so expect the overlap
    fraction near zero there — the record states what was measured,
    not what the TPU scheduler would do.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddp_tpu.models import get_model
    from ddp_tpu.obs.steptime import dispatch_compute_split
    from ddp_tpu.parallel.ddp import (
        create_train_state,
        make_train_step,
        replicate_state,
    )
    from ddp_tpu.parallel.zero import (
        create_zero_state,
        ddp_comm_bytes,
        make_zero_train_step,
        opt_bytes_per_device,
        zero_comm_bytes,
    )
    from ddp_tpu.runtime.mesh import (
        MeshSpec, data_axes, make_mesh, slice_block_size,
    )
    from ddp_tpu.utils.metrics import StatSummary

    devices = jax.devices()
    world = len(devices)
    mesh = make_mesh(MeshSpec(data=world), devices=devices)
    model = get_model("simple_cnn")
    tx = optax.adam(1e-3)
    sample = jnp.zeros((1, 28, 28, 1))
    batch = batch_per_shard * world
    rng = np.random.default_rng(0)
    sh = NamedSharding(mesh, P(data_axes(mesh)))
    images_np = rng.integers(0, 256, (batch, 28, 28, 1), dtype=np.uint8)
    labels_np = rng.integers(0, 10, (batch,)).astype(np.int32)
    images = jax.device_put(images_np, sh)
    labels = jax.device_put(labels_np, sh)

    ddp_state = replicate_state(
        create_train_state(model, tx, sample, seed=0), mesh
    )
    zero_state, layout = create_zero_state(
        model, tx, sample, mesh, seed=0, bucket_mb=bucket_mb
    )
    bf16_state, bf16_layout = create_zero_state(
        model, tx, sample, mesh, seed=0, bucket_mb=bucket_mb,
        gather_dtype="bf16",
    )
    # Two emulated slices for the hierarchical variant (dcn outermost
    # — runtime/mesh.py): world must split 2×(world/2). At world 2 the
    # per-slice group would be 1 (nothing to scatter) — skipped with a
    # note rather than recorded as a vacuous number.
    hier_ok = world >= 4 and world % 2 == 0
    if hier_ok:
        hier_mesh = make_mesh(
            MeshSpec(dcn=2, data=world // 2), devices=devices
        )
        hsh = NamedSharding(hier_mesh, P(data_axes(hier_mesh)))
        h_images = jax.device_put(images_np, hsh)
        h_labels = jax.device_put(labels_np, hsh)
        hier_state, hier_layout = create_zero_state(
            model, tx, sample, hier_mesh, seed=0, bucket_mb=bucket_mb
        )
    # Each variant dispatches through the xprof compile ledger
    # (obs/xprof.py): the record then carries real compile seconds per
    # variant, the HBM high-water of the measured loops, and — the
    # cross-check this bench exists to keep honest — the HLO-derived
    # collective bytes next to the analytic comm_bytes estimates.
    from ddp_tpu.obs.xprof import DeviceMemorySampler, Xprof

    xprof = Xprof(enabled=True)
    hbm = DeviceMemorySampler(enabled=True)

    def zstep(lay, **kw):
        return make_zero_train_step(model, tx, mesh, lay, donate=False, **kw)

    # name -> (instrumented step, state, (images, labels))
    variants = {
        "ddp": (
            xprof.instrument(
                make_train_step(model, tx, mesh, donate=False), "ddp"
            ),
            ddp_state, (images, labels),
        ),
        "zero": (
            xprof.instrument(zstep(layout), "zero"),
            zero_state, (images, labels),
        ),
        "zero_serialized": (
            xprof.instrument(zstep(layout, overlap=False), "zero_serialized"),
            zero_state, (images, labels),
        ),
        "gather_bf16": (
            xprof.instrument(
                zstep(bf16_layout, gather_dtype="bf16"), "gather_bf16"
            ),
            bf16_state, (images, labels),
        ),
        "gather_bf16_serialized": (
            xprof.instrument(
                zstep(bf16_layout, gather_dtype="bf16", overlap=False),
                "gather_bf16_serialized",
            ),
            bf16_state, (images, labels),
        ),
    }
    if hier_ok:
        variants["hier"] = (
            xprof.instrument(
                make_zero_train_step(
                    model, tx, hier_mesh, hier_layout, donate=False
                ),
                "hier",
            ),
            hier_state, (h_images, h_labels),
        )
        variants["hier_serialized"] = (
            xprof.instrument(
                make_zero_train_step(
                    model, tx, hier_mesh, hier_layout, donate=False,
                    overlap=False,
                ),
                "hier_serialized",
            ),
            hier_state, (h_images, h_labels),
        )
    p50 = {}
    split = {}
    final_loss = {}
    for name, (step, state0, (imgs, lbls)) in variants.items():
        state = state0
        summary = StatSummary()
        for i in range(warmup_steps + timed_steps):
            t0 = time.perf_counter()
            state, metrics = step(state, imgs, lbls)
            jax.block_until_ready(metrics.loss)
            if i >= warmup_steps:
                summary.add(time.perf_counter() - t0)
        p50[name] = round(summary.percentile(50), 6)
        final_loss[name] = round(float(metrics.loss), 6)
        # obs/steptime attribution of one more step: dispatch-return
        # vs block_until_ready — the same split the trainer records.
        (_, m2), disp_s, comp_s, _ = dispatch_compute_split(
            step, state, imgs, lbls
        )
        split[name] = {
            "dispatch_s": round(disp_s, 6), "compute_s": round(comp_s, 6),
        }

    def overlap(fast, slow):
        return round(
            max(0.0, 1.0 - p50[fast] / max(p50[slow], 1e-9)), 4
        )

    overlap_fraction = overlap("zero", "zero_serialized")
    opt_mem = {
        "ddp": opt_bytes_per_device(ddp_state.opt_state),
        "zero": opt_bytes_per_device(zero_state.opt_state),
    }
    hbm.sample()
    comm_est = {
        "ddp": ddp_comm_bytes(ddp_state.params, world),
        "zero": zero_comm_bytes(layout, world),
    }
    # Hand ledger vs compiled program: ring-model traffic from the
    # optimized HLO's collective payloads, checked against the
    # analytic estimate each strategy publishes (parallel/zero.py).
    comm_check = {
        name: xprof.comm_check(name, comm_est[name]["total"], world)
        for name in ("ddp", "zero")
    }
    compile_s = {}
    for rec in xprof.ledger_records():
        compile_s[rec["label"]] = round(
            compile_s.get(rec["label"], 0.0) + rec["compile_time_s"], 3
        )

    # --- sub-records: the pod-scale comm variants, each with its own
    # analytic pricing, HLO cross-check, overlap control, and
    # provenance (gather dtype + the mesh's axis shape — what makes
    # BENCH_* comparisons across flat/hier captures greppable in one
    # field, like the platform/backend/cpu_fallback trio).
    def mesh_axes_of(m):
        return {a: int(s) for a, s in m.shape.items() if int(s) > 1}

    bf16_est = zero_comm_bytes(bf16_layout, world, gather_dtype="bf16")
    sub = {
        "gather_bf16": {
            "gather_dtype": "bf16",
            "mesh_axes": mesh_axes_of(mesh),
            "step_time_p50_s": p50["gather_bf16"],
            "dispatch_compute": split["gather_bf16"],
            "overlap_fraction": overlap(
                "gather_bf16", "gather_bf16_serialized"
            ),
            "comm_bytes": bf16_est,
            "hlo_comm_check": xprof.comm_check(
                "gather_bf16", bf16_est["total"], world
            ),
            "opt_state_bytes_per_device": opt_bytes_per_device(
                bf16_state.opt_state
            ),
            "final_loss": final_loss["gather_bf16"],
            "loss_delta_vs_ddp": round(
                abs(final_loss["gather_bf16"] - final_loss["ddp"]), 6
            ),
        },
    }
    # The headline byte claim, ASSERTED: half-width gathers move half
    # the all-gather bytes in the analytic model AND the compiled HLO.
    assert 2 * bf16_est["all_gather"] == comm_est["zero"]["all_gather"]
    bf16_check = sub["gather_bf16"]["hlo_comm_check"]
    zero_check = comm_check["zero"]
    if bf16_check and zero_check:
        ratio = bf16_check["measured_by_kind"]["all_gather"] / max(
            1, zero_check["measured_by_kind"]["all_gather"]
        )
        sub["gather_bf16"]["hlo_ag_ratio_vs_fp32"] = round(ratio, 4)
        assert abs(ratio - 0.5) < 0.05, (
            f"bf16 gather not half-width in HLO: {ratio}"
        )
    if hier_ok:
        hier_est = zero_comm_bytes(
            hier_layout, world // 2, dcn=2
        )
        flat_on_pod = zero_comm_bytes(
            hier_layout, world // 2, dcn=2, hier=False
        )
        hier_check = xprof.comm_check(
            "hier", hier_est["total"], world,
            expected_by_axis=hier_est["by_axis"],
            slice_size=slice_block_size(hier_mesh),
        )
        sub["hier"] = {
            "gather_dtype": "fp32",
            "mesh_axes": mesh_axes_of(hier_mesh),
            "step_time_p50_s": p50["hier"],
            "dispatch_compute": split["hier"],
            "overlap_fraction": overlap("hier", "hier_serialized"),
            "comm_bytes": hier_est,
            "flat_comm_bytes": flat_on_pod,
            "hlo_comm_check": hier_check,
            "opt_state_bytes_per_device": opt_bytes_per_device(
                hier_state.opt_state
            ),
            "final_loss": final_loss["hier"],
            "loss_delta_vs_ddp": round(
                abs(final_loss["hier"] - final_loss["ddp"]), 6
            ),
        }
        # Cross-slice bytes ≤ 1/N_data of the flat all-data traffic —
        # the hierarchy's reason to exist, asserted not narrated.
        assert (
            hier_est["by_axis"]["dcn"]["total"]
            <= flat_on_pod["total"] / (world // 2) + 64
        )
        if hier_check is not None:
            assert hier_check["within_tolerance"], hier_check
    else:
        sub["hier"] = {
            "skipped": f"world {world} < 4: a 2-slice mesh would have "
            "a 1-wide ICI group (nothing to scatter)",
        }
    for rec in sub.values():
        rec.setdefault("gather_dtype", None)
        rec.update(_env_fields())

    return {
        "metric": "zero_weight_update_sharding",
        **_env_fields(),
        "world_size": world,
        "bucket_mb": bucket_mb,
        "buckets": len(layout.buckets),
        "batch": batch,
        "timed_steps": timed_steps,
        "step_time_p50_s": p50,
        "dispatch_compute": split,
        "overlap_fraction": overlap_fraction,
        "comm_bytes": comm_est,
        "hlo_comm_check": comm_check,
        "compile_time_s": compile_s,
        "hbm_high_water_bytes": hbm.high_water_bytes,
        "opt_state_bytes_per_device": opt_mem,
        "opt_memory_ratio": round(
            opt_mem["zero"] / max(1, opt_mem["ddp"]), 4
        ),
        # One-step parity guard: a wrong sharded update would drift
        # the loss; the full pins live in tests/test_zero.py.
        "loss_delta_vs_ddp": round(
            abs(final_loss["zero"] - final_loss["ddp"]), 6
        ),
        "final_loss": final_loss,
        "variants": sub,
    }


def run_elastic_bench(*, timeout: float = 600.0) -> dict:
    """Elastic world-resize drill (ROADMAP item 4 / ISSUE 8) as a
    measured bench entry: a REAL 2-process spawn where rank 1 is
    permanently lost mid-epoch-1 (``--chaos shrink:rank1@step12``)
    under ``--elastic --min_world 1`` — the supervisor reaps the
    world, relaunches it one smaller, and the survivor resumes from
    the epoch-0 checkpoint at the preserved global batch.

    Reports **recovery-time p50**: fault → first post-resize optimizer
    step, measured from the metrics stream's wall clocks (the drill
    runs ``--log_interval 1`` so the last pre-fault record is at most
    one step stale; one drill = one sample, so p50 is that sample —
    the field name states the contract, ``recovery_samples`` states
    the honesty). Plus the **resize-downtime share** of the run's wall
    clock from the goodput sidecar's restart-vs-resize attribution,
    and ``lint_clean`` like the headline record. Always a CPU-spawn
    measurement by construction (``--spawn`` emulates hosts on CPU);
    the number is a *recovery-path latency*, not a throughput claim.
    """
    import os
    import subprocess
    import sys
    import tempfile

    work = tempfile.mkdtemp(prefix="ddp_tpu_elastic_bench_")
    ck = os.path.join(work, "ck")
    metrics_path = os.path.join(work, "metrics.jsonl")
    root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable, os.path.join(root, "train.py"),
        "--spawn", "2", "--elastic", "--min_world", "1",
        "--epochs", "2", "--batch_size", "4",
        "--synthetic_data", "--synthetic_size", "64",
        "--eval_every", "0", "--log_interval", "1",
        "--checkpoint_dir", ck,
        "--data_root", os.path.join(work, "data"),
        "--metrics_file", metrics_path,
        "--chaos", "shrink:rank1@step12",
        "--restart_backoff", "0.1",
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout,
            env=env, cwd=root,
        )
    except subprocess.TimeoutExpired:
        return {
            "metric": "elastic_world_resize",
            "platform": "cpu",
            "backend": "cpu",
            "cpu_fallback": True,
            "error": f"drill timed out after {timeout:.0f}s",
        }
    if proc.returncode != 0:
        return {
            "metric": "elastic_world_resize",
            "platform": "cpu",
            "backend": "cpu",
            "cpu_fallback": True,
            "error": f"drill rc={proc.returncode}: {proc.stderr[-800:]}",
        }
    records = []
    try:
        with open(metrics_path) as f:
            for line in f:
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail — same tolerance as triage
    except OSError:
        pass
    rs_idx = [
        i for i, r in enumerate(records) if r.get("kind") == "run_start"
    ]
    worlds = [records[i].get("data_shards") for i in rs_idx]
    resize_i = None
    for i in rs_idx:
        r = records[i]
        if (
            r.get("prev_data_shards")
            and r.get("data_shards") != r.get("prev_data_shards")
        ):
            resize_i = i
    recovery = None
    if resize_i is not None and resize_i > 0:
        fault_t = records[resize_i - 1].get("time")
        first_step = next(
            (
                r for r in records[resize_i:]
                if r.get("kind") == "step"
            ),
            None,
        )
        if fault_t and first_step:
            recovery = float(first_step["time"]) - float(fault_t)
    side = {}
    try:
        with open(os.path.join(ck, "goodput.json")) as f:
            side = json.load(f)
    except (OSError, ValueError):
        pass
    wall = max(
        1e-9,
        float(side.get("last_flush_unix", 0.0))
        - float(side.get("first_launch_unix", 0.0)),
    )
    resize_down = float(side.get("resize_downtime_s", 0.0))
    steps = [r for r in records if r.get("kind") == "step"]
    return {
        "metric": "elastic_world_resize",
        # --spawn emulates hosts on CPU by design: the drill is a
        # recovery-path latency on emulated hosts, never an on-chip
        # throughput claim — flagged like every other CPU capture.
        "platform": "cpu",
        "backend": "cpu",
        "cpu_fallback": True,
        "world_trajectory": worlds,
        "generations": len(rs_idx),
        "resizes": int(side.get("resizes", 0)),
        "restarts": int(side.get("restarts", 0)),
        "recovery_time_p50_s": (
            round(recovery, 3) if recovery is not None else None
        ),
        "recovery_samples": 1 if recovery is not None else 0,
        "resize_downtime_s": round(resize_down, 3),
        "restart_downtime_s": round(
            float(side.get("restart_downtime_s", 0.0)), 3
        ),
        "resize_downtime_share": round(resize_down / wall, 4),
        "final_step": max((r.get("step", 0) for r in steps), default=0),
        "lint_clean": _lint_clean(),
    }


def run_mpmd_bench(*, timeout: float = 600.0) -> dict:
    """MPMD pipeline runtime (ISSUE 17) vs the in-graph SPMD 1F1B
    control at identical shapes/seeds: a REAL 2-process-per-stage
    spawn (``parallel/mpmd.py``) against the single-program schedule
    on 2 emulated devices.

    Reports step-time p50/p99 and the measured bubble/p2p-wait
    fractions from the stage-tagged step records, per-stage compile
    seconds with the headline assertion of the subsystem — the SUM of
    the per-stage compiles stays below the SPMD single-program
    compile (each stage builds 1/K of the model) — loss-trajectory
    parity vs the control, and the ``kill:stage1`` drill's recovery
    time (fault → first post-restart step, one drill = one sample).
    Always a CPU-spawn measurement by construction; the numbers are
    schedule/recovery characteristics, not a throughput claim.
    """
    import os
    import subprocess
    import sys
    import tempfile

    from ddp_tpu.utils.metrics import StatSummary

    work = tempfile.mkdtemp(prefix="ddp_tpu_mpmd_bench_")
    root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    shape = [
        "--stages", "2", "--steps", "8", "--batch_size", "8",
        "--microbatches", "4", "--seq_len", "16", "--d_model", "32",
    ]
    base = [sys.executable, "-m", "ddp_tpu.parallel.mpmd", *shape]
    provenance = {
        "metric": "mpmd_pipeline_runtime",
        # one emulated CPU device per stage process by design: the
        # drill measures schedule/recovery behavior, never on-chip
        # throughput — flagged like every other CPU capture.
        "platform": "cpu",
        "backend": "cpu",
        "cpu_fallback": True,
    }

    def _fail(what: str, proc=None) -> dict:
        rec = dict(provenance)
        detail = what
        if proc is not None:
            detail += f" rc={proc.returncode}: {proc.stderr[-800:]}"
        rec["error"] = detail
        return rec

    def _records(path: str) -> list:
        out = []
        try:
            with open(path) as f:
                for line in f:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn tail — same tolerance as triage
        except OSError:
            pass
        return out

    # 1) MPMD run (2 stage processes, supervised)
    metrics_path = os.path.join(work, "metrics.jsonl")
    mpmd_json = os.path.join(work, "mpmd.json")
    try:
        proc = subprocess.run(
            base + [
                "--workdir", os.path.join(work, "run"),
                "--metrics_file", metrics_path,
                "--json", mpmd_json,
            ],
            capture_output=True, text=True, timeout=timeout / 3,
            env=env, cwd=root,
        )
    except subprocess.TimeoutExpired:
        return _fail(f"mpmd run timed out after {timeout / 3:.0f}s")
    if proc.returncode != 0:
        return _fail("mpmd run", proc)
    try:
        with open(mpmd_json) as f:
            mpmd = json.load(f)
    except (OSError, ValueError) as e:
        return _fail(f"mpmd result unreadable: {e}")

    # 2) SPMD 1F1B control: same shapes, 2 emulated devices, ONE
    # program (the compile-cost baseline and the parity reference)
    ctl_env = dict(env)
    ctl_env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    ctl_env["JAX_ENABLE_COMPILATION_CACHE"] = "false"
    ctl_json = os.path.join(work, "control.json")
    try:
        proc = subprocess.run(
            base + ["--control", "--json", ctl_json],
            capture_output=True, text=True, timeout=timeout / 3,
            env=ctl_env, cwd=root,
        )
    except subprocess.TimeoutExpired:
        return _fail(f"spmd control timed out after {timeout / 3:.0f}s")
    if proc.returncode != 0:
        return _fail("spmd control", proc)
    try:
        with open(ctl_json) as f:
            control = json.load(f)
    except (OSError, ValueError) as e:
        return _fail(f"control result unreadable: {e}")

    # 3) kill drill: SIGKILL stage 1 mid-run, expect exactly one
    # classified restart and a completed run
    drill_metrics = os.path.join(work, "drill.jsonl")
    drill_json = os.path.join(work, "drill.json")
    try:
        proc = subprocess.run(
            base + [
                "--workdir", os.path.join(work, "drill"),
                "--metrics_file", drill_metrics,
                "--chaos", "kill:stage1@step4",
                "--json", drill_json,
            ],
            capture_output=True, text=True, timeout=timeout / 3,
            env=env, cwd=root,
        )
    except subprocess.TimeoutExpired:
        return _fail(f"kill drill timed out after {timeout / 3:.0f}s")
    if proc.returncode != 0:
        return _fail("kill drill", proc)
    try:
        with open(drill_json) as f:
            drill = json.load(f)
    except (OSError, ValueError) as e:
        return _fail(f"drill result unreadable: {e}")

    # ---- aggregate ---------------------------------------------------
    records = _records(metrics_path)
    steps = [
        r for r in records
        if r.get("kind") == "step" and r.get("stage") is not None
    ]
    times = StatSummary()
    bubble = StatSummary()
    p2p_wait = StatSummary()
    for r in steps:
        wall = r.get("wall_s")
        if not wall:
            continue
        times.add(wall)
        if r.get("bubble_s") is not None:
            bubble.add(r["bubble_s"] / wall)
        if r.get("p2p_wait_s") is not None:
            p2p_wait.add(r["p2p_wait_s"] / wall)
    per_stage = {
        str(k): {
            "compile_s": round(float(f.get("compile_s", 0.0)), 3),
            "compiled_programs": f.get("compiled_programs"),
        }
        for k, f in (mpmd.get("final") or {}).items()
    }
    compile_sum = sum(
        v["compile_s"] for v in per_stage.values()
    )
    ctl_compile = float(control.get("compile_s") or 0.0)
    # THE subsystem claim: every stage compiled 1/K of the model, so
    # even summed across stages the compile bill undercuts the one
    # whole-model SPMD program.
    assert compile_sum < ctl_compile, (
        f"per-stage compiles sum to {compile_sum:.2f}s, not below the "
        f"SPMD single-program {ctl_compile:.2f}s"
    )
    mpmd_losses = []
    for r in sorted(
        (r for r in steps if r["stage"] == 0 and r.get("loss") is not None),
        key=lambda r: r["step"],
    ):
        mpmd_losses.append(float(r["loss"]))
    ctl_losses = [float(v) for v in control.get("losses") or []]
    loss_gap = (
        max(
            abs(a - b) for a, b in zip(mpmd_losses, ctl_losses)
        )
        if mpmd_losses and len(mpmd_losses) == len(ctl_losses)
        else None
    )
    # kill-drill recovery: fault (last step record before the restart
    # stamp) → first step record after it
    drill_recs = _records(drill_metrics)
    restart_recs = [
        r for r in drill_recs if r.get("kind") == "mpmd_restart"
    ]
    recovery = None
    if restart_recs:
        t_restart = float(restart_recs[0]["time"])
        pre = [
            float(r["time"]) for r in drill_recs
            if r.get("kind") == "step" and float(r["time"]) < t_restart
        ]
        post = [
            float(r["time"]) for r in drill_recs
            if r.get("kind") == "step" and float(r["time"]) >= t_restart
        ]
        if pre and post:
            recovery = min(post) - max(pre)
    ctl_steps = [float(s) for s in control.get("step_s") or []]
    ctl_summ = StatSummary()
    for s in ctl_steps[1:]:  # drop the compile-bearing first step
        ctl_summ.add(s)
    return {
        **provenance,
        "stages": mpmd.get("stages"),
        "steps": mpmd.get("steps"),
        "step_time_p50_s": round(times.percentile(50), 4)
        if times.count else None,
        "step_time_p99_s": round(times.percentile(99), 4)
        if times.count else None,
        "control_step_time_p50_s": round(ctl_summ.percentile(50), 4)
        if ctl_summ.count else None,
        "schedule_bubble_fraction": mpmd.get(
            "schedule_bubble_fraction"
        ),
        "measured_bubble_fraction": round(
            bubble.snapshot().get("mean", 0.0), 4
        )
        if bubble.count else None,
        "p2p_wait_fraction": round(
            p2p_wait.snapshot().get("mean", 0.0), 4
        )
        if p2p_wait.count else None,
        "per_stage_compile": per_stage,
        "compile_s_sum": round(compile_sum, 3),
        "control_compile_s": round(ctl_compile, 3),
        "control_compiled_programs": control.get("compiled_programs"),
        "loss_trajectory_max_gap": loss_gap,
        "loss_parity": bool(
            loss_gap is not None and loss_gap < 1e-3
        ),
        "kill_drill_restarts": drill.get("restarts"),
        "kill_drill_recovery_s": round(recovery, 3)
        if recovery is not None else None,
        "recovery_samples": 1 if recovery is not None else 0,
        "kill_drill_final_loss_gap": (
            abs(float(drill["loss"]) - float(mpmd["loss"]))
            if drill.get("loss") is not None
            and mpmd.get("loss") is not None
            else None
        ),
        "lint_clean": _lint_clean(),
    }


def run_zero_bench() -> dict:
    """Headline `zero` entry — in-process when the backend has ≥ 2
    devices, else re-run in a subprocess with 4 emulated CPU devices
    (world ≥ 2 is the point — nothing to scatter at 1 — and 4 lets
    the hierarchical variant emulate 2 slices × 2)."""
    import os
    import subprocess
    import sys

    import jax

    if len(jax.devices()) >= 2:
        return _zero_bench_impl()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--zero-worker"],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": "zero worker timed out"}
    for line in reversed(proc.stdout.splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if (
            isinstance(rec, dict)
            and rec.get("metric") == "zero_weight_update_sharding"
        ):
            rec["emulated_devices"] = True
            return rec
    return {
        "error": f"zero worker rc={proc.returncode}: "
        f"{proc.stderr[-800:]}"
    }


def run_accuracy_bench() -> dict:
    """North-star convergence proof on REAL handwritten-digit data.

    The one end-to-end claim the project is anchored on (BASELINE.md:
    ≥99% test accuracy within 3 MNIST epochs) had never been measured
    on real data — this environment has zero egress, so actual MNIST
    bytes are unreachable and every prior record degraded to the
    synthetic fallback. The real data used here: the UCI handwritten
    digits (sklearn's packaged ``load_digits`` scans — genuine digit
    raster data), vendored into MNIST's IDX container by
    ``scripts/vendor_uci_digits.py`` and committed under
    ``data/uci_digits/`` (1,437 train / 360 test, stratified).

    Two runs through the compiled per-step DDP path (the trainer CLI's
    step; NOT the scanned fast path — measured on this host, XLA:CPU
    compiles the conv step ~200× slower *inside* ``lax.scan`` than the
    identical step standalone, 3.4 s/step vs 15 ms/step, so the
    convergence proof uses the step path that is fast on both
    backends):

    - **reference recipe**: SGD lr=0.01, batch 32, 3 epochs, no
      augmentation — exactly ``/root/reference/train_ddp.py:41,218``
      transplanted onto the real vendored data;
    - **equal-sample budget**: 3 MNIST epochs = 180,000 samples seen;
      on 1,437 real examples that is 125 epochs. Adam + cosine decay +
      ±2px random-shift augmentation (data/augment.py) — the
      north-star ≥0.99 measured at MNIST's own sample budget, with
      the 3-epoch checkpoint of the same run reported alongside.

    Accuracy is evaluated on the untouched real test split; the
    augmentation never touches eval. Runs on whatever backend is up —
    convergence does not need the chip (round-3 verdict, missing #1).
    """
    import os
    import time

    import jax
    import jax.numpy as jnp
    import optax

    import numpy as np

    from ddp_tpu.data import mnist
    from ddp_tpu.data.augment import random_shift
    from ddp_tpu.models import get_model
    from ddp_tpu.parallel.ddp import (
        create_train_state,
        make_train_step,
        replicate_state,
    )
    from ddp_tpu.runtime.mesh import MeshSpec, make_mesh

    t_start = time.perf_counter()
    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
    train = mnist.load(root, "train", variant="uci_digits")
    test = mnist.load(root, "test", variant="uci_digits")
    n_train = int(train.images.shape[0])

    device = jax.devices()[0]
    mesh = make_mesh(MeshSpec(data=1), devices=[device])
    model = get_model("simple_cnn")
    batch = 32
    steps_per_epoch = n_train // batch
    test_x = jnp.asarray(test.images)
    test_y = jnp.asarray(test.labels)

    @jax.jit
    def test_accuracy(params):
        logits = model.apply(
            {"params": params}, test_x.astype(jnp.float32) / 255.0
        )
        return (jnp.argmax(logits, -1) == test_y).mean()

    def train_run(tx, epochs, augment_fn):
        state = replicate_state(
            create_train_state(
                model, tx, jnp.zeros((1, 28, 28, 1)), seed=0
            ),
            mesh,
        )
        step = make_train_step(
            model, tx, mesh, donate=False, seed=0, augment_fn=augment_fn,
        )
        images = jnp.asarray(train.images)
        labels = jnp.asarray(train.labels)
        rng = np.random.default_rng(0)
        acc_at_3 = None
        for e in range(epochs):
            perm = rng.permutation(n_train)
            for b in range(steps_per_epoch):
                sel = perm[b * batch : (b + 1) * batch]
                state, _ = step(state, images[sel], labels[sel])
            if e == 2:
                acc_at_3 = float(test_accuracy(state.params))
        return acc_at_3, float(test_accuracy(state.params))

    # Run 1 — the reference's own recipe on the real data.
    ref_acc3, _ = train_run(optax.sgd(0.01), 3, None)

    # Run 2 — the north star at MNIST's sample budget.
    budget_epochs = (3 * 60_000) // n_train  # = 125
    tuned_tx = optax.adam(
        optax.cosine_decay_schedule(
            1e-3, budget_epochs * steps_per_epoch, alpha=0.1
        )
    )
    tuned_acc3, budget_acc = train_run(tuned_tx, budget_epochs, random_shift)

    return {
        "real_data": True,
        **_env_fields(),
        "dataset": "uci_digits (sklearn load_digits scans, vendored "
                   "as IDX by scripts/vendor_uci_digits.py; real MNIST "
                   "unreachable — zero network egress)",
        "n_train": n_train,
        "n_test": int(test.images.shape[0]),
        "accuracy_3ep_reference_recipe": round(ref_acc3, 4),
        "accuracy_3ep_tuned": round(tuned_acc3, 4),
        "accuracy_mnist_equal_sample_budget": round(budget_acc, 4),
        "equal_budget_epochs": budget_epochs,
        "equal_budget_samples_seen": budget_epochs * steps_per_epoch * batch,
        "mnist_3ep_samples_seen": 180_000,
        "target": 0.99,
        "target_met_at_equal_budget": budget_acc >= 0.99,
        "seconds": round(time.perf_counter() - t_start, 1),
    }


def run_tune_bench() -> dict:
    """Autotuner entry (`python bench.py tune`, ISSUE 18): proves the
    cost model prunes, the search never regresses, and the cache makes
    the second run free.

    Cold pass on a fresh cache: the serve knob grid is enumerated,
    priced via the xprof compile ledger, dominated candidates dropped
    (``pruned_fraction > 0`` asserted — a cost model that prunes
    nothing is dead weight), survivors measured with the serve bench
    harness. ``tuned_p50 <= default_p50`` is asserted — the default
    config is always in the measured set and the winner is the p50
    argmin, so a tuner that can't beat the default returns it.

    Warm pass against the same cache file: asserted to be a pure hit —
    ``cache_hit`` true and ZERO measurements (the loaded-by-default
    path in trainer/serve/fleet costs nothing at startup).
    """
    import tempfile
    import time

    from ddp_tpu.models.lm import LMSpec, init_lm
    from ddp_tpu.tune import TuningCache, tune_serve, tune_zero

    env = _env_fields()
    spec = LMSpec(
        vocab_size=64, total_len=64, d_model=32, depth=1, num_heads=2
    )
    params = init_lm(spec, seed=0)
    with tempfile.TemporaryDirectory() as td:
        cache = TuningCache(os.path.join(td, "tuning_cache.json"))
        t0 = time.perf_counter()
        cold = tune_serve(
            spec, params, cache=cache, slots=2, max_measure=3
        )
        cold_wall = time.perf_counter() - t0
        assert not cold["cache_hit"], cold
        assert cold["pruned_fraction"] > 0, cold
        assert cold["measured"] >= 1, cold
        assert cold["tuned_p50"] <= cold["default_p50"], cold

        warm = tune_serve(
            spec, params, cache=cache, slots=2, max_measure=3
        )
        assert warm["cache_hit"] and warm["measured"] == 0, warm

        zero = tune_zero(
            params, 4, cache=cache, model_sig="bench", dcn=1
        )
        zero_warm = tune_zero(
            params, 4, cache=cache, model_sig="bench", dcn=1
        )
        assert zero_warm["cache_hit"] and zero_warm["measured"] == 0, (
            zero_warm
        )

    _assert_provenance(env)
    return {
        "metric": "autotune_search",
        **env,
        "proposed": cold["proposed"],
        "priced": cold["priced"],
        "pruned": cold["pruned"],
        "pruned_fraction": cold["pruned_fraction"],
        "cost_compiles": cold["cost_compiles"],
        "measured": cold["measured"],
        "measure_deferred": cold.get("measure_deferred", 0),
        "search_wall_s": round(cold_wall, 3),
        "default_p50_s": cold["default_p50"],
        "tuned_p50_s": cold["tuned_p50"],
        "winner": cold["winner"],
        "tuned_leq_default": True,
        "second_run_pure_cache_hit": True,
        "zero_winner": zero["winner"],
        "zero_pruned_fraction": zero["pruned_fraction"],
    }


def _run_extra_benches() -> None:
    """MXU-bound side benches → BENCH_EXTRA.json + stderr (TPU only)."""
    import pathlib
    import sys
    import traceback

    import jax

    if jax.devices()[0].platform != "tpu":
        return
    out = pathlib.Path(__file__).with_name("BENCH_EXTRA.json")
    # Seed from the existing record so a partially-completed run (or
    # one interrupted by a tunnel flap) merges fresh entries over the
    # old ones instead of erasing side benches it never reached.
    extra = {}
    if out.exists():
        try:
            extra = json.loads(out.read_text())
        except (OSError, ValueError):
            extra = {}
    for name, fn in [
        ("vit", run_vit_bench),
        # Layout-tax experiment: T=64 (tile-aligned, mean-pool) vs the
        # T=65 cls-token run above — round-3 verdict weak #5.
        ("vit_t64", lambda: run_vit_bench(use_cls_token=False)),
        ("lm", run_lm_bench),
        ("lm_long", run_lm_long_bench),
        ("decode", run_decode_bench),
        ("decode_gqa", lambda: run_decode_bench(num_kv_heads=2)),
        # Round-5 MoE serving path: routed blocks through the same
        # KV-cache decode scan (GQA×MoE — the Mixtral-class config).
        ("decode_moe", lambda: run_decode_bench(
            num_kv_heads=2, num_experts=8)),
        # The serving data plane (ddp_tpu.serve): continuous-batching
        # engine under open-loop Poisson arrivals — sustained tokens/s
        # + TTFT, the complement of the raw decode scan above.
        ("serve_decode", run_serve_bench),
        # Shared-prefix serving (PR 12): paged KV + radix prefix
        # reuse — hit rate, effective-slots multiplier, TTFT hit vs
        # miss against a fixed-lane control on identical traffic.
        ("serve_prefix", run_serve_prefix_bench),
        # Fleet serving (ISSUE 14): a real 3-replica subprocess fleet
        # behind the router — affinity-vs-random prefix-hit rates
        # (asserted), aggregate tokens/s + p99 TTFT, and the kill
        # drill (zero dropped / zero duplicated / one restart,
        # asserted; recovery time + replays recorded). PR 16 adds the
        # disagg phases: prefill:decode ratio sweep (1:2/1:1/2:1) vs
        # the hybrid control with migration latency p50/p99,
        # migration token identity (asserted), and the prefix
        # directory beating affinity-only under churn (asserted).
        ("serve_fleet", run_serve_fleet_bench),
        # Model lifecycle (ISSUE 20): hot-swap vs rolling restart,
        # in-flight-across-swap completion, cold vs streaming TTFT.
        ("serve_reload", run_serve_reload_bench),
        ("loader", run_loader_bench),
    ]:
        try:
            extra[name] = fn()
        except Exception:  # record, never break the headline bench
            extra[name] = {"error": traceback.format_exc(limit=3)}
        # Write after every entry: a supervisor timeout mid-extras
        # keeps whatever completed instead of losing the whole file.
        out.write_text(json.dumps(extra, indent=2))
    print(json.dumps(extra), file=sys.stderr)


# --- capture supervision (VERDICT.md round-2 "do this" #1) -----------
#
# Round 2 lost its driver-verified TPU record to a transient tunnel
# outage: the environment pre-pins JAX_PLATFORMS, the old fallbacks all
# opted out when pinned ("a pin means that-platform-or-fail"), and the
# backend-init exception propagated as rc=1 / parsed=null. The contract
# is now: this script ALWAYS prints one parseable JSON line and exits 0.
# Architecture: __main__ is a supervisor that runs the measurement in a
# worker subprocess (``bench.py --worker``) — a subprocess boundary is
# the only way to retry backend init (the in-process registry cannot be
# re-initialized) and the only way to bound a *hang* (the tunnel's other
# failure mode: backend init sleeps forever, which no `except` catches).

# The probe imports ddp_tpu first: platform plugins (the axon tunnel)
# pin jax_platforms at import time, overriding the JAX_PLATFORMS env
# var — the package re-applies the env var so a CPU-pinned probe (and
# the CPU fallback worker) really stays off the tunnel.
_PROBE_SRC = "import ddp_tpu, jax; print(jax.devices()[0].platform)"


def _probe_backend(timeout: float) -> bool:
    """Can a fresh process see a device under the current env?

    Runs from this file's directory so ``import ddp_tpu`` resolves
    regardless of the caller's cwd — a probe that fails on ImportError
    would be indistinguishable from a tunnel outage and mislabel a
    healthy-TPU run as a CPU fallback.
    """
    import os
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            timeout=timeout,
            capture_output=True,
            text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return False
    if proc.returncode == 0:
        return True
    if "ImportError" in proc.stderr or "ModuleNotFoundError" in proc.stderr:
        # Not a backend problem — surface it instead of retrying/
        # falling back with a misleading record.
        raise RuntimeError(
            f"bench probe failed to import: {proc.stderr[-1500:]}"
        )
    return False


def _run_worker(env: dict, timeout: float) -> dict | None:
    """Run ``bench.py --worker``; return its parsed headline record.

    Relays the worker's stderr (extras, notes). Returns None on
    timeout, non-zero exit, or unparseable stdout — the supervisor
    decides what to try next.
    """
    import os
    import subprocess
    import sys

    def _decode(s) -> str:
        return s.decode(errors="replace") if isinstance(s, bytes) else (s or "")

    def _scan_for_record(stdout: str) -> dict | None:
        for line in reversed(stdout.splitlines()):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                return rec
        return None

    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            timeout=timeout,
            capture_output=True,
            text=True,
            env=env,
        )
    except subprocess.TimeoutExpired as e:
        print(f"bench: worker timed out after {timeout:.0f}s", file=sys.stderr)
        print(_decode(e.stderr)[-2000:], file=sys.stderr)
        # The worker prints the headline record FIRST, then runs the
        # heavy side benches — a timeout in the extras must not discard
        # an already-valid headline (the round-2 loss mode).
        rec = _scan_for_record(_decode(e.stdout))
        if rec is not None:
            rec["note"] = f"worker timed out after record ({timeout:.0f}s)"
        return rec
    print(proc.stderr[-8000:], file=sys.stderr, end="")
    rec = _scan_for_record(proc.stdout)
    if rec is not None:
        if proc.returncode != 0:
            rec["note"] = f"worker exited rc={proc.returncode} after record"
        return rec
    # (The stderr tail was already relayed above.)
    print(
        f"bench: worker rc={proc.returncode}, no JSON record",
        file=sys.stderr,
    )
    return None


# Global wall-clock budget for the whole capture. Every stage draws
# from one deadline so the worst case is bounded by construction
# (probes + retries + worker + CPU fallback all fit), not by summing
# per-stage timeouts. 40 min total; the CPU fallback's reservation
# guarantees it always gets a usable window even after a worker that
# burns its whole allowance — sized for headline (~240 s) + real-data
# accuracy (~370 s measured) + compile margin on the 1-core host.
_TOTAL_BUDGET_S = 2400.0
_CPU_RESERVE_S = 1000.0


def _supervise() -> dict:
    """Bounded-retry capture: pinned env first, then CPU, never fail.

    Plan, all drawing on one ``_TOTAL_BUDGET_S`` deadline:
      1-3. probe the inherited env (120 s timeout each, 45 s backoff) —
           a flapping tunnel often comes back within minutes;
      4.   first probe success → worker run with every remaining
           second except the CPU reservation (the budget covers the
           headline AND the side benches; a timeout after the headline
           line still keeps the headline, see _run_worker);
      5.   worker failed or probes exhausted → CPU worker on the rest
           of the budget (no extras run off-TPU); ``platform: "cpu"``
           marks the fallback;
      6.   even that failed → structured error record, still rc 0.
    """
    import os
    import sys
    import time

    deadline = time.monotonic() + _TOTAL_BUDGET_S

    def remaining() -> float:
        return deadline - time.monotonic()

    env = dict(os.environ)
    attempts: list[str] = []
    launches = 0
    for i in range(3):
        probe_budget = max(5.0, min(120.0, remaining() - _CPU_RESERVE_S))
        if _probe_backend(timeout=probe_budget):
            attempts.append(f"probe[{i}]: ok")
            worker_budget = max(60.0, remaining() - _CPU_RESERVE_S)
            launches += 1
            rec = _run_worker(env, timeout=worker_budget)
            if rec is not None:
                label = "worker: " + rec.get("note", "ok")
                rec["capture_attempts"] = attempts + [label]
                rec["restarts"] = launches - 1
                return rec
            attempts.append("worker: failed")
            break
        attempts.append(f"probe[{i}]: backend unreachable")
        print(
            f"bench: backend probe {i} failed under "
            f"JAX_PLATFORMS={env.get('JAX_PLATFORMS') or '(unset)'!s}",
            file=sys.stderr,
        )
        if remaining() <= _CPU_RESERVE_S + 120.0:
            attempts.append("probes: budget exhausted")
            break
        if i < 2:
            print("bench: retrying probe in 45s", file=sys.stderr)
            time.sleep(45.0)
    cpu_env = dict(env, JAX_PLATFORMS="cpu")
    launches += 1
    rec = _run_worker(cpu_env, timeout=max(60.0, remaining()))
    if rec is not None:
        rec["capture_attempts"] = attempts + [
            "cpu worker: " + rec.get("note", "ok")
        ]
        # Worker relaunches consumed before this record landed —
        # respawn overhead is part of the published trajectory.
        rec["restarts"] = launches - 1
        return rec
    attempts.append("cpu worker: failed")
    return _error_record("all capture attempts failed", attempts)


def _finalize(record: dict) -> dict:
    """Make every published record self-contained (round-3 weak #1).

    A tunnel-outage round used to publish a CPU-fallback headline
    ("8.7 img/s, vs_baseline 0.0") that reads as a 5,700× regression
    unless the reader correlates three files. Now: a fresh TPU capture
    refreshes BENCH_LKG.json (the committed last-known-good), and any
    non-TPU record embeds it as ``last_tpu`` / ``last_tpu_captured``
    (schema {captured: ISO date, record: {...}} — writer and reader
    agree; a refresh is provenance'd by date) so
    the outage record itself says what the framework does on the chip.
    """
    import pathlib
    import sys

    lkg_path = pathlib.Path(__file__).with_name("BENCH_LKG.json")
    if record.get("platform") == "tpu" and not record.get("error"):
        try:
            import datetime

            lkg_path.write_text(json.dumps({
                "captured": datetime.date.today().isoformat(),
                "record": record,
            }, indent=2) + "\n")
        except OSError as e:  # LKG refresh is best-effort
            print(f"bench: LKG refresh failed: {e}", file=sys.stderr)
        return record
    try:
        lkg = json.loads(lkg_path.read_text())
        record["last_tpu"] = lkg["record"]
        record["last_tpu_captured"] = lkg.get("captured")
        record["note"] = (
            record.get("note", "")
            + " | TPU backend unreachable this capture; last_tpu is the "
            "most recent driver/builder-verified on-chip record"
        ).lstrip(" |")
        # Staleness alarm (ISSUE 19 satellite): a CPU-fallback capture
        # leaning on an LKG more than a week old is quietly comparing
        # against history — say so LOUDLY on stderr and in the record
        # itself, so a long outage can't masquerade as a fresh
        # on-chip trajectory point.
        try:
            import datetime

            captured = datetime.date.fromisoformat(lkg.get("captured"))
            age = (datetime.date.today() - captured).days
            if age > 7 and record.get("cpu_fallback"):
                record["last_tpu_stale_days"] = age
                print(
                    f"bench: WARNING — BENCH_LKG.json is {age} days "
                    f"old (captured {captured.isoformat()}) and this "
                    "capture is a CPU fallback; the embedded last_tpu "
                    "numbers are STALE, not a current on-chip "
                    "measurement",
                    file=sys.stderr,
                )
        except (TypeError, ValueError):
            pass  # undated LKG — the embed above still carries it
    except (OSError, ValueError, KeyError):
        pass  # no LKG on disk — nothing to carry
    return record


def _error_record(error: str, attempts: list[str]) -> dict:
    return {
        "metric": "mnist_ddp_train_throughput",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "platform": "none",
        "backend": "none",
        "cpu_fallback": True,
        "error": error,
        "capture_attempts": attempts,
    }


if __name__ == "__main__":
    import sys

    if "--zero-worker" in sys.argv:
        # Emulated-device measurement process for run_zero_bench (the
        # supervisor/worker spawns this with 2 virtual CPU devices
        # when the backend has only one).
        print(json.dumps(_zero_bench_impl()), flush=True)
        sys.exit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "serve_reload":
        # Model-lifecycle entry (ISSUE 20): in-flight-across-swap
        # completion, cold-vs-streaming TTFT, fleet swap vs /rollz
        # wall-clock — orderings asserted, one JSON line out.
        print(json.dumps(run_serve_reload_bench()), flush=True)
        sys.exit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "tune":
        # Autotuner entry (ISSUE 18): pruned fraction, search
        # wall-clock, tuned-vs-default p50, cache-hit proof. One JSON
        # line, same contract as the headline.
        print(json.dumps(run_tune_bench()), flush=True)
        sys.exit(0)
    if "--worker" in sys.argv:
        # Measurement process: no fallbacks here — the supervisor owns
        # retry/timeout policy. Headline line FIRST so a crash in the
        # heavier side benches cannot lose the driver-contract output.
        result = run_bench()
        print(json.dumps(result), flush=True)
        # Real-data convergence proof (any backend): on success,
        # REPRINT the headline merged with the accuracy record — the
        # supervisor takes the last parseable line, so a crash or
        # timeout in here still leaves the first headline intact.
        try:
            result["real_data_accuracy"] = run_accuracy_bench()
            print(json.dumps(result), flush=True)
        except Exception:
            import traceback

            traceback.print_exc(file=sys.stderr)
        # ZeRO weight-update sharding vs ddp at world ≥ 2 (ROADMAP
        # item 3 / ISSUE 7 acceptance): step-time p50, comm_bytes,
        # optimizer-memory high-water, measured overlap fraction.
        # Merged-and-reprinted like the accuracy record — a crash or
        # timeout here never costs the headline.
        try:
            result["zero"] = run_zero_bench()
            print(json.dumps(result), flush=True)
        except Exception:
            import traceback

            traceback.print_exc(file=sys.stderr)
        # Elastic world-resize recovery drill (ROADMAP item 4 / ISSUE
        # 8): recovery-time p50 (fault → first post-resize step) and
        # the resize-downtime share, from a real 2-process shrink
        # drill. Merged-and-reprinted like the records above — a crash
        # or timeout here never costs the headline.
        try:
            result["elastic"] = run_elastic_bench()
            print(json.dumps(result), flush=True)
        except Exception:
            import traceback

            traceback.print_exc(file=sys.stderr)
        # MPMD pipeline runtime (ISSUE 17): per-stage-process 1F1B vs
        # the in-graph SPMD control — compile-cost sum asserted below
        # the single program, loss parity, kill-drill recovery.
        # Merged-and-reprinted like the records above.
        try:
            result["mpmd"] = run_mpmd_bench()
            print(json.dumps(result), flush=True)
        except Exception:
            import traceback

            traceback.print_exc(file=sys.stderr)
        _run_extra_benches()
    else:
        # The one-parseable-line / rc-0 contract holds even if the
        # supervisor itself blows up (OSError from subprocess spawn
        # under memory pressure, etc.).
        try:
            record = _supervise()
        except BaseException as e:  # noqa: BLE001 — contract over purity
            import traceback

            traceback.print_exc(file=sys.stderr)
            record = _error_record(
                f"supervisor crashed: {type(e).__name__}: {e}", []
            )
        try:
            record = _finalize(record)
        except BaseException:  # noqa: BLE001 — contract over purity
            pass
        print(json.dumps(record), flush=True)
        sys.exit(0)

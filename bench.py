#!/usr/bin/env python
"""Headline benchmark: MNIST DDP training throughput, images/sec/chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Baseline is the driver's north-star target of 50,000 images/sec/chip on
TPU (BASELINE.json) — the reference itself publishes no numbers
(/root/reference/README.md has only a quickstart; see BASELINE.md).

Measures the compiled-epoch fast path (ddp_tpu/train/fast.py): dataset
device-resident as uint8, per-epoch shuffle on device, ``lax.scan`` over
per-batch DDP steps — one dispatch per epoch. This is the framework's
answer to the reference's hot loop (train_ddp.py:195-202), which pays a
Python→C++ crossing per op and a collective sync per batch.
"""

from __future__ import annotations

import json
import time

BASELINE_IMAGES_PER_SEC_PER_CHIP = 50_000.0


def run_bench(
    *,
    global_batch_size: int = 16384,
    warmup_epochs: int = 2,
    timed_epochs: int = 10,
) -> dict:
    # Defaults from a sweep on the v4 chip (2026-07): 16384 beat 4096
    # (419k) and 32768 (430k) at 462k images/sec/chip; 10 timed epochs
    # amortize dispatch/timer noise that dominates sub-second windows.
    # Profiled (xprof op_profile, 2026-07): >50% of device time is the
    # conv2 fwd/grad fusions at ~7% MXU util — the 16384×28×28×32
    # bf16 activations (~0.8 GB/tensor) make the step HBM-bandwidth
    # bound, so batch size and kernel tweaks move it little; the
    # remaining headroom would need an architecture change, not
    # scheduling.
    import jax
    import jax.numpy as jnp
    import optax

    from ddp_tpu.data import mnist
    from ddp_tpu.models import get_model
    from ddp_tpu.parallel.ddp import create_train_state, replicate_state
    from ddp_tpu.runtime.mesh import MeshSpec, make_mesh
    from ddp_tpu.train.fast import device_put_dataset, make_epoch_runner

    devices = jax.devices()
    platform = devices[0].platform
    mesh = make_mesh(MeshSpec(data=len(devices)), devices=devices)

    train = mnist.load("./data", "train", allow_synthetic=True)
    n = (train.images.shape[0] // global_batch_size) * global_batch_size
    images, labels = device_put_dataset(
        train.images[:n], train.labels[:n], mesh
    )

    model = get_model("simple_cnn")
    tx = optax.sgd(0.01)
    compute_dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32
    state = replicate_state(
        create_train_state(model, tx, jnp.zeros((1, 28, 28, 1)), seed=0), mesh
    )
    runner = make_epoch_runner(
        model,
        tx,
        mesh,
        images,
        labels,
        global_batch_size,
        compute_dtype=compute_dtype,
        seed=0,
    )
    images_per_epoch = runner.steps_per_epoch * global_batch_size

    for e in range(warmup_epochs):  # compile + stabilize clocks
        state, metrics = runner(state, e)
        jax.block_until_ready(metrics.loss)

    t0 = time.perf_counter()
    for e in range(warmup_epochs, warmup_epochs + timed_epochs):
        state, metrics = runner(state, e)
    jax.block_until_ready(metrics.loss)
    seconds = time.perf_counter() - t0

    total_images = images_per_epoch * timed_epochs
    per_chip = total_images / seconds / len(devices)
    return {
        "metric": "mnist_ddp_train_throughput",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3),
        "platform": platform,
        "num_chips": len(devices),
        "global_batch_size": global_batch_size,
        "timed_epochs": timed_epochs,
        "final_loss": round(float(metrics.loss[-1]), 4),
        "seconds": round(seconds, 3),
    }


if __name__ == "__main__":
    print(json.dumps(run_bench()))

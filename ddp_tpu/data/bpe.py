"""Self-contained byte-pair-encoding tokenizer — no external deps.

The reference ingests images (/root/reference/data.py:11-14); this
framework's LM family ingests text, and round 2 stopped at raw bytes
(vocab ≤ 256). This module closes VERDICT round-2 missing #4: a real
subword vocabulary trained on the corpus itself, persisted alongside
the checkpoints, wired through ``--vocab_size``.

Algorithm: classic BPE over raw bytes. Training starts from the 256
byte ids and repeatedly merges the most frequent adjacent pair into a
new id until ``vocab_size`` ids exist (or no pair repeats). Encoding
replays the recorded merges in training order — full vectorized passes
over the id stream, the same procedure training used, so train-time
and inference-time segmentations agree by construction. Decoding
expands each id through a byte table built from the merges.

Everything is numpy-vectorized (pair counting via packed int64 ids,
merge application via boolean masks); the only Python-level loop over
positions handles the self-overlap case (pair ``(a, a)`` in runs like
``aaaa``), which vectorized masks cannot resolve left-to-right.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

import numpy as np


def _merge_pass(ids: np.ndarray, a: int, b: int, new_id: int) -> np.ndarray:
    """One full pass: every non-overlapping (a, b) → new_id."""
    if len(ids) < 2:
        return ids
    match = (ids[:-1] == a) & (ids[1:] == b)
    idx = np.flatnonzero(match)
    if len(idx) == 0:
        return ids
    if a == b:
        # Greedy left-to-right on runs: aaa merges the FIRST pair.
        keep, last = [], -2
        for i in idx.tolist():
            if i > last + 1:
                keep.append(i)
                last = i
        idx = np.asarray(keep, dtype=idx.dtype)
    out = ids.copy()
    out[idx] = new_id
    return np.delete(out, idx + 1)


@dataclass(frozen=True)
class BPETokenizer:
    """``merges[k] = (a, b)`` mints id ``256 + k``. vocab_size ≥ 256."""

    merges: tuple[tuple[int, int], ...]

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges)

    def encode(self, text: str | bytes) -> np.ndarray:
        data = text.encode("utf-8") if isinstance(text, str) else text
        ids = np.frombuffer(data, dtype=np.uint8).astype(np.int32)
        for k, (a, b) in enumerate(self.merges):
            ids = _merge_pass(ids, a, b, 256 + k)
        return ids

    def decode_bytes(self, ids) -> bytes:
        """Ids the vocabulary never minted decode to U+FFFD: a model
        embeds ``--vocab_size`` rows, which can exceed the trained
        vocabulary when BPE stopped early — an (undertrained) model
        may emit those ids and decoding must not crash on them."""
        table = self._byte_table()
        unk = "�".encode()
        return b"".join(
            table[i] if 0 <= i < len(table) else unk
            for i in (int(t) for t in np.asarray(ids).ravel())
        )

    def decode(self, ids) -> str:
        return self.decode_bytes(ids).decode("utf-8", errors="replace")

    def _byte_table(self) -> list[bytes]:
        table = [bytes([i]) for i in range(256)]
        for a, b in self.merges:
            table.append(table[a] + table[b])
        return table

    def save(self, path: str) -> None:
        # Per-process tmp name + atomic replace: in multi-process
        # training every rank may train (identical merges — training
        # is deterministic) and save concurrently; a shared tmp path
        # could publish one rank's truncated write.
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"format": "ddp_tpu-bpe-v1",
                 "merges": [list(m) for m in self.merges]},
                f,
            )
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            obj = json.load(f)
        if obj.get("format") != "ddp_tpu-bpe-v1":
            raise ValueError(f"{path}: not a ddp_tpu BPE tokenizer file")
        return cls(merges=tuple((int(a), int(b)) for a, b in obj["merges"]))


def train_bpe(data: bytes, vocab_size: int) -> BPETokenizer:
    """Learn ``vocab_size - 256`` merges from a byte corpus.

    Stops early when no adjacent pair occurs twice (the corpus is
    fully compressed); the resulting vocabulary is then smaller than
    requested — callers who need the exact size check ``vocab_size``.
    """
    if vocab_size < 257:
        raise ValueError(f"vocab_size {vocab_size} adds no merges (≤ 256)")
    ids = np.frombuffer(data, dtype=np.uint8).astype(np.int32)
    merges: list[tuple[int, int]] = []
    for new_id in range(256, vocab_size):
        if len(ids) < 2:
            break
        packed = (ids[:-1].astype(np.int64) << 32) | ids[1:].astype(np.int64)
        vals, counts = np.unique(packed, return_counts=True)
        top = int(counts.max())
        if top < 2:
            break
        # Deterministic tie-break: smallest packed pair (np.unique
        # sorts), so retraining on the same bytes rebuilds the same
        # vocabulary.
        best = int(vals[np.flatnonzero(counts == top)[0]])
        a, b = best >> 32, best & 0xFFFFFFFF
        merges.append((int(a), int(b)))
        ids = _merge_pass(ids, int(a), int(b), new_id)
    return BPETokenizer(merges=tuple(merges))


def load_or_train(
    path: str | None, data: bytes, vocab_size: int
) -> BPETokenizer:
    """Reuse a persisted tokenizer when present, else train + persist.

    A tokenizer saved next to the checkpoints IS part of the model —
    resuming (or generating) with a retrained vocabulary would remap
    every token id — so an existing file wins over retraining, with a
    loud error if its vocabulary cannot serve ``vocab_size``.
    """
    if path and os.path.exists(path):
        tok = BPETokenizer.load(path)
        if tok.vocab_size > vocab_size:
            raise ValueError(
                f"{path} holds {tok.vocab_size} token ids but "
                f"--vocab_size is {vocab_size}; pass --vocab_size "
                f">= {tok.vocab_size} or remove the file to retrain"
            )
        return tok
    tok = train_bpe(data, vocab_size)
    if path:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tok.save(path)
    return tok

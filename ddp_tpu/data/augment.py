"""On-device, jittable train-time augmentation.

The reference applies no augmentation (ToTensor only, data.py:13;
SURVEY.md §2a #6) — enough for MNIST, not for the CIFAR/ImageNet
extension configs where random-crop + horizontal-flip is the standard
recipe behind the accuracy targets. TPU-first placement: augmentation
runs *inside* the jitted train step on the VPU, after the uint8→float
conversion — the host pipeline stays a pure uint8 gather, nothing new
crosses PCIe, and XLA fuses the crop/flip into the step.

All fns share the signature ``fn(rng, images) -> images`` on NHWC
float batches and are deterministic in ``rng`` (replays byte-identically
on resume, like the seed=epoch shuffle).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def random_shift(rng, images, *, pad: int = 2):
    """Random translation by up to ``pad`` pixels (zero fill), no flip.

    The digit-recognition recipe: small translations are
    label-preserving for handwritten digits while horizontal flip is
    not (6↔9, 2↔5). Implemented as zero-pad + per-image random crop
    (``vmap``'d dynamic_slice) — also the crop half of
    ``random_crop_flip``.
    """
    B, H, W, C = images.shape
    padded = jnp.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    offsets = jax.random.randint(rng, (B, 2), 0, 2 * pad + 1)

    def crop(img, off):
        return lax.dynamic_slice(img, (off[0], off[1], 0), (H, W, C))

    return jax.vmap(crop)(padded, offsets)


def random_crop_flip(rng, images, *, pad: int = 4):
    """Zero-pad by ``pad``, random-crop back, random horizontal flip.

    The torchvision ``RandomCrop(padding=4)`` + ``RandomHorizontalFlip``
    recipe (zero padding, like its default), vectorized: per-image
    offsets via ``vmap``'d dynamic_slice.
    """
    r_off, r_flip = jax.random.split(rng)
    images = random_shift(r_off, images, pad=pad)
    flip = jax.random.bernoulli(r_flip, 0.5, (images.shape[0],))
    return jnp.where(flip[:, None, None, None], images[:, :, ::-1, :], images)


def random_flip(rng, images):
    """Horizontal flip only — for inputs where translation hurts."""
    flip = jax.random.bernoulli(rng, 0.5, (images.shape[0],))
    return jnp.where(flip[:, None, None, None], images[:, :, ::-1, :], images)


AUGMENTATIONS = {
    "crop_flip": random_crop_flip,
    "flip": random_flip,
    "shift": random_shift,
}


def get_augmentation(name: str | None):
    """name → fn(rng, images) or None. Raises on unknown names."""
    if name is None or name == "none":
        return None
    if name not in AUGMENTATIONS:
        raise KeyError(
            f"unknown augmentation {name!r}; have {sorted(AUGMENTATIONS)}"
        )
    return AUGMENTATIONS[name]

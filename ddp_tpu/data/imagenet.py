"""ImageNet-1k ingestion: preprocessed-array loader + synthetic fallback.

BASELINE.json config 5 scales the reference's pipeline shape
(/root/reference/data.py) to ImageNet ResNet-50. Full JPEG decode is a
preprocessing concern, not a training-loop one — the TPU-efficient
layout is the dataset as contiguous uint8 NHWC arrays, memory-mapped so
the loader's gather (and the native prefetch pool) reads pages on
demand instead of resident-loading 150 GB. This module therefore:

- loads ``{split}_images.npy`` / ``{split}_labels.npy`` from ``root``
  (written once by any offline preprocessing job; ``np.load(...,
  mmap_mode='r')`` keeps the working set at the touched pages);
- else, when explicitly allowed, generates a deterministic synthetic
  set with ImageNet's exact shapes/dtypes ([N, 224, 224, 3] uint8,
  1000 classes) — class-conditional interference patterns, separable
  enough for convergence smoke tests.
"""

from __future__ import annotations

import os

import numpy as np

from ddp_tpu.data.mnist import Split

IMAGE_SIZE = 224
NUM_CLASSES = 1000


def synthetic(
    num: int,
    *,
    seed: int = 0,
    num_classes: int = NUM_CLASSES,
    side: int = IMAGE_SIZE,
) -> Split:
    """Deterministic ImageNet-shaped synthetic data.

    Per-class plane-wave interference patterns (frequency/phase keyed by
    the label) plus noise — no per-class template bank, so memory stays
    O(batch) even with 1000 classes.
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=num).astype(np.int32)
    yy, xx = np.mgrid[0:side, 0:side].astype(np.float32) / side
    out = np.empty((num, side, side, 3), np.uint8)
    # Chunked, float32-only generation: peak temp memory stays at
    # O(chunk) instead of ~10× the final uint8 array (rng.normal's
    # float64 output alone would double the dataset size).
    chunk = 256
    for lo in range(0, num, chunk):
        lab = labels[lo : lo + chunk]
        fx = (1 + lab % 13).astype(np.float32)[:, None, None]
        fy = (1 + (lab // 13) % 11).astype(np.float32)[:, None, None]
        phase = (lab * 2.618).astype(np.float32)[:, None, None]
        base = np.sin(fx * np.pi * xx[None] + phase) * np.cos(
            fy * np.pi * yy[None]
        )
        img = np.stack(
            [base, np.roll(base, side // 7, axis=1), -base], axis=-1
        )
        img *= 90.0
        img += 128.0
        img += 12.0 * rng.standard_normal(size=img.shape, dtype=np.float32)
        np.clip(img, 0, 255, out=img)
        out[lo : lo + chunk] = img.astype(np.uint8)
    return Split(out, labels)


def load(
    root: str = "./data",
    split: str = "train",
    *,
    allow_synthetic: bool = False,
    synthetic_size: int | None = None,
) -> Split:
    """Load a split as (uint8 NHWC images, int32 labels), mmap-backed."""
    img_path = os.path.join(root, f"imagenet_{split}_images.npy")
    lbl_path = os.path.join(root, f"imagenet_{split}_labels.npy")
    if os.path.exists(img_path) and os.path.exists(lbl_path):
        images = np.load(img_path, mmap_mode="r")
        labels = np.asarray(np.load(lbl_path)).astype(np.int32)
        if images.ndim != 4 or images.dtype != np.uint8:
            raise ValueError(
                f"{img_path}: expected uint8 [N, H, W, C], got "
                f"{images.dtype} {images.shape}"
            )
        if len(images) != len(labels):
            raise ValueError("image/label count mismatch")
        return Split(images, labels)
    if not allow_synthetic:
        raise RuntimeError(
            f"no preprocessed ImageNet arrays under {root!r} "
            f"(need {img_path}); pass allow_synthetic to use the "
            f"deterministic synthetic stand-in"
        )
    n = synthetic_size or (4096 if split == "train" else 1024)
    return synthetic(n, seed=0 if split == "train" else 1)

"""Data layer: dataset readers, deterministic sharding, device loaders.

Replaces the reference's torchvision + DistributedSampler + DataLoader
stack (``data.py``) with a torch-free pipeline: raw IDX/binary readers,
a pure-function shard sampler with exact DistributedSampler semantics,
and a double-buffered device-sharded loader.
"""

from ddp_tpu.data.sampler import ShardSampler  # noqa: F401
from ddp_tpu.data.loader import ShardedLoader, Batch  # noqa: F401
from ddp_tpu.data import mnist  # noqa: F401

"""Deterministic per-process data sharding.

Capability parity with ``torch.utils.data.DistributedSampler`` as the
reference uses it (``data.py:16-19`` with ``shuffle=True``, plus
``sampler.set_epoch(epoch)`` at ``train_ddp.py:193``). The semantics
reproduced exactly (SURVEY.md §2b N10):

- per-epoch reshuffle seeded by ``seed + epoch`` (torch's
  ``g.manual_seed(self.seed + self.epoch)``),
- pad the shuffled index list to a multiple of ``num_shards`` by
  wrapping from its start (torch: ``indices += indices[:pad]``),
- shard ``r`` takes the strided slice ``indices[r::num_shards]``,

so each epoch every sample is seen exactly once (padding duplicates
aside), shards are disjoint, and all shards have equal length. The
permutation itself comes from JAX's threefry PRNG rather than torch's
Mersenne generator — the *semantics* are the contract, not torch's
bitstream.

Unlike the reference this is a pure function of (epoch, shard) — no
mutable ``set_epoch`` state — so it can run inside jit and on device.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np


def rescale_per_shard_batch(
    global_batch: int, num_shards: int, *, grad_accum_steps: int = 1
) -> int:
    """Per-shard batch that preserves ``global_batch`` at the LIVE
    shard count — the elastic-resize half of the shard math.

    The global batch is the optimizer's contract: it defines what one
    step *means* (and therefore what the step counter, the LR schedule
    and steps-per-epoch mean). When an elastic restart changes the
    shard count, the per-shard batch must absorb the change so the
    global batch — and every checkpointed step-counter semantic —
    survives. The shard slicing above makes the rescale exact: shard
    ``r`` of N takes ``indices[r::N]``, so one step's union of
    per-shard slices is the same contiguous window of the global
    permutation at ANY divisor world size — a world-2 step and a
    world-1 step consume identical sample sets in identical order.

    Raises when the preserved global batch cannot tile the new
    topology (indivisible, or below one example per shard) — silently
    changing the global batch would corrupt the run's semantics.
    """
    denom = num_shards * max(1, grad_accum_steps)
    per = global_batch // denom
    if per < 1 or per * denom != global_batch:
        raise ValueError(
            f"elastic resize: global batch {global_batch} cannot be "
            f"preserved over {num_shards} shard(s)"
            + (
                f" x {grad_accum_steps} accumulation steps"
                if grad_accum_steps > 1
                else ""
            )
            + " — it must divide evenly with >= 1 example per shard"
        )
    return per


@dataclasses.dataclass(frozen=True)
class ShardSampler:
    """Index plan for one shard of a dataset across an epoch."""

    num_examples: int
    num_shards: int
    shard_id: int
    shuffle: bool = True
    seed: int = 0

    def __post_init__(self):
        if not 0 <= self.shard_id < self.num_shards:
            raise ValueError(f"shard_id {self.shard_id} not in [0,{self.num_shards})")

    @property
    def total_size(self) -> int:
        """Dataset size padded up to a multiple of num_shards."""
        per = -(-self.num_examples // self.num_shards)  # ceil div
        return per * self.num_shards

    @property
    def shard_size(self) -> int:
        return self.total_size // self.num_shards

    def epoch_indices(self, epoch: int) -> np.ndarray:
        """Global index order for ``epoch`` (before shard slicing)."""
        if self.shuffle:
            # The epoch plan is a deliberate per-epoch device round
            # trip (--sanitize found the implicit spelling): the key
            # upload runs in an explicit allow window — device_put
            # can't replace it, int32 canonicalization would reject
            # the seeds >= 2**31 that key() folds 64-bit — and the
            # readback is an explicit device_get. Bit-identical to
            # the old spelling for every seed (pinned by
            # test_sanitize).
            with jax.transfer_guard("allow"):
                key = jax.random.key(self.seed + epoch)
            perm = jax.device_get(
                jax.random.permutation(key, self.num_examples, independent=False)
            )
        else:
            perm = np.arange(self.num_examples)
        pad = self.total_size - self.num_examples
        if pad:
            perm = np.concatenate([perm, perm[:pad]])
        return perm

    def shard_indices(self, epoch: int) -> np.ndarray:
        """This shard's sample indices for ``epoch`` (strided slice)."""
        return self.epoch_indices(epoch)[self.shard_id :: self.num_shards]

    def num_batches(self, batch_size: int, drop_last: bool = True) -> int:
        if drop_last:
            return self.shard_size // batch_size
        return -(-self.shard_size // batch_size)

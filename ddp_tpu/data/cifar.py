"""CIFAR-10/100 ingestion without torchvision: raw binary reader.

The driver's extension configs (BASELINE.json 3 and 4) swap the
reference's MNIST pipeline (/root/reference/data.py:11-14) for CIFAR.
Same design as ddp_tpu.data.mnist: download-with-mirrors into ``root``
idempotently, parse the raw format directly, keep uint8 NHWC in memory
(normalization happens inside the jitted step), and degrade to a
deterministic synthetic set only when explicitly allowed.

Binary layout (the "-binary" tarballs):
- CIFAR-10: 6 files × 10000 records of [label u8][3072 u8 RGB, CHW].
- CIFAR-100: train/test files, records of [coarse u8][fine u8][3072 u8].
Pixels are stored channel-planar (CHW); we transpose to HWC.
"""

from __future__ import annotations

import os
import tarfile

import numpy as np

from ddp_tpu.data.mnist import Split

_MIRRORS = (
    "https://www.cs.toronto.edu/~kriz/",
    "https://ossci-datasets.s3.amazonaws.com/",
)
_TARS = {
    "cifar10": "cifar-10-binary.tar.gz",
    "cifar100": "cifar-100-binary.tar.gz",
}
_TRAIN_FILES = {
    "cifar10": [f"cifar-10-batches-bin/data_batch_{i}.bin" for i in range(1, 6)],
    "cifar100": ["cifar-100-binary/train.bin"],
}
_TEST_FILES = {
    "cifar10": ["cifar-10-batches-bin/test_batch.bin"],
    "cifar100": ["cifar-100-binary/test.bin"],
}


def _fetch_tar(root: str, name: str) -> str:
    fname = _TARS[name]
    path = os.path.join(root, fname)
    if os.path.exists(path):
        return path
    os.makedirs(root, exist_ok=True)
    # Mirror rotation with per-mirror bounded jittered retry
    # (data/fetch.py) — transient mirror failures recover; offline
    # (DNS) fails fast.
    from ddp_tpu.data.fetch import fetch_from_mirrors

    return fetch_from_mirrors(_MIRRORS, fname, path)


def parse_records(raw: bytes, *, name: str) -> Split:
    """Decode one binary batch file into (uint8 NHWC images, labels).

    Native (C++) decode first — the CHW→HWC transpose runs in
    dataio.cpp without a numpy strided-copy pass — Python fallback
    otherwise (only when a cached native build exists; see
    native.available(build=False)).
    """
    label_bytes = 1 if name == "cifar10" else 2  # cifar100: coarse+fine
    record = label_bytes + 3072
    if len(raw) % record:
        raise ValueError(f"{name} batch size {len(raw)} not a multiple of {record}")
    from ddp_tpu import native

    if native.available(build=False):
        images, labels = native.cifar_decode(raw, label_bytes)
        return Split(images, labels)
    arr = np.frombuffer(raw, np.uint8).reshape(-1, record)
    labels = arr[:, label_bytes - 1].astype(np.int32)  # fine label for cifar100
    images = (
        arr[:, label_bytes:]
        .reshape(-1, 3, 32, 32)  # CHW planar
        .transpose(0, 2, 3, 1)  # → NHWC
    )
    return Split(np.ascontiguousarray(images), labels)


def _load_split(root: str, name: str, split: str) -> Split:
    members = (_TRAIN_FILES if split == "train" else _TEST_FILES)[name]
    for attempt in range(2):
        tar_path = _fetch_tar(root, name)
        parts: list[Split] = []
        try:
            with tarfile.open(tar_path, "r:gz") as tf:
                for member in members:
                    raw = tf.extractfile(member).read()  # type: ignore[union-attr]
                    parts.append(parse_records(raw, name=name))
        except (tarfile.TarError, EOFError, KeyError):
            # Corrupt cache (truncated download, mirror error page):
            # drop it so _fetch_tar re-downloads instead of failing on
            # the same bad bytes forever; one retry, then propagate.
            os.remove(tar_path)
            if attempt:
                raise
            continue
        return Split(
            np.concatenate([p.images for p in parts]),
            np.concatenate([p.labels for p in parts]),
        )
    raise AssertionError("unreachable")


def synthetic(num: int, *, seed: int = 0, num_classes: int = 10) -> Split:
    """Deterministic CIFAR-shaped synthetic data (offline fallback)."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 32
    templates = np.stack(
        [
            np.stack(
                [
                    np.sin((c + 2) * np.pi * xx + ch) * np.cos((c % 5 + 1) * np.pi * yy)
                    for ch in range(3)
                ],
                axis=-1,
            )
            for c in range(num_classes)
        ]
    )  # [C, 32, 32, 3] in [-1, 1]
    labels = rng.integers(0, num_classes, size=num).astype(np.int32)
    base = (templates[labels] * 0.5 + 0.5) * 200.0
    noise = rng.normal(0.0, 20.0, size=base.shape)
    images = np.clip(base + noise, 0, 255).astype(np.uint8)
    return Split(images, labels)


def load(
    root: str = "./data",
    split: str = "train",
    *,
    name: str = "cifar10",
    allow_synthetic: bool = False,
    synthetic_size: int | None = None,
) -> Split:
    try:
        return _load_split(root, name, split)
    except (RuntimeError, OSError, ValueError, KeyError, tarfile.TarError, EOFError) as e:
        if isinstance(e, KeyError) and name not in _TARS:
            raise
        if not allow_synthetic:
            raise
        n = synthetic_size or (50_000 if split == "train" else 10_000)
        return synthetic(
            n,
            seed=0 if split == "train" else 1,
            num_classes=10 if name == "cifar10" else 100,
        )

"""Synthetic long-sequence classification data (offline, deterministic).

The reference has no sequence data at all (SURVEY.md §5 long-context:
absent — 28×28 images only); this supplies the input for the
long-context trainer path (``--model long_context``): each class is a
characteristic temporal frequency pattern projected into ``d_in``
feature channels, plus noise — separable enough that a converging
trainer is measurable, long enough that sequence parallelism is
actually exercised.

Shapes mirror the image pipeline's contract (first dim = sample) so
``ShardedLoader`` and the eval loop work unchanged: features are
``[N, T, d_in]`` float32, labels ``[N]`` int32.
"""

from __future__ import annotations

import numpy as np

from ddp_tpu.data.mnist import Split


def synthetic(
    num: int,
    *,
    total_len: int = 2048,
    d_in: int = 16,
    num_classes: int = 10,
    seed: int = 0,
) -> Split:
    rng = np.random.default_rng(seed)
    t = np.linspace(0.0, 1.0, total_len, dtype=np.float32)
    # Class templates come from a FIXED generator, independent of the
    # split seed: train and test must agree on what a class looks like
    # (seed only varies the samples drawn from those classes).
    template_rng = np.random.default_rng(0xC1A55)
    mixes = template_rng.normal(size=(num_classes, d_in)).astype(np.float32)
    biases = template_rng.normal(size=(num_classes, d_in)).astype(np.float32)
    labels = rng.integers(0, num_classes, size=num).astype(np.int32)
    waves = np.sin(
        2.0 * np.pi * (labels[:, None] + 1.0) * t[None, :]
        + rng.uniform(0, 2 * np.pi, size=(num, 1)).astype(np.float32)
    ).astype(np.float32)  # [N, T]
    x = waves[:, :, None] * mixes[labels][:, None, :]  # [N, T, d]
    # A per-class constant channel bias: the sin component has zero
    # time-mean, so without this a mean-pooling head must first learn
    # frequency features before ANY signal appears — fine for research,
    # terrible for a 2-epoch smoke run. The bias makes short demos
    # converge while the frequency structure still rewards attention.
    x += 0.5 * biases[labels][:, None, :]
    x += rng.normal(0.0, 0.3, size=x.shape).astype(np.float32)
    return Split(x.astype(np.float32), labels)


def synthetic_tokens(
    num: int,
    *,
    total_len: int = 2048,
    vocab_size: int = 64,
    seed: int = 0,
) -> np.ndarray:
    """Deterministic token streams for causal-LM training: arithmetic
    progressions ``(start + stride·t) mod V`` with per-sample start and
    stride. After two tokens the continuation is fully determined, so a
    working attention/LM path drives next-token accuracy toward 1 —
    and a broken causal mask (peeking at the future) shows up as
    suspiciously instant perfection. Returns ``[num, total_len]`` int32.
    """
    rng = np.random.default_rng(seed)
    strides = np.asarray([1, 2, 3, 5, 7])
    start = rng.integers(0, vocab_size, size=(num, 1))
    stride = strides[rng.integers(0, len(strides), size=(num, 1))]
    t = np.arange(total_len)[None, :]
    return ((start + stride * t) % vocab_size).astype(np.int32)

"""Shared dataset-download helper: bounded, jittered retry.

A transient mirror failure (HTTP 5xx, a reset connection, a truncated
body) used to kill a training run on first touch of the dataset —
the single most avoidable failure in a fresh container. Downloads now
retry with bounded exponential backoff and deterministic jitter
(seeded per URL, so retry timing cannot synchronize a fleet of
workers into a thundering herd against the same mirror).

Only failures that another attempt could plausibly fix are retried.
DNS resolution failure (``socket.gaierror``), refused connections and
unreachable networks fail FAST — they mean "offline" or "mirror
gone", and retrying them would stall every offline run (the synthetic
fallback path constructs a Trainer in seconds precisely because these
fail immediately). Callers keep their own mirror rotation; this
module makes each mirror attempt robust, not the mirror list.
"""

from __future__ import annotations

import http.client
import os
import random
import socket
import time
import urllib.error
import urllib.request
import zlib
from typing import Callable

DEFAULT_ATTEMPTS = 3
DEFAULT_BASE_DELAY_S = 0.5
DEFAULT_MAX_DELAY_S = 8.0
DEFAULT_JITTER = 0.25  # ± fraction of the backoff delay


def is_transient(exc: BaseException) -> bool:
    """Could a retry plausibly succeed?

    Transient: HTTP 5xx / 408 / 429, truncated bodies, timeouts,
    reset/broken connections. NOT transient: 4xx client errors, DNS
    failure, refused/unreachable networks — those are configuration
    or offline conditions a 2-second backoff cannot fix.
    """
    if isinstance(exc, urllib.error.ContentTooShortError):
        return True  # truncated body — the canonical torn download
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500 or exc.code in (408, 429)
    if isinstance(exc, http.client.IncompleteRead):
        return True
    reason = getattr(exc, "reason", exc)
    if isinstance(reason, socket.gaierror):
        return False  # no DNS — offline, fail fast to the fallback
    if isinstance(
        reason, (ConnectionRefusedError, OSError)
    ) and getattr(reason, "errno", None) in (
        101,  # ENETUNREACH
        111,  # ECONNREFUSED
        113,  # EHOSTUNREACH
    ):
        return False
    if isinstance(
        reason,
        (socket.timeout, TimeoutError, ConnectionResetError, BrokenPipeError),
    ):
        return True
    # Remaining URLError/OSError: unknown cause — one retry is cheap
    # relative to losing the run.
    return isinstance(exc, (urllib.error.URLError, OSError))


def backoff_delays(
    url: str,
    attempts: int = DEFAULT_ATTEMPTS,
    *,
    base_delay: float = DEFAULT_BASE_DELAY_S,
    max_delay: float = DEFAULT_MAX_DELAY_S,
    jitter: float = DEFAULT_JITTER,
    salt: int | None = None,
) -> list[float]:
    """The (attempts - 1) sleep durations between retries of ``url``.

    Deterministic within a process: jitter is seeded from the URL plus
    a per-process ``salt`` (default: the pid), so one worker's
    schedule is reproducible while different files — and different
    WORKERS fetching the same file — desynchronize instead of
    retrying in lockstep against the same mirror. Bounded by
    ``(1 + jitter) * max_delay`` per gap by construction.
    """
    if salt is None:
        salt = os.getpid()
    rng = random.Random(zlib.crc32(url.encode()) ^ salt)
    delays = []
    for i in range(max(0, attempts - 1)):
        d = min(max_delay, base_delay * (2.0 ** i))
        delays.append(max(0.0, d * (1.0 + jitter * rng.uniform(-1.0, 1.0))))
    return delays


def fetch_with_retry(
    url: str,
    dest: str,
    *,
    attempts: int = DEFAULT_ATTEMPTS,
    base_delay: float = DEFAULT_BASE_DELAY_S,
    max_delay: float = DEFAULT_MAX_DELAY_S,
    jitter: float = DEFAULT_JITTER,
    retrieve: Callable[[str, str], object] = urllib.request.urlretrieve,
    sleep: Callable[[float], None] = time.sleep,
) -> str:
    """Download ``url`` → ``dest`` atomically, retrying transient
    failures up to ``attempts`` times with jittered exponential
    backoff. Raises the last error (non-transient errors raise
    immediately). ``retrieve``/``sleep`` are injectable for tests.
    """
    delays = backoff_delays(
        url, attempts,
        base_delay=base_delay, max_delay=max_delay, jitter=jitter,
    )
    tmp = dest + ".part"
    last: BaseException | None = None
    for attempt in range(max(1, attempts)):
        try:
            retrieve(url, tmp)
            os.replace(tmp, dest)
            return dest
        except (urllib.error.URLError, OSError, http.client.HTTPException) as e:
            last = e
            try:  # never leave a torn .part for the next attempt
                os.remove(tmp)
            except OSError:
                pass
            if not is_transient(e) or attempt >= len(delays):
                raise
            sleep(delays[attempt])
    raise last  # pragma: no cover — loop always returns or raises


def fetch_from_mirrors(
    mirrors,
    fname: str,
    dest: str,
    *,
    attempts: int = DEFAULT_ATTEMPTS,
) -> str:
    """Mirror rotation over ``fetch_with_retry`` (the shared loader
    loop — MNIST and CIFAR must not drift on which exceptions rotate
    to the next mirror). Note ``http.client.HTTPException`` (e.g.
    IncompleteRead) is not an OSError — missing it would abandon the
    remaining mirrors. Raises RuntimeError naming the last error when
    every mirror fails."""
    last_err: BaseException | None = None
    for mirror in mirrors:
        try:
            return fetch_with_retry(mirror + fname, dest, attempts=attempts)
        except (
            urllib.error.URLError, OSError, http.client.HTTPException
        ) as e:
            last_err = e
    raise RuntimeError(
        f"could not download {fname} from any mirror: {last_err}"
    )

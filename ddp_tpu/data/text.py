"""Byte-level text corpus for the causal LM — real data, no tokenizer.

The reference trains on images only (/root/reference/data.py); round 1
gave the LM nothing but synthetic token streams (VERDICT.md "do this"
#3: "add one real text dataset — byte-level corpus file is enough").
This reads ANY file as a uint8 byte stream and chunks it into fixed-
length training sequences: vocab = 256 raw bytes, zero external
dependencies, zero egress.

Chunking is non-overlapping (the standard LM epoch layout); the
train/test split cuts by SEQUENCE index after chunking, so the test
tail never leaks into training windows.
"""

from __future__ import annotations

import numpy as np

from ddp_tpu.data.mnist import Split


def load_text_corpus(
    path: str,
    seq_len: int,
    *,
    vocab_size: int = 256,
    test_fraction: float = 0.1,
) -> tuple[Split, Split]:
    """File of bytes → (train, test) Splits of [N, seq_len] int32 tokens.

    ``vocab_size`` must cover every byte present (≥ 256 always works;
    smaller vocabularies are validated so an out-of-range byte fails
    here, not as a garbage embedding lookup). Labels are zeros — the
    LM's targets are the shifted tokens themselves (models/lm.py).
    """
    data = np.fromfile(path, dtype=np.uint8)
    n_seq = len(data) // seq_len
    if n_seq < 2:
        raise ValueError(
            f"{path}: {len(data)} bytes yield {n_seq} sequences of "
            f"length {seq_len}; need at least 2 (shrink --seq_len?)"
        )
    if vocab_size < 256:
        hi = int(data.max())
        if hi >= vocab_size:
            raise ValueError(
                f"{path} contains byte {hi} ≥ --vocab_size {vocab_size}; "
                "use --vocab_size 256 for arbitrary files"
            )
    tokens = (
        data[: n_seq * seq_len].reshape(n_seq, seq_len).astype(np.int32)
    )
    n_test = max(1, int(n_seq * test_fraction))
    n_train = n_seq - n_test
    if n_train < 1:
        raise ValueError(f"{path}: corpus too small to split ({n_seq} seqs)")
    mk = lambda t: Split(t, np.zeros(len(t), np.int32))
    return mk(tokens[:n_train]), mk(tokens[n_train:])

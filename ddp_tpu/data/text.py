"""Text corpus for the causal LM — byte-level or BPE-subword.

The reference trains on images only (/root/reference/data.py); round 1
gave the LM nothing but synthetic token streams, round 2 added this
byte-level reader (vocab = 256 raw bytes, zero external dependencies,
zero egress), and round 3 grew the subword path: ``vocab_size > 256``
trains a self-contained BPE tokenizer on the corpus (data/bpe.py),
persists it alongside the checkpoints, and feeds the LM subword ids —
the dataset-ingestion equivalence axis (/root/reference/data.py:11-14)
at a real LM vocabulary.

Chunking is non-overlapping (the standard LM epoch layout); the
train/test split cuts by SEQUENCE index after chunking, so the test
tail never leaks into training windows. The BPE vocabulary is trained
on the leading train fraction of the BYTE stream for the same reason.
"""

from __future__ import annotations

import numpy as np

from ddp_tpu.data.mnist import Split


def load_text_corpus(
    path: str,
    seq_len: int,
    *,
    vocab_size: int = 256,
    test_fraction: float = 0.1,
    tokenizer_path: str | None = None,
) -> tuple[Split, Split]:
    """File of text → (train, test) Splits of [N, seq_len] int32 tokens.

    ``vocab_size ≤ 256``: raw bytes (values validated against the
    vocabulary so an out-of-range byte fails here, not as a garbage
    embedding lookup). ``vocab_size > 256``: BPE — an existing
    ``tokenizer_path`` file is reused (it is part of the model), else
    one is trained on the train fraction and saved there. Labels are
    zeros — the LM's targets are the shifted tokens themselves
    (models/lm.py).
    """
    data = np.fromfile(path, dtype=np.uint8)
    if vocab_size > 256:
        from ddp_tpu.data.bpe import load_or_train

        n_train_bytes = len(data) - max(1, int(len(data) * test_fraction))
        tok = load_or_train(
            tokenizer_path, data[:n_train_bytes].tobytes(), vocab_size
        )
        data = tok.encode(data.tobytes())
    elif vocab_size < 256:
        hi = int(data.max())
        if hi >= vocab_size:
            raise ValueError(
                f"{path} contains byte {hi} ≥ --vocab_size {vocab_size}; "
                "use --vocab_size 256 for arbitrary files"
            )
    n_seq = len(data) // seq_len
    if n_seq < 2:
        raise ValueError(
            f"{path}: {len(data)} tokens yield {n_seq} sequences of "
            f"length {seq_len}; need at least 2 (shrink --seq_len?)"
        )
    tokens = (
        np.asarray(data[: n_seq * seq_len])
        .reshape(n_seq, seq_len)
        .astype(np.int32)
    )
    n_test = max(1, int(n_seq * test_fraction))
    n_train = n_seq - n_test
    if n_train < 1:
        raise ValueError(f"{path}: corpus too small to split ({n_seq} seqs)")
    mk = lambda t: Split(t, np.zeros(len(t), np.int32))
    return mk(tokens[:n_train]), mk(tokens[n_train:])

"""MNIST ingestion without torchvision: raw IDX reader + offline fallback.

Capability parity with ``data.py:11-14`` (``datasets.MNIST(root='./data',
download=True, transform=ToTensor())``):

- download the four IDX gz files into ``root`` (with mirror fallback),
  idempotently — a cached copy is used without touching the network,
  like torchvision's ``download=True``;
- parse the IDX format directly (magic, dims, uint8 payload);
- normalization matches ``ToTensor()`` exactly: uint8 → float / 255,
  **no mean/std normalization** (SURVEY.md §2a #6). Scaling is deferred
  to the (jitted) train step so the dataset stays uint8 in memory —
  4× less HBM and host→device traffic than eager fp32.

When the machine has no network and no cache, ``load(...,
allow_synthetic=True)`` degrades to a deterministic synthetic set with
MNIST's exact shapes/dtypes — class-conditional blob templates plus
noise, separable enough that convergence tests are meaningful.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import NamedTuple

import numpy as np

# The IDX file family is shared by MNIST's drop-in siblings; variants
# differ only in mirror URLs (and cache subdirectory). All are 28×28
# grayscale, 10 classes, 60k/10k splits.
_VARIANT_MIRRORS = {
    "mnist": (
        "https://storage.googleapis.com/cvdf-datasets/mnist/",
        "https://ossci-datasets.s3.amazonaws.com/mnist/",
        "http://yann.lecun.com/exdb/mnist/",
    ),
    "fashion_mnist": (
        "http://fashion-mnist.s3-website.eu-central-1.amazonaws.com/",
        "https://storage.googleapis.com/tensorflow/tf-keras-datasets/",
    ),
    "kmnist": (
        "http://codh.rois.ac.jp/kmnist/dataset/kmnist/",
    ),
    # Vendored-only (no mirrors): real UCI handwritten-digit scans
    # re-packaged into the MNIST IDX container by
    # scripts/vendor_uci_digits.py and committed under data/uci_digits/
    # — the real-data convergence proof for zero-egress environments.
    "uci_digits": (),
}
_FILES = {
    "train_images": "train-images-idx3-ubyte.gz",
    "train_labels": "train-labels-idx1-ubyte.gz",
    "test_images": "t10k-images-idx3-ubyte.gz",
    "test_labels": "t10k-labels-idx1-ubyte.gz",
}


class Split(NamedTuple):
    images: np.ndarray  # [N, 28, 28, 1] uint8 (NHWC)
    labels: np.ndarray  # [N] int32


def parse_idx(raw: bytes) -> np.ndarray:
    """Parse one IDX-format buffer (images or labels).

    Format: 2 zero bytes, dtype code, ndim, then ndim big-endian uint32
    dims, then the payload.
    """
    if len(raw) < 4:
        raise ValueError("truncated IDX header")
    zero, dtype_code, ndim = raw[0] << 8 | raw[1], raw[2], raw[3]
    if zero != 0:
        raise ValueError(f"bad IDX magic prefix {raw[:2]!r}")
    dtypes = {
        0x08: np.uint8,
        0x09: np.int8,
        0x0B: np.dtype(">i2"),
        0x0C: np.dtype(">i4"),
        0x0D: np.dtype(">f4"),
        0x0E: np.dtype(">f8"),
    }
    if dtype_code not in dtypes:
        raise ValueError(f"bad IDX dtype code {dtype_code:#x}")
    header_end = 4 + 4 * ndim
    dims = struct.unpack(f">{ndim}I", raw[4:header_end])
    arr = np.frombuffer(raw, dtype=dtypes[dtype_code], offset=header_end)
    expected = int(np.prod(dims)) if ndim else 0
    if arr.size != expected:
        raise ValueError(f"IDX payload size {arr.size} != {expected} for dims {dims}")
    return arr.reshape(dims)


def _fetch(root: str, fname: str, variant: str = "mnist") -> str:
    # MNIST keeps the flat ``root`` layout (parity with data.py:11 and
    # existing caches); siblings get a subdirectory since the file
    # names collide across variants.
    base = root if variant == "mnist" else os.path.join(root, variant)
    path = os.path.join(base, fname)
    if os.path.exists(path):
        return path
    if not _VARIANT_MIRRORS[variant]:
        raise RuntimeError(
            f"{variant!r} is vendored-only ({fname} not found under "
            f"{base}); run scripts/vendor_uci_digits.py or point "
            "--data_root at a checkout that committed data/uci_digits/"
        )
    os.makedirs(base, exist_ok=True)
    # Mirror rotation with per-mirror bounded jittered retry
    # (data/fetch.py): a transient mirror hiccup no longer kills the
    # run on first touch; genuinely-offline failures (DNS) still fail
    # fast so the synthetic fallback stays instant.
    from ddp_tpu.data.fetch import fetch_from_mirrors

    return fetch_from_mirrors(_VARIANT_MIRRORS[variant], fname, path)


def _read_idx_file(path: str) -> np.ndarray:
    """Decode one IDX file, native (C++) decoder first, Python fallback."""
    from ddp_tpu import native

    if native.available(build=False):
        return native.read_idx(path)
    return parse_idx(gzip.decompress(open(path, "rb").read()))


def _load_pair(root: str, split: str, variant: str = "mnist") -> Split:
    images = _read_idx_file(_fetch(root, _FILES[f"{split}_images"], variant))[
        ..., None
    ]
    labels = _read_idx_file(
        _fetch(root, _FILES[f"{split}_labels"], variant)
    ).astype(np.int32)
    if images.shape[0] != labels.shape[0]:
        raise ValueError("image/label count mismatch")
    return Split(np.ascontiguousarray(images), labels)


def synthetic(
    num: int, *, seed: int = 0, num_classes: int = 10, side: int = 28
) -> Split:
    """Deterministic MNIST-shaped synthetic data (offline fallback).

    Each class gets a fixed smooth template; samples are the template
    plus pixel noise and a random shift — linearly separable enough to
    train on, hard enough that accuracy is not trivially 100%.
    """
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:side, 0:side].astype(np.float32) / side
    templates = np.stack(
        [
            np.sin((c + 2) * np.pi * xx + c) * np.cos((c % 4 + 1) * np.pi * yy)
            for c in range(num_classes)
        ]
    )  # [C, H, W] in [-1, 1]
    labels = rng.integers(0, num_classes, size=num).astype(np.int32)
    base = (templates[labels] * 0.5 + 0.5) * 200.0
    noise = rng.normal(0.0, 20.0, size=base.shape)
    images = np.clip(base + noise, 0, 255).astype(np.uint8)[..., None]
    return Split(images, labels)


def load(
    root: str = "./data",
    split: str = "train",
    *,
    variant: str = "mnist",
    allow_synthetic: bool = False,
    synthetic_size: int | None = None,
) -> Split:
    """Load an MNIST-family split as (uint8 NHWC images, int32 labels).

    ``variant`` selects the sibling dataset (mnist | fashion_mnist |
    kmnist — same IDX container, different bytes). ``allow_synthetic``
    gates the offline fallback so accidental network failure can't
    silently swap datasets in a real run.
    """
    if variant not in _VARIANT_MIRRORS:
        raise KeyError(
            f"unknown variant {variant!r}; have {sorted(_VARIANT_MIRRORS)}"
        )
    try:
        return _load_pair(root, split, variant)
    except (RuntimeError, OSError, ValueError):
        if not allow_synthetic:
            raise
        n = synthetic_size or (60_000 if split == "train" else 10_000)
        return synthetic(n, seed=0 if split == "train" else 1)

"""Dataset registry: name → (train split, test split) loaders."""

from __future__ import annotations

from typing import Callable

from ddp_tpu.data.mnist import Split

_LOADERS: dict[str, Callable[..., tuple[Split, Split]]] = {}


def register(name: str):
    def deco(fn):
        _LOADERS[name] = fn
        return fn

    return deco


def load_dataset(
    name: str,
    root: str = "./data",
    *,
    allow_synthetic: bool = False,
    synthetic_size: int | None = None,
) -> tuple[Split, Split]:
    if name not in _LOADERS:
        raise KeyError(f"unknown dataset {name!r}; have {sorted(_LOADERS)}")
    return _LOADERS[name](
        root, allow_synthetic=allow_synthetic, synthetic_size=synthetic_size
    )


def _mnist_family(variant):
    def loader(root, *, allow_synthetic, synthetic_size):
        from ddp_tpu.data import mnist

        train = mnist.load(
            root, "train", variant=variant,
            allow_synthetic=allow_synthetic, synthetic_size=synthetic_size,
        )
        test = mnist.load(
            root,
            "test",
            variant=variant,
            allow_synthetic=allow_synthetic,
            synthetic_size=(
                max(1, synthetic_size // 6) if synthetic_size else None
            ),
        )
        return train, test

    return loader


register("mnist")(_mnist_family("mnist"))
register("fashion_mnist")(_mnist_family("fashion_mnist"))
register("kmnist")(_mnist_family("kmnist"))


def _cifar(name):
    def loader(root, *, allow_synthetic, synthetic_size):
        from ddp_tpu.data import cifar

        train = cifar.load(
            root,
            "train",
            name=name,
            allow_synthetic=allow_synthetic,
            synthetic_size=synthetic_size,
        )
        test = cifar.load(
            root,
            "test",
            name=name,
            allow_synthetic=allow_synthetic,
            synthetic_size=(max(1, synthetic_size // 5) if synthetic_size else None),
        )
        return train, test

    return loader


register("cifar10")(_cifar("cifar10"))
register("cifar100")(_cifar("cifar100"))


@register("imagenet")
def _imagenet(root, *, allow_synthetic, synthetic_size):
    from ddp_tpu.data import imagenet

    train = imagenet.load(
        root, "train", allow_synthetic=allow_synthetic,
        synthetic_size=synthetic_size,
    )
    test = imagenet.load(
        root,
        "test",
        allow_synthetic=allow_synthetic,
        synthetic_size=(max(1, synthetic_size // 4) if synthetic_size else None),
    )
    return train, test


def load_split(
    name: str,
    root: str,
    split: str,
    *,
    allow_synthetic: bool = False,
    synthetic_size: int | None = None,
):
    """Load ONE split — inference tooling must not pay for (or
    download) the train split just to evaluate the test set."""
    kw = dict(allow_synthetic=allow_synthetic, synthetic_size=synthetic_size)
    if name in ("mnist", "fashion_mnist", "kmnist"):
        from ddp_tpu.data import mnist

        return mnist.load(root, split, variant=name, **kw)
    if name in ("cifar10", "cifar100"):
        from ddp_tpu.data import cifar

        return cifar.load(root, split, name=name, **kw)
    if name == "imagenet":
        from ddp_tpu.data import imagenet

        return imagenet.load(root, split, **kw)
    raise KeyError(f"unknown dataset {name!r}; have {sorted(_LOADERS)}")


NUM_CLASSES = {
    "mnist": 10,
    "fashion_mnist": 10,
    "kmnist": 10,
    "cifar10": 10,
    "cifar100": 100,
    "imagenet": 1000,
}

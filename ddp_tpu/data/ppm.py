"""Raw-image decode without any imaging dependency: binary PPM/PGM.

The ImageNet ingest (scripts/preprocess_imagenet.py) decodes JPEG/PNG
through PIL when it is installed — but the framework must be able to
start from raw images with NOTHING beyond numpy (VERDICT.md round-1
"do this" #6: "a raw-JPEG (or PPM) decode path ... so
preprocess_imagenet can start from images, not arrays"). Binary
PPM (P6, RGB) and PGM (P5, grayscale) are the classic zero-dependency
interchange formats every image tool can emit (``convert x.jpg
x.ppm``). Decode order: the native C++ reader (native/dataio.cpp
``dt_ppm_read``) when the toolchain is available, else the pure-Python
parser below — both pinned equal by tests/test_ppm.py.

``resize_bilinear`` + ``center_crop`` supply the preprocessing the PIL
path gets from ``Image.resize``/``crop``, in plain numpy.
"""

from __future__ import annotations

import numpy as np


def parse_ppm(raw: bytes) -> np.ndarray:
    """Binary PPM (P6) / PGM (P5) bytes → uint8 [H, W, C] array.

    Header: magic, then width/height/maxval separated by whitespace
    and ``#`` comments, then ONE whitespace byte, then the payload.
    maxval must fit a byte (the 16-bit variant is not accepted).
    """
    if len(raw) < 2 or raw[:1] != b"P" or raw[1:2] not in (b"5", b"6"):
        raise ValueError("not a binary PPM/PGM (magic P5/P6)")
    channels = 3 if raw[1:2] == b"6" else 1
    pos = 2
    fields = []
    while len(fields) < 3:
        while pos < len(raw) and raw[pos : pos + 1].isspace():
            pos += 1
        if pos < len(raw) and raw[pos : pos + 1] == b"#":
            while pos < len(raw) and raw[pos : pos + 1] != b"\n":
                pos += 1
            continue
        start = pos
        while pos < len(raw) and raw[pos : pos + 1].isdigit():
            pos += 1
        if start == pos:
            raise ValueError("malformed PPM header")
        fields.append(int(raw[start:pos]))
    if pos >= len(raw) or not raw[pos : pos + 1].isspace():
        raise ValueError("malformed PPM header (no payload separator)")
    pos += 1
    w, h, maxval = fields
    if w <= 0 or h <= 0 or not 0 < maxval <= 255:
        raise ValueError(f"unsupported PPM dims/maxval {fields}")
    n = h * w * channels
    payload = raw[pos : pos + n]
    if len(payload) < n:
        raise ValueError(f"truncated PPM payload: {len(payload)} < {n}")
    # copy(): a writable array, matching the native path's contract.
    return np.frombuffer(payload, np.uint8).reshape(h, w, channels).copy()


def read_ppm(path: str) -> np.ndarray:
    """Decode a PPM/PGM file → uint8 [H, W, C]; native fast path.

    On a host without the full framework environment (importing
    ``ddp_tpu`` pulls jax), the native binding is unreachable — the
    pure-Python parser serves alone, keeping this path numpy-only.
    """
    try:
        from ddp_tpu import native

        if native.available(build=False):
            return native.read_ppm(path)
    except Exception:  # jax-free host or native decode failure
        pass
    with open(path, "rb") as f:
        return parse_ppm(f.read())


def resize_bilinear(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """uint8 [H, W, C] → uint8 [out_h, out_w, C], bilinear, pixel-center
    aligned (the standard image-resize convention)."""
    h, w = img.shape[:2]
    y = np.clip((np.arange(out_h) + 0.5) * h / out_h - 0.5, 0, h - 1)
    x = np.clip((np.arange(out_w) + 0.5) * w / out_w - 0.5, 0, w - 1)
    y0 = np.floor(y).astype(np.int64)
    x0 = np.floor(x).astype(np.int64)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (y - y0).astype(np.float32)[:, None, None]
    wx = (x - x0).astype(np.float32)[None, :, None]
    f = img.astype(np.float32)
    top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
    bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return np.clip(np.rint(out), 0, 255).astype(np.uint8)


def center_crop(img: np.ndarray, size: int) -> np.ndarray:
    h, w = img.shape[:2]
    top, left = (h - size) // 2, (w - size) // 2
    return img[top : top + size, left : left + size]


def decode_resized(path: str, resize: int, size: int) -> np.ndarray:
    """PPM/PGM file → [size, size, 3] uint8: shorter side to ``resize``,
    center-crop ``size`` — the same recipe as the PIL decode path."""
    img = read_ppm(path)
    if img.shape[2] == 1:  # grayscale → RGB
        img = np.repeat(img, 3, axis=2)
    h, w = img.shape[:2]
    scale = resize / min(w, h)
    img = resize_bilinear(
        img, max(size, round(h * scale)), max(size, round(w * scale))
    )
    return center_crop(img, size)

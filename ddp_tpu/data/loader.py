"""Input pipeline: per-process sharded batching with device prefetch.

Capability parity with ``data.py:21-25`` (``DataLoader(num_workers=2,
pin_memory=True)`` over a ``DistributedSampler``), redesigned for the
TPU execution model:

- The reference overlaps host decode with compute via worker
  subprocesses and pins host memory for async H2D copies. Here the
  equivalent is double-buffered ``jax.device_put``: batch ``i+1`` is
  dispatched to the devices while batch ``i``'s step runs — JAX
  transfers are async, so one Python thread suffices where torch needs
  a worker pool.
- Each *process* materializes only its shard (``ShardSampler`` with
  ``num_shards = process_count``); the global array is assembled from
  process-local shards with ``make_array_from_process_local_data``, so
  no host ever holds the global batch — this is what makes the same
  loader multi-host-correct where the reference's per-rank DataLoader
  pattern is.
- uint8 images travel to the device; the float conversion (ToTensor's
  /255) happens inside the jitted step on the MXU-adjacent VPU, saving
  4× host→device bandwidth.
"""

from __future__ import annotations

from typing import Iterator, NamedTuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.data.sampler import ShardSampler
from ddp_tpu.runtime.mesh import data_axes


class Batch(NamedTuple):
    images: jax.Array  # [B, H, W, C] uint8, sharded over the data axes
    labels: jax.Array  # [B] int32, sharded over the data axes


class ShardedLoader:
    """Deterministic, epoch-reshuffled, device-sharded batch stream."""

    # Below this many bytes per local batch the native worker pool is
    # auto-disabled — the handoff overhead exceeds the gather it
    # offloads (bench.py loader micro-bench: MNIST-sized rows lose,
    # ImageNet-sized rows win).
    POOL_MIN_BATCH_BYTES = 1 << 20

    @classmethod
    def pool_would_engage(cls, batch_bytes: int) -> bool:
        """The native-pool gate: big-enough batches AND a spare core.

        Single source of the policy — the loader consults it at
        construction and bench.py reports it alongside the loader
        micro-bench so the recorded context cannot drift from the
        code.
        """
        import os

        return (
            batch_bytes >= cls.POOL_MIN_BATCH_BYTES
            and (os.cpu_count() or 1) >= 2
        )

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        mesh: Mesh,
        global_batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        num_workers: int = 0,
    ):
        self.mesh = mesh
        self.global_batch_size = global_batch_size
        procs = jax.process_count()
        if global_batch_size % procs:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by {procs} processes"
            )
        shard_count = int(np.prod([mesh.shape[a] for a in data_axes(mesh)]))
        if global_batch_size % shard_count:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"{shard_count} data-parallel shards"
            )
        self.local_batch_size = global_batch_size // procs
        spec = P(data_axes(self.mesh))
        self._img_sharding = NamedSharding(mesh, spec)
        self._lbl_sharding = NamedSharding(mesh, spec)
        if procs > 1:
            # Each process materializes a DISJOINT contiguous sample
            # shard. That is only well-defined when every device's
            # batch slice lies inside its own process's block —
            # otherwise the assembled array would hold
            # replicated-but-different blocks (e.g. a non-data axis
            # like pipe spanning the processes while batch blocks
            # replicate across it).
            shape = (global_batch_size, *images.shape[1:])
            for dev, idx in self._img_sharding.devices_indices_map(
                shape
            ).items():
                sl = idx[0]
                lo = 0 if sl.start is None else sl.start
                hi = global_batch_size if sl.stop is None else sl.stop
                p = dev.process_index
                if lo < p * self.local_batch_size or hi > (p + 1) * self.local_batch_size:
                    raise ValueError(
                        f"device {dev} (process {p}) covers batch rows "
                        f"[{lo}, {hi}) outside its process's block — "
                        f"this mesh cannot be fed by process-sharded "
                        f"loading; give the mesh a data axis spanning "
                        f"the processes"
                    )
        self.images = images
        self.labels = labels
        # Shard the *sample stream* by process; device-level sharding of
        # each assembled batch is handled by the sharding spec above.
        self.sampler = ShardSampler(
            num_examples=len(images),
            num_shards=procs,
            shard_id=jax.process_index(),
            shuffle=shuffle,
            seed=seed,
        )
        # Optional native worker pool — the C++ analogue of the
        # reference's DataLoader(num_workers=2) (data.py:22). 0 keeps
        # the single-thread Python gather; >0 tries the native path and
        # falls back (with a warning) if no toolchain is available.
        self._prefetcher = None
        if num_workers > 0 and images.dtype != np.uint8:
            # The C++ gather ring is a byte-pipeline (uint8 images);
            # float feature streams (e.g. the long-context sequences)
            # use the Python gather, which is not the bottleneck there.
            import logging

            logging.getLogger("ddp_tpu").warning(
                "num_workers=%d requested but the native pipeline is "
                "uint8-only (%s data); using Python gather",
                num_workers,
                images.dtype,
            )
            num_workers = 0
        if num_workers > 0:
            import os as _os

            batch_bytes = self.local_batch_size * int(
                np.prod(images.shape[1:])
            )
            if not self.pool_would_engage(batch_bytes):
                # A worker pool is overhead, not help, when one batch
                # gathers in microseconds (MNIST-sized rows) or when
                # there is no spare core to run it on — the ticket/
                # slot handoff costs more than the memcpy it offloads
                # (both regimes measured: bench.py loader micro-bench).
                # Auto-disable instead of making the reference's
                # num_workers=2 default a pessimization.
                import logging

                logging.getLogger("ddp_tpu").info(
                    "num_workers=%d auto-disabled: %d-byte batches, "
                    "%s host cores (pool threshold: %d bytes and >1 "
                    "core)",
                    num_workers, batch_bytes, _os.cpu_count(),
                    self.POOL_MIN_BATCH_BYTES,
                )
                num_workers = 0
        if num_workers > 0:
            from ddp_tpu import native

            if native.available():
                self._prefetcher = native.NativePrefetcher(
                    self.images,
                    self.labels,
                    self.local_batch_size,
                    num_workers=num_workers,
                )
            else:
                import logging

                logging.getLogger("ddp_tpu").warning(
                    "num_workers=%d requested but native pipeline "
                    "unavailable; using Python gather",
                    num_workers,
                )

    def steps_per_epoch(self) -> int:
        # The final partial batch is always dropped: SPMD steps need
        # static shapes, and re-padding mid-epoch isn't worth a
        # recompile for <1 batch of data (the reference's DataLoader
        # keeps it, at 60000/64 a 0.05% difference per epoch).
        return self.sampler.shard_size // self.local_batch_size

    def _host_batches(
        self, epoch: int, skip_batches: int = 0
    ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        idx = self.sampler.shard_indices(epoch)
        if skip_batches:
            # Mid-epoch resume: the index plan is deterministic in
            # (seed, epoch), so dropping the consumed prefix continues
            # the exact same data order.
            idx = idx[skip_batches * self.local_batch_size :]
        if self._prefetcher is not None:
            yield from self._prefetcher.epoch(idx)
            return
        lb = self.local_batch_size
        n_full = len(idx) // lb
        for b in range(n_full):
            sel = idx[b * lb : (b + 1) * lb]
            yield self.images[sel], self.labels[sel]

    def close(self) -> None:
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None

    def epoch(self, epoch: int, skip_batches: int = 0) -> Iterator[Batch]:
        """Batches for ``epoch``, prefetched one step ahead.

        ``epoch`` plays the role of ``sampler.set_epoch(epoch)`` at
        train_ddp.py:193 — same data order on re-runs, reshuffled per
        epoch. ``skip_batches`` resumes mid-epoch after a preemption
        save (the consumed prefix of the deterministic plan is
        dropped).
        """

        def put(img_np: np.ndarray, lbl_np: np.ndarray) -> Batch:
            if jax.process_count() == 1:
                return Batch(
                    jax.device_put(img_np, self._img_sharding),
                    jax.device_put(lbl_np, self._lbl_sharding),
                )
            return Batch(
                jax.make_array_from_process_local_data(self._img_sharding, img_np),
                jax.make_array_from_process_local_data(self._lbl_sharding, lbl_np),
            )

        pending: Batch | None = None
        for img_np, lbl_np in self._host_batches(epoch, skip_batches):
            nxt = put(img_np, lbl_np)  # async dispatch — overlaps prior step
            if pending is not None:
                yield pending
            pending = nxt
        if pending is not None:
            yield pending

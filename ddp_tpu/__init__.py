"""ddp_tpu — a TPU-native distributed data-parallel training framework.

A ground-up JAX/XLA re-design of the capability surface of
``zahmedy/PyTorch-Distributed-Data-Parallel-DDP-Trainer`` (the reference):
multi-process SPMD launch, process-group init/teardown with backend
selection, data-parallel training with gradient all-reduce, per-rank
deterministic data sharding with per-epoch shuffling, rank-0
checkpointing, and latest-checkpoint auto-resume — expressed as
``jax.distributed`` + ``Mesh`` + ``shard_map``/``pjit`` + ``lax.pmean``
+ Orbax, not as a port of the reference's torch/c10d architecture.

Layer map (mirrors SURVEY.md §1, re-homed for TPU):

  L5  CLI / launcher       train.py (repo root)
  L4  Orchestration        ddp_tpu.train.trainer
  L3  Models / Data        ddp_tpu.models / ddp_tpu.data
  L2  Runtime              ddp_tpu.runtime (dist context, mesh)
  L1  Native               XLA:TPU compiler, ICI collectives, Pallas
                           kernels (ddp_tpu.ops), C++ data plane
"""

__version__ = "0.1.0"

from ddp_tpu.runtime.dist import DistContext, setup, cleanup  # noqa: F401
from ddp_tpu.runtime.mesh import make_mesh  # noqa: F401

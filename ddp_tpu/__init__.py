"""ddp_tpu — a TPU-native distributed data-parallel training framework.

A ground-up JAX/XLA re-design of the capability surface of
``zahmedy/PyTorch-Distributed-Data-Parallel-DDP-Trainer`` (the reference):
multi-process SPMD launch, process-group init/teardown with backend
selection, data-parallel training with gradient all-reduce, per-rank
deterministic data sharding with per-epoch shuffling, rank-0
checkpointing, and latest-checkpoint auto-resume — expressed as
``jax.distributed`` + ``Mesh`` + ``shard_map``/``pjit`` + ``lax.pmean``
+ Orbax, not as a port of the reference's torch/c10d architecture.

Layer map (mirrors SURVEY.md §1, re-homed for TPU):

  L5  CLI / launcher       train.py (repo root)
  L4  Orchestration        ddp_tpu.train.trainer
  L3  Models / Data        ddp_tpu.models / ddp_tpu.data
  L2  Runtime              ddp_tpu.runtime (dist context, mesh)
  L1  Native               XLA:TPU compiler, ICI collectives, Pallas
                           kernels (ddp_tpu.ops), C++ data plane
"""

__version__ = "0.2.0"

import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    # Some TPU platform plugins (e.g. the axon tunnel) pin
    # jax_platforms at import, overriding the JAX_PLATFORMS env var.
    # Honor the env var explicitly, once, for every consumer of the
    # package — offline/CPU-forced invocations (tests, scripts, dev
    # boxes) must never touch the TPU tunnel, and must not hang when
    # it is unreachable. Safe here: importing jax does not initialize
    # a backend, and this runs before any jax USE by the package.
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])


def _install_jax_compat() -> None:
    """Make ``jax.shard_map(..., check_vma=)`` work on jax < 0.6.

    The codebase speaks the modern surface (top-level ``jax.shard_map``
    with the ``check_vma`` kwarg). Older jax (e.g. 0.4.x) ships the
    same function as ``jax.experimental.shard_map.shard_map`` with the
    kwarg named ``check_rep``. Alias + translate once at package
    import, so every ddp_tpu module (and the test suite, which always
    imports ddp_tpu first) runs on either jax without scattering
    version branches through the parallel layer.
    """
    import jax as _j

    if not hasattr(_j.lax, "axis_size"):
        # Same era: no lax.axis_size either. The traced psum(1, axis)
        # is the old idiom — equivalent everywhere this codebase calls
        # it (inside shard_map bodies, for index arithmetic).
        _j.lax.axis_size = lambda axis_name: _j.lax.psum(1, axis_name)

    if hasattr(_j, "shard_map"):
        return
    from functools import wraps as _wraps

    from jax.experimental.shard_map import shard_map as _shard_map

    @_wraps(_shard_map)
    def _compat_shard_map(f, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, **kwargs)

    _j.shard_map = _compat_shard_map


_install_jax_compat()

from ddp_tpu.runtime.dist import DistContext, setup, cleanup  # noqa: F401
from ddp_tpu.runtime.mesh import MeshSpec, make_mesh  # noqa: F401


def __getattr__(name):
    """Lazy top-level API: ``from ddp_tpu import Trainer, TrainConfig``.

    Deferred imports keep ``import ddp_tpu`` light (no flax/optax/orbax
    pull-in) for tools that only need the runtime layer.
    """
    if name == "Trainer":
        from ddp_tpu.train.trainer import Trainer

        return Trainer
    if name == "TrainConfig":
        from ddp_tpu.train.config import TrainConfig

        return TrainConfig
    if name == "CheckpointManager":
        from ddp_tpu.train.checkpoint import CheckpointManager

        return CheckpointManager
    if name == "get_model":
        from ddp_tpu.models import get_model

        return get_model
    raise AttributeError(f"module 'ddp_tpu' has no attribute {name!r}")

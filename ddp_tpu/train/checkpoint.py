"""Checkpoint save / discovery / resume — the reference's biggest subsystem.

Contract parity with train_ddp.py (≈136 of its 227 trainer lines,
SURVEY.md §5):

- save once per epoch into ``./checkpoints`` with the epoch number in
  the path (train_ddp.py:204-209);
- on startup, discover the latest checkpoint and resume from
  ``epoch + 1`` (train_ddp.py:49-89), from-scratch when none exists;
- restore must leave *every* process with identical state — the
  reference hand-rolls a 130-line byte-level broadcast protocol for
  this (train_ddp.py:100-186); Orbax restore is collective by design,
  so the protocol collapses into one call.

Deliberate divergences from the reference's literal behavior (its
*intent* per README.md:47, with its verified defects fixed —
SURVEY.md §2a #8):

- optimizer state IS restored (the reference reads ``ckpt["optimizer"]``
  at train_ddp.py:88 and silently drops it);
- "latest" means highest epoch number, not newest st_ctime
  (train_ddp.py:57) — ctime ordering breaks under copy/restore of the
  checkpoint dir;
- saves are atomic (Orbax commit-dir protocol), so a crash mid-save
  can't leave a corrupt "latest" for discovery to trip on;
- the broadcast-resume protocol's four bugs (missing src, stale local
  num_keys, undefined model_state off rank 0, dropped optimizer state)
  have no analogue here by construction.
"""

from __future__ import annotations

import logging
import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from ddp_tpu.parallel.ddp import TrainState

logger = logging.getLogger("ddp_tpu")

# Checkpoint format version, saved as a ``fmt`` scalar alongside the
# state. The qkv-layout ladder (models/vit.py MultiHeadAttention):
#   1 — (no ``fmt`` key) q/k/v-major fused columns;
#   2 — HEAD-MAJOR MHA columns ([head, q|k|v, head_dim], round 3: TP
#       shards are whole heads) and BLOCK-layout GQA columns
#       ([q·H | k·H_kv | v·H_kv]);
#   3 — GROUP-MAJOR GQA columns ([kv-group: q·G | k | v] × H_kv,
#       round 4: GQA×TP shards are whole kv groups). MHA trees are
#       bit-identical between 2 and 3 and restore freely.
# Each step has IDENTICAL shapes to the last, so a silent restore
# would scramble attention — restore refuses stale attention-bearing
# trees and points at scripts/convert_qkv_layout.py instead.
CHECKPOINT_FORMAT = 3


def _has_fused_qkv(tree: Any) -> bool:
    """Does any leaf path contain an ``attn/qkv`` projection?"""
    found = False

    def visit(path, _):
        nonlocal found
        keys = [str(getattr(k, "key", k)) for k in path]
        if "qkv" in keys:
            found = True

    jax.tree_util.tree_map_with_path(visit, tree)
    return found


def _has_gqa_qkv(tree: Any) -> bool:
    """Any ``attn/qkv`` KERNEL with out-dim ≠ 3×in-dim (the GQA
    signature: (H + 2·H_kv)·Dh < 3·d_model when H_kv < H). Rank-
    agnostic on the LEADING dims: pipelined-LM checkpoints stack
    stage params ([S, …] / [v, S, …]), so kernels are 3-D/4-D there —
    only the trailing (in, out) pair is the layout signature."""
    found = False

    def visit(path, leaf):
        nonlocal found
        keys = [str(getattr(k, "key", k)) for k in path]
        if (
            "qkv" in keys
            and keys[-1] == "kernel"
            and getattr(leaf, "ndim", 0) >= 2
            and leaf.shape[-1] != 3 * leaf.shape[-2]
        ):
            found = True

    jax.tree_util.tree_map_with_path(visit, tree)
    return found


def _check_qkv_format(fmt: int | None, tree: Any, source: str) -> None:
    f = fmt or 1
    if f < 2 and _has_fused_qkv(tree):
        raise RuntimeError(
            f"{source} predates the head-major fused-qkv layout "
            f"(format {f} < {CHECKPOINT_FORMAT}) and contains "
            "attention weights — restoring it here would silently "
            "scramble q/k/v across heads (same shapes, different "
            "column order). Convert it once with "
            "scripts/convert_qkv_layout.py --num_heads <H>."
        )
    if f == 2 and _has_gqa_qkv(tree):
        raise RuntimeError(
            f"{source} holds grouped-query attention weights in the "
            "format-2 BLOCK layout ([q·H | k·H_kv | v·H_kv]); round 4 "
            "moved GQA to group-major columns so TP shards are whole "
            "kv groups — same shapes, different order, a silent "
            "restore would scramble attention. Convert it once with "
            "scripts/convert_qkv_layout.py --num_heads <H> "
            "--num_kv_heads <K>."
        )


# --- LM spec sidecar --------------------------------------------------
#
# The architecture fields an LM checkpoint's shapes cannot carry —
# head count, MoE routing (top_k, gate normalization), sequence
# strategy — ride next to the checkpoints as one JSON file, like the
# tokenizer does (trainer writes ``tokenizer.json`` beside the epochs).
# Inference tooling (scripts/predict.py, scripts/serve.py) merges it
# over the shape-derived spec (models/lm.py derive_lm_spec), so a
# checkpoint trained at --moe_top_k 1 serves with top-1 routing
# instead of silently assuming the top-2 default (round-5 ADVICE).

LM_SPEC_FILENAME = "lm_spec.json"


def save_lm_spec(directory: str, spec: Any) -> str:
    """Write ``spec`` (an LMSpec) as JSON beside the checkpoints."""
    import json

    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, LM_SPEC_FILENAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(dict(spec._asdict()), f, indent=2, sort_keys=True)
    os.replace(tmp, path)  # atomic like the checkpoint commits
    return path


def load_lm_spec_fields(directory: str) -> dict:
    """Read the sidecar → field dict ({} when absent or unreadable).

    Returns a plain dict (not an LMSpec) filtered to the fields the
    CURRENT LMSpec knows, so older/newer sidecars degrade to whatever
    subset still applies instead of failing construction.
    """
    import json

    from ddp_tpu.models.lm import LMSpec

    path = os.path.join(directory, LM_SPEC_FILENAME)
    try:
        with open(path) as f:
            fields = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(fields, dict):
        return {}
    return {k: v for k, v in fields.items() if k in LMSpec._fields}


def derive_spec_with_sidecar(
    directory: str, params: Any, *, num_heads_fallback: int
):
    """Restored params + ``lm_spec.json`` sidecar → LMSpec.

    The shared inference-tooling recipe (scripts/predict.py,
    scripts/serve.py): shapes are ground truth, the sidecar supplies
    what they cannot carry (head count, MoE routing, strategy), and
    ``num_heads_fallback`` (a CLI flag) covers sidecar-less
    checkpoints. Raises ValueError when the tree is not a causal-LM
    tree or the head count does not explain the shapes.
    """
    from ddp_tpu.models.lm import derive_lm_spec

    sidecar = load_lm_spec_fields(directory)
    return derive_lm_spec(
        params,
        num_heads=sidecar.pop("num_heads", num_heads_fallback),
        **sidecar,
    )


class CheckpointManager:
    """Per-epoch checkpoints with latest-epoch auto-resume.

    ``last_restored_spe`` / ``last_restored_mid_batch`` hold what the
    most recently restored checkpoint recorded (None / 0 for legacy
    checkpoints): the steps-per-epoch it was written under, and how
    many batches into its tagged epoch the state is (0 = the epoch
    completed). The trainer uses the pair to re-enter a preempted
    epoch at the exact batch — an explicit marker, not step-counter
    arithmetic, so imported checkpoints with foreign step offsets
    (scripts/import_torch_checkpoint.py) can never alias a mid-epoch
    position.
    """

    last_restored_spe: int | None = None
    last_restored_mid_batch: int = 0

    def __init__(
        self,
        directory: str = "./checkpoints",
        *,
        max_to_keep: int | None = None,
        async_save: bool = True,
        keep_best_metric: str | None = None,
    ):
        """``keep_best_metric``: retain the ``max_to_keep`` checkpoints
        with the HIGHEST value of that metric (passed to ``save``) PLUS
        the chronologically latest one — best-N alone would delete the
        newest checkpoint whenever it underperforms, silently breaking
        latest-epoch auto-resume (restarts would re-train completed
        epochs). Saves without metrics (preemption artifacts) are
        always preserved.
        """
        self._dir = os.path.abspath(directory)
        self._keep_best_fallback: tuple | None = None
        opts_kwargs: dict = dict(
            max_to_keep=None if keep_best_metric else max_to_keep,
            create=True,
            enable_async_checkpointing=async_save,
            step_prefix="epoch",
        )
        if keep_best_metric:
            try:
                from orbax.checkpoint.checkpoint_managers import (
                    AnyPreservationPolicy,
                    BestN,
                    LatestN,
                )

                opts_kwargs["preservation_policy"] = AnyPreservationPolicy(
                    [
                        LatestN(1),  # auto-resume anchor
                        BestN(
                            get_metric_fn=lambda m: m[keep_best_metric],
                            # reverse=False keeps the HIGHEST metric
                            # values (empirically: reverse=True retains
                            # the lowest)
                            reverse=False,
                            n=max_to_keep,
                            keep_checkpoints_without_metrics=True,
                        ),
                    ]
                )
            except ImportError:
                # orbax < 0.11: no preservation policies, and the old
                # best_fn API cannot express best-N PLUS the latest
                # anchor. Emulate with explicit deletes after each
                # save (_prune_keep_best); metrics are tracked
                # in-process, and saves whose metric was never seen
                # are kept — the keep_checkpoints_without_metrics
                # behaviour.
                self._keep_best_fallback = (keep_best_metric, max_to_keep, {})
        opts = ocp.CheckpointManagerOptions(**opts_kwargs)
        # Explicit handler so item_metadata works before any save/
        # restore call registered one (the template-free inference path
        # in a fresh process).
        self._mgr = ocp.CheckpointManager(
            self._dir, options=opts,
            item_handlers=ocp.StandardCheckpointHandler(),
        )

    @property
    def directory(self) -> str:
        return self._dir

    def latest_epoch(self) -> int | None:
        """Discovery: the reference's "latest file in ./checkpoints"."""
        return self._mgr.latest_step()

    def all_epochs(self) -> list[int]:
        """Every saved epoch tag, ascending."""
        return sorted(self._mgr.all_steps() or [])

    def metadata(self, epoch: int) -> dict:
        """Shape/dtype metadata tree for one epoch (no array reads)."""
        return dict(self._mgr.item_metadata(epoch))

    def save(
        self,
        epoch: int,
        state: TrainState,
        *,
        overwrite: bool = False,
        steps_per_epoch: int = 0,
        mid_batch: int = 0,
        metrics: dict | None = None,
    ) -> bool:
        """Save ``{params, opt_state, step}`` for ``epoch``.

        Collective: every process calls it; Orbax elects writers — the
        multi-host-safe version of the reference's ``if rank == 0:
        torch.save(...)`` (train_ddp.py:204).

        Same-epoch conflicts (a mid-epoch preemption artifact already
        holds this tag): with ``overwrite=False`` the save is skipped —
        the old artifact stays valid and the NEXT epoch's save
        supersedes it, so no crash window ever leaves the directory
        without a usable latest. ``overwrite=True`` (preemption saves
        replacing an older same-epoch artifact) deletes then saves;
        a crash inside that window falls back to the previous epoch —
        recompute, never corruption.
        """
        if epoch in (self._mgr.all_steps() or []):
            if not overwrite:
                logger.info(
                    "Checkpoint for epoch %d already exists (preemption "
                    "artifact) — keeping it; a later save supersedes it",
                    epoch,
                )
                return False
            self._mgr.delete(epoch)
        # steps_per_epoch and the explicit mid-epoch batch position ride
        # along so resume needs no step-counter arithmetic (which a
        # changed config or an imported foreign checkpoint would break);
        # mid_batch 0 means the tagged epoch completed.
        tree = dict(
            state._asdict(),
            # 0-d arrays, not numpy scalars: older orbax
            # StandardCheckpointHandlers reject np.int32(...) leaves.
            spe=np.asarray(steps_per_epoch, np.int32),
            mid_batch=np.asarray(mid_batch, np.int32),
            fmt=np.asarray(CHECKPOINT_FORMAT, np.int32),
        )
        self._mgr.save(
            epoch, args=ocp.args.StandardSave(tree), metrics=metrics
        )
        if self._keep_best_fallback is not None:
            self._prune_keep_best(epoch, metrics)
        return True

    def _prune_keep_best(self, epoch: int, metrics: dict | None) -> None:
        """best-N ∪ latest retention for orbax versions without
        preservation policies (see __init__). Runs after each save;
        under async saving the in-flight step is not yet listed, so
        the previous latest survives one extra round — pruned by the
        next save, never the auto-resume anchor."""
        metric_name, n, seen = self._keep_best_fallback
        if metrics and metric_name in metrics:
            seen[epoch] = metrics[metric_name]
        steps = self._mgr.all_steps() or []
        if not steps:
            return
        best = sorted(
            (s for s in steps if s in seen),
            key=lambda s: seen[s],
            reverse=True,
        )
        # n=None means unbounded (the new-orbax path keeps every
        # metric-bearing save then too) — only slice for a real bound.
        if n is not None:
            best = best[:n]
        keep = set(best) | {max(steps)}
        keep |= {s for s in steps if s not in seen}  # metric-less saves
        for s in steps:
            if s not in keep:
                self._mgr.delete(s)

    def restore(self, state_like: TrainState, epoch: int | None = None) -> tuple[TrainState, int]:
        """Restore → (state, epoch). ``state_like`` supplies the tree
        structure/shardings (its values are discarded)."""
        if epoch is None:
            epoch = self.latest_epoch()
            if epoch is None:
                raise FileNotFoundError(f"no checkpoints in {self._dir}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like._asdict())
        abstract["spe"] = jax.ShapeDtypeStruct((), np.int32)
        abstract["mid_batch"] = jax.ShapeDtypeStruct((), np.int32)
        abstract["fmt"] = jax.ShapeDtypeStruct((), np.int32)
        # Migration ladder: older checkpoints lack "fmt" (and before
        # that "mid_batch", "spe", "model_state"); retry dropping the
        # optional keys oldest-format-last.
        ladder = (
            (),
            ("fmt",),
            ("fmt", "mid_batch"),
            ("fmt", "mid_batch", "spe"),
            ("fmt", "mid_batch", "spe", "model_state"),
        )
        for drop in ladder:
            attempt = {k: v for k, v in abstract.items() if k not in drop}
            try:
                restored = dict(
                    self._mgr.restore(
                        epoch, args=ocp.args.StandardRestore(attempt)
                    )
                )
                break
            except (ValueError, KeyError):
                if drop == ladder[-1]:
                    raise
        restored.setdefault("model_state", state_like.model_state)
        fmt = int(restored.pop("fmt", 1))
        _check_qkv_format(
            fmt, restored["params"], f"checkpoint epoch {epoch}"
        )
        self.last_restored_spe = int(restored.pop("spe", 0)) or None
        if "mid_batch" in restored:
            self.last_restored_mid_batch = int(restored.pop("mid_batch"))
        elif self.last_restored_spe:
            # Pre-mid_batch checkpoint: its intra-epoch position is
            # encoded only in the step counter (the old scheme, valid
            # because nothing but the trainer ever wrote that format).
            self.last_restored_mid_batch = (
                int(restored["step"]) % self.last_restored_spe
            )
        else:
            self.last_restored_mid_batch = 0
        return TrainState(**restored), epoch

    def delete_after(self, epoch: int) -> list[int]:
        """Delete every checkpoint tagged LATER than ``epoch``.

        The rewind contract (``--resume_epoch``): the branch being
        abandoned must not survive as "latest", or a crash in the
        rewound run would auto-resume exactly the state the user chose
        to discard. Returns the deleted tags.
        """
        stale = sorted(e for e in (self._mgr.all_steps() or []) if e > epoch)
        for e in stale:
            self._mgr.delete(e)
        return stale

    _pytree_mgr = None

    def read_partial(self, epoch: int, keys: tuple[str, ...]) -> dict:
        """Read ONLY ``keys`` of a checkpoint, topology-independent.

        The abstract tree comes from the checkpoint's own metadata (no
        model/optimizer construction); explicit single-device shardings
        replace the recorded ones, which reference the topology the
        checkpoint was WRITTEN under (e.g. an 8-device emulated mesh)
        and cannot deserialize elsewhere. Skipped entries pay no I/O
        (``partial_restore`` — an Adam opt_state is 2× the params).
        """
        meta = dict(self._mgr.item_metadata(epoch))
        wanted = {k: meta[k] for k in keys if k in meta}
        dev = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
        abstract = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=dev),
            wanted,
        )
        restore_args = jax.tree.map(
            lambda _: ocp.ArrayRestoreArgs(sharding=dev), abstract
        )
        if self._pytree_mgr is None:
            # The main manager is registered for the Standard handler;
            # partial restore needs the PyTree one. One lazy instance
            # serves every read (scripts iterate all epochs).
            self._pytree_mgr = ocp.CheckpointManager(
                self._dir,
                options=ocp.CheckpointManagerOptions(step_prefix="epoch"),
                item_handlers=ocp.PyTreeCheckpointHandler(),
            )
        try:
            args = ocp.args.PyTreeRestore(
                item=abstract,
                restore_args=restore_args,
                partial_restore=True,
            )
        except TypeError:
            # orbax < 0.9: no partial_restore kwarg — an empty
            # transforms dict is the era's partial-restore idiom
            # (checkpoint keys absent from ``item`` are dropped).
            args = ocp.args.PyTreeRestore(
                item=abstract,
                restore_args=restore_args,
                transforms={},
            )
        return dict(self._pytree_mgr.restore(epoch, args=args))

    def restore_for_inference(
        self, epoch: int | None = None
    ) -> tuple[Any, Any, int]:
        """Template-free restore → ``(params, model_state, epoch)``.

        Inference tooling (scripts/predict.py) loads ANY run's
        checkpoint without knowing which optimizer produced it; the
        optimizer state is never read.
        """
        if epoch is None:
            epoch = self.latest_epoch()
            if epoch is None:
                raise FileNotFoundError(f"no checkpoints in {self._dir}")
        restored = self.read_partial(epoch, ("params", "model_state", "fmt"))
        fmt = restored.pop("fmt", None)
        _check_qkv_format(
            int(fmt) if fmt is not None else None,
            restored["params"],
            f"checkpoint epoch {epoch}",
        )
        return restored["params"], restored.get("model_state", {}), epoch

    def restore_or_init(
        self, state: TrainState
    ) -> tuple[TrainState, int]:
        """The auto-resume entry: (state, start_epoch).

        Mirrors train_ddp.py:49-89's flag dance — resume from latest
        epoch + 1 when a checkpoint exists, else epoch 0 fresh.
        """
        latest = self.latest_epoch()
        if latest is None:
            logger.info("No checkpoint found — starting from scratch")
            return state, 0
        restored, epoch = self.restore(state, latest)
        logger.info("Resumed from checkpoint epoch %d", epoch)
        return restored, epoch + 1

    def wait(self) -> None:
        """Block until async saves are durable (call before exit)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
        if self._pytree_mgr is not None:
            self._pytree_mgr.close()
            self._pytree_mgr = None

"""Checkpoint save / discovery / resume — the reference's biggest subsystem.

Contract parity with train_ddp.py (≈136 of its 227 trainer lines,
SURVEY.md §5):

- save once per epoch into ``./checkpoints`` with the epoch number in
  the path (train_ddp.py:204-209);
- on startup, discover the latest checkpoint and resume from
  ``epoch + 1`` (train_ddp.py:49-89), from-scratch when none exists;
- restore must leave *every* process with identical state — the
  reference hand-rolls a 130-line byte-level broadcast protocol for
  this (train_ddp.py:100-186); Orbax restore is collective by design,
  so the protocol collapses into one call.

Deliberate divergences from the reference's literal behavior (its
*intent* per README.md:47, with its verified defects fixed —
SURVEY.md §2a #8):

- optimizer state IS restored (the reference reads ``ckpt["optimizer"]``
  at train_ddp.py:88 and silently drops it);
- "latest" means highest epoch number, not newest st_ctime
  (train_ddp.py:57) — ctime ordering breaks under copy/restore of the
  checkpoint dir;
- saves are atomic (Orbax commit-dir protocol), so a crash mid-save
  can't leave a corrupt "latest" for discovery to trip on;
- the broadcast-resume protocol's four bugs (missing src, stale local
  num_keys, undefined model_state off rank 0, dropped optimizer state)
  have no analogue here by construction.
"""

from __future__ import annotations

import json
import logging
import os
import zlib
from typing import Any, Sequence

import jax
import numpy as np
import orbax.checkpoint as ocp

from ddp_tpu.parallel.ddp import TrainState

logger = logging.getLogger("ddp_tpu")

# Checkpoint format version, saved as a ``fmt`` scalar alongside the
# state. The qkv-layout ladder (models/vit.py MultiHeadAttention):
#   1 — (no ``fmt`` key) q/k/v-major fused columns;
#   2 — HEAD-MAJOR MHA columns ([head, q|k|v, head_dim], round 3: TP
#       shards are whole heads) and BLOCK-layout GQA columns
#       ([q·H | k·H_kv | v·H_kv]);
#   3 — GROUP-MAJOR GQA columns ([kv-group: q·G | k | v] × H_kv,
#       round 4: GQA×TP shards are whole kv groups). MHA trees are
#       bit-identical between 2 and 3 and restore freely.
# Each step has IDENTICAL shapes to the last, so a silent restore
# would scramble attention — restore refuses stale attention-bearing
# trees and points at scripts/convert_qkv_layout.py instead.
CHECKPOINT_FORMAT = 3


def _has_fused_qkv(tree: Any) -> bool:
    """Does any leaf path contain an ``attn/qkv`` projection?"""
    found = False

    def visit(path, _):
        nonlocal found
        keys = [str(getattr(k, "key", k)) for k in path]
        if "qkv" in keys:
            found = True

    jax.tree_util.tree_map_with_path(visit, tree)
    return found


def _has_gqa_qkv(tree: Any) -> bool:
    """Any ``attn/qkv`` KERNEL with out-dim ≠ 3×in-dim (the GQA
    signature: (H + 2·H_kv)·Dh < 3·d_model when H_kv < H). Rank-
    agnostic on the LEADING dims: pipelined-LM checkpoints stack
    stage params ([S, …] / [v, S, …]), so kernels are 3-D/4-D there —
    only the trailing (in, out) pair is the layout signature."""
    found = False

    def visit(path, leaf):
        nonlocal found
        keys = [str(getattr(k, "key", k)) for k in path]
        if (
            "qkv" in keys
            and keys[-1] == "kernel"
            and getattr(leaf, "ndim", 0) >= 2
            and leaf.shape[-1] != 3 * leaf.shape[-2]
        ):
            found = True

    jax.tree_util.tree_map_with_path(visit, tree)
    return found


def _check_qkv_format(fmt: int | None, tree: Any, source: str) -> None:
    f = fmt or 1
    if f < 2 and _has_fused_qkv(tree):
        raise RuntimeError(
            f"{source} predates the head-major fused-qkv layout "
            f"(format {f} < {CHECKPOINT_FORMAT}) and contains "
            "attention weights — restoring it here would silently "
            "scramble q/k/v across heads (same shapes, different "
            "column order). Convert it once with "
            "scripts/convert_qkv_layout.py --num_heads <H>."
        )
    if f == 2 and _has_gqa_qkv(tree):
        raise RuntimeError(
            f"{source} holds grouped-query attention weights in the "
            "format-2 BLOCK layout ([q·H | k·H_kv | v·H_kv]); round 4 "
            "moved GQA to group-major columns so TP shards are whole "
            "kv groups — same shapes, different order, a silent "
            "restore would scramble attention. Convert it once with "
            "scripts/convert_qkv_layout.py --num_heads <H> "
            "--num_kv_heads <K>."
        )


# --- checkpoint integrity manifests -----------------------------------
#
# Orbax's commit protocol makes a *crash mid-save* atomic, but nothing
# defends the committed bytes afterwards: a torn copy, a truncated
# restore from object storage, bit rot, or a chaos drill
# (runtime/chaos.py ckpt_corrupt) leaves a "latest" that passes
# discovery and fails — or worse, silently corrupts — the restore.
# Every save therefore gets a sidecar manifest (``epoch_N.manifest.json``
# next to the step directory) listing each file's size and CRC-32;
# restore-time discovery verifies the latest manifest and, on mismatch,
# QUARANTINES the step directory (renamed aside, never deleted — it is
# evidence) and falls back to the previous intact epoch, so the
# auto-resume path recovers instead of crashing. CRC-32 is an
# integrity check against accidents, not an authenticity check against
# adversaries. Manifest-less epochs (pre-upgrade checkpoints, or a
# save whose process died before ``wait()``) are accepted unverified —
# integrity never makes old checkpoints unreadable.

MANIFEST_SUFFIX = ".manifest.json"
QUARANTINE_PREFIX = "quarantine."


def _crc32_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(block, crc)


def _manifest_path(root: str, epoch: int) -> str:
    return os.path.join(root, f"epoch_{epoch}{MANIFEST_SUFFIX}")


def build_manifest(step_dir: str) -> dict:
    """Walk a committed step directory → {relpath: {size, crc32}}."""
    files: dict[str, dict] = {}
    for dirpath, _, names in os.walk(step_dir):
        for name in sorted(names):
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, step_dir)
            files[rel] = {
                "size": os.path.getsize(path),
                "crc32": _crc32_file(path),
            }
    return {"version": 1, "files": files}


def write_manifest(root: str, epoch: int) -> str | None:
    """Manifest the committed ``epoch_<N>`` dir (atomic tmp+replace).
    Returns the manifest path, or None when the step dir is absent."""
    step_dir = os.path.join(root, f"epoch_{epoch}")
    if not os.path.isdir(step_dir):
        return None
    manifest = build_manifest(step_dir)
    path = _manifest_path(root, epoch)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f, sort_keys=True)
    os.replace(tmp, path)
    return path


def verify_manifest(root: str, epoch: int) -> list[str] | None:
    """Check ``epoch_<N>`` against its manifest.

    Returns ``None`` when no (readable) manifest exists — the epoch is
    UNVERIFIABLE and accepted for compatibility; ``[]`` when every
    listed file matches; otherwise a list of human-readable problems
    (missing file / size mismatch / checksum mismatch). Files present
    on disk but absent from the manifest are ignored — descriptors and
    later tooling may legitimately add them.
    """
    path = _manifest_path(root, epoch)
    try:
        with open(path) as f:
            manifest = json.load(f)
        listed = dict(manifest["files"])
    except (OSError, ValueError, KeyError, TypeError):
        return None
    step_dir = os.path.join(root, f"epoch_{epoch}")
    problems: list[str] = []
    for rel, meta in sorted(listed.items()):
        p = os.path.join(step_dir, rel)
        try:
            size = os.path.getsize(p)
        except OSError:
            problems.append(f"{rel}: missing")
            continue
        if size != meta.get("size"):
            problems.append(
                f"{rel}: size {size} != manifest {meta.get('size')}"
            )
            continue
        if _crc32_file(p) != meta.get("crc32"):
            problems.append(f"{rel}: checksum mismatch")
    return problems


# --- LM spec sidecar --------------------------------------------------
#
# The architecture fields an LM checkpoint's shapes cannot carry —
# head count, MoE routing (top_k, gate normalization), sequence
# strategy — ride next to the checkpoints as one JSON file, like the
# tokenizer does (trainer writes ``tokenizer.json`` beside the epochs).
# Inference tooling (scripts/predict.py, scripts/serve.py) merges it
# over the shape-derived spec (models/lm.py derive_lm_spec), so a
# checkpoint trained at --moe_top_k 1 serves with top-1 routing
# instead of silently assuming the top-2 default (round-5 ADVICE).

LM_SPEC_FILENAME = "lm_spec.json"


def save_lm_spec(directory: str, spec: Any) -> str:
    """Write ``spec`` (an LMSpec) as JSON beside the checkpoints."""
    import json

    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, LM_SPEC_FILENAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(dict(spec._asdict()), f, indent=2, sort_keys=True)
    os.replace(tmp, path)  # atomic like the checkpoint commits
    return path


def load_lm_spec_fields(directory: str) -> dict:
    """Read the sidecar → field dict ({} when absent or unreadable).

    Returns a plain dict (not an LMSpec) filtered to the fields the
    CURRENT LMSpec knows, so older/newer sidecars degrade to whatever
    subset still applies instead of failing construction.
    """
    import json

    from ddp_tpu.models.lm import LMSpec

    path = os.path.join(directory, LM_SPEC_FILENAME)
    try:
        with open(path) as f:
            fields = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(fields, dict):
        return {}
    return {k: v for k, v in fields.items() if k in LMSpec._fields}


def derive_spec_with_sidecar(
    directory: str, params: Any, *, num_heads_fallback: int
):
    """Restored params + ``lm_spec.json`` sidecar → LMSpec.

    The shared inference-tooling recipe (scripts/predict.py,
    scripts/serve.py): shapes are ground truth, the sidecar supplies
    what they cannot carry (head count, MoE routing, strategy), and
    ``num_heads_fallback`` (a CLI flag) covers sidecar-less
    checkpoints. Raises ValueError when the tree is not a causal-LM
    tree or the head count does not explain the shapes.
    """
    from ddp_tpu.models.lm import derive_lm_spec

    sidecar = load_lm_spec_fields(directory)
    return derive_lm_spec(
        params,
        num_heads=sidecar.pop("num_heads", num_heads_fallback),
        **sidecar,
    )


# --- elastic world-resize contract ------------------------------------
#
# The one fact an elastic relaunch cannot re-derive from its own flags:
# the ORIGINAL global batch size. Config flags are per-shard
# (``--batch_size`` × live shards), so a shrunk world would silently
# halve the global batch — changing what a step means, desynchronizing
# the checkpointed step counter from the LR schedule and the
# steps-per-epoch the mid-epoch resume markers were written under. The
# first generation records the contract once; every later generation
# rescales its per-shard batch to honor it
# (data/sampler.rescale_per_shard_batch). Write-once on purpose: the
# contract is the run's invariant, not the latest generation's shape.

ELASTIC_FILENAME = "elastic.json"


def save_elastic_contract(
    directory: str, *, global_batch_size: int, world_size: int
) -> str | None:
    """Record the run's global-batch contract (first generation only —
    an existing contract is never overwritten). Returns the path, or
    None when a contract already existed."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, ELASTIC_FILENAME)
    if os.path.exists(path):
        return None
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(
            {
                "global_batch_size": int(global_batch_size),
                "world_size": int(world_size),
            },
            f,
        )
    os.replace(tmp, path)
    return path


def load_elastic_contract(directory: str) -> dict:
    """The recorded contract, or {} (first generation / non-elastic
    run / unreadable sidecar — all mean "no rescale to honor")."""
    path = os.path.join(directory, ELASTIC_FILENAME)
    try:
        with open(path) as f:
            contract = json.load(f)
    except (OSError, ValueError):
        return {}
    return contract if isinstance(contract, dict) else {}


class CheckpointManager:
    """Per-epoch checkpoints with latest-epoch auto-resume.

    ``last_restored_spe`` / ``last_restored_mid_batch`` hold what the
    most recently restored checkpoint recorded (None / 0 for legacy
    checkpoints): the steps-per-epoch it was written under, and how
    many batches into its tagged epoch the state is (0 = the epoch
    completed). The trainer uses the pair to re-enter a preempted
    epoch at the exact batch — an explicit marker, not step-counter
    arithmetic, so imported checkpoints with foreign step offsets
    (scripts/import_torch_checkpoint.py) can never alias a mid-epoch
    position.
    """

    last_restored_spe: int | None = None
    last_restored_mid_batch: int = 0

    def __init__(
        self,
        directory: str = "./checkpoints",
        *,
        max_to_keep: int | None = None,
        async_save: bool = True,
        keep_best_metric: str | None = None,
    ):
        """``keep_best_metric``: retain the ``max_to_keep`` checkpoints
        with the HIGHEST value of that metric (passed to ``save``) PLUS
        the chronologically latest one — best-N alone would delete the
        newest checkpoint whenever it underperforms, silently breaking
        latest-epoch auto-resume (restarts would re-train completed
        epochs). Saves without metrics (preemption artifacts) are
        always preserved.
        """
        self._dir = os.path.abspath(directory)
        self._keep_best_fallback: tuple | None = None
        opts_kwargs: dict = dict(
            max_to_keep=None if keep_best_metric else max_to_keep,
            create=True,
            enable_async_checkpointing=async_save,
            step_prefix="epoch",
        )
        if keep_best_metric:
            try:
                from orbax.checkpoint.checkpoint_managers import (
                    AnyPreservationPolicy,
                    BestN,
                    LatestN,
                )

                opts_kwargs["preservation_policy"] = AnyPreservationPolicy(
                    [
                        LatestN(1),  # auto-resume anchor
                        BestN(
                            get_metric_fn=lambda m: m[keep_best_metric],
                            # reverse=False keeps the HIGHEST metric
                            # values (empirically: reverse=True retains
                            # the lowest)
                            reverse=False,
                            n=max_to_keep,
                            keep_checkpoints_without_metrics=True,
                        ),
                    ]
                )
            except ImportError:
                # orbax < 0.11: no preservation policies, and the old
                # best_fn API cannot express best-N PLUS the latest
                # anchor. Emulate with explicit deletes after each
                # save (_prune_keep_best); metrics are tracked
                # in-process, and saves whose metric was never seen
                # are kept — the keep_checkpoints_without_metrics
                # behaviour.
                self._keep_best_fallback = (keep_best_metric, max_to_keep, {})
        opts = ocp.CheckpointManagerOptions(**opts_kwargs)
        # Explicit handler so item_metadata works before any save/
        # restore call registered one (the template-free inference path
        # in a fresh process).
        self._opts = opts
        self._mgr = ocp.CheckpointManager(
            self._dir, options=opts,
            item_handlers=ocp.StandardCheckpointHandler(),
        )
        # Integrity bookkeeping: epochs saved but not yet manifested
        # (async saves aren't durable until committed — manifests are
        # written at the next wait()/save()), and what THIS process
        # quarantined (the trainer surfaces these as fallback events).
        self._manifest_pending: set[int] = set()
        self.quarantined: list[dict] = []

    @property
    def directory(self) -> str:
        return self._dir

    def latest_epoch(self) -> int | None:
        """Discovery: the reference's "latest file in ./checkpoints"."""
        return self._mgr.latest_step()

    # ---- integrity: manifests, verification, quarantine --------------

    @staticmethod
    def _is_manifest_writer() -> bool:
        # One writer per world: every process shares the filesystem in
        # single-host spawns, and concurrent identical writes would
        # only race on the rename.
        return jax.process_index() == 0

    def _flush_manifests(self) -> None:
        """Write manifests for pending epochs that are now COMMITTED.

        Commit is detected by the final ``epoch_<N>`` directory
        existing — NOT by ``all_steps()``, which orbax populates
        optimistically at ``save()`` time while an async save is still
        writing into its ``...orbax-checkpoint-tmp-...`` directory
        (the atomic rename to ``epoch_<N>`` is the commit point).
        Cheap to call opportunistically; in-flight saves simply stay
        pending until ``wait()``/``close()``.
        """
        for epoch in sorted(self._manifest_pending):
            if not os.path.isdir(
                os.path.join(self._dir, f"epoch_{epoch}")
            ):
                continue  # async save not yet committed
            self._manifest_pending.discard(epoch)
            if not self._is_manifest_writer():
                continue
            try:
                write_manifest(self._dir, epoch)
            except OSError as e:  # integrity is best-effort, never fatal
                logger.warning(
                    "manifest write for epoch %d failed: %s", epoch, e
                )

    def _drop_manifest(self, epoch: int) -> None:
        self._manifest_pending.discard(epoch)
        try:
            os.remove(_manifest_path(self._dir, epoch))
        except OSError:
            pass

    def _delete_epoch(self, epoch: int) -> None:
        self._mgr.delete(epoch)
        self._drop_manifest(epoch)

    def _reload_steps(self) -> None:
        """Refresh the manager's step view after an out-of-band rename
        (quarantine). ``reload()`` re-scans the directory — safe
        because quarantined names use a DASH (``quarantine.epoch-N``):
        orbax's step scanner splits names on "_", so an underscore
        name would still parse as step N and the re-scan would then
        fail to find its directory. Deliberately not a manager
        rebuild: ``CheckpointManager.__init__``/``close()`` are not
        process-symmetric-safe, and only SOME ranks reload (a one-rank
        rebuild deadlocked the multi-process resume)."""
        self._mgr.reload()

    def verify_epoch(self, epoch: int) -> list[str] | None:
        """Manifest check → problems ([] ok, None unverifiable)."""
        return verify_manifest(self._dir, epoch)

    def quarantine_epoch(self, epoch: int, problems: list[str]) -> str | None:
        """Rename a corrupt epoch ASIDE (never delete — it is the
        post-mortem evidence) so discovery stops seeing it; its
        manifest moves inside the quarantined directory. Concurrent
        ranks race benignly: the loser's rename fails and the epoch is
        already gone. Returns the quarantine path (None if a peer got
        there first)."""
        src = os.path.join(self._dir, f"epoch_{epoch}")
        # Dash, not underscore: orbax's step scanner splits names on
        # "_", so "quarantine.epoch_1" would still parse as step 1 —
        # the quarantined name must not contain an epoch_<N> token.
        dst = os.path.join(
            self._dir, f"{QUARANTINE_PREFIX}epoch-{epoch}"
        )
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(
                self._dir, f"{QUARANTINE_PREFIX}epoch-{epoch}.{n}"
            )
        try:
            os.rename(src, dst)
        except OSError:
            dst = None  # a peer rank quarantined it first
        else:
            try:
                os.replace(
                    _manifest_path(self._dir, epoch),
                    os.path.join(dst, "ddp_tpu" + MANIFEST_SUFFIX),
                )
            except OSError:
                pass
            logger.error(
                "Checkpoint epoch %d failed integrity verification "
                "(%s) — quarantined to %s; falling back to the "
                "previous intact checkpoint",
                epoch, "; ".join(problems) or "unknown", dst,
            )
        self._manifest_pending.discard(epoch)
        self.quarantined.append(
            {"epoch": epoch, "path": dst, "problems": list(problems)}
        )
        self._reload_steps()
        return dst

    def latest_intact_epoch(self) -> int | None:
        """Latest epoch that passes integrity verification, walking
        backwards past (and quarantining) corrupt ones. Manifest-less
        epochs are accepted unverified. None when nothing usable is
        left.

        Multi-process: only process 0 verifies and quarantines —
        peers would multiply the CRC read of a multi-GB checkpoint by
        world size and race the quarantine renames. A barrier pairs
        the two sides (every process calls it exactly once), so peers
        read the post-quarantine view; this also sequences rank 0's
        process-start chaos (``ckpt_corrupt``) before any peer's
        discovery. Assumes the checkpoint dir is one shared (local/
        NFS) filesystem, like every sidecar here.
        """
        multi = jax.process_count() > 1

        def barrier():
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("ckpt_integrity_verify")

        if multi and jax.process_index() != 0:
            barrier()
            self._reload_steps()  # see process 0's quarantine renames
            return self._mgr.latest_step()
        try:
            while True:
                epoch = self._mgr.latest_step()
                if epoch is None:
                    return None
                problems = self.verify_epoch(epoch)
                if not problems:  # [] verified-ok, or None unverifiable
                    return epoch
                if self.quarantine_epoch(epoch, problems) is None and (
                    epoch == self._mgr.latest_step()
                ):
                    # The rename failed AND the epoch is still visible
                    # (read-only dir, not a peer's racing quarantine):
                    # looping would verify the same bytes forever.
                    raise RuntimeError(
                        f"checkpoint epoch {epoch} fails integrity "
                        f"verification ({'; '.join(problems)}) and "
                        f"cannot be quarantined — is {self._dir} "
                        "writable?"
                    )
        finally:
            # Process 0 reaches this on EVERY exit (including the
            # raise above — peers then fail on their own rather than
            # hanging in a barrier no one will join).
            if multi:
                barrier()

    def all_epochs(self) -> list[int]:
        """Every saved epoch tag, ascending."""
        return sorted(self._mgr.all_steps() or [])

    def metadata(self, epoch: int) -> dict:
        """Shape/dtype metadata tree for one epoch (no array reads)."""
        return dict(self._mgr.item_metadata(epoch))

    def save(
        self,
        epoch: int,
        state: TrainState,
        *,
        overwrite: bool = False,
        steps_per_epoch: int = 0,
        mid_batch: int = 0,
        metrics: dict | None = None,
    ) -> bool:
        """Save ``{params, opt_state, step}`` for ``epoch``.

        Collective: every process calls it; Orbax elects writers — the
        multi-host-safe version of the reference's ``if rank == 0:
        torch.save(...)`` (train_ddp.py:204).

        Same-epoch conflicts (a mid-epoch preemption artifact already
        holds this tag): with ``overwrite=False`` the save is skipped —
        the old artifact stays valid and the NEXT epoch's save
        supersedes it, so no crash window ever leaves the directory
        without a usable latest. ``overwrite=True`` (preemption saves
        replacing an older same-epoch artifact) deletes then saves;
        a crash inside that window falls back to the previous epoch —
        recompute, never corruption.
        """
        if epoch in (self._mgr.all_steps() or []):
            if not overwrite:
                logger.info(
                    "Checkpoint for epoch %d already exists (preemption "
                    "artifact) — keeping it; a later save supersedes it",
                    epoch,
                )
                return False
            self._delete_epoch(epoch)
        # steps_per_epoch and the explicit mid-epoch batch position ride
        # along so resume needs no step-counter arithmetic (which a
        # changed config or an imported foreign checkpoint would break);
        # mid_batch 0 means the tagged epoch completed.
        tree = dict(
            state._asdict(),
            # 0-d arrays, not numpy scalars: older orbax
            # StandardCheckpointHandlers reject np.int32(...) leaves.
            spe=np.asarray(steps_per_epoch, np.int32),
            mid_batch=np.asarray(mid_batch, np.int32),
            fmt=np.asarray(CHECKPOINT_FORMAT, np.int32),
        )
        self._mgr.save(
            epoch, args=ocp.args.StandardSave(tree), metrics=metrics
        )
        # Integrity manifest: pending until the (possibly async) save
        # commits — flushed opportunistically now (earlier saves have
        # committed by this point) and at wait()/close().
        self._manifest_pending.add(epoch)
        self._flush_manifests()
        if self._keep_best_fallback is not None:
            self._prune_keep_best(epoch, metrics)
        return True

    def _prune_keep_best(self, epoch: int, metrics: dict | None) -> None:
        """best-N ∪ latest retention for orbax versions without
        preservation policies (see __init__). Runs after each save;
        under async saving the in-flight step is not yet listed, so
        the previous latest survives one extra round — pruned by the
        next save, never the auto-resume anchor."""
        metric_name, n, seen = self._keep_best_fallback
        if metrics and metric_name in metrics:
            seen[epoch] = metrics[metric_name]
        steps = self._mgr.all_steps() or []
        if not steps:
            return
        best = sorted(
            (s for s in steps if s in seen),
            key=lambda s: seen[s],
            reverse=True,
        )
        # n=None means unbounded (the new-orbax path keeps every
        # metric-bearing save then too) — only slice for a real bound.
        if n is not None:
            best = best[:n]
        keep = set(best) | {max(steps)}
        keep |= {s for s in steps if s not in seen}  # metric-less saves
        for s in steps:
            if s not in keep:
                self._delete_epoch(s)

    def restore(
        self,
        state_like: TrainState,
        epoch: int | None = None,
        *,
        opt_reshape=None,
    ) -> tuple[TrainState, int]:
        """Restore → (state, epoch). ``state_like`` supplies the tree
        structure/shardings (its values are discarded).

        ``epoch=None`` runs verified discovery: corrupt/truncated
        epochs are quarantined and discovery falls back to the
        previous intact one (``latest_intact_epoch``). An EXPLICIT
        epoch that fails verification raises instead — the caller
        named that state on purpose; silently substituting another
        would be worse than failing.

        ``opt_reshape`` makes the restore world-shape-agnostic for
        optimizer states whose GLOBAL shapes depend on the world size
        (the zero strategy's padded flat buckets,
        parallel/zero.ZeroElasticReshaper). Protocol: ``plan(meta)``
        receives the checkpoint's opt_state shape metadata and returns
        either None (shapes match the live template — the ordinary
        templated restore runs, resharding on load) or an abstract
        tree in the SAVED shapes; ``apply(restored)`` then converts the
        old-world values into the live layout. Params/step/model_state
        always restore templated on the live shardings — that half is
        reshard-on-load by construction (tests/test_elastic_shard.py).
        """
        if epoch is None:
            epoch = self.latest_intact_epoch()
            if epoch is None:
                raise FileNotFoundError(f"no checkpoints in {self._dir}")
        else:
            problems = self.verify_epoch(epoch)
            if problems:
                raise RuntimeError(
                    f"checkpoint epoch {epoch} fails integrity "
                    f"verification: {'; '.join(problems)} — restore a "
                    "different epoch, or delete its manifest to force "
                    "an unverified read"
                )
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like._asdict())
        abstract["spe"] = jax.ShapeDtypeStruct((), np.int32)
        abstract["mid_batch"] = jax.ShapeDtypeStruct((), np.int32)
        abstract["fmt"] = jax.ShapeDtypeStruct((), np.int32)
        reshape_apply = None
        if opt_reshape is not None:
            try:
                meta_opt = dict(self._mgr.item_metadata(epoch)).get(
                    "opt_state"
                )
            except (OSError, ValueError, KeyError, TypeError):
                meta_opt = None  # legacy/partial checkpoint: restore as-is
            if meta_opt is not None:
                override = opt_reshape.plan(meta_opt)
                if override is not None:
                    abstract["opt_state"] = override
                    reshape_apply = opt_reshape.apply
                    logger.warning(
                        "Checkpoint epoch %d holds optimizer state "
                        "bucketed for a different world size — "
                        "re-bucketing on restore (elastic resize)",
                        epoch,
                    )
        # Migration ladder: older checkpoints lack "fmt" (and before
        # that "mid_batch", "spe", "model_state"); retry dropping the
        # optional keys oldest-format-last.
        ladder = (
            (),
            ("fmt",),
            ("fmt", "mid_batch"),
            ("fmt", "mid_batch", "spe"),
            ("fmt", "mid_batch", "spe", "model_state"),
        )
        for drop in ladder:
            attempt = {k: v for k, v in abstract.items() if k not in drop}
            try:
                restored = dict(
                    self._mgr.restore(
                        epoch, args=ocp.args.StandardRestore(attempt)
                    )
                )
                break
            except (ValueError, KeyError):
                if drop == ladder[-1]:
                    raise
        if reshape_apply is not None and "opt_state" in restored:
            restored["opt_state"] = reshape_apply(restored["opt_state"])
        restored.setdefault("model_state", state_like.model_state)
        fmt = int(restored.pop("fmt", 1))
        _check_qkv_format(
            fmt, restored["params"], f"checkpoint epoch {epoch}"
        )
        self.last_restored_spe = int(restored.pop("spe", 0)) or None
        if "mid_batch" in restored:
            self.last_restored_mid_batch = int(restored.pop("mid_batch"))
        elif self.last_restored_spe:
            # Pre-mid_batch checkpoint: its intra-epoch position is
            # encoded only in the step counter (the old scheme, valid
            # because nothing but the trainer ever wrote that format).
            self.last_restored_mid_batch = (
                int(restored["step"]) % self.last_restored_spe
            )
        else:
            self.last_restored_mid_batch = 0
        return TrainState(**restored), epoch

    def delete_after(self, epoch: int) -> list[int]:
        """Delete every checkpoint tagged LATER than ``epoch``.

        The rewind contract (``--resume_epoch``): the branch being
        abandoned must not survive as "latest", or a crash in the
        rewound run would auto-resume exactly the state the user chose
        to discard. Returns the deleted tags.
        """
        stale = sorted(e for e in (self._mgr.all_steps() or []) if e > epoch)
        for e in stale:
            self._delete_epoch(e)
        return stale

    _pytree_mgr = None

    def read_partial(self, epoch: int, keys: tuple[str, ...]) -> dict:
        """Read ONLY ``keys`` of a checkpoint, topology-independent.

        The abstract tree comes from the checkpoint's own metadata (no
        model/optimizer construction); explicit single-device shardings
        replace the recorded ones, which reference the topology the
        checkpoint was WRITTEN under (e.g. an 8-device emulated mesh)
        and cannot deserialize elsewhere. Skipped entries pay no I/O
        (``partial_restore`` — an Adam opt_state is 2× the params).
        """
        meta = dict(self._mgr.item_metadata(epoch))
        wanted = {k: meta[k] for k in keys if k in meta}
        return self._restore_subtree(epoch, wanted)

    def _restore_subtree(self, epoch: int, wanted: dict) -> dict:
        """Restore exactly the metadata subtree ``wanted`` (any
        nesting depth) with single-device shardings — the shared tail
        of ``read_partial`` and ``read_params_children``."""
        dev = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
        abstract = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=dev),
            wanted,
        )
        restore_args = jax.tree.map(
            lambda _: ocp.ArrayRestoreArgs(sharding=dev), abstract
        )
        if self._pytree_mgr is None:
            # The main manager is registered for the Standard handler;
            # partial restore needs the PyTree one. One lazy instance
            # serves every read (scripts iterate all epochs).
            self._pytree_mgr = ocp.CheckpointManager(
                self._dir,
                options=ocp.CheckpointManagerOptions(step_prefix="epoch"),
                item_handlers=ocp.PyTreeCheckpointHandler(),
            )
        try:
            args = ocp.args.PyTreeRestore(
                item=abstract,
                restore_args=restore_args,
                partial_restore=True,
            )
        except TypeError:
            # orbax < 0.9: no partial_restore kwarg — an empty
            # transforms dict is the era's partial-restore idiom
            # (checkpoint keys absent from ``item`` are dropped).
            args = ocp.args.PyTreeRestore(
                item=abstract,
                restore_args=restore_args,
                transforms={},
            )
        return dict(self._pytree_mgr.restore(epoch, args=args))

    def params_metadata(self, epoch: int):
        """Shape/dtype metadata of the checkpoint's ``params`` entry —
        NO tensor data is read. The leaves carry ``.shape``/``.dtype``
        like arrays do, so ``models/lm.derive_lm_spec`` runs on the
        metadata tree directly: streaming restore
        (serve/lifecycle.py) derives the engine spec and starts
        compiling before a single weight byte arrives."""
        meta = dict(self._mgr.item_metadata(epoch))
        if "params" not in meta:
            raise KeyError(
                f"checkpoint epoch {epoch} has no params entry"
            )
        return meta["params"]

    def read_params_children(
        self, epoch: int, names: Sequence[str]
    ) -> dict:
        """Restore ONLY the named top-level children of ``params``.

        The streaming-restore primitive (serve/lifecycle.py): the
        embedding + first-K-blocks group restores and opens admission
        while the deep blocks are still in flight on a second call.
        Unknown names are skipped (the group splitter works from the
        same metadata, so a miss means a racing rewrite — the caller's
        residency check catches it). Returns ``{child: tree}``.
        """
        params_meta = self.params_metadata(epoch)
        sel = {k: params_meta[k] for k in names if k in params_meta}
        if not sel:
            return {}
        restored = self._restore_subtree(epoch, {"params": sel})
        return dict(restored["params"])

    def restore_for_inference(
        self, epoch: int | None = None
    ) -> tuple[Any, Any, int]:
        """Template-free restore → ``(params, model_state, epoch)``.

        Inference tooling (scripts/predict.py) loads ANY run's
        checkpoint without knowing which optimizer produced it; the
        optimizer state is never read. Discovery is integrity-verified
        like ``restore`` (corrupt latest → quarantine + fall back).
        """
        if epoch is None:
            epoch = self.latest_intact_epoch()
            if epoch is None:
                raise FileNotFoundError(f"no checkpoints in {self._dir}")
        else:
            problems = self.verify_epoch(epoch)
            if problems:
                raise RuntimeError(
                    f"checkpoint epoch {epoch} fails integrity "
                    f"verification: {'; '.join(problems)}"
                )
        restored = self.read_partial(epoch, ("params", "model_state", "fmt"))
        fmt = restored.pop("fmt", None)
        _check_qkv_format(
            int(fmt) if fmt is not None else None,
            restored["params"],
            f"checkpoint epoch {epoch}",
        )
        return restored["params"], restored.get("model_state", {}), epoch

    def restore_or_init(
        self, state: TrainState, *, opt_reshape=None
    ) -> tuple[TrainState, int]:
        """The auto-resume entry: (state, start_epoch).

        Mirrors train_ddp.py:49-89's flag dance — resume from latest
        epoch + 1 when a checkpoint exists, else epoch 0 fresh.
        ``opt_reshape`` passes through to ``restore`` (the elastic
        world-resize hook).
        """
        # Single-process only: multi-process ranks may reach this
        # pre-check at different times relative to process 0's
        # quarantine renames, and a rank that short-circuits here
        # would skip the verification barrier its peers are blocked
        # in. Multi-process ALWAYS enters restore() (the barrier
        # pairs), and "nothing usable" surfaces as FileNotFoundError
        # on every rank consistently.
        if jax.process_count() == 1 and self.latest_epoch() is None:
            logger.info("No checkpoint found — starting from scratch")
            return state, 0
        try:
            # epoch=None → verified discovery with quarantine fallback.
            restored, epoch = self.restore(
                state, None, opt_reshape=opt_reshape
            )
        except FileNotFoundError:
            # Nothing to restore — either the directory is empty, or
            # EVERY checkpoint failed verification and was quarantined
            # (recompute beats restoring corruption, and the
            # quarantined evidence survives for the post-mortem).
            logger.warning(
                "No intact checkpoint in %s (%d quarantined) — "
                "starting from scratch",
                self._dir, len(self.quarantined),
            )
            return state, 0
        logger.info("Resumed from checkpoint epoch %d", epoch)
        return restored, epoch + 1

    def wait(self) -> None:
        """Block until async saves are durable (call before exit);
        durable saves then get their integrity manifests."""
        self._mgr.wait_until_finished()
        self._flush_manifests()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._flush_manifests()
        self._mgr.close()
        if self._pytree_mgr is not None:
            self._pytree_mgr.close()
            self._pytree_mgr = None

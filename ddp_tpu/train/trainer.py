"""Training orchestration — the ``ddp_train`` body (train_ddp.py:17-212).

Same observable flow as the reference's only framework function:
setup → model → data → optimizer → auto-resume → epoch/batch loop with
process-0 loss logging every ``log_interval`` batches → per-epoch
checkpoint → cleanup. Plus what the reference lacks but its north star
requires: a test-split eval loop (accuracy) and step/throughput metrics.

Architectural difference, on purpose: the reference's hot loop crosses
Python→C++ per op and syncs on a collective each backward; here the
whole step (forward, backward, all-reduce, update) is one compiled XLA
program, and the Python loop just feeds it batches and reads metrics.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from collections import deque
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ddp_tpu.data.loader import ShardedLoader
from ddp_tpu.data.registry import load_dataset
from ddp_tpu.models import get_model
from ddp_tpu.obs.goodput import (
    GoodputAccountant,
    mfu as _mfu,
    peak_flops_per_chip,
    train_flops_per_example,
)
from ddp_tpu.obs.health import (
    HealthHaltError,
    HealthMonitor,
    NonFiniteLossError,
    group_layout,
    parse_inject,
)
from ddp_tpu.obs.recorder import FlightRecorder, snapshot_env
from ddp_tpu.obs.sentry import AnomalySentry, SentryConfig
from ddp_tpu.obs.steptime import StepAttributor, dispatch_compute_split
from ddp_tpu.obs.tracer import Tracer
from ddp_tpu.obs.xprof import DeviceMemorySampler, Xprof
from ddp_tpu.parallel.ddp import (
    create_train_state,
    make_eval_step,
    make_train_step,
    replicate_state,
)
from ddp_tpu.runtime import consensus, dist
from ddp_tpu.runtime.chaos import ChaosEngine
from ddp_tpu.runtime.mesh import MeshSpec, data_axes, make_mesh
from ddp_tpu.train.checkpoint import CheckpointManager
from ddp_tpu.train.config import TrainConfig
from ddp_tpu.utils.logging import setup_logging
from ddp_tpu.utils.metrics import MetricsWriter
from ddp_tpu.utils.watchdog import StepWatchdog

logger = logging.getLogger("ddp_tpu")


def _ctor_accepts(model_name: str, kwarg: str) -> bool:
    """Does the registry model's constructor take ``kwarg``?

    Signature inspection (explicit parameter or **kwargs) — a
    capability check, not exception-message sniffing, so a genuine
    TypeError from construction is never misread as "drop the kwarg".
    """
    import inspect

    from ddp_tpu.models import _REGISTRY

    ctor = _REGISTRY.get(model_name)
    if ctor is None:
        return False
    try:
        params = inspect.signature(ctor).parameters
    except (TypeError, ValueError):
        return False
    return kwarg in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    )



def _check_ulysses_heads(num_heads: int, mesh_model: int, mesh_seq: int):
    """Ulysses re-shards each model member's LOCAL heads
    (num_heads/mesh_model) over ``seq`` — one definition for the seq
    AND pipe families so the rule cannot drift; fail at construction,
    not at first trace (parallel/ring.py)."""
    local_heads = num_heads // max(1, mesh_model)
    if local_heads % max(1, mesh_seq):
        raise ValueError(
            f"ulysses shards attention heads: {local_heads} heads per "
            f"model shard ({num_heads} total / --mesh_model "
            f"{mesh_model}) not divisible by --mesh_seq {mesh_seq}"
        )

def _check_tp_dims(config: TrainConfig) -> None:
    """Megatron TP divisibility rules, shared by the seq family and
    the whole pipe family (LM and ViT — one definition, none may
    drift): attention heads and the 4×d_model MLP hidden dim split
    over ``model``. (The ViT's mlp_dim is embed_dim × mlp_ratio,
    which coincides with 4×d_model because the trainer pins
    mlp_ratio=4; a configurable ratio must update this rule.)"""
    d_model = config.model_dim or 64
    if config.num_heads % config.mesh_model:
        raise ValueError(
            f"tensor parallelism splits attention heads: "
            f"--num_heads {config.num_heads} not divisible by "
            f"--mesh_model {config.mesh_model}"
        )
    if (d_model * 4) % config.mesh_model:
        raise ValueError(
            f"tensor parallelism splits the MLP hidden dim: "
            f"{d_model * 4} (4 × --model_dim) not divisible "
            f"by --mesh_model {config.mesh_model}"
        )


@dataclasses.dataclass
class EpochStats:
    epoch: int
    mean_loss: float
    seconds: float
    images_per_sec: float


class Trainer:
    def __init__(self, config: TrainConfig, ctx: dist.DistContext | None = None):
        self.config = config
        self.ctx = ctx or dist.setup(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
            backend=config.backend,
            emulate_devices=config.emulate_devices,
        )
        setup_logging(self.ctx.process_id)
        # Observability (ddp_tpu.obs), constructed first so dataset
        # staging and step-builder work below can be spanned: tracer +
        # per-step attribution, both gated on --trace_dir (disabled
        # mode is pinned free by tests/test_obs.py).
        self.tracer = Tracer(
            enabled=bool(config.trace_dir),
            ring_events=config.trace_ring_events,
            process_id=self.ctx.process_id,
        )
        # Compiled-program introspection (--xprof, obs/xprof.py): the
        # hot-path jit programs are instrumented below (per family, at
        # the site where the raw jit object is in hand) so every
        # compile lands in a ledger with XLA-measured FLOPs/memory/
        # collectives, recompiles carry culprits, and the step/epoch
        # records gain the device-memory high-water. Disabled,
        # instrument() is the identity and the sampler returns {} —
        # pinned free like the tracer.
        if config.xprof and config.fast_epoch:
            raise ValueError(
                "--xprof instruments the per-step hot path, but "
                "--fast_epoch runs a whole epoch as ONE dispatch "
                "(dispatch_compute_split already reports its compile "
                "count) — drop one of the two"
            )
        self._xprof = Xprof(enabled=config.xprof)
        self._hbm = DeviceMemorySampler(enabled=config.xprof)
        self._xprof_cursor = 0
        self._comm_checked = False
        self._attr = StepAttributor(
            enabled=bool(config.trace_dir), tracer=self.tracer,
            xprof=self._xprof,
        )
        # Run health (obs/health.py): the in-graph stats pass rides the
        # step builders; the monitor/sentry are constructed after the
        # metrics writer below. Validated here so a bad combination
        # fails before any device work.
        self._health_inject = parse_inject(config.health_inject_nan)
        if self._health_inject is not None and not config.health:
            raise ValueError("--health_inject_nan requires --health")
        if config.health and config.fast_epoch:
            raise ValueError(
                "--health retires per-step gradient stats, but "
                "--fast_epoch runs a whole epoch as ONE dispatch with "
                "no per-step host visibility — drop one of the two"
            )
        if config.health and config.model == "pipe_vit":
            raise ValueError(
                "--health needs a step that computes gradient stats; "
                "the pipe_vit step does not (it reports no grad_norm "
                "either) — use pipe_lm or a non-pipe model"
            )
        # Multi-process --health_action checkpoint|halt: sentry events
        # come from HOST-local signals (wall-clock deltas, the process
        # compile counter), so one rank can see an anomaly its peers
        # don't — but ckpt.save is collective and a one-rank halt
        # leaves peers blocked in the next step's collective. Events
        # are therefore DEFERRED to the next agreement point (the same
        # deterministic cadence the preemption flag uses), where one
        # allgather (runtime/consensus.agree_any) turns "any rank saw
        # it" into "every rank acts together" — the PR-4 restriction,
        # lifted. Deferred events ride these queues:
        self._pending_halt: list[dict] = []
        self._pending_rescue: list[dict] = []
        # Keyword bundle for the step builders that support the fused
        # health pass; {} leaves unsupported builders' graphs untouched.
        hkw = (
            dict(health=True, health_inject=self._health_inject)
            if config.health
            else {}
        )

        if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
            # Repeat CLI runs skip the first-compile wait (~20-40s on
            # TPU). Compiled programs are keyed by HLO+flags, so a
            # config change recompiles correctly. "" explicitly
            # disables — including un-setting a cache a previous
            # Trainer in this process enabled (the config is
            # process-global).
            #
            # CPU backends leave the DEFAULT cache off: XLA:CPU AOT
            # deserialization is machine-feature-sensitive (the
            # tests/conftest.py round-6 finding — cache-loaded
            # executables SIGSEGV/SIGABRT on mismatched hosts;
            # reproduced on resumed --health runs, whose larger step
            # crosses the 1s persistence threshold), and a CPU
            # compile is seconds, not the 20-40s the cache exists to
            # save. An explicit --compile_cache_dir (≠ the default)
            # or the env var still opts in anywhere.
            cache_dir = config.compile_cache_dir
            if (
                cache_dir == TrainConfig.compile_cache_dir
                and jax.default_backend() == "cpu"
            ):
                cache_dir = ""
            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.expanduser(cache_dir) if cache_dir else None,
            )

        devices = jax.devices()
        if config.num_devices > 0:
            devices = devices[: config.num_devices]
        # Sequence family: token-sharded models over the seq axis
        # (ring/Ulysses attention) with their own step/eval builders —
        # the long-context classifier and the causal LM.
        self.lm_mode = config.model == "causal_lm"
        if config.moe_experts and not (
            self.lm_mode or config.model == "pipe_lm"
        ):
            raise ValueError(
                "--moe_experts routes the causal LM's MLPs: use "
                "--model causal_lm or pipe_lm (images have "
                "--model vit_moe_tiny)"
            )
        if config.moe_experts and config.moe_every < 1:
            raise ValueError(
                f"--moe_every must be >= 1, got {config.moe_every}"
            )
        if (
            config.moe_experts
            and config.model == "pipe_lm"
            and (config.model_depth or 1) % config.moe_every
        ):
            # One stacked stage tree feeds one shard_map trace, so
            # every chunk must have the SAME routed-block positions;
            # the global every-k pattern is chunk-periodic iff k
            # divides the per-stage depth. Flat models with k not
            # dividing D (e.g. depth 6 = 2 stages x 3, moe_every 2)
            # need per-chunk param-tree structures, which stacked
            # SPMD stages cannot express — use --model causal_lm for
            # those, or pick k | model_depth (any k, including odd
            # depths: --model_depth 3 --moe_every 3, or 1).
            raise ValueError(
                "the pipelined MoE-LM needs --moe_every "
                f"({config.moe_every}) to divide --model_depth "
                f"({config.model_depth or 1}): stages must be "
                "structure-uniform for parameter stacking (the flat "
                "--model causal_lm expresses any pattern)"
            )
        self.seq_mode = config.model == "long_context" or self.lm_mode
        if config.mesh_seq > 1 and not (
            self.seq_mode or config.model == "pipe_lm"
        ):
            raise ValueError(
                "--mesh_seq shards tokens, which only the sequence "
                "models have: use --model long_context, causal_lm, or "
                "pipe_lm (PP×SP)"
            )
        # Pipeline family: the whole model rides the pipe axis under
        # GPipe / 1F1B / interleaved — the ViT (models/pipeline_vit.py)
        # and, since round 4, the causal LM (models/pipeline_lm.py,
        # which additionally composes with Megatron TP over ``model``:
        # the PP×TP layout).
        self.pipe_lm_mode = config.model == "pipe_lm"
        self.pipe_mode = config.model == "pipe_vit" or self.pipe_lm_mode
        if config.mesh_pipe > 1 and not self.pipe_mode:
            raise ValueError(
                "--mesh_pipe cuts a model into stages, which only the "
                "pipeline family has: use --model pipe_vit or pipe_lm"
            )
        if self.pipe_mode and config.mesh_pipe < 2:
            raise ValueError(
                f"--model {config.model} needs --mesh_pipe >= 2 (a "
                "1-stage pipeline is the plain step — drop the flag)"
            )
        if self.pipe_mode and (
            (config.mesh_expert > 1 and not self.pipe_lm_mode)
            or (config.mesh_seq > 1 and not self.pipe_lm_mode)
            or config.zero1
            or config.grad_accum_steps > 1
            # augment is image-family: the pipelined ViT takes it
            # (applied to the global batch before microbatching);
            # token data has nothing to crop.
            or (
                self.pipe_lm_mode
                and config.augment not in (None, "none")
            )
        ):
            raise ValueError(
                f"--model {config.model} composes with the data axis, "
                "fsdp (ZeRO-sharded stage params), tp (--mesh_model, "
                "PP×TP)"
                + (", expert (--mesh_expert, PP×EP), seq "
                   "(--mesh_seq, PP×SP — ulysses under 1f1b/"
                   "interleaved, ring under gpipe)"
                   if self.pipe_lm_mode else ", augment")
                + ", --fast_epoch, bf16, remat, label smoothing, EMA "
                "and LR schedules — not "
                + ("" if self.pipe_lm_mode else "expert/seq/")
                + "zero1, accumulation (use --num_microbatches)"
                + (", or augment" if self.pipe_lm_mode else "")
            )
        if self.pipe_mode and config.mesh_model > 1:
            _check_tp_dims(config)
        if (self.seq_mode or self.pipe_mode) and (
            config.num_heads < 1
            or (config.model_dim or 64) % config.num_heads
        ):
            # One guard for both spec-driven families (the registry
            # models fix their own head counts).
            raise ValueError(
                f"--num_heads {config.num_heads} must be >= 1 and "
                f"divide --model_dim {config.model_dim or 64}"
            )
        if config.num_kv_heads:
            if not (
                (self.seq_mode and config.model == "causal_lm")
                or self.pipe_lm_mode
            ):
                raise ValueError(
                    "--num_kv_heads (grouped-query attention) shrinks "
                    "the causal LM's generation KV cache: use --model "
                    "causal_lm or pipe_lm (or drop the flag)"
                )
            if (
                config.num_kv_heads < 1
                or config.num_heads % config.num_kv_heads
            ):
                raise ValueError(
                    f"--num_kv_heads {config.num_kv_heads} must be >= 1 "
                    f"and divide --num_heads {config.num_heads}"
                )
            if (
                config.mesh_model > 1
                and config.num_kv_heads % config.mesh_model
            ):
                raise ValueError(
                    "GQA under TP shards whole kv groups: "
                    f"--num_kv_heads {config.num_kv_heads} not "
                    f"divisible by --mesh_model {config.mesh_model}"
                )
        if self.pipe_mode and config.num_microbatches < 1:
            raise ValueError(
                f"--num_microbatches must be >= 1, got "
                f"{config.num_microbatches}"
            )
        if config.virtual_stages < 1:
            raise ValueError(
                f"--virtual_stages must be >= 1, got {config.virtual_stages}"
            )
        if config.virtual_stages > 1 and not self.pipe_mode:
            raise ValueError(
                "--virtual_stages cuts a pipelined model into chunks: "
                "use --model pipe_vit or pipe_lm (with --mesh_pipe "
                "and --pipe_schedule interleaved)"
            )
        if config.virtual_stages > 1 and config.pipe_schedule != "interleaved":
            raise ValueError(
                "--virtual_stages places multiple model chunks per "
                "device, which only the interleaved schedule streams: "
                "add --pipe_schedule interleaved"
            )
        if self.pipe_mode and config.num_microbatches % config.mesh_pipe:
            raise ValueError(
                f"--num_microbatches {config.num_microbatches} must be "
                f"a multiple of --mesh_pipe {config.mesh_pipe} (the "
                "sharded stream rests microbatch m on device m mod S)"
            )
        # Any non-data axis > 1 switches to the GSPMD step — tensor/
        # fsdp/expert sharding by annotation (parallel/spmd.py). A pure
        # data mesh keeps the explicit shard_map DDP step.
        self.use_spmd = (
            config.mesh_model > 1
            or config.mesh_fsdp > 1
            or config.mesh_expert > 1
            or config.zero1  # opt-state sharding rides the GSPMD step
        )
        # ZeRO-style weight-update sharding (--parallel zero,
        # parallel/zero.py): reduce-scatter grads, 1/N sharded
        # optimizer update, all-gather params. Validated here, before
        # any device or dataset work, so a bad combination fails with
        # the flags named.
        # Two-level pod geometry (--mesh_dcn, runtime/mesh.py): the
        # slice axis is a replica axis of the explicit shard_map
        # families — the DDP image step (flat reduction spans it) and
        # the zero step (which goes HIERARCHICAL over it). The
        # annotation-driven/pipelined/sequence paths have not earned
        # the axis yet; reject with the flags named.
        if config.mesh_dcn < 1:
            raise ValueError(
                f"--mesh_dcn must be >= 1, got {config.mesh_dcn}"
            )
        if config.mesh_dcn > 1 and (
            self.use_spmd
            or self.pipe_mode
            or self.seq_mode
            or config.fast_epoch
        ):
            raise ValueError(
                "--mesh_dcn slices the replica axes of the explicit "
                "shard_map families: the DDP image path and --parallel "
                "zero (hierarchical collectives). Drop the slice axis "
                "or the GSPMD/pipe/seq/fast_epoch flags"
            )
        self.zero_mode = config.parallel == "zero"
        # Tuning cache (ddp_tpu.tune): fill zero knobs the command
        # line left at defaults from the cached winner for this model
        # shape. Applied BEFORE the validation below so a cached value
        # passes the same checks a flag would; explicit flags always
        # win (config.explicit_flags, set by TrainConfig.from_args —
        # directly-constructed configs fall back to comparing against
        # the dataclass defaults). --tuned off, or no cache file:
        # nothing here runs and every record stays byte-identical.
        self._tuning: dict | None = None
        if self.zero_mode and getattr(config, "tuned", "off") != "off":
            from ddp_tpu.tune import (
                apply_tuned,
                cache_key,
                resolve_cache,
                train_signature,
            )

            _tcache = resolve_cache(config.tuned, config.checkpoint_dir)
            _tent = (
                _tcache.lookup(cache_key("zero", train_signature(config)))
                if _tcache is not None
                else None
            )
            if _tent is not None:
                explicit = getattr(config, "explicit_flags", None)
                if explicit is None:
                    defaults = {
                        f.name: f.default
                        for f in dataclasses.fields(type(config))
                    }
                    explicit = {
                        k
                        for k in ("zero_bucket_mb", "zero_gather_dtype")
                        if getattr(config, k) != defaults.get(k)
                    }
                current = {
                    "zero_bucket_mb": config.zero_bucket_mb,
                    "zero_gather_dtype": config.zero_gather_dtype,
                }
                merged, applied, overridden = apply_tuned(
                    current, _tent["config"], explicit=set(explicit)
                )
                config.zero_bucket_mb = merged["zero_bucket_mb"]
                config.zero_gather_dtype = merged["zero_gather_dtype"]
                self._tuning = {
                    "site": "zero",
                    "cache": _tcache.path,
                    "applied": applied,
                    "overridden": overridden,
                }
        # Global-norm clipping under zero is applied IN-STEP from the
        # scattered shards (psum of per-shard squared sums); the
        # optimizer is then built without the chained optax clip.
        self._zero_clip = 0.0
        if self.zero_mode:
            from ddp_tpu.train.optim import check_zero_compatible

            if config.zero1 or config.mesh_fsdp > 1 or config.mesh_expert > 1:
                raise ValueError(
                    "--parallel zero shards the update over the data "
                    "axis; fsdp/expert meshes (and --zero1) already "
                    "shard optimizer state their own way — fsdp IS "
                    "ZeRO-3 — drop the axes/flag or --parallel"
                )
            if (
                config.mesh_model > 1 or config.mesh_seq > 1
            ) and not self.lm_mode:
                raise ValueError(
                    "--parallel zero composes with model/seq axes on "
                    "--model causal_lm only (the GSPMD expression "
                    "shards buckets over data and replicates them over "
                    "the model axes); this model keeps the data axis "
                    "only"
                )
            if config.mesh_pipe > 1:
                raise ValueError(
                    "--parallel zero composes with the data axis only "
                    "(the sharded update scatters over it); drop "
                    "--mesh_pipe or --parallel"
                )
            if self.pipe_mode or (self.seq_mode and not self.lm_mode):
                raise ValueError(
                    f"--parallel zero covers the DDP image family and "
                    f"--model causal_lm; {config.model!r} keeps its "
                    "own update path"
                )
            if config.fast_epoch:
                raise ValueError(
                    "--fast_epoch scans the plain DDP step; the zero "
                    "strategy has its own hot loop — drop one"
                )
            if config.health:
                raise ValueError(
                    "--health groups gradient stats by layer path, but "
                    "--parallel zero only materializes 1/N FLAT "
                    "gradient shards (the reduced full-gradient tree "
                    "never exists) — drop one"
                )
            check_zero_compatible(
                config.optimizer,
                grad_clip_norm=config.grad_clip_norm,
                ema_decay=config.ema_decay,
            )
            self._zero_clip = config.grad_clip_norm
            if config.zero_bucket_mb <= 0:
                raise ValueError(
                    f"--zero_bucket_mb must be > 0, got "
                    f"{config.zero_bucket_mb}"
                )
        self._zero_layout = None
        # Per-step collective-payload estimate (parallel/zero.py): set
        # on the strategies whose comm story the bench compares (plain
        # DDP and zero); None elsewhere omits the metrics field. The
        # by-axis split is present exactly when the step is
        # hierarchical (dcn > 1) — flat streams keep their schema.
        self._comm_bytes: int | None = None
        self._comm_by_axis: dict | None = None
        # The once-per-run xprof cross-check compares _comm_bytes to
        # the WHOLE program's collectives — only honest when the
        # estimate covers them all. The zero×model/seq composition's
        # program also carries TP/SP activation collectives the
        # update-payload estimate deliberately omits, so the check is
        # disabled there (the estimate still stamps records).
        self._comm_check_enabled = True
        from ddp_tpu.data.augment import get_augmentation

        self.dataset = config.dataset
        if self.dataset == "auto":
            self.dataset = (
                "synthetic_seq"
                if self.seq_mode or self.pipe_lm_mode
                else "mnist"
            )
        # Round 1 walled the sequence family off from everything but
        # data+seq (VERDICT.md weak #4); round 2 lifted fsdp
        # (parallel/seq_fsdp.py), accumulation, and label smoothing;
        # round 3 lifts tensor parallelism (parallel/tp.py — Megatron
        # column/row inside the shard_map step, composing with seq and
        # fsdp) and expert parallelism for the MoE-LM (models/moe.py
        # MoEMLP all-to-all dispatch over the ``expert`` axis); round 4
        # lifts --fast_epoch for the causal LM (train/fast.py
        # make_lm_epoch_runner — the compiled-epoch dispatch over the
        # same raw step). What remains out: zero1 (subsumed by fsdp,
        # which shards moments too), the image-only augment pipeline,
        # and fast_epoch for the long-context classifier.
        if self.seq_mode and (
            config.zero1
            or (config.fast_epoch and not self.lm_mode)
            or get_augmentation(config.augment) is not None
        ):
            raise ValueError(
                f"--model {config.model} composes with data/seq/fsdp/"
                "model/expert mesh axes, accumulation, label smoothing "
                "and bf16 — but not zero1 (use --mesh_fsdp), augment"
                + (
                    ""
                    if self.lm_mode
                    else ", or --fast_epoch (causal_lm only)"
                )
            )
        if (self.seq_mode or self.pipe_lm_mode) and config.mesh_expert > 1:
            if not config.moe_experts:
                raise ValueError(
                    "--mesh_expert shards MoE expert weights: give the "
                    "LM experts with --moe_experts N (or drop the axis)"
                )
            if config.moe_experts % config.mesh_expert:
                raise ValueError(
                    f"--moe_experts {config.moe_experts} not divisible "
                    f"by --mesh_expert {config.mesh_expert}"
                )
        if self.seq_mode and config.mesh_model > 1:
            # TP×MoE composes since round 5 (the Megatron-MoE layout):
            # attention heads shard over ``model`` in routed blocks
            # too, the expert MLPs stay replicated across ``model``
            # (experts shard over --mesh_expert — EP owns the MoE
            # sharding story).
            _check_tp_dims(config)
        mesh_spec = MeshSpec(
            data=-1,
            pipe=config.mesh_pipe,
            model=config.mesh_model,
            fsdp=config.mesh_fsdp,
            expert=config.mesh_expert,
            seq=config.mesh_seq,
            dcn=config.mesh_dcn,
        )
        if config.elastic:
            # Elastic world resize (docs/ROBUSTNESS.md): this process
            # may be a relaunch of a differently-sized world. The mesh
            # is re-derived from the LIVE device count (the fixed axes
            # are the sharding contract and must still tile it), and
            # the per-shard batch below absorbs the change so the
            # recorded global batch — what a step MEANS — survives.
            if self.pipe_mode:
                raise ValueError(
                    "--elastic excludes the in-graph pipeline family: "
                    "stage params rest per-device, so a resize would "
                    "need stage re-placement, not a reshard. For an "
                    "elastic pipeline use the MPMD runtime (python -m "
                    "ddp_tpu.parallel.mpmd) — one process per stage, "
                    "per-stage restart and checkpoint-sliced resume — "
                    "or drop --elastic"
                )
            from ddp_tpu.runtime.mesh import live_world_spec

            mesh_spec = live_world_spec(mesh_spec, len(devices))
        self.mesh = make_mesh(mesh_spec, devices=devices)
        self.data_shards = int(
            np.prod([self.mesh.shape[a] for a in data_axes(self.mesh)])
        )
        # With accumulation the loader delivers k microbatches' worth at
        # once; the step splits them and applies one update.
        self.per_shard_batch = config.batch_size
        self.global_batch_size = (
            self.per_shard_batch * self.data_shards * config.grad_accum_steps
        )
        if config.elastic:
            # Honor the run's recorded global-batch contract: flags are
            # per-shard, so at a resized world the natural product above
            # would change the global batch — and with it the meaning
            # of the checkpointed step counter, the LR schedule, and
            # the mid-epoch resume markers. The sampler's shard math
            # makes the rescale exact (same sample windows per step at
            # any divisor world — data/sampler.py).
            from ddp_tpu.data.sampler import rescale_per_shard_batch
            from ddp_tpu.train.checkpoint import load_elastic_contract

            contract = load_elastic_contract(config.checkpoint_dir)
            recorded = int(contract.get("global_batch_size") or 0)
            if recorded and recorded != self.global_batch_size:
                self.per_shard_batch = rescale_per_shard_batch(
                    recorded,
                    self.data_shards,
                    grad_accum_steps=config.grad_accum_steps,
                )
                logger.warning(
                    "Elastic resize: preserving recorded global batch "
                    "%d over %d data shard(s) — per-shard batch %d -> "
                    "%d",
                    recorded,
                    self.data_shards,
                    config.batch_size,
                    self.per_shard_batch,
                )
                self.global_batch_size = recorded

        from ddp_tpu.data.registry import NUM_CLASSES
        from ddp_tpu.train.optim import make_optimizer

        if self.seq_mode:
            if config.seq_len % max(1, config.mesh_seq):
                raise ValueError(
                    f"--seq_len {config.seq_len} not divisible by "
                    f"--mesh_seq {config.mesh_seq}"
                )
            if self.lm_mode:
                from ddp_tpu.models.lm import LMSpec

                self.seq_spec = LMSpec(
                    vocab_size=config.vocab_size,
                    total_len=config.seq_len,
                    d_model=config.model_dim or 64,
                    depth=config.model_depth or 2,
                    num_heads=config.num_heads,
                    strategy=config.seq_strategy,
                    remat=config.remat,
                    num_experts=config.moe_experts,
                    moe_every=config.moe_every,
                    moe_top_k=config.moe_top_k,
                    moe_normalize_gates=config.moe_normalize_gates,
                    num_kv_heads=config.num_kv_heads,
                )
            else:
                from ddp_tpu.models.seq_transformer import (
                    SeqTransformerSpec,
                )

                self.seq_spec = SeqTransformerSpec(
                    num_classes=config.num_classes or 10,
                    total_len=config.seq_len,
                    d_in=config.seq_dim,
                    d_model=config.model_dim or 64,
                    depth=config.model_depth or 2,
                    num_heads=config.num_heads,
                    strategy=config.seq_strategy,
                    remat=config.remat,
                )
            if config.seq_strategy == "ulysses":
                _check_ulysses_heads(
                    self.seq_spec.num_heads, config.mesh_model,
                    config.mesh_seq,
                )
            self.model = None  # spec-driven; no registry module
        elif self.pipe_mode:
            # Spec built after the data split is known (patch size
            # follows the image side); no registry module.
            self.model = None
        else:
            model_kw = {}
            if config.model_depth is not None:
                model_kw["depth"] = config.model_depth
            if config.remat:
                model_kw["remat"] = True
            if self.use_spmd and _ctor_accepts(
                config.model, "attention_fn"
            ):
                # The GSPMD step partitions by annotation; a compiled
                # Mosaic custom call (the flash default on TPU) has no
                # partitioning rule there, unlike the shard_map paths
                # (DDP/seq/pipe) where Pallas is first-class. Route
                # attention through a shard_map ISLAND instead
                # (ops/attention.py gspmd_flash_attention): batch over
                # the data axes, heads over model — which resolves to
                # plain dense XLA below FLASH_MIN_LEN keys (all the
                # image family today, T≤197, where one fused einsum
                # chain wins) and to the Pallas kernel above it, so a
                # long-sequence GSPMD model keeps the kernel. On CPU
                # both branches are the dense path, unchanged.
                from ddp_tpu.ops.attention import gspmd_flash_attention

                model_kw["attention_fn"] = gspmd_flash_attention(self.mesh)
            n_classes = config.num_classes or NUM_CLASSES.get(self.dataset, 10)
            try:
                self.model = get_model(
                    config.model, num_classes=n_classes, **model_kw
                )
            except TypeError as e:
                if config.remat and "remat" in str(e):
                    raise ValueError(
                        f"--remat is not supported by model {config.model!r} "
                        "(no block stack to rematerialize)"
                    ) from e
                raise
        milestones = tuple(
            int(m) for m in config.lr_milestones.split(",") if m.strip()
        )
        self._opt_kwargs = dict(
            lr=config.lr,
            momentum=config.momentum,
            weight_decay=config.weight_decay,
            warmup_steps=config.warmup_steps,
            decay_steps=config.decay_steps,
            # In zero mode the clip moves into the sharded step (a
            # chained optax clip would read PER-SHARD norms there).
            grad_clip_norm=(
                0.0 if self.zero_mode else config.grad_clip_norm
            ),
            ema_decay=config.ema_decay,
            lr_milestones=milestones,
            lr_decay_factor=config.lr_decay_factor,
        )
        self.optimizer = make_optimizer(config.optimizer, **self._opt_kwargs)
        from ddp_tpu.train.optim import make_schedule

        # The schedule alone, for logging the current lr per step —
        # derived from the SAME kwargs the optimizer was built with so
        # the logged lr can't drift from the trained one.
        self._lr_schedule = make_schedule(
            self._opt_kwargs["lr"],
            **{
                k: self._opt_kwargs[k]
                for k in (
                    "warmup_steps", "decay_steps",
                    "lr_milestones", "lr_decay_factor",
                )
            },
        )

        token_mode = self.lm_mode or self.pipe_lm_mode
        if self.seq_mode or self.pipe_lm_mode:
            if self.dataset == "text":
                # Real data for the LM: a corpus file — raw bytes at
                # --vocab_size <= 256, BPE subwords above (the trained
                # tokenizer persists next to the checkpoints: it is
                # part of the model, and generation needs it to decode).
                if not token_mode:
                    raise ValueError(
                        "--dataset text is causal-LM data (bytes, no "
                        "class labels): use --model causal_lm or pipe_lm"
                    )
                if not config.text_file:
                    raise ValueError("--dataset text needs --text_file PATH")
                from ddp_tpu.data.text import load_text_corpus

                train_split, test_split = load_text_corpus(
                    config.text_file, config.seq_len,
                    vocab_size=config.vocab_size,
                    tokenizer_path=os.path.join(
                        config.checkpoint_dir, "tokenizer.json"
                    ),
                )
            elif self.dataset != "synthetic_seq":
                raise ValueError(
                    f"--model {config.model} trains on sequences, not "
                    f"{self.dataset!r}: use --dataset synthetic_seq, "
                    "--dataset text (or leave --dataset unset)"
                )
            else:
                from ddp_tpu.data import sequences
                from ddp_tpu.data.mnist import Split

                n = config.synthetic_size or 2048

                def seq_split(count, seed):
                    if token_mode:
                        toks = sequences.synthetic_tokens(
                            count, total_len=config.seq_len,
                            vocab_size=config.vocab_size, seed=seed,
                        )
                        # labels unused: targets are the shifted tokens
                        return Split(toks, np.zeros(count, np.int32))
                    return sequences.synthetic(
                        count, total_len=config.seq_len, d_in=config.seq_dim,
                        num_classes=self.seq_spec.num_classes, seed=seed,
                    )

                train_split = seq_split(n, config.seed)
                test_split = seq_split(max(1, n // 6), config.seed + 1)
        else:
            train_split, test_split = load_dataset(
                self.dataset,
                config.data_root,
                allow_synthetic=config.synthetic_data,
                synthetic_size=config.synthetic_size,
            )
        self.train_split, self.test_split = train_split, test_split
        self.loader = ShardedLoader(
            train_split.images,
            train_split.labels,
            self.mesh,
            self.global_batch_size,
            shuffle=config.shuffle,
            seed=config.seed,
            # The fast path never drains the loader, and the seq path
            # feeds float sequences the byte-pipeline can't serve —
            # don't spin up (or warn about) a pool that can't be used.
            num_workers=0
            if (config.fast_epoch or self.seq_mode or self.pipe_lm_mode)
            else config.num_workers,
        )

        compute_dtype = jnp.bfloat16 if config.compute_dtype == "bfloat16" else jnp.float32
        augment_fn = get_augmentation(config.augment)
        sample = jnp.zeros(
            (1, *train_split.images.shape[1:]), jnp.float32
        )
        if self.seq_mode:
            from ddp_tpu.parallel.ddp import TrainState

            if self.lm_mode:
                from ddp_tpu.models.lm import (
                    create_lm_train_state,
                    init_lm,
                    make_lm_eval_step,
                    make_lm_train_step,
                )

                if self.zero_mode:
                    # The causal LM rides the IN-GRAPH GSPMD zero
                    # expression (parallel/zero.py zero_gspmd_update):
                    # the bucket layout is built from abstract shapes
                    # so no replicated moment tree ever materializes.
                    # model/seq axes compose here — buckets shard over
                    # data and replicate over them (check_zero_mesh
                    # allow_model_axes).
                    from ddp_tpu.parallel.zero import (
                        build_layout,
                        check_zero_mesh,
                        zero_comm_bytes,
                    )

                    check_zero_mesh(self.mesh, allow_model_axes=True)
                    seq_spec = self.seq_spec
                    self._zero_layout = build_layout(
                        jax.eval_shape(
                            lambda: init_lm(seq_spec, seed=config.seed)
                        ),
                        int(self.mesh.shape["data"]),
                        bucket_mb=config.zero_bucket_mb,
                    )
                    self._comm_bytes = zero_comm_bytes(
                        self._zero_layout,
                        int(self.mesh.shape["data"]),
                        grad_accum_steps=config.grad_accum_steps,
                        gspmd=True,
                        gather_dtype=config.zero_gather_dtype,
                    )["total"]
                    if config.mesh_model > 1 or config.mesh_seq > 1:
                        # TP/SP activation collectives are in the
                        # program but not the update-payload estimate
                        # — a ratio check would alarm spuriously.
                        self._comm_check_enabled = False
                # Instrumented HERE (not on the label-dropping lambda
                # below): only the raw jit object can lower for the
                # xprof compile ledger.
                lm_step = self._xprof.instrument(
                    make_lm_train_step(
                        self.seq_spec, self.optimizer, self.mesh,
                        compute_dtype=compute_dtype,
                        grad_accum_steps=config.grad_accum_steps,
                        label_smoothing=config.label_smoothing,
                        zero_layout=self._zero_layout,
                        zero_gather_dtype=(
                            config.zero_gather_dtype
                            if self.zero_mode
                            else None
                        ),
                        zero_grad_clip_norm=self._zero_clip,
                        **hkw,
                    ),
                    "train_step",
                )
                # labels ride the loader but the LM has no use for
                # them — targets are the shifted tokens.
                self.train_step = lambda s, toks, lbls: lm_step(s, toks)
                self.eval_step = make_lm_eval_step(
                    self.seq_spec, self.mesh, compute_dtype=compute_dtype,
                )
                st = create_lm_train_state(
                    self.seq_spec, self.optimizer, self.mesh,
                    seed=config.seed,
                    zero_layout=self._zero_layout,
                    zero_gather_dtype=(
                        config.zero_gather_dtype if self.zero_mode else None
                    ),
                )
            else:
                from ddp_tpu.models.seq_transformer import (
                    create_seq_train_state,
                    make_seq_parallel_eval_step,
                    make_seq_parallel_train_step,
                )

                self.train_step = make_seq_parallel_train_step(
                    self.seq_spec, self.optimizer, self.mesh,
                    compute_dtype=compute_dtype,
                    grad_accum_steps=config.grad_accum_steps,
                    label_smoothing=config.label_smoothing,
                    **hkw,
                )
                self.eval_step = make_seq_parallel_eval_step(
                    self.seq_spec, self.mesh, compute_dtype=compute_dtype,
                )
                st = create_seq_train_state(
                    self.seq_spec, self.optimizer, self.mesh,
                    seed=config.seed,
                )
            # The trainer's state type (checkpoint schema parity);
            # model_state stays {} — the model is stateless. Replicate
            # EVERY leaf (incl. the step scalar) over the mesh so
            # restored checkpoints come back with uniform shardings —
            # unless fsdp/tp sharded the params at rest, in which case
            # those placements ARE the contract and must survive.
            st_tr = TrainState(
                step=st.step, params=st.params,
                opt_state=st.opt_state, model_state={},
            )
            self.state = (
                st_tr
                if config.mesh_fsdp > 1
                or config.mesh_model > 1
                or config.mesh_expert > 1
                # zero: the data-sharded flat moments ARE the contract
                # — a blanket replicate would silently undo the win.
                or self.zero_mode
                else replicate_state(st_tr, self.mesh)
            )
        elif self.pipe_lm_mode:
            from ddp_tpu.models.pipeline_lm import (
                PipeLMConfig,
                PipeLMState,
                create_pipe_lm_state,
                make_pipe_lm_1f1b_train_step,
                make_pipe_lm_eval_step,
                make_pipe_lm_interleaved_train_step,
                make_pipe_lm_train_step,
            )
            from ddp_tpu.parallel.ddp import TrainState
            from ddp_tpu.parallel.pipeline import bubble_fraction

            self._check_pipe_batch(config)
            interleaved = config.pipe_schedule == "interleaved"
            if config.mesh_seq > 1:
                if config.seq_len % config.mesh_seq:
                    raise ValueError(
                        f"--seq_len {config.seq_len} not divisible by "
                        f"--mesh_seq {config.mesh_seq}"
                    )
                if (
                    config.pipe_schedule != "gpipe"
                    and config.seq_strategy == "ring"
                ):
                    raise ValueError(
                        "PP×SP under the hand-scheduled schedules "
                        "(1f1b/interleaved) needs --seq_strategy "
                        "ulysses: ring's ppermute hops have no replica "
                        "groups and the schedules' fwd/bwd branches "
                        "diverge across pipe stages "
                        "(models/pipeline_lm.py has the full story); "
                        "ring works under --pipe_schedule gpipe"
                    )
                if config.seq_strategy == "ulysses":
                    _check_ulysses_heads(
                        config.num_heads, config.mesh_model,
                        config.mesh_seq,
                    )
            self.pipe_cfg = PipeLMConfig(
                vocab_size=config.vocab_size,
                seq_len=config.seq_len,
                d_model=config.model_dim or 64,
                num_heads=config.num_heads,
                num_stages=config.mesh_pipe,
                depth_per_stage=config.model_depth or 1,
                num_microbatches=config.num_microbatches,
                remat=config.remat,
                virtual_stages=config.virtual_stages,
                label_smoothing=config.label_smoothing,
                tp_size=config.mesh_model,
                num_kv_heads=config.num_kv_heads,
                num_experts=config.moe_experts,
                moe_every=config.moe_every,
                moe_top_k=config.moe_top_k,
                moe_normalize_gates=config.moe_normalize_gates,
                ep_size=config.mesh_expert,
                sp_size=config.mesh_seq,
                sp_strategy=config.seq_strategy,
            )
            if config.moe_experts:
                logger.info(
                    "Pipelined MoE: %d experts every %d-th block; the "
                    "GShard load-balance aux loss is not collected on "
                    "the pipe path (routing + capacity dropping still "
                    "apply) — use --model causal_lm for the full aux "
                    "objective",
                    config.moe_experts,
                    config.moe_every,
                )
            logger.info(
                "Pipeline LM: %d stages × %d virtual × %d blocks, %d "
                "microbatches, %s schedule, tp=%d, bubble fraction %.3f",
                self.pipe_cfg.num_stages,
                self.pipe_cfg.virtual_stages,
                self.pipe_cfg.depth_per_stage,
                self.pipe_cfg.num_microbatches,
                config.pipe_schedule,
                self.pipe_cfg.tp_size,
                bubble_fraction(
                    self.pipe_cfg.num_stages,
                    self.pipe_cfg.num_microbatches
                    * self.pipe_cfg.virtual_stages,
                ),
            )
            make_step = {
                "1f1b": make_pipe_lm_1f1b_train_step,
                "interleaved": make_pipe_lm_interleaved_train_step,
            }.get(config.pipe_schedule, make_pipe_lm_train_step)
            # Instrumented on the raw jit object (the state-converting
            # wrapper below cannot lower).
            pipe_step = self._xprof.instrument(
                make_step(
                    self.pipe_cfg, self.optimizer, self.mesh,
                    compute_dtype=compute_dtype,
                    **hkw,
                ),
                "train_step",
            )

            def step(ts, tokens, labels):
                del labels  # targets are the shifted tokens
                ps, metrics = pipe_step(
                    PipeLMState(ts.step, ts.params, ts.opt_state), tokens
                )
                return (
                    ts._replace(
                        step=ps.step, params=ps.params,
                        opt_state=ps.opt_state,
                    ),
                    metrics,
                )

            self.train_step = step
            self.eval_step = make_pipe_lm_eval_step(
                self.pipe_cfg, self.mesh, compute_dtype=compute_dtype
            )
            st = create_pipe_lm_state(
                self.pipe_cfg, self.optimizer, self.mesh,
                seed=config.seed, interleaved=interleaved,
            )
            # Stage params rest sharded over pipe (and model/fsdp when
            # composed) — those placements are the contract.
            self.state = TrainState(
                step=st.step,
                params=st.params,
                opt_state=st.opt_state,
                model_state={},
            )
        elif self.pipe_mode:
            from ddp_tpu.models.pipeline_vit import (
                PipeViTConfig,
                PipeViTState,
                create_pipe_vit_state,
                create_pipe_vit_state_interleaved,
                make_pipe_vit_1f1b_train_step,
                make_pipe_vit_apply,
                make_pipe_vit_interleaved_train_step,
                make_pipe_vit_train_step,
                sequential_apply_interleaved,
            )
            import optax

            from ddp_tpu.parallel.common import _preprocess
            from ddp_tpu.parallel.ddp import TrainState
            from ddp_tpu.parallel.pipeline import bubble_fraction

            self._check_pipe_batch(config)
            H = int(train_split.images.shape[1])
            pipe_heads = config.num_heads  # validated in __init__ above
            interleaved = config.pipe_schedule == "interleaved"
            self.pipe_cfg = PipeViTConfig(
                num_classes=config.num_classes
                or NUM_CLASSES.get(self.dataset, 10),
                patch_size=7 if H % 7 == 0 else 4,
                embed_dim=config.model_dim or 64,
                num_heads=pipe_heads,
                num_stages=config.mesh_pipe,
                depth_per_stage=config.model_depth or 1,
                num_microbatches=config.num_microbatches,
                remat=config.remat,
                virtual_stages=config.virtual_stages,
                tp_size=config.mesh_model,
            )
            if interleaved:
                from ddp_tpu.parallel.interleaved import schedule_interleaved

                sched = schedule_interleaved(
                    self.pipe_cfg.num_stages,
                    self.pipe_cfg.num_microbatches,
                    self.pipe_cfg.virtual_stages,
                )
                logger.info(
                    "Pipeline: %d stages × %d virtual × %d blocks, %d "
                    "microbatches, interleaved schedule, bubble "
                    "fraction %.3f (plain 1F1B: %.3f)",
                    self.pipe_cfg.num_stages,
                    self.pipe_cfg.virtual_stages,
                    self.pipe_cfg.depth_per_stage,
                    self.pipe_cfg.num_microbatches,
                    sched.bubble_fraction(),
                    bubble_fraction(
                        self.pipe_cfg.num_stages,
                        self.pipe_cfg.num_microbatches,
                    ),
                )
            else:
                logger.info(
                    "Pipeline: %d stages × %d blocks, %d microbatches, "
                    "%s schedule, bubble fraction %.3f",
                    self.pipe_cfg.num_stages, self.pipe_cfg.depth_per_stage,
                    self.pipe_cfg.num_microbatches, config.pipe_schedule,
                    bubble_fraction(
                        self.pipe_cfg.num_stages,
                        self.pipe_cfg.num_microbatches,
                    ),
                )
            make_step = {
                "1f1b": make_pipe_vit_1f1b_train_step,
                "interleaved": make_pipe_vit_interleaved_train_step,
            }.get(config.pipe_schedule, make_pipe_vit_train_step)
            # Instrumented on the raw jit object (the state-converting
            # wrapper below cannot lower).
            pipe_step = self._xprof.instrument(
                make_step(
                    self.pipe_cfg, self.optimizer, self.mesh,
                    compute_dtype=compute_dtype,
                    label_smoothing=config.label_smoothing,
                    augment_fn=augment_fn, seed=config.seed,
                ),
                "train_step",
            )

            def step(ts, images, labels):
                ps, metrics = pipe_step(
                    PipeViTState(ts.step, ts.params, ts.opt_state),
                    images, labels,
                )
                return (
                    ts._replace(
                        step=ps.step, params=ps.params,
                        opt_state=ps.opt_state,
                    ),
                    metrics,
                )

            self.train_step = step
            if interleaved:
                # Eval rides the dense forward over the [v, S] chunk
                # layout — XLA gathers each chunk's weights as it
                # goes; eval is off the step's critical path.
                pipe_cfg = self.pipe_cfg
                apply_fn = jax.jit(
                    lambda p, x: sequential_apply_interleaved(pipe_cfg, p, x)
                )
            else:
                apply_fn = jax.jit(make_pipe_vit_apply(self.pipe_cfg, self.mesh))

            def eval_step(params, model_state, images, labels, weights):
                del model_state
                logits = apply_fn(
                    params, _preprocess(images, compute_dtype)
                ).astype(jnp.float32)
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels
                )
                correct = ((jnp.argmax(logits, -1) == labels) * weights).sum()
                return correct, (loss * weights).sum()

            self.eval_step = jax.jit(eval_step)
            make_state = (
                create_pipe_vit_state_interleaved
                if interleaved
                else create_pipe_vit_state
            )
            st = make_state(
                self.pipe_cfg, self.optimizer, sample, self.mesh,
                seed=config.seed,
            )
            # Stage params rest sharded over pipe — those placements
            # are the contract (like fsdp above); don't replicate.
            self.state = TrainState(
                step=st.step,
                params=st.params,
                opt_state=st.opt_state,
                model_state={},
            )
        elif self.use_spmd:
            from ddp_tpu.parallel.spmd import (
                create_spmd_state,
                make_spmd_eval_step,
                make_spmd_train_step,
            )

            self.train_step = make_spmd_train_step(
                self.model, self.optimizer, self.mesh,
                compute_dtype=compute_dtype, seed=config.seed,
                grad_accum_steps=config.grad_accum_steps,
                augment_fn=augment_fn,
                label_smoothing=config.label_smoothing,
                zero1=config.zero1,
                **hkw,
            )
            self.eval_step = make_spmd_eval_step(
                self.model, self.mesh, compute_dtype=compute_dtype
            )
            self.state = create_spmd_state(
                self.model, self.optimizer, sample, self.mesh,
                seed=config.seed,
                zero1=config.zero1,
            )
        elif self.zero_mode:
            # The explicit-collective (shard_map) zero step: bucketed
            # psum_scatter / 1/N update / all_gather in place of the
            # DDP pmean — parity-pinned against make_train_step.
            from ddp_tpu.parallel.zero import (
                create_zero_state,
                make_zero_train_step,
                zero_comm_bytes,
            )

            self.state, self._zero_layout = create_zero_state(
                self.model, self.optimizer, sample, self.mesh,
                seed=config.seed, bucket_mb=config.zero_bucket_mb,
                gather_dtype=config.zero_gather_dtype,
            )
            self.train_step = make_zero_train_step(
                self.model, self.optimizer, self.mesh, self._zero_layout,
                compute_dtype=compute_dtype, seed=config.seed,
                grad_accum_steps=config.grad_accum_steps,
                augment_fn=augment_fn,
                label_smoothing=config.label_smoothing,
                gather_dtype=config.zero_gather_dtype,
                grad_clip_norm=self._zero_clip,
            )
            self.eval_step = make_eval_step(
                self.model, self.mesh, compute_dtype=compute_dtype
            )
            cb = zero_comm_bytes(
                self._zero_layout,
                int(self.mesh.shape["data"]),
                grad_accum_steps=config.grad_accum_steps,
                dcn=config.mesh_dcn,
                gather_dtype=config.zero_gather_dtype,
            )
            self._comm_bytes = cb["total"]
            self._comm_by_axis = cb.get("by_axis")
        else:
            self.train_step = make_train_step(
                self.model, self.optimizer, self.mesh,
                compute_dtype=compute_dtype, seed=config.seed,
                grad_accum_steps=config.grad_accum_steps,
                augment_fn=augment_fn,
                label_smoothing=config.label_smoothing,
                **hkw,
            )
            self.eval_step = make_eval_step(
                self.model, self.mesh, compute_dtype=compute_dtype
            )
            state = create_train_state(
                self.model, self.optimizer, sample, seed=config.seed
            )
            self.state = replicate_state(state, self.mesh)
            # The comm story the zero bench compares against: the full
            # fp32 gradient ring all-reduce, every step.
            from ddp_tpu.parallel.zero import ddp_comm_bytes

            self._comm_bytes = ddp_comm_bytes(
                self.state.params, self.data_shards
            )["total"]
        # Families whose train/eval steps are the raw jit objects get
        # instrumented here in one place (the seq classifier, GSPMD,
        # zero, and plain-DDP steps; the lm/pipe branches wrapped
        # their inner jits above — their outer state adapters cannot
        # lower). Identity when --xprof is off.
        if self._xprof.enabled:
            if hasattr(self.train_step, "lower"):
                self.train_step = self._xprof.instrument(
                    self.train_step, "train_step"
                )
            if hasattr(self.eval_step, "lower"):
                self.eval_step = self._xprof.instrument(
                    self.eval_step, "eval_step"
                )
        self.fast_runner = None
        if config.fast_epoch:
            if not (self.lm_mode or self.pipe_mode) and (
                self.use_spmd or config.grad_accum_steps > 1
            ):
                raise ValueError(
                    "--fast_epoch supports the pure-DDP step without "
                    "gradient accumulation (or the causal LM / "
                    "pipeline families)"
                )
            if not config.shuffle:
                raise ValueError(
                    "--fast_epoch always reshuffles per epoch "
                    "(on-device permutation); drop --no_shuffle"
                )
            if config.watchdog_timeout > 0:
                raise ValueError(
                    "--fast_epoch runs a whole epoch as one dispatch "
                    "with no per-step progress beats, so a step-scale "
                    "--watchdog_timeout would kill healthy runs; drop "
                    "one of the two flags"
                )
            from ddp_tpu.train.fast import (
                device_put_dataset,
                device_put_replicated,
                make_epoch_runner,
                make_lm_epoch_runner,
                make_pipe_lm_epoch_runner,
                make_pipe_vit_epoch_runner,
            )

            if self.pipe_lm_mode:
                # Round-5 wall lift: the pipelined LM rides the
                # compiled-epoch dispatch like the flat LM — the raw
                # pipe step (any schedule) scanned on device.
                from ddp_tpu.models.pipeline_lm import PipeLMState

                dev_tokens = device_put_replicated(
                    train_split.images, self.mesh,  # tokens ride .images
                    tracer=self.tracer,
                )
                runner = make_pipe_lm_epoch_runner(
                    self.pipe_cfg, self.optimizer, self.mesh,
                    dev_tokens, self.global_batch_size,
                    schedule=config.pipe_schedule,
                    compute_dtype=compute_dtype, seed=config.seed,
                )
                self.fast_runner = self._wrap_pipe_runner(
                    runner, PipeLMState
                )
            elif self.pipe_mode:
                from ddp_tpu.models.pipeline_vit import PipeViTState

                dev_images, dev_labels = device_put_dataset(
                    train_split.images, train_split.labels, self.mesh,
                    tracer=self.tracer,
                )
                runner = make_pipe_vit_epoch_runner(
                    self.pipe_cfg, self.optimizer, self.mesh,
                    dev_images, dev_labels, self.global_batch_size,
                    schedule=config.pipe_schedule,
                    compute_dtype=compute_dtype, seed=config.seed,
                    augment_fn=augment_fn,
                    label_smoothing=config.label_smoothing,
                )
                self.fast_runner = self._wrap_pipe_runner(
                    runner, PipeViTState
                )
            elif self.lm_mode:
                dev_tokens = device_put_replicated(
                    train_split.images, self.mesh,  # tokens ride .images
                    tracer=self.tracer,
                )
                self.fast_runner = make_lm_epoch_runner(
                    self.seq_spec, self.optimizer, self.mesh,
                    dev_tokens, self.global_batch_size,
                    compute_dtype=compute_dtype, seed=config.seed,
                    grad_accum_steps=config.grad_accum_steps,
                    label_smoothing=config.label_smoothing,
                )
            else:
                # Full arrays on device: the runner permutes all n
                # images per epoch and drops a DIFFERENT tail of the
                # permutation each time (make_epoch_runner), matching
                # the step path's coverage — a static [:usable]
                # truncation would exclude the same images every epoch.
                dev_images, dev_labels = device_put_dataset(
                    train_split.images, train_split.labels, self.mesh,
                    tracer=self.tracer,
                )
                self.fast_runner = make_epoch_runner(
                    self.model, self.optimizer, self.mesh,
                    dev_images, dev_labels, self.global_batch_size,
                    compute_dtype=compute_dtype, seed=config.seed,
                    augment_fn=augment_fn,
                    label_smoothing=config.label_smoothing,
                )
        if config.keep_best and config.eval_every != 1:
            raise ValueError(
                "--keep_best ranks checkpoints by eval accuracy, so "
                "every epoch needs one: set --eval_every 1"
            )
        if config.keep_best and config.max_checkpoints is None:
            raise ValueError(
                "--keep_best retains the --max_checkpoints best epochs; "
                "without --max_checkpoints it would keep everything — "
                "set --max_checkpoints N (or drop --keep_best)"
            )
        # World-shape-agnostic restore hook: the zero strategy's flat
        # bucket shapes are world-dependent (padded to the replica
        # count), so an elastic resize must RE-BUCKET them on restore —
        # everything else reshards by Orbax templating. None for every
        # other strategy (restore behaves exactly as before).
        self._opt_reshape = None
        if self.zero_mode:
            from ddp_tpu.parallel.zero import ZeroElasticReshaper

            self._opt_reshape = ZeroElasticReshaper(
                self.optimizer, self._zero_layout, self.mesh,
                gather_dtype=config.zero_gather_dtype,
            )
        self.ckpt = CheckpointManager(
            config.checkpoint_dir,
            max_to_keep=config.max_checkpoints,
            keep_best_metric="accuracy" if config.keep_best else None,
        )
        self.metrics_writer = MetricsWriter(
            config.metrics_file, enabled=self.ctx.is_main
        )
        # Goodput accounting is always on — one tiny sidecar next to
        # the checkpoints, loaded/written only during train().
        self._goodput = GoodputAccountant(
            os.path.join(config.checkpoint_dir, "goodput.json"),
            enabled=self.ctx.is_main,
        )
        # Analytic train-FLOPs per example (None for unknown models —
        # MFU is then absent, never silently zero) against the mesh's
        # aggregate peak.
        self._flops_per_example = self._estimate_flops_per_example()
        self._peak_flops = (
            peak_flops_per_chip(devices[0]) * self.mesh.devices.size
        )
        # Runtime sanitizer (--sanitize, runtime/sanitize.py): the
        # transfer guard arms around the hot loop in _train_epoch
        # (deliberate syncs run in allow() windows); disabled it is a
        # nullcontext, pinned free like the tracer. The watchdog half
        # rides the existing StepWatchdog: with no explicit
        # --watchdog_timeout, --sanitize arms it at --sanitize_timeout
        # with the desync-diagnosing abort. Not under --fast_epoch —
        # one dispatch per epoch has no per-step beats (the same
        # reason an explicit step-scale timeout is rejected there).
        from ddp_tpu.runtime.sanitize import Sanitizer, desync_abort

        self._sanitizer = Sanitizer(config.sanitize)
        self._wd_dump_reason = "watchdog_timeout"
        wd_timeout = config.watchdog_timeout
        wd_kwargs = {}
        if (
            config.sanitize
            and wd_timeout <= 0
            and config.sanitize_timeout > 0
            and not config.fast_epoch
        ):
            wd_timeout = config.sanitize_timeout
            wd_kwargs["on_timeout"] = desync_abort(self.ctx.num_processes)
            self._wd_dump_reason = "suspected_desync"
        # Constructed here, armed in train() (start/stop bracket the run).
        self._watchdog = StepWatchdog(wd_timeout, **wd_kwargs)
        # Deterministic fault injection (--chaos, runtime/chaos.py):
        # each rank arms its share of the plan; the per-rank ledger
        # next to the checkpoints makes every event once-only across
        # restarts, so a relaunch loop recovers instead of re-dying.
        self._chaos = ChaosEngine(
            config.chaos,
            rank=self.ctx.process_id,
            ledger_path=os.path.join(
                config.checkpoint_dir,
                f"chaos_ledger.rank{self.ctx.process_id}.json",
            ),
            seed=config.seed,
        )
        if self._chaos.has_step_events() and config.fast_epoch:
            raise ValueError(
                "--chaos step-triggered events need the per-step loop, "
                "but --fast_epoch runs a whole epoch as ONE dispatch — "
                "use epoch-triggered events (…@epochN) or drop "
                "--fast_epoch"
            )
        # Flight recorder: host-dict ring next to the checkpoints, one
        # file per rank; the directory is only created on dump (a
        # Trainer that never trains must not create checkpoint_dir).
        self._recorder = FlightRecorder(
            config.checkpoint_dir,
            rank=self.ctx.process_id,
            capacity=config.flight_records,
        )
        if self._xprof.enabled:
            # OOM forensics: the dump collects the compile ledger and
            # a FRESH memory sample at dump time (a provider, not a
            # snapshot) — what was compiled, how big, and how full the
            # device was when the run died.
            self._recorder.set_provider(
                "xprof",
                lambda: {
                    "compile_ledger": self._xprof.ledger_records(),
                    "memory": self._hbm.sample(),
                },
            )
        # Anomaly sentry + one-step-behind health monitor. The group-
        # path layout comes from the SAME group_layout the in-graph
        # pass uses, so the [G] vectors decode without drift.
        self._sentry = (
            AnomalySentry(
                SentryConfig(
                    window=config.health_window,
                    min_steps=max(2, min(8, config.health_window // 2)),
                    cooldown=config.health_window,
                )
            )
            if config.health
            else None
        )
        self._health = HealthMonitor(
            enabled=config.health,
            paths=group_layout(self.state.params)[0]
            if config.health
            else (),
            sentry=self._sentry,
            metrics=self.metrics_writer,
            tracer=self.tracer,
            recorder=self._recorder,
        )
        self._last_health_ckpt: int | None = None
        # Epoch tag held by a rescue save from THIS run: the boundary
        # save must then force-overwrite, or the completed epoch's
        # state (and its keep_best metric) would silently stay the
        # stale mid-epoch rescue until epoch+1 commits.
        self._rescued_epoch: int | None = None
        # Live Prometheus exposition (--metrics_port): one daemon
        # thread serving /metricsz from the snapshot dict the loop
        # updates at the log cadence. Stopped in close().
        self._prom_state: dict[str, Any] = {}
        self._metrics_port = None
        if config.metrics_port is not None and self.ctx.is_main:
            from ddp_tpu.obs.promtext import MetricsPort, render_train

            self._metrics_port = MetricsPort(
                lambda: render_train(self._prom_snapshot()),
                port=config.metrics_port,
            ).start()
            logger.info(
                "Prometheus exposition at %s/metricsz",
                self._metrics_port.url,
            )
        self._raw_eval_count = 0  # companion raw evals under EMA
        self._preempt_requested = False
        self.history: list[EpochStats] = []

    # ---- the reference's epoch/batch loop (train_ddp.py:192-209) ----

    @staticmethod
    def _wrap_pipe_runner(runner, state_cls):
        """Adapt a pipe-family epoch runner (PipeLMState/PipeViTState)
        to the trainer's TrainState — the same conversion the per-step
        wrappers do; NamedTuple construction shares buffers, so
        donation still applies."""

        def wrapped(ts, epoch):
            ps, metrics = runner(
                state_cls(ts.step, ts.params, ts.opt_state), epoch
            )
            return (
                ts._replace(
                    step=ps.step, params=ps.params, opt_state=ps.opt_state
                ),
                metrics,
            )

        wrapped.steps_per_epoch = runner.steps_per_epoch
        return wrapped

    def _check_pipe_batch(self, config: TrainConfig) -> None:
        """Microbatch divisibility guards shared by both pipe families."""
        if self.global_batch_size % config.num_microbatches:
            raise ValueError(
                f"global batch {self.global_batch_size} (batch_size "
                f"× data shards) not divisible by "
                f"--num_microbatches {config.num_microbatches}"
            )
        mb_size = self.global_batch_size // config.num_microbatches
        if mb_size % self.data_shards:
            raise ValueError(
                f"microbatch size {mb_size} (global batch "
                f"{self.global_batch_size} / {config.num_microbatches} "
                f"microbatches) not divisible by {self.data_shards} "
                "data shards — each microbatch shards over the data "
                "axis"
            )

    def _estimate_flops_per_example(self) -> float | None:
        """Analytic train FLOPs per example for MFU (obs/goodput.py).

        "Example" matches the throughput unit the trainer already
        reports: an image for the image family, a whole sequence for
        the token/sequence families. None when no estimator exists —
        the metrics stream then omits ``mfu`` rather than lying.
        """
        from ddp_tpu.obs.goodput import (
            lm_train_flops_per_sequence,
            seq_classifier_train_flops,
            vit_train_flops,
        )

        cfg = self.config
        if self.lm_mode:
            return lm_train_flops_per_sequence(self.seq_spec)
        if self.seq_mode:
            return seq_classifier_train_flops(self.seq_spec)
        if self.pipe_lm_mode:
            pc = self.pipe_cfg
            total_depth = (
                pc.num_stages * pc.depth_per_stage * pc.virtual_stages
            )
            from ddp_tpu.models.lm import LMSpec

            return lm_train_flops_per_sequence(
                LMSpec(
                    vocab_size=pc.vocab_size,
                    total_len=pc.seq_len,
                    d_model=pc.d_model,
                    depth=total_depth,
                    num_heads=pc.num_heads,
                    num_experts=pc.num_experts,
                    moe_every=pc.moe_every,
                    moe_top_k=pc.moe_top_k,
                    num_kv_heads=pc.num_kv_heads,
                )
            )
        if self.pipe_mode:
            pc = self.pipe_cfg
            return vit_train_flops(
                tuple(self.train_split.images.shape[1:]),
                pc.num_classes,
                patch_size=pc.patch_size,
                embed_dim=pc.embed_dim,
                depth=pc.num_stages * pc.depth_per_stage * pc.virtual_stages,
                num_heads=pc.num_heads,
            )
        from ddp_tpu.data.registry import NUM_CLASSES

        return train_flops_per_example(
            cfg.model,
            image_shape=tuple(self.train_split.images.shape[1:]),
            num_classes=cfg.num_classes or NUM_CLASSES.get(self.dataset, 10),
            depth=cfg.model_depth,
        )

    def _step_obs_fields(self, timing) -> dict:
        """JSONL fields for one attributed step ({} when attribution
        is off — the step record's schema only widens under
        --trace_dir)."""
        if timing is None:
            return {}
        fields = {
            "input_wait_s": round(timing.input_wait_s, 6),
            "dispatch_s": round(timing.dispatch_s, 6),
            "compute_s": round(timing.compute_s, 6),
            "recompiles": timing.recompiles,
        }
        wall = timing.wall_s
        m = _mfu(
            self.global_batch_size / wall if wall > 0 else 0.0,
            self._flops_per_example,
            self._peak_flops,
        )
        if m is not None:
            fields["mfu"] = round(m, 6)
        return fields

    def _xprof_step_fields(self) -> dict:
        """Log-cadence xprof work: sample device memory (step-record
        fields + Perfetto counter track), drain fresh compile events
        into the metrics stream/flight recorder, and run the one-time
        comm-bytes cross-check. {} when --xprof is off — the step
        record's schema only widens under the flag (the disabled-mode
        byte-identity pin).
        """
        if not self._xprof.enabled:
            return {}
        mem = self._hbm.sample()
        fields = {
            k: mem[k]
            for k in (
                "hbm_used_bytes", "hbm_high_water_bytes",
                "hbm_headroom_frac",
            )
            if k in mem
        }
        if self.tracer.enabled and mem:
            self.tracer.counter(
                "hbm",
                {
                    "used_bytes": mem["hbm_used_bytes"],
                    "high_water_bytes": mem["hbm_high_water_bytes"],
                },
            )
        self._xprof_cursor, events = self._xprof.events_after(
            self._xprof_cursor
        )
        for ev in events:
            rec = {
                k: ev[k]
                for k in (
                    "label", "signature", "shape_diff",
                    "compile_time_s", "flops",
                )
                if ev.get(k) is not None
            }
            self.metrics_writer.write("compile", **rec)
            self._recorder.record("compile", **rec)
        # Hand-ledger vs compiled-program collectives, once per run:
        # the ddp/zero strategies price their per-step payload
        # analytically (parallel/zero.py); the first compiled
        # train_step says what XLA actually emits. World 1 has no
        # collectives to check.
        if (
            self._comm_bytes is not None
            and self._comm_check_enabled
            and not self._comm_checked
            and self.data_shards >= 2
        ):
            from ddp_tpu.runtime.mesh import slice_block_size

            check = self._xprof.comm_check(
                "train_step", self._comm_bytes, self.data_shards,
                # Hierarchical steps additionally pin each fabric:
                # HLO collectives attribute to ici/dcn by their
                # replica-group membership (obs/xprof.py).
                expected_by_axis=self._comm_by_axis,
                slice_size=(
                    slice_block_size(self.mesh)
                    if self._comm_by_axis is not None
                    else None
                ),
            )
            if check is not None:
                self._comm_checked = True
                self.metrics_writer.write("xprof_check", **check)
                if check["within_tolerance"]:
                    logger.info(
                        "xprof comm check: analytic %d bytes vs HLO %d "
                        "(ratio %s) — within tolerance",
                        check["expected_comm_bytes"],
                        check["measured_comm_bytes"],
                        check["ratio"],
                    )
                else:
                    logger.warning(
                        "xprof comm check FAILED: analytic %d bytes vs "
                        "HLO-derived %d (ratio %s) — the comm_bytes "
                        "estimate drifted from the compiled program",
                        check["expected_comm_bytes"],
                        check["measured_comm_bytes"],
                        check["ratio"],
                    )
        self._prom_state["compile_programs"] = self._xprof.program_count
        self._prom_state["compile_seconds_total"] = round(
            self._xprof.total_compile_s, 4
        )
        self._prom_state.update(fields)
        return fields

    def _prom_snapshot(self) -> dict:
        """Live dict for the /metricsz train exposition (promtext)."""
        snap = dict(self._prom_state)
        if self._health.enabled:
            h = self._health.snapshot()
            snap.setdefault("loss", h.get("loss"))
            snap.setdefault("grad_norm", h.get("grad_norm"))
            snap["health_events"] = h.get("events")
            if "nonfinite_layer" in h or "nonfinite_step" in h:
                snap["nonfinite_layer"] = h.get("nonfinite_layer")
                snap["nonfinite_step"] = h.get("nonfinite_step")
        if self._sentry is not None:
            snap["step_time"] = self._sentry.snapshot()["step_time_s"]
        gp = self._goodput.snapshot()
        if gp:
            snap["goodput"] = gp.get("goodput")
        # Stamped at run_start; a snapshot scraped before train()
        # simply renders no build_info gauge (absent ≠ zero).
        bi = getattr(self, "_build_info", None)
        if bi:
            snap["build_info"] = bi
        return snap

    def _on_health_events(
        self, events, *, epoch: int, ran: int
    ) -> None:
        """Apply --health_action to a batch of sentry/provenance
        events. ``ran`` = batches completed within this epoch (the
        mid-epoch checkpoint position, host-known — no sync).

        Single process acts immediately. Multi-process DEFERS: the
        events are rank-local but halt/checkpoint are collective, so
        they queue for the next agreement point (``_sync_flags`` at
        the log cadence / epoch boundary), where every rank adopts the
        OR and enters the collective action together.
        """
        for ev in events:
            logger.warning(
                "health[%s] at step %s: %s",
                ev.get("detector"),
                ev.get("step"),
                {k: v for k, v in ev.items() if k not in ("detector", "step")},
            )
        action = self.config.health_action
        if action != "warn" and self.ctx.num_processes > 1:
            if action == "halt":
                self._pending_halt.extend(events)
            else:  # checkpoint: nonfinite states are never rescuable
                self._pending_rescue.extend(
                    e for e in events if e.get("detector") != "nonfinite"
                )
            return
        if action == "halt":
            dump = self._recorder.dump("health_halt")
            raise HealthHaltError(list(events), dump_path=dump)
        if action == "checkpoint":
            # Never "rescue" a non-finite state: by the time the
            # provenance event is ingested (one step behind) the
            # params already took NaN updates — overwrite-saving them
            # would shadow the last GOOD checkpoint and auto-resume
            # would restore straight into the divergence. Sentry
            # anomalies (spike/explosion/straggler/recompiles) are
            # still-finite states worth pinning; nonfinite is not.
            rescuable = [
                e for e in events if e.get("detector") != "nonfinite"
            ]
            if not rescuable:
                return
            # At most one rescue checkpoint per sentry window: a storm
            # of events must not turn into a storm of checkpoint I/O.
            step = int(rescuable[-1].get("step", 0))
            if (
                self._last_health_ckpt is not None
                and step - self._last_health_ckpt
                < self.config.health_window
            ):
                return
            self._last_health_ckpt = step
            self._rescued_epoch = epoch
            spe = self.loader.steps_per_epoch()
            self.ckpt.save(
                epoch, self.state, overwrite=True, steps_per_epoch=spe,
                mid_batch=ran if 0 < ran < spe else 0,
            )
            # Block until committed: the async save must not still be
            # writing this epoch tag when the epoch-boundary save (or
            # a second rescue) touches it — and a rescue checkpoint
            # that a crash can outrun would be no rescue at all.
            self.ckpt.wait()
            logger.warning(
                "health: checkpoint-and-continue saved epoch %d at "
                "batch %d (step %d)", epoch, ran, step,
            )

    def _install_preemption_handler(self):
        """SIGTERM → finish the in-flight step, checkpoint, exit clean.

        Preemptible/spot TPU VMs get SIGTERM before reclaim; the
        reference would lose the whole epoch (it has no handler —
        SURVEY.md §5 failure detection). Returns the previous handler
        (restored after training); no-op off the main thread.
        """
        import signal
        import threading

        if threading.current_thread() is not threading.main_thread():
            return (False, None)

        def _on_term(signum, frame):
            logger.warning(
                "SIGTERM received — will checkpoint at the next step "
                "boundary and exit"
            )
            self._preempt_requested = True
            # Dump NOW, not at the checkpoint boundary: preemption
            # grace windows are short, and a second SIGKILL-style
            # reclaim must still find the post-mortem on disk. The
            # boundary checkpoint then supersedes nothing — the dump
            # is evidence, not state.
            self._recorder.record("signal", signal="SIGTERM")
            self._recorder.dump("sigterm")

        try:
            return (True, signal.signal(signal.SIGTERM, _on_term))
        except ValueError:  # non-main interpreter contexts
            return (False, None)

    def _sync_flags(self, host_step: int) -> tuple[bool, bool, bool]:
        """ONE allgather carrying the three rank-local escalations →
        world-agreed (preempt, halt, rescue). A collective call: every
        rank must reach it at the same deterministic point (the log
        cadence in the step loop, and each epoch boundary). The rescue
        flag already folds in this rank's throttle window so an agreed
        rescue is performed by every rank unconditionally — any
        post-agreement local filtering would desynchronize the
        collective save.
        """
        rescue_ok = (
            self._last_health_ckpt is None
            or host_step - self._last_health_ckpt
            >= self.config.health_window
        )
        pre, halt, rescue = consensus.agree_any(
            [
                self._preempt_requested,
                bool(self._pending_halt),
                bool(self._pending_rescue) and rescue_ok,
            ],
            num_processes=self.ctx.num_processes,
        )
        if pre:
            self._preempt_requested = True
        return pre, halt, rescue

    def _act_on_agreed(
        self, halt: bool, rescue: bool, *, epoch: int, ran: int,
        host_step: int,
    ) -> None:
        """Perform the world-agreed health action on THIS rank.

        Every rank calls this after ``_sync_flags`` said halt/rescue,
        with identical (epoch, ran, host_step) — ranks whose own
        sentry saw nothing still participate (their event list is a
        ``peer`` placeholder): the save is collective and the halt
        must take every rank down together, not strand survivors in
        the next step's collective.
        """
        if halt:
            events = self._pending_halt or [
                {"detector": "peer", "step": host_step}
            ]
            self._pending_halt = []
            dump = self._recorder.dump("health_halt")
            raise HealthHaltError(list(events), dump_path=dump)
        if rescue:
            self._pending_rescue = []
            self._last_health_ckpt = host_step
            self._rescued_epoch = epoch
            spe = self.loader.steps_per_epoch()
            self.ckpt.save(
                epoch, self.state, overwrite=True, steps_per_epoch=spe,
                mid_batch=ran if 0 < ran < spe else 0,
            )
            self.ckpt.wait()
            logger.warning(
                "health: world-agreed checkpoint-and-continue saved "
                "epoch %d at batch %d (step %d)", epoch, ran, host_step,
            )

    def _fresh_opt_state(self, params):
        """A from-scratch optimizer state in the LIVE layout: the zero
        strategy's flat data-sharded buckets, or plain ``init`` —
        ``--reset_opt_state`` under ``--parallel zero`` must not graft
        a tree-shaped state onto a bucket-sharded step."""
        if self.zero_mode:
            from ddp_tpu.parallel.zero import create_zero_opt_state

            return create_zero_opt_state(
                params, self.optimizer, self.mesh, self._zero_layout,
                gather_dtype=self.config.zero_gather_dtype,
            )
        return self.optimizer.init(params)

    def _restore_or_init(self):
        """Auto-resume, tolerant of --ema_decay being turned ON since
        the checkpoint was written (or a torch-imported checkpoint):
        restore the EMA-less optimizer layout and graft a fresh EMA
        initialized from the restored params. Other optimizer-config
        changes can't be reconciled — fail with the flags named instead
        of Orbax's raw pytree-mismatch error.
        """
        from ddp_tpu.train.optim import EmaState, ema_params, make_optimizer

        def prune_rewound_branch(epoch):
            # Rewind is a branch: the discarded later epochs must not
            # remain discoverable as "latest" (a crash would
            # auto-resume the branch the user just backed out of).
            stale = self.ckpt.delete_after(epoch)
            if stale:
                logger.warning(
                    "Rewind to epoch %d: deleted the abandoned "
                    "branch's checkpoints %s", epoch, stale,
                )

        def do_restore(state):
            if self.config.resume_epoch is not None:
                restored, epoch = self.ckpt.restore(
                    state, self.config.resume_epoch,
                    opt_reshape=self._opt_reshape,
                )
                prune_rewound_branch(epoch)
                logger.info("Resumed from requested epoch %d", epoch)
                return restored, epoch + 1
            return self.ckpt.restore_or_init(
                state, opt_reshape=self._opt_reshape
            )

        if self.config.reset_opt_state:
            # Weights only; the optimizer (schedules, moments, step
            # counter, EMA) starts fresh — the explicit recipe-change
            # path, layout-independent by construction. No
            # latest_epoch() pre-check: in multi-process runs a rank
            # short-circuiting on a racing view of the directory would
            # skip the verification barrier inside the restore (the
            # restore_or_init pairing rule) — absence surfaces as
            # FileNotFoundError on every rank consistently instead.
            try:
                params, model_state, epoch = (
                    self.ckpt.restore_for_inference(
                        self.config.resume_epoch
                    )
                )
            except FileNotFoundError:
                return self.state, 0
            if self.config.resume_epoch is not None:
                prune_rewound_branch(epoch)
            # A mid-epoch preemption artifact (mid_batch > 0) tags an
            # UNFINISHED epoch; promoting it to "completed" silently
            # skips its remaining batches. The normal restore path
            # re-enters the epoch — with a fresh optimizer that replay
            # bookkeeping doesn't apply, so warn instead.
            try:
                mid = int(
                    self.ckpt.read_partial(epoch, ("mid_batch",)).get(
                        "mid_batch", 0
                    )
                )
            except Exception:  # legacy checkpoint without the key
                mid = 0
            if mid > 0:
                logger.warning(
                    "--reset_opt_state restored a mid-epoch artifact "
                    "(epoch %d stopped at batch %d); its remaining "
                    "batches are skipped and training continues at "
                    "epoch %d", epoch, mid, epoch + 1,
                )
            # Adopt the live state's shardings (replicated or GSPMD
            # rule layout), then rebuild optimizer state from the
            # restored params so e.g. the EMA starts from them.
            params = jax.tree.map(
                lambda tpl, arr: jax.device_put(arr, tpl.sharding),
                self.state.params,
                params,
            )
            if model_state:
                model_state = jax.tree.map(
                    lambda tpl, arr: jax.device_put(arr, tpl.sharding),
                    self.state.model_state,
                    model_state,
                )
            else:
                model_state = self.state.model_state
            logger.warning(
                "Restored epoch %d weights with a FRESH optimizer "
                "state (--reset_opt_state)", epoch,
            )
            return (
                self.state._replace(
                    params=params,
                    model_state=model_state,
                    opt_state=self._fresh_opt_state(params),
                ),
                epoch + 1,
            )

        try:
            return do_restore(self.state)
        except (ValueError, KeyError) as e:
            if self.config.ema_decay:
                tx_noema = make_optimizer(
                    self.config.optimizer,
                    **dict(self._opt_kwargs, ema_decay=0.0),
                )
                alt = self.state._replace(
                    opt_state=tx_noema.init(self.state.params)
                )
                try:
                    restored, start_epoch = do_restore(alt)
                except (ValueError, KeyError):
                    restored = None
                if restored is not None and ema_params(restored.opt_state) is None:
                    logger.info(
                        "Checkpoint has no EMA (written without "
                        "--ema_decay) — initializing the EMA from the "
                        "restored params"
                    )
                    ema = EmaState(
                        ema=jax.tree.map(
                            lambda p: jnp.array(p, copy=True), restored.params
                        )
                    )
                    return (
                        restored._replace(
                            opt_state=(restored.opt_state, ema)
                        ),
                        start_epoch,
                    )
            raise RuntimeError(
                "Checkpoint optimizer state does not match the current "
                "optimizer config — changed --optimizer / --momentum / "
                "--ema_decay / --grad_clip_norm or a schedule flag "
                "(--warmup_steps / --decay_steps / --lr_milestones; "
                "schedules add a step-count state) since it was "
                "written? Re-run with --reset_opt_state to keep the "
                "weights and start the optimizer fresh, or point "
                "--checkpoint_dir elsewhere."
            ) from e

    def train(self) -> dict[str, Any]:
        cfg = self.config
        if self.lm_mode and self.ctx.is_main:
            # Architecture sidecar for inference tooling: the fields
            # the checkpoint shapes cannot carry (num_heads, MoE
            # routing, strategy) persist beside the epochs, like the
            # tokenizer does. Written here, not at construction — a
            # Trainer that never trains must not create checkpoint_dir.
            from ddp_tpu.train.checkpoint import save_lm_spec

            save_lm_spec(cfg.checkpoint_dir, self.seq_spec)
        if cfg.elastic and self.ctx.is_main:
            # Record the run's global-batch contract ONCE (first
            # generation); relaunched generations read it in __init__
            # and rescale their per-shard batch to honor it.
            from ddp_tpu.train.checkpoint import save_elastic_contract

            save_elastic_contract(
                cfg.checkpoint_dir,
                global_batch_size=self.global_batch_size,
                world_size=self.ctx.num_processes,
            )
        # Process-start chaos (ckpt_corrupt) fires BEFORE discovery so
        # the integrity/quarantine fallback below is what it drills.
        self._chaos.on_start(cfg.checkpoint_dir)
        self.state, start_epoch = self._restore_or_init()
        # Integrity fallbacks during discovery (train/checkpoint.py):
        # a corrupt latest was quarantined and an earlier epoch
        # restored. Surface each as a metrics record + flight-recorder
        # event so triage (scripts/health_report.py) sees WHAT state
        # the run actually resumed from.
        resumed = start_epoch - 1 if start_epoch > 0 else None
        for q in self.ckpt.quarantined:
            self.metrics_writer.write(
                "fallback",
                epoch=q["epoch"],
                resumed_epoch=resumed,
                quarantined_path=q["path"],
                problems=q["problems"][:8],
            )
            self._recorder.record(
                "ckpt_fallback",
                epoch=q["epoch"],
                resumed_epoch=resumed,
                problems=q["problems"][:8],
            )
        # Restart-aware goodput: the sidecar (if any) carries the
        # first launch's clock and prior productive seconds, so a
        # preempt/resume cycle accumulates instead of resetting — and
        # the live world size, so a relaunch whose world CHANGED is
        # attributed as resize downtime, not restart downtime. The
        # "world" here is the DATA-PARALLEL world (device shards, not
        # process count): it is what the shard math, the zero bucket
        # layout and the batch rescale actually key on, and it moves
        # for both resize kinds — lost hosts (spawn workers) and lost
        # local devices (--emulate_devices drills).
        self._goodput.start_run(world_size=self.data_shards)
        # Durable immediately: a generation killed before its first
        # epoch boundary must still leave its world size (and launch
        # clock) on disk, or the NEXT generation's restart/resize
        # downtime attribution would skip a boundary.
        self._goodput.flush()
        # Flight-recorder context: what a post-mortem needs but no
        # step record carries — config, env, mesh, rank.
        self._recorder.set_context(
            config=dataclasses.asdict(cfg),
            env=snapshot_env(),
            mesh={a: int(self.mesh.shape[a]) for a in self.mesh.axis_names},
            rank=self.ctx.process_id,
            num_processes=self.ctx.num_processes,
        )
        # Old-world → new-world transition, from the goodput sidecar's
        # recorded world (None on the first generation). Rides both the
        # flight recorder AND the metrics stream: the run_start metrics
        # record is the triage anchor (scripts/health_report.py world
        # trajectory; the elastic drill pins). Written from straight-
        # line code exactly once per train() call — one generation, one
        # record carrying the restart count (pinned by test_metrics and
        # the elastic drills).
        world_fields = {
            "world_size": self.ctx.num_processes,
            "data_shards": self.data_shards,
        }
        if self._goodput.prev_world is not None:
            world_fields["prev_data_shards"] = self._goodput.prev_world
        # Build provenance on the generation anchor (ISSUE 11): the
        # same version/jax/backend/platform block bench records carry,
        # so a resumed run that crossed an image upgrade — or a fleet
        # member running skewed code — is visible from the stream
        # alone. Matching ddp_tpu_build_info gauge on /metricsz.
        from ddp_tpu.obs.recorder import build_info

        self._build_info = build_info()
        # Tuning provenance rides run_start (and its own `tuning`
        # record, the health_report triage input) ONLY when the cache
        # was actually consulted and hit — default runs keep today's
        # record schema byte for byte.
        tuning_fields = (
            {"tuning": self._tuning} if self._tuning else {}
        )
        self._recorder.record(
            "run_start", start_epoch=start_epoch,
            restarts=self._goodput.restarts,
            build_info=self._build_info, **world_fields,
            **tuning_fields,
        )
        self.metrics_writer.write(
            "run_start",
            start_epoch=start_epoch,
            restarts=self._goodput.restarts,
            global_batch_size=self.global_batch_size,
            build_info=self._build_info,
            **world_fields,
            **tuning_fields,
        )
        if self._tuning:
            self.metrics_writer.write(
                "tuning",
                cache_hit=True,
                site=self._tuning["site"],
                cache=self._tuning["cache"],
                applied=self._tuning["applied"],
                overridden=self._tuning["overridden"],
            )
        # Mid-epoch preemption saves are tagged with their (incomplete)
        # epoch and record how many batches ran as an explicit
        # mid_batch marker; resume re-enters that epoch at that batch.
        start_batch = 0
        spe = self.loader.steps_per_epoch()
        mid = self.ckpt.last_restored_mid_batch
        if self.fast_runner is None and mid:
            # Explicit mid-epoch marker (recorded at save time) — never
            # derived from step-counter arithmetic, which an imported
            # foreign checkpoint's step offset or a changed config
            # would silently corrupt. Only trust the position when the
            # checkpoint was written under the SAME steps-per-epoch.
            tag = start_epoch - 1
            if self.ckpt.last_restored_spe == spe and 0 < mid < spe:
                start_epoch = tag
                start_batch = mid
                logger.info(
                    "Resuming mid-epoch: epoch %d, batch %d (step %d)",
                    start_epoch,
                    start_batch,
                    int(self.state.step),
                )
            else:
                logger.warning(
                    "Checkpoint was preempted at batch %d under %s "
                    "steps/epoch; current config has %d — resuming at "
                    "epoch granularity",
                    mid,
                    self.ckpt.last_restored_spe,
                    spe,
                )
        if start_epoch >= cfg.epochs:
            logger.info(
                "Checkpoint epoch %d ≥ requested epochs %d — nothing to do",
                start_epoch - 1,
                cfg.epochs,
            )
        profiling = False
        if cfg.profile_dir and self.ctx.is_main:
            jax.profiler.start_trace(cfg.profile_dir)
            profiling = True
        self._watchdog.start()
        # Watchdog forensics: a hang must leave the same post-mortem
        # artifacts as a crash. os._exit(124) skips every finally, so
        # the dump/export run from the abort path itself.
        wd_forensic = None
        if self._recorder.enabled or self.tracer.enabled:
            from ddp_tpu.utils.watchdog import register_forensics

            def wd_forensic():
                self._recorder.dump(self._wd_dump_reason)
                self._export_trace()

            register_forensics(wd_forensic)
        self._preempt_requested = False
        handler_installed, prev_handler = self._install_preemption_handler()
        preempted = False
        last_eval: tuple[float, float] | None = None
        try:
            try:
                for epoch in range(start_epoch, cfg.epochs):
                    skip = start_batch if epoch == start_epoch else 0
                    epoch_start_step = int(self.state.step)
                    with self.tracer.span("epoch", {"epoch": epoch}):
                        stats = self._train_epoch(epoch, skip)
                    # Agreement at the epoch boundary: a SIGTERM that
                    # landed after the last in-loop cadence check —
                    # or a health event the monitor drained at the
                    # epoch tail — must still stop every host on the
                    # same side of the epoch, or survivors would
                    # block in the next epoch's first collective.
                    if self.ctx.num_processes > 1:
                        boundary_step = int(self.state.step)
                        pre, halt, rescue = self._sync_flags(
                            boundary_step
                        )
                        if halt or rescue:
                            ran = boundary_step - epoch_start_step + skip
                            self._act_on_agreed(
                                halt, rescue, epoch=epoch, ran=ran,
                                host_step=boundary_step,
                            )
                    else:
                        pre = self._preempt_requested
                    if pre:
                        # Mid-epoch state, tagged with the incomplete
                        # epoch; overwrite any older preemption save.
                        # No metrics on purpose: metric-less saves are
                        # always preserved under keep_best (a ranked
                        # sentinel would be garbage-collected as worst
                        # and the preemption state lost).
                        # Position within the epoch measured relative
                        # to this epoch's entry step (absolute step
                        # values carry import/config offsets); >= spe
                        # means the epoch actually completed before the
                        # boundary-preemption landed → mid_batch 0.
                        ran = int(self.state.step) - epoch_start_step + skip
                        self.ckpt.save(
                            epoch, self.state, overwrite=True,
                            steps_per_epoch=spe,
                            mid_batch=ran if 0 < ran < spe else 0,
                        )
                        logger.warning(
                            "Preempted during epoch %d at step %d — "
                            "checkpointed; re-run to resume",
                            epoch,
                            int(self.state.step),
                        )
                        preempted = True
                        break
                    self.history.append(stats)
                    do_eval = bool(
                        cfg.eval_every and (epoch + 1) % cfg.eval_every == 0
                    )
                    # keep_best needs the metric AT save time, so eval
                    # runs first only there; otherwise save first — a
                    # failure during a long eval must not lose the
                    # fully-trained epoch.
                    if cfg.keep_best and do_eval:
                        last_eval = self.evaluate()
                        metrics = {"accuracy": last_eval[0]}
                    else:
                        last_eval, metrics = None, None
                    # overwrite=False: if a mid-epoch preemption
                    # artifact holds this tag, keep it (redo-on-crash)
                    # rather than opening a delete-before-commit window;
                    # a later epoch's save supersedes it. If this was
                    # the LAST epoch, supersede explicitly below.
                    with self.tracer.span("checkpoint.save", {"epoch": epoch}):
                        saved = self.ckpt.save(
                            epoch, self.state, steps_per_epoch=spe,
                            metrics=metrics,
                        )
                    if not saved and (
                        epoch == cfg.epochs - 1
                        or self._rescued_epoch == epoch
                    ):
                        # The tag is held by the LAST epoch's earlier
                        # artifact, or by THIS run's mid-epoch rescue
                        # save — both must be superseded by the
                        # completed-epoch state (with its keep_best
                        # metric). Prior-run preemption artifacts keep
                        # the redo-on-crash semantics above.
                        self.ckpt.save(
                            epoch, self.state, overwrite=True,
                            steps_per_epoch=spe, metrics=metrics,
                        )
                    if do_eval and last_eval is None:
                        last_eval = self.evaluate()
                    if last_eval is not None:
                        logger.info(
                            "Epoch %d eval: accuracy %.4f loss %.4f",
                            epoch,
                            *last_eval,
                        )
                        # Eval accuracy joins the live exposition
                        # (render_train's ddp_tpu_train_accuracy).
                        self._prom_state["accuracy"] = last_eval[0]
            finally:
                if profiling:
                    jax.profiler.stop_trace()
                self.ckpt.wait()
            if preempted:
                return {
                    "epochs_run": len(self.history),
                    "preempted": True,
                    "final_accuracy": None,
                    "final_loss": None,
                    "history": [dataclasses.asdict(h) for h in self.history],
                }
            # Reuse the last per-epoch eval rather than re-running it.
            # Still inside the watchdog window: a hang in the final
            # eval collective or checkpoint flush must crash, not stall.
            final_acc, final_loss = last_eval or self.evaluate()
        except BaseException as e:
            # Post-mortem on ANY exit-by-exception. Errors that
            # already dumped (HealthHaltError, NonFiniteLossError)
            # carry their path — don't overwrite their reason.
            if getattr(e, "dump_path", None) is None:
                self._recorder.record(
                    "exception",
                    type=type(e).__name__,
                    message=str(e)[:500],
                )
                self._recorder.dump(f"exception:{type(e).__name__}")
            raise
        finally:
            if wd_forensic is not None:
                from ddp_tpu.utils.watchdog import unregister_forensics

                unregister_forensics(wd_forensic)
            self._watchdog.stop()
            if handler_installed:
                import signal

                # prev None means a non-Python (C-installed) handler we
                # cannot reinstate — SIG_DFL beats leaving ours bound
                # to this finished Trainer.
                signal.signal(
                    signal.SIGTERM,
                    prev_handler if prev_handler is not None else signal.SIG_DFL,
                )
            self._goodput.flush()
            self._export_trace()
        logger.info("Final test accuracy %.4f (loss %.4f)", final_acc, final_loss)
        self._prom_state["accuracy"] = final_acc
        gp = self._goodput.snapshot()
        self.metrics_writer.write(
            "final", accuracy=final_acc, loss=final_loss,
            epochs_run=len(self.history),
            **({"goodput": gp} if gp else {}),
            # The LM community's headline eval number; loss is the
            # mean next-token cross-entropy, so this is exp(loss).
            **(
                {"perplexity": round(float(np.exp(final_loss)), 4)}
                if (self.lm_mode or self.pipe_lm_mode)
                and np.isfinite(final_loss)
                and np.isfinite(np.exp(final_loss))
                else {}
            ),
        )
        # The end-of-run finiteness gate: a diverged run must FAIL
        # with its provenance (layer/step when health was on) and the
        # flight-recorder dump path — not end 0 with a silently
        # degraded final record. The record above is still written
        # (loss serializes as null) so the stream shows the death.
        # The empty-test-split degenerate case (evaluate() returns
        # nan by construction) is not a divergence.
        if not np.isfinite(final_loss) and len(self.test_split[0]) > 0:
            self.metrics_writer.flush()
            dump = self._recorder.dump("nonfinite_final_loss")
            raise NonFiniteLossError(
                float(final_loss),
                dump_path=dump,
                first_nonfinite=self._health.first_nonfinite,
            )
        return {
            "epochs_run": len(self.history),
            "final_accuracy": final_acc,
            "final_loss": final_loss,
            "history": [dataclasses.asdict(h) for h in self.history],
        }

    def _export_trace(self) -> None:
        """Per-rank crash-safe trace export (every rank writes its own
        file; scripts/trace_merge.py joins them on one timeline)."""
        if not (self.tracer.enabled and self.config.trace_dir):
            return
        try:
            path = self.tracer.export_to_dir(self.config.trace_dir)
        except OSError as e:
            logger.warning("trace export failed: %s", e)
            return
        logger.info("Wrote span trace to %s", path)

    # How far the host may run ahead of the devices. Unbounded async
    # dispatch deadlocks the emulated-CPU collective rendezvous when the
    # cores are oversubscribed, and on real chips just buffers garbage;
    # a small window keeps dispatch overlapped with compute.
    MAX_INFLIGHT_STEPS = 8

    def _train_epoch(self, epoch: int, skip_batches: int = 0) -> EpochStats:
        # Epoch-triggered chaos (…@epochN) fires on BOTH paths; step
        # triggers need the per-step loop (guarded at construction).
        self._chaos.on_epoch(epoch)
        if self.fast_runner is not None:
            # The fast path has no mid-epoch granularity (one dispatch
            # per epoch); preemption is honored between epochs.
            return self._train_epoch_fast(epoch)
        cfg = self.config
        from ddp_tpu.train.optim import lr_at

        logger.info("Starting epoch %d", epoch)  # train_ddp.py:194 parity
        t0 = time.perf_counter()
        # Host-side step numbering: the k-th dispatch of this epoch
        # sees step0 + k in-graph. One sync at epoch entry; the loop
        # itself never reads the device step counter.
        step0 = int(self.state.step)
        losses = []
        last_metrics = None
        n_batches = 0
        inflight: deque = deque()
        # Attribution (--trace_dir) times each loader fetch and splits
        # dispatch-return from block_until_ready; disabled, batches()
        # hands back the raw iterator and on_step returns immediately.
        attr = self._attr
        # --sanitize: the guard makes any IMPLICIT transfer in this
        # loop raise at the offending call (runtime/sanitize.py — the
        # dynamic half of lint rule DDP002). The loop's DELIBERATE
        # syncs each run in an allow() window below: the log-cadence
        # reads, the one-step-behind health retire, the consensus
        # gather. Disabled, both are nullcontexts.
        with self._sanitizer.guard():
            for batch_idx, batch in enumerate(
                attr.batches(self.loader.epoch(epoch, skip_batches)),
                start=skip_batches,
            ):
                # Chaos trigger point (--chaos): "step N" fires before
                # the dispatch that would run global step N — kills/
                # SIGTERMs land here, input stalls sleep here (the
                # straggler sentry and goodput accounting see them
                # like real ones).
                self._chaos.on_step(step0 + n_batches)
                self.state, metrics = self.train_step(
                    self.state, batch.images, batch.labels
                )
                timing = attr.on_step(metrics.loss)
                host_step = step0 + n_batches  # this dispatch's step
                self._recorder.record(
                    "step", epoch=epoch, batch=batch_idx, step=host_step
                )
                if self._health.enabled:
                    # Retires the PREVIOUS step's [G] health vectors
                    # (one step behind the dispatch — the only added
                    # sync, hence the allow window) and runs the
                    # sentry; events apply --health_action.
                    with self._sanitizer.allow():
                        events = self._health.on_step(host_step, metrics)
                        if events:
                            self._on_health_events(
                                events, epoch=epoch, ran=batch_idx + 1
                            )
                last_metrics = metrics
                n_batches += 1
                inflight.append(metrics.loss)
                if len(inflight) > self.MAX_INFLIGHT_STEPS:
                    jax.block_until_ready(inflight.popleft())
                # Progress beat AFTER the bounded sync above: a hung
                # collective stalls that block_until_ready, beats
                # stop, and the watchdog converts the hang into a
                # crash.
                self._watchdog.beat()
                if self.ctx.num_processes == 1:
                    if self._preempt_requested:
                        break  # caller checkpoints the mid-epoch state
                elif batch_idx % cfg.log_interval == 0:
                    # Multi-host: breaking on the local flag alone
                    # would leave peers blocked in the next step's
                    # collective. ONE agreement gather at this
                    # deterministic cadence carries the preemption
                    # flag AND the deferred health escalations
                    # (_on_health_events), so every process halts /
                    # checkpoints / exits at the SAME batch.
                    with self._sanitizer.allow():
                        pre, halt, rescue = self._sync_flags(host_step)
                        if halt or rescue:
                            self._act_on_agreed(
                                halt, rescue, epoch=epoch,
                                ran=batch_idx + 1, host_step=host_step,
                            )
                    if pre:
                        break
                if batch_idx % cfg.log_interval == 0:
                    # train_ddp.py:201-202 parity: rank-0 loss print.
                    # .item() syncs, so only at the log cadence — the
                    # allow window marks it deliberate under
                    # --sanitize.
                    with self._sanitizer.allow():
                        loss = float(metrics.loss)
                        losses.append(loss)
                        step_now = int(self.state.step)
                        logger.info(
                            "Epoch %d Batch %d Loss %.4f",
                            epoch, batch_idx, loss,
                        )
                        gn = (
                            {}
                            if metrics.grad_norm is None
                            else {
                                "grad_norm": round(
                                    float(metrics.grad_norm), 6
                                )
                            }
                        )
                        lr_now = round(
                            lr_at(self._lr_schedule, max(0, step_now - 1)),
                            8,
                        )
                        obs_fields = self._step_obs_fields(timing)
                        # Device-memory sample + compile-event drain
                        # (host-side reads, no device sync — inside
                        # the window only because metrics/recorder
                        # writes belong with the other log-cadence
                        # bookkeeping). {} when --xprof is off.
                        xprof_fields = self._xprof_step_fields()
                    self.metrics_writer.write(
                        "step",
                        epoch=epoch,
                        batch=batch_idx,
                        step=step_now,
                        loss=loss,
                        lr=lr_now,
                        **gn,
                        **obs_fields,
                        **xprof_fields,
                        # Analytic per-step collective payload
                        # (parallel/zero.py estimates — static per
                        # strategy, no sync): present on the ddp/zero
                        # paths so the sharded update's comm story is
                        # auditable next to the step times. The
                        # hierarchical step splits it per fabric.
                        **(
                            {"comm_bytes": self._comm_bytes}
                            if self._comm_bytes is not None
                            else {}
                        ),
                        **(
                            {
                                "comm_bytes_ici": self._comm_by_axis[
                                    "ici"
                                ]["total"],
                                "comm_bytes_dcn": self._comm_by_axis[
                                    "dcn"
                                ]["total"],
                            }
                            if self._comm_by_axis is not None
                            else {}
                        ),
                    )
                    self._recorder.record(
                        "log", step=step_now, epoch=epoch,
                        batch=batch_idx, loss=loss, **gn,
                    )
                    # Live exposition state (--metrics_port /metricsz).
                    self._prom_state.update(
                        step=step_now, epoch=epoch, loss=loss, lr=lr_now,
                        **gn,
                    )
                    if "mfu" in obs_fields:
                        self._prom_state["mfu"] = obs_fields["mfu"]
        if last_metrics is not None:
            jax.block_until_ready(last_metrics.loss)
        # The monitor still owes the LAST step's ingestion (it runs
        # one behind); provenance for a final-step NaN lands here.
        tail_events = self._health.drain()
        if tail_events:
            self._on_health_events(
                tail_events, epoch=epoch, ran=n_batches + skip_batches
            )
        seconds = time.perf_counter() - t0
        return self._finish_epoch(epoch, losses, n_batches, seconds)

    def _finish_epoch(
        self,
        epoch: int,
        losses: list,
        n_batches: int,
        seconds: float,
        obs_extra: dict | None = None,
    ) -> EpochStats:
        """Shared epoch-summary contract for the step and fast paths."""
        images = n_batches * self.global_batch_size
        stats = EpochStats(
            epoch=epoch,
            mean_loss=float(np.mean(losses)) if losses else float("nan"),
            seconds=seconds,
            images_per_sec=images / seconds if seconds else 0.0,
        )
        logger.info(
            "Epoch %d done: %d batches in %.2fs (%.0f images/sec global)",
            epoch,
            n_batches,
            seconds,
            stats.images_per_sec,
        )
        extra = dict(obs_extra or {})
        if self.seq_mode:
            # For sequence models the sample rate is sequences/sec;
            # tokens/sec is the number the field actually compares.
            extra["tokens_per_sec"] = round(
                stats.images_per_sec * self.config.seq_len, 1
            )
        # Attribution totals from the step loop (empty on the fast
        # path, which passes its own obs_extra; empty when disabled).
        totals = self._attr.finish_epoch()
        if totals.steps:
            extra.update(
                input_wait_s=round(totals.input_wait_s, 4),
                dispatch_s=round(totals.dispatch_s, 4),
                compute_s=round(totals.compute_s, 4),
                recompiles=totals.recompiles,
            )
        # MFU needs only the epoch rate + the analytic estimate —
        # reported whenever the model has an estimator, traced or not.
        epoch_mfu = _mfu(
            stats.images_per_sec, self._flops_per_example, self._peak_flops
        )
        if epoch_mfu is not None:
            extra["mfu"] = round(epoch_mfu, 6)
        # Goodput accrues per epoch and flushes per epoch: a kill
        # between epochs loses at most one epoch of accounting.
        self._goodput.add_productive(seconds)
        self._goodput.flush()
        gp = self._goodput.snapshot()
        if gp:
            extra["goodput"] = gp["goodput"]
        if self._health.enabled:
            # Cumulative sentry/provenance event count: a triage pass
            # over epoch records sees WHERE anomalies clustered.
            extra["health_events"] = int(
                sum(self._health.events_total.values())
            )
        if self._comm_bytes is not None:
            extra["comm_bytes"] = self._comm_bytes
        if self._comm_by_axis is not None:
            extra["comm_bytes_ici"] = self._comm_by_axis["ici"]["total"]
            extra["comm_bytes_dcn"] = self._comm_by_axis["dcn"]["total"]
        if self._xprof.enabled:
            # Epoch-boundary memory sample + compile totals (the drain
            # inside also flushes compiles paid outside the log
            # cadence — eval, restore — to the metrics stream).
            xf = self._xprof_step_fields()
            for k in ("hbm_high_water_bytes", "hbm_headroom_frac"):
                if k in xf:
                    extra[k] = xf[k]
            extra["compile_s"] = round(self._xprof.total_compile_s, 4)
            extra["compiled_programs"] = self._xprof.program_count
        self.metrics_writer.write(
            "epoch",
            epoch=epoch,
            batches=n_batches,
            seconds=round(seconds, 3),
            images_per_sec=round(stats.images_per_sec, 1),
            mean_loss=stats.mean_loss,
            **extra,
        )
        self._recorder.record(
            "epoch", epoch=epoch, batches=n_batches,
            seconds=round(seconds, 3), mean_loss=stats.mean_loss,
        )
        self._prom_state["epoch"] = epoch
        self._prom_state["images_per_sec"] = round(stats.images_per_sec, 1)
        if epoch_mfu is not None:
            self._prom_state["mfu"] = round(epoch_mfu, 6)
        if totals.steps:
            self._prom_state["recompiles"] = (
                self._prom_state.get("recompiles", 0) + totals.recompiles
            )
        return stats

    def _train_epoch_fast(self, epoch: int) -> EpochStats:
        """One dispatch for the whole epoch (train/fast.py).

        Per-step losses come back as one stacked array; the reference's
        every-``log_interval`` loss lines are printed from it after the
        device sync, so observable output matches the step path.
        """
        cfg = self.config
        logger.info("Starting epoch %d (compiled fast path)", epoch)
        obs_extra = None
        t0 = time.perf_counter()
        # --sanitize: the epoch dispatch is the whole hot loop here —
        # the guard proves it transfer-free; the stacked per-step
        # losses are read AFTER it (outside the guard), where host
        # reads belong.
        if self._attr.enabled:
            # Per-EPOCH attribution — the whole epoch is one dispatch,
            # so dispatch-return vs block_until_ready is all the host
            # can observe of it (steptime.dispatch_compute_split).
            with self._sanitizer.guard():
                (self.state, metrics), disp_s, comp_s, recompiles = (
                    dispatch_compute_split(
                        self.fast_runner, self.state, epoch
                    )
                )
            self.tracer.complete("epoch.dispatch", t0, disp_s)
            self.tracer.complete(
                "epoch.compute", t0 + disp_s, comp_s,
                {"recompiles": recompiles} if recompiles else None,
            )
            obs_extra = {
                "dispatch_s": round(disp_s, 4),
                "compute_s": round(comp_s, 4),
                "recompiles": recompiles,
            }
        else:
            with self._sanitizer.guard():
                self.state, metrics = self.fast_runner(self.state, epoch)
        losses_all = np.asarray(metrics.loss)
        gnorms_all = (
            None if metrics.grad_norm is None else np.asarray(metrics.grad_norm)
        )
        seconds = time.perf_counter() - t0
        n_batches = len(losses_all)
        end_step = int(self.state.step)  # one sync, outside the loop
        losses = []
        from ddp_tpu.train.optim import lr_at

        for batch_idx in range(0, n_batches, cfg.log_interval):
            loss = float(losses_all[batch_idx])
            losses.append(loss)
            step_no = end_step - n_batches + batch_idx + 1
            logger.info("Epoch %d Batch %d Loss %.4f", epoch, batch_idx, loss)
            gn = (
                {}
                if gnorms_all is None
                else {"grad_norm": round(float(gnorms_all[batch_idx]), 6)}
            )
            self.metrics_writer.write(
                "step", epoch=epoch, batch=batch_idx,
                step=step_no,
                loss=loss,
                lr=round(lr_at(self._lr_schedule, max(0, step_no - 1)), 8),
                **gn,
            )
        return self._finish_epoch(
            epoch, losses, n_batches, seconds, obs_extra
        )

    # ---- eval (absent in the reference; required by the north star) ----

    def evaluate(self, *, use_ema: bool | None = None) -> tuple[float, float]:
        """Full test-split accuracy/loss, batched over the mesh.

        The split is padded with wraparound to a global-batch multiple;
        padding carries weight 0 so the totals are exact. In multi-host
        runs each process feeds its contiguous slice of the padded
        split. With ``--ema_decay`` the averaged parameters are
        evaluated (the point of keeping them) and the raw-weights
        accuracy is logged alongside — early in a run the EMA lags far
        behind and a single number would read as a regression.
        """
        eval_params = self.state.params
        if use_ema is None:
            use_ema = bool(self.config.ema_decay)
        if use_ema:
            from ddp_tpu.train.optim import ema_params

            averaged = ema_params(self.state.opt_state)
            if averaged is None:
                logger.warning(
                    "evaluate(use_ema=True) but no EMA state exists "
                    "(--ema_decay off?) — evaluating RAW weights"
                )
            else:
                eval_params = averaged
                # Companion raw-weights eval for the first couple of
                # evals only: that's when the EMA lags enough to read
                # as a regression, and a full second test-split pass
                # per epoch forever is not worth one log line.
                if self._raw_eval_count < 2:
                    self._raw_eval_count += 1
                    raw_acc, raw_loss = self.evaluate(use_ema=False)
                    logger.info(
                        "Eval with raw (non-EMA) weights: accuracy "
                        "%.4f loss %.4f", raw_acc, raw_loss,
                    )
        images, labels = self.test_split
        # Accumulation exists to keep the per-forward footprint at
        # batch_size×shards — eval must not undo that by running one
        # k×-sized forward. per_shard_batch (not config.batch_size):
        # an elastic resize rescaled it to preserve the global batch.
        bs = self.per_shard_batch * self.data_shards
        n = len(images)
        if n == 0:
            return float("nan"), float("nan")
        padded = -(-n // bs) * bs
        weights = np.ones(padded, np.float32)
        weights[n:] = 0.0
        idx = np.arange(padded) % n
        procs, pid = jax.process_count(), jax.process_index()
        if bs % procs:
            # Mirror the loader's guard (data/loader.py): a silent
            # floor-divide here would evaluate a truncated split.
            raise ValueError(
                f"eval batch {bs} (batch_size × data shards) not "
                f"divisible by {procs} processes"
            )
        local = bs // procs
        correct_total, loss_total = 0.0, 0.0
        for b in range(padded // bs):
            lo = b * bs + pid * local
            sel = idx[lo : lo + local]
            img_np, lbl_np, w_np = images[sel], labels[sel], weights[lo : lo + local]
            if procs == 1:
                put = lambda a, s: jax.device_put(a, s)
            else:
                put = lambda a, s: jax.make_array_from_process_local_data(s, a)
            c, l = self.eval_step(
                eval_params,
                self.state.model_state,
                put(img_np, self.loader._img_sharding),
                put(lbl_np, self.loader._lbl_sharding),
                put(w_np, self.loader._lbl_sharding),
            )
            correct_total += float(c)
            loss_total += float(l)
            # Eval progress counts as progress — a slow (healthy) eval
            # must not trip the hang detector.
            self._watchdog.beat()
        return correct_total / n, loss_total / n

    def close(self) -> None:
        self.loader.close()
        self.ckpt.close()
        self.metrics_writer.close()
        if self._metrics_port is not None:
            self._metrics_port.stop()
            self._metrics_port = None

"""Compiled-epoch fast path: the whole training epoch as ONE XLA program.

The reference's hot loop (train_ddp.py:195-202) crosses Python→C++ per
op and per batch; the host-loader path here (train.trainer) already
compiles each *step*, but for small models the per-step dispatch from a
single Python thread is still the ceiling. This module removes the host
from the loop entirely, which is what the ≥50k images/sec/chip target
requires (SURVEY.md §7 "hard parts"):

- the dataset lives on device, uint8, replicated (MNIST: 47 MB — HBM
  noise);
- the per-epoch shuffle (DistributedSampler ``set_epoch`` semantics:
  seed=epoch permutation, pad-to-multiple) is computed on device;
- ``lax.scan`` drives the per-shard DDP step over all batches, each
  device gathering its stripe of each global batch;
- one dispatch per epoch, one device sync at the end.

Semantics match the step-at-a-time path: same sampler contract (keyed
permutation, per-device stripes, final partial batch dropped — see
ShardedLoader.steps_per_epoch), same DDP all-reduce, same SGD update —
pinned by tests/test_fast.py comparing the two paths batch-for-batch.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.parallel.ddp import (
    StepMetrics,
    TrainState,
    _world,
    make_per_shard_step,
)
from ddp_tpu.runtime.mesh import data_axes


def device_put_replicated(array, mesh: Mesh, tracer=None):
    """Stage one array on device, replicated across the mesh.

    Multi-process meshes can't ``device_put`` onto non-addressable
    devices; there every process supplies the SAME full array (dataset
    loading is deterministic) and
    ``make_array_from_process_local_data`` assembles the replicated
    global — which is also the runner's correctness precondition: the
    per-epoch permutation is computed from the same key on every
    device, so identical staging ⇒ identical batches.

    ``tracer`` (ddp_tpu.obs) spans the staging: for large datasets
    this host→HBM copy is the fast path's one up-front cost, and it
    belongs on the same timeline as the epochs it amortizes into.
    """
    from ddp_tpu.obs.tracer import Tracer

    rep = NamedSharding(mesh, P())
    with (tracer or Tracer()).span(
        "fast.stage_dataset", {"bytes": int(array.nbytes)}
    ):
        if jax.process_count() == 1:
            staged = jax.device_put(jnp.asarray(array), rep)
        else:
            import numpy as np

            staged = jax.make_array_from_process_local_data(
                rep, np.asarray(array)
            )
        if tracer is not None and tracer.enabled:
            # Only when measuring: the span must cover the copy, not
            # just its enqueue. Untraced staging stays async.
            jax.block_until_ready(staged)
        return staged


def device_put_dataset(images, labels, mesh: Mesh, tracer=None):
    """Stage the full (images, labels) dataset replicated on device."""
    return (
        device_put_replicated(images, mesh, tracer),
        device_put_replicated(labels, mesh, tracer),
    )


def make_epoch_runner(
    model,
    optimizer,
    mesh: Mesh,
    images: jax.Array,
    labels: jax.Array,
    global_batch_size: int,
    *,
    compute_dtype=jnp.float32,
    seed: int = 0,
    donate: bool = True,
    augment_fn=None,
    label_smoothing: float = 0.0,
) -> Callable[[TrainState, jax.Array], tuple[TrainState, StepMetrics]]:
    """Build ``run(state, epoch) -> (state, stacked per-step metrics)``.

    ``images``/``labels`` must be device-resident and replicated (see
    ``device_put_dataset``). Batches-per-epoch is static:
    ``num_examples // global_batch_size`` (final partial batch dropped,
    matching ShardedLoader).
    """
    axes = data_axes(mesh)
    shards = _world(mesh, axes)
    if global_batch_size % shards:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by {shards} shards"
        )
    local_bs = global_batch_size // shards
    n = images.shape[0]
    steps = n // global_batch_size
    if steps == 0:
        raise ValueError(
            f"dataset of {n} examples yields zero batches of {global_batch_size}"
        )
    per_shard_step = make_per_shard_step(
        model, optimizer, axes, shards, compute_dtype=compute_dtype, seed=seed,
        augment_fn=augment_fn, label_smoothing=label_smoothing,
    )

    def per_device_epoch(state: TrainState, epoch, imgs, lbls):
        # Same-keyed permutation on every device — identical plan, no
        # communication. ShardSampler semantics: seed+epoch keying.
        perm = jax.random.permutation(jax.random.key(seed + epoch), n)
        # This device's stripe: shard s takes rows [b*G + s*local, ...)
        # of the permuted order for batch b.
        offset = _linear_shard_index(axes) * local_bs

        def body(state, t):
            idx = lax.dynamic_slice(perm, (t * global_batch_size + offset,), (local_bs,))
            batch_img = jnp.take(imgs, idx, axis=0)
            batch_lbl = jnp.take(lbls, idx, axis=0)
            return per_shard_step(state, batch_img, batch_lbl)

        return lax.scan(body, state, jnp.arange(steps))

    sharded = jax.shard_map(
        per_device_epoch,
        mesh=mesh,
        in_specs=(P(), P(), P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )

    def run(state: TrainState, epoch) -> tuple[TrainState, StepMetrics]:
        return jitted(state, jnp.asarray(epoch, jnp.int32))

    jitted = jax.jit(
        lambda state, epoch: sharded(state, epoch, images, labels),
        donate_argnums=(0,) if donate else (),
    )
    run.steps_per_epoch = steps  # type: ignore[attr-defined]
    return run


def _global_scan_runner(
    raw_step, arrays, global_batch_size: int, *, seed: int, donate: bool,
    what: str = "examples",
):
    """The permute-slice-scan epoch skeleton shared by every
    GLOBAL-level runner (LM, pipe-LM, pipe-ViT — the steps own their
    sharding internally, so the scan wraps them on global arrays; the
    image-DDP runner scans per-device inside its own shard_map and
    stays separate). One definition so the sampler keying
    (seed+epoch), tail-drop, and donation semantics cannot drift
    between the fast/step parity guarantees of different families."""
    n = arrays[0].shape[0]
    steps = n // global_batch_size
    if steps == 0:
        raise ValueError(
            f"dataset of {n} {what} yields zero batches of "
            f"{global_batch_size}"
        )

    def epoch_fn(state, epoch, *arrs):
        perm = jax.random.permutation(jax.random.key(seed + epoch), n)

        def body(state, t):
            idx = lax.dynamic_slice(
                perm, (t * global_batch_size,), (global_batch_size,)
            )
            return raw_step(
                state, *(jnp.take(a, idx, axis=0) for a in arrs)
            )

        return lax.scan(body, state, jnp.arange(steps))

    jitted = jax.jit(
        lambda state, epoch: epoch_fn(state, epoch, *arrays),
        donate_argnums=(0,) if donate else (),
    )

    def run(state, epoch):
        return jitted(state, jnp.asarray(epoch, jnp.int32))

    run.steps_per_epoch = steps  # type: ignore[attr-defined]
    return run


def make_lm_epoch_runner(
    spec,
    optimizer,
    mesh: Mesh,
    tokens: jax.Array,
    global_batch_size: int,
    *,
    compute_dtype=jnp.float32,
    seed: int = 0,
    donate: bool = True,
    grad_accum_steps: int = 1,
    label_smoothing: float = 0.0,
):
    """Compiled-epoch fast path for the causal LM (round-3 ask #9).

    ``run(state, epoch) -> (state, stacked per-step metrics)``: the
    token dataset lives on device replicated
    (``device_put_replicated``), the per-epoch permutation is computed
    on device with ShardSampler's seed+epoch keying, and one
    ``lax.scan`` drives the SAME raw step ``make_lm_train_step``
    builds (``jit=False``) over all batches — one dispatch per epoch,
    matching the step path batch-for-batch (tests/test_fast.py).

    Unlike the image runner (which scans per-device inside one
    shard_map), the LM step already owns its sharding story
    (shard_map over seq/fsdp/model inside) — the scan wraps it at the
    global level and GSPMD keeps the per-step layouts.
    """
    from ddp_tpu.models.lm import make_lm_train_step

    raw_step = make_lm_train_step(
        spec, optimizer, mesh, donate=False, compute_dtype=compute_dtype,
        grad_accum_steps=grad_accum_steps, label_smoothing=label_smoothing,
        jit=False,
    )
    return _global_scan_runner(
        raw_step, (tokens,), global_batch_size, seed=seed, donate=donate,
        what="sequences",
    )


def make_pipe_lm_epoch_runner(
    cfg,
    optimizer,
    mesh: Mesh,
    tokens: jax.Array,
    global_batch_size: int,
    *,
    schedule: str = "gpipe",
    compute_dtype=jnp.float32,
    seed: int = 0,
    donate: bool = True,
):
    """Compiled-epoch fast path for the pipelined LM (round-5 ask #5).

    Identical shape to ``make_lm_epoch_runner``: token dataset
    device-resident, seed+epoch-keyed permutation on device, one
    ``lax.scan`` over the raw (unjitted) pipe step — GPipe, 1F1B, or
    interleaved per ``schedule``. The pipe step owns its sharding
    story (shard_map over pipe/data/fsdp/model/expert inside), so the
    scan wraps it at the global level. Runs on ``PipeLMState``; the
    trainer converts at the boundary like its per-step wrapper does.
    Loss-identical to the step loop (tests/test_trainer_fast.py).
    """
    from ddp_tpu.models.pipeline_lm import (
        make_pipe_lm_1f1b_train_step,
        make_pipe_lm_interleaved_train_step,
        make_pipe_lm_train_step,
    )

    make_step = {
        "1f1b": make_pipe_lm_1f1b_train_step,
        "interleaved": make_pipe_lm_interleaved_train_step,
    }.get(schedule, make_pipe_lm_train_step)
    raw_step = make_step(
        cfg, optimizer, mesh, donate=False, compute_dtype=compute_dtype,
        jit=False,
    )
    return _global_scan_runner(
        raw_step, (tokens,), global_batch_size, seed=seed, donate=donate,
        what="sequences",
    )


def make_pipe_vit_epoch_runner(
    cfg,
    optimizer,
    mesh: Mesh,
    images: jax.Array,
    labels: jax.Array,
    global_batch_size: int,
    *,
    schedule: str = "gpipe",
    compute_dtype=jnp.float32,
    seed: int = 0,
    donate: bool = True,
    augment_fn=None,
    label_smoothing: float = 0.0,
):
    """Compiled-epoch fast path for the pipelined ViT — the image
    sibling of ``make_pipe_lm_epoch_runner`` (same global-level scan;
    augment/label smoothing ride inside the pipe step, which already
    applies them to the global batch before microbatching). NOTE for
    CPU runs: the patch-embed conv inside a ``lax.scan`` hits the
    XLA:CPU scan-conv pathology (~200× slower than the standalone
    step, measured round 4) — this path is for TPU benches; tests pin
    correctness on tiny step counts only."""
    from ddp_tpu.models.pipeline_vit import (
        make_pipe_vit_1f1b_train_step,
        make_pipe_vit_interleaved_train_step,
        make_pipe_vit_train_step,
    )

    make_step = {
        "1f1b": make_pipe_vit_1f1b_train_step,
        "interleaved": make_pipe_vit_interleaved_train_step,
    }.get(schedule, make_pipe_vit_train_step)
    raw_step = make_step(
        cfg, optimizer, mesh, donate=False, compute_dtype=compute_dtype,
        label_smoothing=label_smoothing, augment_fn=augment_fn,
        seed=seed, jit=False,
    )
    return _global_scan_runner(
        raw_step, (images, labels), global_batch_size, seed=seed,
        donate=donate,
    )


def _linear_shard_index(axes) -> jax.Array:
    """Flat index of this device within the data-parallel axes."""
    idx = jnp.zeros((), jnp.int32)
    for a in axes:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx

"""Compiled-epoch fast path: the whole training epoch as ONE XLA program.

The reference's hot loop (train_ddp.py:195-202) crosses Python→C++ per
op and per batch; the host-loader path here (train.trainer) already
compiles each *step*, but for small models the per-step dispatch from a
single Python thread is still the ceiling. This module removes the host
from the loop entirely, which is what the ≥50k images/sec/chip target
requires (SURVEY.md §7 "hard parts"):

- the dataset lives on device, uint8, replicated (MNIST: 47 MB — HBM
  noise);
- the per-epoch shuffle (DistributedSampler ``set_epoch`` semantics:
  seed=epoch permutation, pad-to-multiple) is computed on device;
- ``lax.scan`` drives the per-shard DDP step over all batches, each
  device gathering its stripe of each global batch;
- one dispatch per epoch, one device sync at the end.

Semantics match the step-at-a-time path: same sampler contract (keyed
permutation, per-device stripes, final partial batch dropped — see
ShardedLoader.steps_per_epoch), same DDP all-reduce, same SGD update —
pinned by tests/test_fast.py comparing the two paths batch-for-batch.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.parallel.ddp import (
    StepMetrics,
    TrainState,
    _world,
    make_per_shard_step,
)
from ddp_tpu.runtime.mesh import data_axes


def device_put_dataset(images, labels, mesh: Mesh):
    """Stage the full dataset on device, replicated across the mesh.

    Multi-process meshes can't ``device_put`` onto non-addressable
    devices; there every process supplies the SAME full array (dataset
    loading is deterministic) and
    ``make_array_from_process_local_data`` assembles the replicated
    global — which is also the runner's correctness precondition: the
    per-epoch permutation is computed from the same key on every
    device, so identical staging ⇒ identical batches.
    """
    rep = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.device_put(jnp.asarray(images), rep), jax.device_put(
            jnp.asarray(labels), rep
        )
    import numpy as np

    return (
        jax.make_array_from_process_local_data(rep, np.asarray(images)),
        jax.make_array_from_process_local_data(rep, np.asarray(labels)),
    )


def make_epoch_runner(
    model,
    optimizer,
    mesh: Mesh,
    images: jax.Array,
    labels: jax.Array,
    global_batch_size: int,
    *,
    compute_dtype=jnp.float32,
    seed: int = 0,
    donate: bool = True,
    augment_fn=None,
    label_smoothing: float = 0.0,
) -> Callable[[TrainState, jax.Array], tuple[TrainState, StepMetrics]]:
    """Build ``run(state, epoch) -> (state, stacked per-step metrics)``.

    ``images``/``labels`` must be device-resident and replicated (see
    ``device_put_dataset``). Batches-per-epoch is static:
    ``num_examples // global_batch_size`` (final partial batch dropped,
    matching ShardedLoader).
    """
    axes = data_axes(mesh)
    shards = _world(mesh, axes)
    if global_batch_size % shards:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by {shards} shards"
        )
    local_bs = global_batch_size // shards
    n = images.shape[0]
    steps = n // global_batch_size
    if steps == 0:
        raise ValueError(
            f"dataset of {n} examples yields zero batches of {global_batch_size}"
        )
    per_shard_step = make_per_shard_step(
        model, optimizer, axes, shards, compute_dtype=compute_dtype, seed=seed,
        augment_fn=augment_fn, label_smoothing=label_smoothing,
    )

    def per_device_epoch(state: TrainState, epoch, imgs, lbls):
        # Same-keyed permutation on every device — identical plan, no
        # communication. ShardSampler semantics: seed+epoch keying.
        perm = jax.random.permutation(jax.random.key(seed + epoch), n)
        # This device's stripe: shard s takes rows [b*G + s*local, ...)
        # of the permuted order for batch b.
        offset = _linear_shard_index(axes) * local_bs

        def body(state, t):
            idx = lax.dynamic_slice(perm, (t * global_batch_size + offset,), (local_bs,))
            batch_img = jnp.take(imgs, idx, axis=0)
            batch_lbl = jnp.take(lbls, idx, axis=0)
            return per_shard_step(state, batch_img, batch_lbl)

        return lax.scan(body, state, jnp.arange(steps))

    sharded = jax.shard_map(
        per_device_epoch,
        mesh=mesh,
        in_specs=(P(), P(), P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )

    def run(state: TrainState, epoch) -> tuple[TrainState, StepMetrics]:
        return jitted(state, jnp.asarray(epoch, jnp.int32))

    jitted = jax.jit(
        lambda state, epoch: sharded(state, epoch, images, labels),
        donate_argnums=(0,) if donate else (),
    )
    run.steps_per_epoch = steps  # type: ignore[attr-defined]
    return run


def _linear_shard_index(axes) -> jax.Array:
    """Flat index of this device within the data-parallel axes."""
    idx = jnp.zeros((), jnp.int32)
    for a in axes:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx

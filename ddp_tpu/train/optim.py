"""Optimizer factory.

The reference hard-codes ``SGD(lr=0.01)`` (train_ddp.py:41) — that stays
the default for parity. The extension configs need more: ResNets train
with momentum + weight decay, ViTs with AdamW + cosine decay and
warmup, so those are first-class here, all as optax transforms (pure,
jit-compatible, state rides TrainState.opt_state and checkpoints
through Orbax — fixing the reference's dropped-optimizer-state bug,
SURVEY.md §2a #8).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class EmaState(NamedTuple):
    """Exponential moving average of the *parameters* (not updates).

    Lives inside ``opt_state`` so it checkpoints, shards (GSPMD lays it
    out like the params it mirrors), and restores with zero extra
    plumbing — the TrainState pytree never changes shape.
    """

    ema: Any


def param_ema(decay: float) -> optax.GradientTransformation:
    """Track an EMA of the post-update parameters.

    Appended (via ``optax.chain``) AFTER the update rule: ``update``
    sees the final deltas plus the pre-update params, so the new params
    are ``apply_updates(params, updates)`` — the EMA follows what the
    optimizer actually writes. Retrieval: :func:`ema_params`.
    """
    if not 0.0 < decay < 1.0:
        raise ValueError(f"ema decay must be in (0, 1), got {decay}")

    def init(params):
        # A real copy, not jnp.asarray: aliasing the live param buffers
        # would make the train step's donate_argnums hand XLA the same
        # buffer twice (params AND opt_state.ema) — a runtime error.
        return EmaState(ema=jax.tree.map(lambda p: jnp.array(p, copy=True), params))

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("param_ema needs params; use optax.chain")
        new_params = optax.apply_updates(params, updates)
        ema = jax.tree.map(
            lambda e, p: decay * e + (1.0 - decay) * p, state.ema, new_params
        )
        return updates, EmaState(ema)

    return optax.GradientTransformation(init, update)


def ema_params(opt_state) -> Any | None:
    """Pull the EMA param tree out of an optimizer state, or None."""
    leaves = jax.tree_util.tree_flatten(
        opt_state, is_leaf=lambda s: isinstance(s, EmaState)
    )[0]
    for leaf in leaves:
        if isinstance(leaf, EmaState):
            return leaf.ema
    return None


def make_schedule(
    lr: float,
    *,
    warmup_steps: int = 0,
    decay_steps: int = 0,
    lr_milestones: tuple[int, ...] = (),
    lr_decay_factor: float = 0.1,
):
    """The learning-rate schedule alone — shared by ``make_optimizer``
    and observability (logging the CURRENT lr per step without
    `inject_hyperparams`, which would change the opt-state layout and
    break checkpoint compatibility). Returns a float or a callable
    ``schedule(count) -> lr``.
    """
    if decay_steps > 0 and lr_milestones:
        raise ValueError(
            "decay_steps (cosine) and lr_milestones (staircase) are "
            "mutually exclusive schedules"
        )
    if decay_steps > 0:
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0 if warmup_steps else lr,
            peak_value=lr,
            warmup_steps=warmup_steps,
            decay_steps=decay_steps,
        )
    if lr_milestones:
        if sorted(lr_milestones) != list(lr_milestones):
            raise ValueError(f"lr_milestones must ascend: {lr_milestones}")
        stair = optax.piecewise_constant_schedule(
            lr, {int(m): lr_decay_factor for m in lr_milestones}
        )
        if warmup_steps > 0:
            # NOT join_schedules: it re-zeroes the count past each
            # boundary, which would silently shift every milestone by
            # warmup_steps. Milestones are global step numbers.
            warm = optax.linear_schedule(0.0, lr, warmup_steps)

            def schedule(count):
                return jnp.where(
                    count < warmup_steps, warm(count), stair(count)
                )

            return schedule
        return stair
    if warmup_steps > 0:
        return optax.linear_schedule(0.0, lr, warmup_steps)
    return lr


def lr_at(schedule, step: int) -> float:
    """Evaluate a ``make_schedule`` result at a step (float passthrough)."""
    if callable(schedule):
        return float(schedule(step))
    return float(schedule)


def check_zero_compatible(
    name: str,
    *,
    grad_clip_norm: float = 0.0,
    ema_decay: float = 0.0,
) -> None:
    """Reject optimizer configs the ZeRO sharded update cannot run.

    ``--parallel zero`` (parallel/zero.py) executes the update rule on
    1/N flat parameter SHARDS, so every transform in the chain must be
    *elementwise* — sgd, momentum, adam, adamw, weight decay and the
    schedules all are (their ``init``/``update`` accept the sharded
    moment trees unchanged). One config knob is not, and composing it
    is out of scope rather than silently wrong: the parameter EMA
    keeps a full-shape parameter average inside ``opt_state`` and
    ``evaluate()`` reads it back as a param tree — flat 1/N shards
    cannot serve either end.

    ``--grad_clip_norm`` DOES compose (it used to be rejected here):
    the global norm is one scalar, and the scattered buckets partition
    the reduced gradient exactly, so a psum of per-shard squared sums
    over the shard axis is the whole-tree norm without ever
    materializing the full gradient. The zero steps apply it in-step
    (``make_zero_train_step``/``zero_gspmd_update`` ``grad_clip_norm``
    — the Trainer builds the optimizer WITHOUT the chained optax clip
    and threads the knob there instead; parity-pinned against the ddp
    chain). ``grad_clip_norm`` stays in the signature so the composing
    rule is documented at the same door that once rejected it.

    A structural backstop at layout time (parallel/zero.py
    ``_opt_template``: every state leaf scalar or bucket-shaped)
    additionally catches hand-built optimizers whose STATE has the
    wrong shape; direct-API callers composing their own optax chains
    own the elementwise contract themselves (a chained
    ``clip_by_global_norm`` carries EmptyState and would silently clip
    PER SHARD — use the step's knob, not the chain).
    """
    del name, grad_clip_norm
    if ema_decay:
        raise ValueError(
            "--ema_decay keeps a full-shape parameter average inside "
            "opt_state, which --parallel zero shards flat — "
            "evaluate-with-EMA could never see whole params; drop one"
        )


def make_optimizer(
    name: str = "sgd",
    *,
    lr: float = 0.01,
    momentum: float = 0.0,
    weight_decay: float = 0.0,
    warmup_steps: int = 0,
    decay_steps: int = 0,
    grad_clip_norm: float = 0.0,
    ema_decay: float = 0.0,
    lr_milestones: tuple[int, ...] = (),
    lr_decay_factor: float = 0.1,
) -> optax.GradientTransformation:
    """Build the update rule.

    Schedules: ``decay_steps > 0`` → warmup+cosine; ``lr_milestones``
    (step numbers) → piecewise-constant ×``lr_decay_factor`` at each
    milestone (the classic ResNet staircase), composable with warmup.
    """
    schedule = make_schedule(
        lr,
        warmup_steps=warmup_steps,
        decay_steps=decay_steps,
        lr_milestones=lr_milestones,
        lr_decay_factor=lr_decay_factor,
    )

    if name == "sgd":
        tx = optax.sgd(schedule, momentum=momentum or None)
        if weight_decay:
            tx = optax.chain(
                optax.add_decayed_weights(weight_decay), tx
            )
    elif name == "adamw":
        if momentum:
            raise ValueError("momentum is an SGD knob; adamw has betas")
        tx = optax.adamw(schedule, weight_decay=weight_decay)
    elif name == "adam":
        if weight_decay:
            raise ValueError("adam ignores weight_decay — use adamw")
        if momentum:
            raise ValueError("momentum is an SGD knob; adam has betas")
        tx = optax.adam(schedule)
    else:
        raise ValueError(f"unknown optimizer {name!r}")

    if grad_clip_norm:
        tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), tx)
    if ema_decay:
        tx = optax.chain(tx, param_ema(ema_decay))
    return tx

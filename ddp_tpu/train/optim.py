"""Optimizer factory.

The reference hard-codes ``SGD(lr=0.01)`` (train_ddp.py:41) — that stays
the default for parity. The extension configs need more: ResNets train
with momentum + weight decay, ViTs with AdamW + cosine decay and
warmup, so those are first-class here, all as optax transforms (pure,
jit-compatible, state rides TrainState.opt_state and checkpoints
through Orbax — fixing the reference's dropped-optimizer-state bug,
SURVEY.md §2a #8).
"""

from __future__ import annotations

import optax


def make_optimizer(
    name: str = "sgd",
    *,
    lr: float = 0.01,
    momentum: float = 0.0,
    weight_decay: float = 0.0,
    warmup_steps: int = 0,
    decay_steps: int = 0,
    grad_clip_norm: float = 0.0,
) -> optax.GradientTransformation:
    """Build the update rule; ``decay_steps > 0`` enables cosine decay."""
    if decay_steps > 0:
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0 if warmup_steps else lr,
            peak_value=lr,
            warmup_steps=warmup_steps,
            decay_steps=decay_steps,
        )
    elif warmup_steps > 0:
        schedule = optax.linear_schedule(0.0, lr, warmup_steps)
    else:
        schedule = lr

    if name == "sgd":
        tx = optax.sgd(schedule, momentum=momentum or None)
        if weight_decay:
            tx = optax.chain(
                optax.add_decayed_weights(weight_decay), tx
            )
    elif name == "adamw":
        if momentum:
            raise ValueError("momentum is an SGD knob; adamw has betas")
        tx = optax.adamw(schedule, weight_decay=weight_decay)
    elif name == "adam":
        if weight_decay:
            raise ValueError("adam ignores weight_decay — use adamw")
        if momentum:
            raise ValueError("momentum is an SGD knob; adam has betas")
        tx = optax.adam(schedule)
    else:
        raise ValueError(f"unknown optimizer {name!r}")

    if grad_clip_norm:
        tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), tx)
    return tx

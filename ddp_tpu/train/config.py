"""Run configuration — the reference's CLI surface plus its hard-codes.

Parity: ``--epochs`` (default 10) and ``--batch_size`` (default 32,
per data shard) match train_ddp.py:216-218 exactly. Everything the
reference hard-codes becomes a named field with the reference value as
default: lr=0.01 (train_ddp.py:41), checkpoint dir ``./checkpoints``
(train_ddp.py:53), data root ``./data`` (data.py:11), log interval 100
(train_ddp.py:201). The ``--world_size`` flag README.md:72 advertises
but never implements exists here as ``--num_devices`` (how many devices
to use; -1 = all).
"""

from __future__ import annotations

import argparse
import dataclasses


@dataclasses.dataclass
class TrainConfig:
    # Reference CLI (train_ddp.py:216-218)
    epochs: int = 10
    batch_size: int = 32  # per data-parallel shard, like per-rank bs=32

    # Reference hard-codes, surfaced
    lr: float = 0.01  # train_ddp.py:41
    momentum: float = 0.0  # SGD(lr=0.01) → momentum 0
    checkpoint_dir: str = "./checkpoints"  # train_ddp.py:53
    data_root: str = "./data"  # data.py:11
    log_interval: int = 100  # train_ddp.py:201
    seed: int = 0
    shuffle: bool = True  # data.py:18
    num_workers: int = 2  # data.py:22 — native C++ prefetch pool size

    # Framework knobs (no reference analogue)
    model: str = "simple_cnn"
    model_depth: int | None = None  # None = family default (e.g. ViT 12)
    # Width for the sequence family (long_context/causal_lm d_model);
    # registry models fix their widths per family name.
    model_dim: int | None = None
    # Attention heads for the spec-driven families (seq + pipe);
    # registry models fix theirs. head_dim = model_dim / num_heads —
    # 128-wide heads measurably fill the MXU better (bench.py).
    num_heads: int = 4
    # Grouped-query attention for the causal LM: kv heads < num_heads
    # shrink the generation KV cache (and its decode bandwidth) by
    # the group factor. 0 = plain MHA.
    num_kv_heads: int = 0
    augment: str | None = None  # data/augment.py: "crop_flip" | "flip"
    # "auto" resolves per model family: mnist normally, synthetic_seq
    # for --model long_context. An explicit image dataset with the
    # long-context model is an error, not a silent substitution.
    dataset: str = "auto"
    num_classes: int | None = None  # None = infer from dataset
    optimizer: str = "sgd"  # sgd | adam | adamw
    weight_decay: float = 0.0
    warmup_steps: int = 0
    decay_steps: int = 0  # >0 enables cosine decay to this many steps
    grad_clip_norm: float = 0.0
    # Staircase decay: lr ×= lr_decay_factor at each step milestone
    # (e.g. "3000,6000"); mutually exclusive with decay_steps (cosine).
    lr_milestones: str = ""
    lr_decay_factor: float = 0.1
    label_smoothing: float = 0.0  # soft targets (1-α)·one_hot + α/K
    # >0: track an EMA of params in opt_state and evaluate with it —
    # the standard ViT/ResNet eval-quality lever; checkpoints carry it.
    ema_decay: float = 0.0
    grad_accum_steps: int = 1  # microbatches accumulated per update
    backend: str | None = None  # None = auto (tpu if present else cpu)
    num_devices: int = -1  # devices on the data axis; -1 = all
    # Mesh geometry past pure DDP (runtime/mesh.py axis vocabulary).
    # Any axis > 1 switches the trainer to the GSPMD step
    # (parallel/spmd.py): tensor / ZeRO-style / expert parallelism.
    mesh_model: int = 1  # tensor parallelism
    # Pipeline parallelism (--model pipe_vit): stages over the pipe
    # axis; microbatches stream through (parallel/pipeline.py), with
    # --pipe_schedule picking differentiable GPipe, hand-scheduled
    # 1F1B (parallel/one_f1b.py — O(S) activation stash), or
    # interleaved 1F1B (parallel/interleaved.py — --virtual_stages v
    # model chunks per device, bubble (S−1)/(v·M+S−1)).
    mesh_pipe: int = 1
    pipe_schedule: str = "gpipe"  # gpipe | 1f1b | interleaved
    virtual_stages: int = 1  # interleaved only: chunks per device
    num_microbatches: int = 4
    mesh_fsdp: int = 1  # parameter+optimizer sharding
    mesh_expert: int = 1  # MoE expert parallelism
    # Sequence/context parallelism: tokens shard over the seq axis
    # (ring or Ulysses attention). For the sequence models —
    # --model long_context (classifier) or causal_lm (decoder LM) —
    # on the synthetic_seq dataset.
    mesh_seq: int = 1
    # Two-level pod geometry: number of SLICES on the mesh's outermost
    # dcn axis (runtime/mesh.py). Slices are joined by the slow
    # inter-slice fabric; the hierarchical zero step reduce-scatters
    # within a slice over ICI and exchanges only 1/N shards across
    # slices over DCN. On CPU, --spawn P --emulate_devices K emulates
    # P slices of K chips (process boundaries = the slow fabric).
    mesh_dcn: int = 1
    seq_len: int = 2048  # total sequence length (long_context/causal_lm)
    seq_dim: int = 16  # input feature channels per token
    seq_strategy: str = "ring"  # ring | ulysses
    vocab_size: int = 256  # causal_lm token vocabulary
    # >0: causal_lm/pipe_lm route every --moe_every-th block's MLP
    # through this many experts (GShard top-k routing).
    moe_experts: int = 0
    # Which blocks route: block i (1-based) hosts experts iff
    # i % moe_every == 0. 1 = every block (fully-routed). The pipe
    # family needs moe_every to divide --model_depth (stages must be
    # structure-uniform for parameter stacking — models/pipeline_lm.py).
    moe_every: int = 2
    # Routing config for those MoE blocks: experts per token, and
    # whether the surviving top-k gates renormalize to sum to 1.
    # Recorded in the lm_spec.json checkpoint sidecar so the decode /
    # serving path reproduces the training routing (round-5 ADVICE).
    moe_top_k: int = 2
    moe_normalize_gates: bool = True
    # Real LM data: a file read as raw bytes (--dataset text),
    # chunked into seq_len sequences (data/text.py). No tokenizer dep.
    text_file: str | None = None
    zero1: bool = False  # shard optimizer state over data (ZeRO stage 1)
    # Weight-update strategy on the data-parallel path. "auto" keeps
    # the mesh-derived choice (shard_map DDP / GSPMD). "zero" is the
    # ZeRO-style sharded update (parallel/zero.py): reduce-scatter
    # grads in size-targeted buckets, run the optimizer on 1/N flat
    # shards (moments REST sharded — Adam memory divides by the
    # replica count), all-gather params. Covers the DDP image family
    # (explicit shard_map collectives) and the causal LM (in-graph
    # GSPMD expression); parity-pinned against the replicated update.
    parallel: str = "auto"  # auto | zero
    # Bucket size target for the zero reduce-scatters (the knob DDP's
    # C++ reducer calls bucket_cap_mb): smaller buckets give the
    # scheduler more collectives to overlap with backward compute,
    # larger ones amortize per-collective latency.
    zero_bucket_mb: float = 4.0
    # Wire dtype of the zero parameter all-gather. "fp32" (default) is
    # bit-identical to the pre-flag path. "bf16" halves the dominant
    # all-gather bytes (PAPERS.md #3's headline win): the optimizer
    # math and the fp32 MASTER shards (kept in opt_state, sharded like
    # the moments) stay full precision — only the forward sees
    # bf16-rounded params, so rounding never compounds across steps.
    zero_gather_dtype: str = "fp32"  # fp32 | bf16
    # Tuning cache (ddp_tpu.tune): auto = load tuning_cache.json
    # beside checkpoint_dir and fill zero knobs left at defaults from
    # the cached winner (explicit flags always win); off = never
    # touch it; a path = that cache file.
    tuned: str = "auto"
    # Rematerialize block activations in the backward (jax.checkpoint):
    # HBM for FLOPs. Supported by the block-structured families
    # (resnet*, vit*, vit_moe*); simple_cnn has no block stack to remat.
    remat: bool = False
    emulate_devices: int | None = None  # N virtual CPU devices (dev box)
    # Persistent XLA compilation cache: repeat runs skip the 20-40s
    # first-compile on TPU. "" disables; env JAX_COMPILATION_CACHE_DIR
    # takes precedence when set.
    compile_cache_dir: str = "~/.cache/ddp_tpu/xla"
    compute_dtype: str = "float32"  # "bfloat16" for mixed precision
    eval_every: int = 1  # epochs between test-split evals (0 = only final)
    # Compiled-epoch fast path (train/fast.py): dataset device-resident,
    # on-device shuffle, lax.scan over the epoch — one dispatch/epoch.
    # Single-process, pure-DDP, no grad accumulation.
    fast_epoch: bool = False
    max_checkpoints: int | None = None  # None = keep all, like the reference
    # Resume from a specific saved epoch instead of the latest —
    # rewind-and-retrain (e.g. after a bad LR change). The abandoned
    # branch's LATER checkpoints are deleted on restore, so a crash
    # mid-rewind can never auto-resume the discarded branch.
    resume_epoch: int | None = None
    # Restore ONLY params/model_state and start the optimizer (and its
    # schedules/step counter) fresh. The escape hatch for changing the
    # recipe mid-run — a checkpoint's optimizer state is unusable under
    # a different --optimizer/schedule layout.
    reset_opt_state: bool = False
    # Retain the max_checkpoints BEST-accuracy epochs instead of the
    # most recent (requires eval_every=1 so every save has a metric).
    keep_best: bool = False
    synthetic_data: bool = False  # offline fallback dataset
    synthetic_size: int | None = None
    profile_dir: str | None = None  # jax.profiler trace output
    metrics_file: str | None = None  # JSONL metrics from process 0
    # Host-level observability (ddp_tpu.obs): per-rank span traces
    # (Perfetto trace_event JSON), per-step input-wait/dispatch/compute
    # attribution in the metrics stream, and MFU per step. Off (None)
    # by default — disabled mode is pinned free (tests/test_obs.py).
    # Attribution synchronizes each step, so expect the bounded-
    # inflight overlap to disappear while it is on: a diagnosis mode.
    trace_dir: str | None = None
    # Bounded trace memory: the ring keeps the LAST this-many events.
    trace_ring_events: int = 65536
    # Compiled-program introspection (ddp_tpu.obs.xprof): instrument
    # the hot-path jit programs so every compile is ledgered (label,
    # arg-shape signature, compile wall-time, XLA-measured FLOPs/
    # bytes, memory breakdown, HLO collective payloads), recompiles
    # get culprits instead of counts, and step/epoch records carry
    # the device-memory high-water/headroom. A diagnosis mode like
    # --trace_dir; off (default) is pinned free.
    xprof: bool = False
    # Abort the process when no step completes for this many seconds
    # (0 = off). Converts a hung collective into a crash the launcher
    # detects, so restart+resume can recover. Set generously above the
    # first-step compile time.
    watchdog_timeout: float = 0.0
    # Run health (ddp_tpu.obs.health): fuse per-layer-group gradient
    # stats (norms, max-abs, non-finite counts, update/param ratio)
    # into the train step, retire them one step behind the dispatch,
    # attribute the FIRST non-finite gradient to its layer path and
    # step, and run the anomaly sentry (loss spike / grad explosion /
    # straggler / recompile storm) over the per-step records. Off by
    # default; disabled mode is pinned free (tests/test_health.py).
    health: bool = False
    # What an anomaly-sentry event does: log loudly ("warn"), save an
    # overwrite mid-epoch checkpoint and keep going ("checkpoint"), or
    # raise HealthHaltError after dumping the flight recorder ("halt").
    health_action: str = "warn"
    # Sentry rolling-baseline window (steps).
    health_window: int = 32
    # Fault injection for drills and tests: poison one layer group's
    # gradients with NaN at one step, INSIDE the compiled graph —
    # "layer/group@step", e.g. "block1/attn@3". Requires --health.
    health_inject_nan: str | None = None
    # Flight recorder (ddp_tpu.obs.recorder): ring of the last N step
    # records + config/env/mesh context, dumped crash-safely (per
    # rank, next to the checkpoints) on exception, SIGTERM, the
    # non-finite final-loss gate, and watchdog kill. 0 disables.
    flight_records: int = 256
    # Serve the live train counters as Prometheus text at
    # http://127.0.0.1:PORT/metricsz (obs/promtext.py). None = off;
    # 0 binds an ephemeral port (logged at startup).
    metrics_port: int | None = None
    # Deterministic fault injection (runtime/chaos.py): a comma-
    # separated schedule of kills / SIGTERMs / input stalls /
    # checkpoint corruption at exact steps/epochs, e.g.
    # "kill:rank1@step20,stall:input@step5:2.5s,ckpt_corrupt:latest".
    # Every event fires ONCE across restarts (per-rank ledger next to
    # the checkpoints) — see docs/ROBUSTNESS.md for the grammar.
    chaos: str | None = None
    # Runtime sanitizer (runtime/sanitize.py): arm
    # jax.transfer_guard("disallow") around the train hot loop so any
    # IMPLICIT host<->device transfer raises at the offending call
    # (the dynamic half of scripts/lint.py's DDP002), and arm the
    # step watchdog at --sanitize_timeout with a desync-diagnosing
    # abort when --watchdog_timeout is unset. A diagnosis mode, like
    # --trace_dir.
    sanitize: bool = False
    # Desync-watchdog timeout under --sanitize (seconds; only applies
    # when --watchdog_timeout is 0). Must clear the first-step
    # compile. 0 disables the watchdog half.
    sanitize_timeout: float = 300.0
    # Restart-with-resume under --spawn: when a rank dies, the
    # launcher reaps the whole world and relaunches it (fresh
    # coordinator, exponential backoff) up to this many times; each
    # generation auto-resumes from the latest checkpoint and counts
    # as a restart in goodput.json. 0 = fail fast (the old behavior).
    max_restarts: int = 0
    # Base seconds for the launcher's exponential restart backoff
    # (backoff = restart_backoff * 2^i, capped at 30 s).
    restart_backoff: float = 1.0
    # Elastic world resize (docs/ROBUSTNESS.md "Elastic world resize").
    # Supervisor side (--spawn): a rank that exits with the SHRINK code
    # is permanently gone — relaunch the world one smaller (down to
    # --min_world) instead of failing; GROW relaunches one larger.
    # Worker side (any launch mode): re-derive the mesh from the LIVE
    # device count, preserve the recorded global batch by rescaling the
    # per-shard batch (elastic.json contract), and restore checkpoints
    # world-shape-agnostically (reshard on load; zero re-buckets).
    # Pipeline models are excluded for now (stage placement is
    # per-device; MPMD is its own roadmap item).
    elastic: bool = False
    # Smallest world an elastic supervisor may shrink to; shrinking
    # below raises instead of silently degrading further.
    min_world: int = 1

    # Multi-process / multi-host (reference: spawn at train_ddp.py:222-224
    # + env:// rendezvous at utils.py:7-11)
    spawn: int = 1  # >1: fork N local jax.distributed processes
    coordinator_address: str | None = None  # host:port, MASTER_ADDR role
    num_processes: int | None = None
    process_id: int | None = None

    @classmethod
    def parser(cls) -> argparse.ArgumentParser:
        p = argparse.ArgumentParser(description="TPU-native DDP trainer")
        p.add_argument("--epochs", type=int, default=cls.epochs)
        p.add_argument("--batch_size", type=int, default=cls.batch_size)
        p.add_argument("--lr", type=float, default=cls.lr)
        p.add_argument("--momentum", type=float, default=cls.momentum)
        p.add_argument("--checkpoint_dir", default=cls.checkpoint_dir)
        p.add_argument("--data_root", default=cls.data_root)
        p.add_argument("--log_interval", type=int, default=cls.log_interval)
        p.add_argument("--seed", type=int, default=cls.seed)
        p.add_argument("--no_shuffle", action="store_true")
        p.add_argument("--num_workers", type=int, default=cls.num_workers)
        p.add_argument("--model", default=cls.model)
        p.add_argument("--model_depth", type=int, default=None)
        p.add_argument("--model_dim", type=int, default=None)
        p.add_argument("--num_heads", type=int, default=cls.num_heads)
        p.add_argument(
            "--num_kv_heads", type=int, default=cls.num_kv_heads
        )
        p.add_argument(
            "--augment", default=None, choices=("none", "crop_flip", "flip")
        )
        p.add_argument("--dataset", default=cls.dataset)
        p.add_argument("--num_classes", type=int, default=None)
        p.add_argument(
            "--optimizer", default=cls.optimizer, choices=("sgd", "adam", "adamw")
        )
        p.add_argument("--weight_decay", type=float, default=cls.weight_decay)
        p.add_argument("--warmup_steps", type=int, default=cls.warmup_steps)
        p.add_argument("--decay_steps", type=int, default=cls.decay_steps)
        p.add_argument("--grad_clip_norm", type=float, default=cls.grad_clip_norm)
        p.add_argument("--lr_milestones", default=cls.lr_milestones)
        p.add_argument(
            "--lr_decay_factor", type=float, default=cls.lr_decay_factor
        )
        p.add_argument(
            "--label_smoothing", type=float, default=cls.label_smoothing
        )
        p.add_argument("--ema_decay", type=float, default=cls.ema_decay)
        p.add_argument(
            "--grad_accum_steps", type=int, default=cls.grad_accum_steps
        )
        p.add_argument("--backend", default=None, choices=(None, "tpu", "cpu"))
        p.add_argument("--num_devices", type=int, default=cls.num_devices)
        p.add_argument("--mesh_model", type=int, default=cls.mesh_model)
        p.add_argument("--mesh_pipe", type=int, default=cls.mesh_pipe)
        p.add_argument(
            "--pipe_schedule", default=cls.pipe_schedule,
            choices=("gpipe", "1f1b", "interleaved"),
        )
        p.add_argument(
            "--virtual_stages", type=int, default=cls.virtual_stages
        )
        p.add_argument(
            "--num_microbatches", type=int, default=cls.num_microbatches
        )
        p.add_argument("--mesh_fsdp", type=int, default=cls.mesh_fsdp)
        p.add_argument("--mesh_expert", type=int, default=cls.mesh_expert)
        p.add_argument("--mesh_seq", type=int, default=cls.mesh_seq)
        p.add_argument("--seq_len", type=int, default=cls.seq_len)
        p.add_argument("--seq_dim", type=int, default=cls.seq_dim)
        p.add_argument(
            "--seq_strategy", default=cls.seq_strategy,
            choices=("ring", "ulysses"),
        )
        p.add_argument("--vocab_size", type=int, default=cls.vocab_size)
        p.add_argument("--moe_experts", type=int, default=cls.moe_experts)
        p.add_argument(
            "--moe_every", type=int, default=cls.moe_every,
            help="route every k-th block's MLP (1 = all blocks)",
        )
        p.add_argument(
            "--moe_top_k", type=int, default=cls.moe_top_k,
            help="experts each token visits (GShard top-k routing)",
        )
        p.add_argument(
            "--moe_raw_gates", action="store_true",
            help="combine experts with raw top-k gate values instead "
            "of renormalizing them to sum to 1",
        )
        p.add_argument(
            "--text_file", default=cls.text_file,
            help="byte-level corpus for --dataset text (causal_lm)",
        )
        p.add_argument("--zero1", action="store_true")
        p.add_argument(
            "--parallel", default=cls.parallel, choices=("auto", "zero"),
            help="weight-update strategy: zero = ZeRO-style sharded "
            "update (reduce-scatter grads, 1/N optimizer shards, "
            "all-gather params — parallel/zero.py)",
        )
        p.add_argument(
            "--zero_bucket_mb", type=float, default=cls.zero_bucket_mb,
            help="gradient bucket size target for --parallel zero "
            "(MB; smaller = more overlap-schedulable collectives)",
        )
        p.add_argument(
            "--zero_gather_dtype", default=cls.zero_gather_dtype,
            choices=("fp32", "bf16"),
            help="wire dtype of the zero param all-gather: bf16 halves "
            "the dominant collective while fp32 master shards keep the "
            "update exact (fp32 = bit-identical default)",
        )
        p.add_argument(
            "--tuned", default=cls.tuned, metavar="auto|off|PATH",
            help="tuning cache (ddp_tpu.tune, scripts/autotune.py): "
            "'auto' loads tuning_cache.json beside --checkpoint_dir "
            "and fills zero knobs left at their defaults from the "
            "cached winner for this model shape — explicit flags "
            "always win; 'off' disables; a path loads that file",
        )
        p.add_argument(
            "--mesh_dcn", type=int, default=cls.mesh_dcn,
            help="pod slices on the outermost dcn axis: the zero step "
            "goes hierarchical (reduce-scatter within a slice over "
            "ICI, exchange 1/N shards across slices over DCN)",
        )
        p.add_argument("--remat", action="store_true")
        p.add_argument("--emulate_devices", type=int, default=None)
        p.add_argument(
            "--compile_cache_dir", default=cls.compile_cache_dir,
        )
        p.add_argument(
            "--compute_dtype", default=cls.compute_dtype,
            choices=("float32", "bfloat16"),
        )
        p.add_argument("--eval_every", type=int, default=cls.eval_every)
        p.add_argument("--fast_epoch", action="store_true")
        p.add_argument("--max_checkpoints", type=int, default=None)
        p.add_argument("--resume_epoch", type=int, default=None)
        p.add_argument("--reset_opt_state", action="store_true")
        p.add_argument("--keep_best", action="store_true")
        p.add_argument("--synthetic_data", action="store_true")
        p.add_argument("--synthetic_size", type=int, default=None)
        p.add_argument("--profile_dir", default=None)
        p.add_argument("--metrics_file", default=None)
        p.add_argument(
            "--trace_dir", default=None,
            help="emit per-rank Perfetto span traces + step-time "
            "attribution + MFU (ddp_tpu.obs; see docs/OBSERVABILITY.md)",
        )
        p.add_argument(
            "--trace_ring_events", type=int, default=cls.trace_ring_events,
        )
        p.add_argument(
            "--xprof", action="store_true",
            help="compiled-program introspection: per-executable "
            "compile ledger (XLA FLOPs/memory/collectives), recompile "
            "culprits, HBM high-water in step/epoch records "
            "(ddp_tpu.obs.xprof; see docs/OBSERVABILITY.md)",
        )
        p.add_argument(
            "--watchdog_timeout", type=float, default=cls.watchdog_timeout
        )
        p.add_argument(
            "--health", action="store_true",
            help="per-layer gradient health stats + NaN provenance + "
            "anomaly sentry (ddp_tpu.obs.health; see "
            "docs/OBSERVABILITY.md)",
        )
        p.add_argument(
            "--health_action", default=cls.health_action,
            choices=("warn", "checkpoint", "halt"),
            help="what an anomaly event does: log / overwrite-"
            "checkpoint and continue / halt with HealthHaltError",
        )
        p.add_argument(
            "--health_window", type=int, default=cls.health_window,
        )
        p.add_argument(
            "--health_inject_nan", default=None, metavar="LAYER@STEP",
            help="fault injection: NaN one layer group's grads at one "
            "step (drills/tests; requires --health)",
        )
        p.add_argument(
            "--flight_records", type=int, default=cls.flight_records,
            help="flight-recorder ring size (last N step records "
            "dumped on crash/SIGTERM/watchdog kill; 0 = off)",
        )
        p.add_argument(
            "--metrics_port", type=int, default=None,
            help="serve live train counters as Prometheus text at "
            "/metricsz on this port (0 = ephemeral)",
        )
        p.add_argument(
            "--chaos", default=None, metavar="SPEC",
            help="deterministic fault injection, e.g. "
            "'kill:rank1@step20,sigterm:rank0@epoch1,"
            "stall:input@step5:2.5s,ckpt_corrupt:latest' "
            "(docs/ROBUSTNESS.md; events fire once across restarts)",
        )
        p.add_argument(
            "--sanitize", action="store_true",
            help="arm jax.transfer_guard('disallow') around the hot "
            "loop (implicit host transfers raise) plus the desync "
            "watchdog — the runtime half of scripts/lint.py "
            "(docs/ANALYSIS.md)",
        )
        p.add_argument(
            "--sanitize_timeout", type=float,
            default=cls.sanitize_timeout,
            help="desync-watchdog seconds under --sanitize (when "
            "--watchdog_timeout is unset; 0 = guard only)",
        )
        p.add_argument(
            "--max_restarts", type=int, default=cls.max_restarts,
            help="with --spawn: relaunch the whole world from the "
            "latest checkpoint up to N times after a rank dies",
        )
        p.add_argument(
            "--restart_backoff", type=float,
            default=cls.restart_backoff,
            help="base seconds for the exponential restart backoff",
        )
        p.add_argument(
            "--elastic", action="store_true",
            help="survive world RESIZE, not just restart: with --spawn "
            "the supervisor relaunches with however many workers "
            "remain (scale-down) or are restored (scale-up); workers "
            "re-derive the mesh from the live world, preserve the "
            "recorded global batch, and reshard/re-bucket checkpoints "
            "on restore (docs/ROBUSTNESS.md)",
        )
        p.add_argument(
            "--min_world", type=int, default=cls.min_world,
            help="with --elastic: smallest world the supervisor may "
            "shrink to (shrinking below fails the run)",
        )
        # Discovery: print the registries and exit (handled in train.py
        # before config construction).
        p.add_argument("--list_models", action="store_true")
        p.add_argument("--list_datasets", action="store_true")
        p.add_argument("--spawn", type=int, default=cls.spawn)
        p.add_argument("--coordinator_address", default=None)
        p.add_argument("--num_processes", type=int, default=None)
        p.add_argument("--process_id", type=int, default=None)
        return p

    @classmethod
    def from_namespace(cls, ns) -> "TrainConfig":
        kwargs = dict(vars(ns))
        kwargs["shuffle"] = not kwargs.pop("no_shuffle")
        kwargs["moe_normalize_gates"] = not kwargs.pop("moe_raw_gates")
        # action flags, not config state (handled by train.py)
        kwargs.pop("list_models", None)
        kwargs.pop("list_datasets", None)
        return cls(**kwargs)

    @staticmethod
    def scan_explicit_flags(argv=None) -> frozenset:
        """Which flags the user ACTUALLY typed (vs defaulted): the
        tuning cache's precedence rule is explicit-flag-beats-cache,
        and argparse alone can't distinguish ``--zero_bucket_mb 4``
        from the 4.0 default. Callers attach the result as a plain
        attribute — not a field — so ``dataclasses.asdict()``
        (flight-recorder context, restart argv round-trips) is
        unchanged."""
        import sys

        raw = list(sys.argv[1:]) if argv is None else list(argv)
        explicit = set()
        for tok in raw:
            if tok.startswith("--"):
                explicit.add(tok[2:].split("=", 1)[0].replace("-", "_"))
        return frozenset(explicit)

    @classmethod
    def from_args(cls, argv=None) -> "TrainConfig":
        cfg = cls.from_namespace(cls.parser().parse_args(argv))
        cfg.explicit_flags = cls.scan_explicit_flags(argv)
        return cfg

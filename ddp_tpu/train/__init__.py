"""Training orchestration layer (the reference's L4)."""

from ddp_tpu.train.config import TrainConfig  # noqa: F401
from ddp_tpu.train.trainer import Trainer  # noqa: F401
from ddp_tpu.train.checkpoint import CheckpointManager  # noqa: F401

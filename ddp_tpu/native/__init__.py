"""ctypes bindings for the native (C++) data-pipeline runtime.

The reference's input path leans on PyTorch's native stack — IDX decode
in torchvision (reference data.py:11-14) and the C++ DataLoader worker
pool (reference data.py:21-25). ``dataio.cpp`` is this framework's own
native equivalent; this module compiles it on demand with the system
``g++`` (no pybind11 in the image — plain C ABI + ctypes), caches the
shared object next to the source keyed by a source hash, and falls back
gracefully (``available() -> False``) when no toolchain is present so
the pure-Python path keeps working.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import threading
from pathlib import Path

import numpy as np

logger = logging.getLogger("ddp_tpu")

_SRC = Path(__file__).resolve().parent / "dataio.cpp"
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False

# IDX dtype code → numpy dtype (big-endian where multi-byte, as stored).
_IDX_DTYPES = {
    0x08: np.dtype(np.uint8),
    0x09: np.dtype(np.int8),
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}


def _cache_path() -> Path:
    """Where the compiled library lives, keyed by a source hash."""
    tag = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    return _SRC.parent / "_build" / f"libdataio-{tag}.so"


def _build() -> Path:
    out = _cache_path()
    if out.exists():
        return out
    out.parent.mkdir(exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(out.parent))
    os.close(fd)
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        str(_SRC), "-o", tmp, "-lz",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)  # atomic: concurrent builders race benignly
    except subprocess.CalledProcessError as e:
        os.unlink(tmp)
        raise RuntimeError(f"native build failed: {e.stderr}") from e
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return out


def _load() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        try:
            lib = ctypes.CDLL(str(_build()))
        except (OSError, RuntimeError) as e:
            logger.warning("native data pipeline unavailable: %s", e)
            return None
        lib.dt_idx_read.restype = ctypes.c_int
        lib.dt_idx_read.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64 * 8,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.dt_free.restype = None
        lib.dt_free.argtypes = [ctypes.c_void_p]
        lib.dt_cifar_decode.restype = ctypes.c_int
        lib.dt_cifar_decode.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.dt_loader_create.restype = ctypes.c_void_p
        lib.dt_loader_create.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32,
        ]
        lib.dt_loader_start_epoch.restype = None
        lib.dt_loader_start_epoch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.dt_loader_next.restype = ctypes.c_int
        lib.dt_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.dt_loader_destroy.restype = None
        lib.dt_loader_destroy.argtypes = [ctypes.c_void_p]
        lib.dt_ppm_read.restype = ctypes.c_int
        lib.dt_ppm_read.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        _LIB = lib
        return _LIB


def available(build: bool = True) -> bool:
    """True when the native library is loaded (or loadable).

    ``build=False`` never triggers a compile: it answers True only if
    the library is already loaded or a cached build exists on disk —
    callers with a cheap Python fallback (e.g. the IDX reader) use this
    so a cold environment doesn't pay a blocking g++ run for four small
    files. The prefetcher path (which has no fallback) builds on demand.
    """
    if _LIB is not None:
        return True
    if not build:
        try:
            if not _cache_path().exists():
                return False
        except OSError:
            return False
    return _load() is not None


def read_idx(path: str | os.PathLike) -> np.ndarray:
    """Decode an IDX file (raw or gzipped) natively.

    Same contract as the Python ``ddp_tpu.data.mnist.parse_idx`` on the
    decompressed bytes — used as its fast path.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    data = ctypes.POINTER(ctypes.c_uint8)()
    length = ctypes.c_int64()
    ndim = ctypes.c_int32()
    dims = (ctypes.c_int64 * 8)()
    dtype_code = ctypes.c_int32()
    rc = lib.dt_idx_read(
        os.fspath(path).encode(), ctypes.byref(data), ctypes.byref(length),
        ctypes.byref(ndim), dims, ctypes.byref(dtype_code),
    )
    if rc != 0:
        raise ValueError(
            f"dt_idx_read({path!r}) failed: "
            f"{ {1: 'io error', 2: 'bad gzip', 3: 'bad header', 4: 'size mismatch'}.get(rc, rc) }"
        )
    try:
        dt = _IDX_DTYPES[dtype_code.value]
        flat = np.ctypeslib.as_array(data, shape=(length.value,)).view(dt)
        return flat.reshape(tuple(dims[i] for i in range(ndim.value))).copy()
    finally:
        lib.dt_free(data)


def read_ppm(path: str | os.PathLike) -> np.ndarray:
    """Decode a binary PPM (P6) / PGM (P5) file natively → [H, W, C].

    Same contract as the pure-Python ``ddp_tpu.data.ppm.parse_ppm`` —
    used as its fast path by the raw-image ImageNet ingest.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    data = ctypes.POINTER(ctypes.c_uint8)()
    h = ctypes.c_int32()
    w = ctypes.c_int32()
    c = ctypes.c_int32()
    rc = lib.dt_ppm_read(
        os.fspath(path).encode(), ctypes.byref(data), ctypes.byref(h),
        ctypes.byref(w), ctypes.byref(c),
    )
    if rc != 0:
        raise ValueError(
            f"dt_ppm_read({path!r}) failed: "
            f"{ {1: 'io error', 3: 'bad header', 4: 'truncated payload'}.get(rc, rc) }"
        )
    try:
        n = h.value * w.value * c.value
        flat = np.ctypeslib.as_array(data, shape=(n,))
        return flat.reshape(h.value, w.value, c.value).copy()
    finally:
        lib.dt_free(data)


def cifar_decode(raw: bytes, label_bytes: int) -> tuple[np.ndarray, np.ndarray]:
    """Decode a CIFAR binary batch natively (CHW→HWC transpose in C++).

    Same contract as ``ddp_tpu.data.cifar.parse_records`` on the raw
    member bytes — used as its fast path.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    record = label_bytes + 3072
    if label_bytes not in (1, 2) or not raw or len(raw) % record:
        raise ValueError(
            f"malformed CIFAR batch: {len(raw)} bytes, record {record}"
        )
    n = len(raw) // record
    # Caller-allocated outputs (dt_loader_next convention): C++ fills
    # the numpy buffers directly, no malloc/copy/free round-trip.
    images = np.empty((n, 32, 32, 3), np.uint8)
    labels = np.empty((n,), np.int32)
    rc = lib.dt_cifar_decode(
        raw, len(raw), label_bytes,
        images.ctypes.data, labels.ctypes.data,
    )
    if rc != 0:
        raise ValueError(f"dt_cifar_decode failed: code {rc}")
    return images, labels


class NativePrefetcher:
    """Threaded batch assembly over a memory-resident dataset.

    The native analogue of ``DataLoader(num_workers=N, pin_memory=True)``
    (reference data.py:21-25): C++ workers gather sample rows into a ring
    of staging buffers ahead of the training loop. The *index plan* for
    each epoch comes from the caller (the ShardSampler), so shuffle
    determinism and DistributedSampler parity stay in one place.
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        *,
        num_workers: int = 2,
        queue_depth: int = 8,
    ):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        if images.dtype != np.uint8:
            raise TypeError(f"images must be uint8, got {images.dtype}")
        if len(images) != len(labels):
            raise ValueError("image/label count mismatch")
        # Keep contiguous owned references alive for the C++ side.
        self._images = np.ascontiguousarray(images)
        self._labels = np.ascontiguousarray(labels, dtype=np.int32)
        self._item_shape = self._images.shape[1:]
        self._item_bytes = int(np.prod(self._item_shape)) if self._item_shape else 1
        self.batch_size = int(batch_size)
        self._lib = lib
        self._handle = lib.dt_loader_create(
            self._images.ctypes.data, self._labels.ctypes.data,
            len(self._images), self._item_bytes, self.batch_size,
            int(num_workers), int(queue_depth),
        )
        if not self._handle:
            raise RuntimeError("dt_loader_create failed")
        self._draining = False

    def epoch(self, indices: np.ndarray):
        """Yield ``(images, labels)`` batches for the given index plan."""
        if self._handle is None:
            raise RuntimeError("prefetcher closed")
        if self._draining:
            raise RuntimeError("previous epoch not fully drained")
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        if idx.size and (idx.min() < 0 or idx.max() >= len(self._images)):
            raise IndexError("index plan out of range")
        self._lib.dt_loader_start_epoch(self._handle, idx.ctypes.data, idx.size)
        self._draining = True
        try:
            n_batches = idx.size // self.batch_size
            for _ in range(n_batches):
                img = np.empty((self.batch_size, *self._item_shape), np.uint8)
                lbl = np.empty((self.batch_size,), np.int32)
                rc = self._lib.dt_loader_next(
                    self._handle, img.ctypes.data, lbl.ctypes.data
                )
                assert rc == 1
                yield img, lbl
        finally:
            # If the consumer abandoned the epoch mid-way, drain the
            # remaining batches so workers quiesce and the next
            # start_epoch is safe. close() may already have destroyed
            # the handle (generator GC'd after Trainer.close()).
            if self._handle is not None:
                scratch_i = np.empty(
                    (self.batch_size, *self._item_shape), np.uint8
                )
                scratch_l = np.empty((self.batch_size,), np.int32)
                while self._lib.dt_loader_next(
                    self._handle, scratch_i.ctypes.data, scratch_l.ctypes.data
                ):
                    pass
            self._draining = False

    def close(self) -> None:
        if self._handle is not None:
            self._lib.dt_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass

// Native data-pipeline runtime for the TPU-native trainer.
//
// The reference's input pipeline rides PyTorch's native machinery:
// torchvision's IDX decode (reference data.py:11-14) and the C++-backed
// DataLoader worker pool with pinned staging buffers (reference
// data.py:21-25, `num_workers=2, pin_memory=True`). This library is the
// framework's own native equivalent:
//
//   * dt_idx_read     — IDX-format decode (raw or gzip) off the Python heap
//   * DtLoader        — a threaded batch-assembly pool: workers gather
//                       sample rows into a ring of staging buffers ahead
//                       of the consumer, delivered strictly in batch order
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
// Thread model: N worker threads claim batch tickets under a mutex, fill
// the slot `ticket % depth` once it is free, and mark it ready; the
// consumer (`dt_loader_next`) waits for slot readiness in order, copies
// out, frees the slot. Epochs are started with an explicit index plan so
// shuffle semantics (and their torch DistributedSampler parity) stay in
// the Python sampler — determinism lives in one place.

#include <zlib.h>

#include <atomic>
#include <cctype>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// Read an entire file into a heap buffer. Returns false on IO error.
bool read_file(const char* path, std::vector<uint8_t>& out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  if (sz < 0) {
    std::fclose(f);
    return false;
  }
  std::fseek(f, 0, SEEK_SET);
  out.resize(static_cast<size_t>(sz));
  size_t got = sz ? std::fread(out.data(), 1, out.size(), f) : 0;
  std::fclose(f);
  return got == out.size();
}

// Inflate a gzip stream (magic 0x1f 0x8b) into `out`.
bool gunzip(const std::vector<uint8_t>& in, std::vector<uint8_t>& out) {
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  // 15+16: max window, gzip wrapper.
  if (inflateInit2(&zs, 15 + 16) != Z_OK) return false;
  zs.next_in = const_cast<Bytef*>(in.data());
  zs.avail_in = static_cast<uInt>(in.size());
  out.clear();
  std::vector<uint8_t> chunk(1 << 20);
  int rc = Z_OK;
  while (rc != Z_STREAM_END) {
    zs.next_out = chunk.data();
    zs.avail_out = static_cast<uInt>(chunk.size());
    rc = inflate(&zs, Z_NO_FLUSH);
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&zs);
      return false;
    }
    out.insert(out.end(), chunk.data(),
               chunk.data() + (chunk.size() - zs.avail_out));
  }
  inflateEnd(&zs);
  return true;
}

}  // namespace

extern "C" {

// Decode an IDX file (raw or gzipped) at `path`.
// On success returns 0 and fills:
//   *out_data  — malloc'd payload (big-endian for multi-byte dtypes, as
//                stored); caller frees with dt_free
//   *out_len   — payload bytes
//   *out_ndim  — number of dims (<= 8)
//   out_dims   — the dims
//   *out_dtype — the IDX dtype code (0x08 uint8 ... 0x0E float64)
// Error codes: 1 io, 2 gzip, 3 header, 4 size mismatch.
int dt_idx_read(const char* path, uint8_t** out_data, int64_t* out_len,
                int32_t* out_ndim, int64_t out_dims[8], int32_t* out_dtype) {
  std::vector<uint8_t> raw;
  if (!read_file(path, raw)) return 1;
  if (raw.size() >= 2 && raw[0] == 0x1f && raw[1] == 0x8b) {
    std::vector<uint8_t> inflated;
    if (!gunzip(raw, inflated)) return 2;
    raw.swap(inflated);
  }
  if (raw.size() < 4 || raw[0] != 0 || raw[1] != 0) return 3;
  int dtype = raw[2];
  int ndim = raw[3];
  if (ndim < 0 || ndim > 8) return 3;
  size_t header = 4 + 4 * static_cast<size_t>(ndim);
  if (raw.size() < header) return 3;
  int64_t count = 1;
  for (int i = 0; i < ndim; ++i) {
    uint32_t d = (uint32_t(raw[4 + 4 * i]) << 24) |
                 (uint32_t(raw[5 + 4 * i]) << 16) |
                 (uint32_t(raw[6 + 4 * i]) << 8) | uint32_t(raw[7 + 4 * i]);
    out_dims[i] = d;
    count *= d;
  }
  int64_t item = 0;
  switch (dtype) {
    case 0x08:
    case 0x09:
      item = 1;
      break;
    case 0x0B:
      item = 2;
      break;
    case 0x0C:
    case 0x0D:
      item = 4;
      break;
    case 0x0E:
      item = 8;
      break;
    default:
      return 3;
  }
  int64_t payload = count * item;
  if (static_cast<int64_t>(raw.size() - header) != payload) return 4;
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(payload ? payload : 1));
  if (!buf) return 1;
  std::memcpy(buf, raw.data() + header, static_cast<size_t>(payload));
  *out_data = buf;
  *out_len = payload;
  *out_ndim = ndim;
  *out_dtype = dtype;
  return 0;
}

void dt_free(void* p) { std::free(p); }

// Decode a binary PPM (P6, RGB) or PGM (P5, gray) image at `path` —
// the zero-dependency raw-image format for the ImageNet ingest path
// (scripts/preprocess_imagenet.py): header `P6`, whitespace- and
// `#`-comment-separated width/height/maxval (maxval <= 255), then a
// raw payload of h*w*channels bytes.
// On success returns 0 and fills *out_data (malloc'd [h, w, c]
// interleaved uint8; caller frees with dt_free), *out_h/*out_w/*out_c.
// Error codes: 1 io, 3 header/format, 4 size mismatch.
int dt_ppm_read(const char* path, uint8_t** out_data, int32_t* out_h,
                int32_t* out_w, int32_t* out_c) {
  std::vector<uint8_t> raw;
  if (!read_file(path, raw)) return 1;
  if (raw.size() < 2 || raw[0] != 'P' || (raw[1] != '5' && raw[1] != '6'))
    return 3;
  int channels = raw[1] == '6' ? 3 : 1;
  size_t pos = 2;
  long fields[3];  // width, height, maxval
  for (int f = 0; f < 3; ++f) {
    // Skip whitespace and `#` comments (which run to end of line).
    for (;;) {
      while (pos < raw.size() && std::isspace(raw[pos])) ++pos;
      if (pos < raw.size() && raw[pos] == '#') {
        while (pos < raw.size() && raw[pos] != '\n') ++pos;
        continue;
      }
      break;
    }
    if (pos >= raw.size() || !std::isdigit(raw[pos])) return 3;
    long v = 0;
    while (pos < raw.size() && std::isdigit(raw[pos])) {
      v = v * 10 + (raw[pos] - '0');
      if (v > (1l << 30)) return 3;
      ++pos;
    }
    fields[f] = v;
  }
  // Exactly ONE whitespace byte separates the header from the payload.
  if (pos >= raw.size() || !std::isspace(raw[pos])) return 3;
  ++pos;
  long w = fields[0], h = fields[1], maxval = fields[2];
  if (w <= 0 || h <= 0 || maxval <= 0 || maxval > 255) return 3;
  int64_t payload = int64_t(w) * h * channels;
  if (static_cast<int64_t>(raw.size() - pos) < payload) return 4;
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(payload));
  if (!buf) return 1;
  std::memcpy(buf, raw.data() + pos, static_cast<size_t>(payload));
  *out_data = buf;
  *out_h = static_cast<int32_t>(h);
  *out_w = static_cast<int32_t>(w);
  *out_c = channels;
  return 0;
}

// Decode a CIFAR binary batch (already in memory — the files live
// inside tarballs): n records of
//   [label_bytes of labels][3072 bytes RGB, channel-planar CHW].
// label_bytes: 1 (CIFAR-10) or 2 (CIFAR-100: coarse then fine — the
// FINE label, the last byte, is kept, matching data/cifar.py).
// Caller-buffer convention (like dt_loader_next): Python computes
// n = size / record, allocates the numpy outputs, and passes their
// data pointers — no double-buffering:
//   out_images — [n, 32, 32, 3] uint8, filled HWC-interleaved here so
//                the Python side needs no transpose/copy pass
//   out_labels — [n] int32
// Returns 0 on success, 3 on malformed input (size not a multiple of
// the record, or bad label_bytes).
int dt_cifar_decode(const uint8_t* data, int64_t size, int32_t label_bytes,
                    uint8_t* out_images, int32_t* out_labels) {
  constexpr int64_t kSide = 32, kChan = 3;
  constexpr int64_t kPixels = kSide * kSide;       // 1024 per plane
  constexpr int64_t kImageBytes = kPixels * kChan; // 3072
  if (label_bytes != 1 && label_bytes != 2) return 3;
  const int64_t record = label_bytes + kImageBytes;
  if (!data || !out_images || !out_labels || size <= 0 || size % record != 0)
    return 3;
  const int64_t n = size / record;
  for (int64_t r = 0; r < n; ++r) {
    const uint8_t* rec = data + r * record;
    out_labels[r] = rec[label_bytes - 1];  // fine label for CIFAR-100
    const uint8_t* planes = rec + label_bytes;
    uint8_t* dst = out_images + r * kImageBytes;
    // CHW planes → HWC interleaved.
    for (int64_t p = 0; p < kPixels; ++p) {
      dst[p * kChan + 0] = planes[p];
      dst[p * kChan + 1] = planes[kPixels + p];
      dst[p * kChan + 2] = planes[2 * kPixels + p];
    }
  }
  return 0;
}

// ---------------------------------------------------------------------
// Threaded prefetching batch loader.
// ---------------------------------------------------------------------

struct Slot {
  std::vector<uint8_t> img;
  std::vector<int32_t> lbl;
  int64_t batch_id = -1;  // batch stored here; -1 = free
  bool ready = false;     // fill complete
};

struct DtLoader {
  const uint8_t* items = nullptr;   // [num_items, item_bytes], row-major
  const int32_t* labels = nullptr;  // [num_items]
  int64_t num_items = 0;
  int64_t item_bytes = 0;
  int64_t batch_size = 0;
  int32_t depth = 0;

  std::vector<Slot> slots;
  std::vector<std::thread> workers;
  std::vector<int64_t> indices;  // owned copy of the epoch plan

  int64_t n_batches = 0;
  int64_t tickets_issued = 0;  // next batch id a worker may claim
  int64_t next_out = 0;        // next batch id the consumer expects
  bool shutdown = false;

  std::mutex mu;
  std::condition_variable cv_worker;    // slot freed / epoch started / stop
  std::condition_variable cv_consumer;  // slot became ready

  void worker_loop() {
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      cv_worker.wait(
          lk, [&] { return shutdown || tickets_issued < n_batches; });
      if (shutdown) return;
      int64_t t = tickets_issued++;
      Slot& s = slots[t % depth];
      // Sliding-window gate: fill only once every earlier ticket that
      // used this slot has been consumed (t - depth < next_out). A
      // plain "slot free" check deadlocks when two workers hold
      // tickets for the same slot and the later one wins the race.
      cv_worker.wait(lk, [&] { return shutdown || t < next_out + depth; });
      if (shutdown) return;
      s.batch_id = t;
      s.ready = false;
      lk.unlock();
      const int64_t* plan = indices.data() + t * batch_size;
      uint8_t* img = s.img.data();
      int32_t* lbl = s.lbl.data();
      for (int64_t i = 0; i < batch_size; ++i) {
        int64_t src = plan[i];
        std::memcpy(img + i * item_bytes, items + src * item_bytes,
                    static_cast<size_t>(item_bytes));
        lbl[i] = labels[src];
      }
      lk.lock();
      s.ready = true;
      cv_consumer.notify_all();
    }
  }
};

DtLoader* dt_loader_create(const uint8_t* items, const int32_t* labels,
                           int64_t num_items, int64_t item_bytes,
                           int64_t batch_size, int32_t num_workers,
                           int32_t queue_depth) {
  if (!items || !labels || num_items <= 0 || item_bytes <= 0 ||
      batch_size <= 0 || num_workers <= 0 || queue_depth <= 0)
    return nullptr;
  DtLoader* L = new DtLoader();
  L->items = items;
  L->labels = labels;
  L->num_items = num_items;
  L->item_bytes = item_bytes;
  L->batch_size = batch_size;
  L->depth = queue_depth;
  L->slots.resize(queue_depth);
  for (auto& s : L->slots) {
    s.img.resize(static_cast<size_t>(batch_size * item_bytes));
    s.lbl.resize(static_cast<size_t>(batch_size));
  }
  for (int i = 0; i < num_workers; ++i)
    L->workers.emplace_back([L] { L->worker_loop(); });
  return L;
}

// Begin a new epoch with an explicit index plan (values in
// [0, num_items)). Trailing indices that don't fill a whole batch are
// dropped — drop_last semantics, matching the Python loader. Must not be
// called while the previous epoch is still being drained.
void dt_loader_start_epoch(DtLoader* L, const int64_t* indices, int64_t n) {
  std::lock_guard<std::mutex> lk(L->mu);
  int64_t nb = n / L->batch_size;
  L->indices.assign(indices, indices + nb * L->batch_size);
  L->n_batches = nb;
  L->tickets_issued = 0;
  L->next_out = 0;
  for (auto& s : L->slots) {
    s.batch_id = -1;
    s.ready = false;
  }
  L->cv_worker.notify_all();
}

// Copy the next batch into caller buffers. Returns 1 on success, 0 when
// the epoch is exhausted. Blocks while workers catch up.
int dt_loader_next(DtLoader* L, uint8_t* img_out, int32_t* lbl_out) {
  std::unique_lock<std::mutex> lk(L->mu);
  if (L->next_out >= L->n_batches) return 0;
  int64_t want = L->next_out;
  Slot& s = L->slots[want % L->depth];
  L->cv_consumer.wait(lk, [&] {
    return L->shutdown || (s.batch_id == want && s.ready);
  });
  if (L->shutdown) return 0;
  std::memcpy(img_out, s.img.data(), s.img.size());
  std::memcpy(lbl_out, s.lbl.data(), s.lbl.size() * sizeof(int32_t));
  s.batch_id = -1;
  s.ready = false;
  L->next_out = want + 1;
  L->cv_worker.notify_all();
  return 1;
}

void dt_loader_destroy(DtLoader* L) {
  if (!L) return;
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->shutdown = true;
    L->cv_worker.notify_all();
    L->cv_consumer.notify_all();
  }
  for (auto& t : L->workers) t.join();
  delete L;
}

}  // extern "C"

"""Attention kernels on [B, T, H, D] arrays.

The framework's attention contract: ``fn(q, k, v) -> out`` with all
four arrays shaped [batch, tokens, heads, head_dim]. Everything above
(the ViT family) is kernel-agnostic; everything below (dense reference,
blockwise/flash-style, the sequence-parallel ring in
ddp_tpu.parallel.ring) implements this one signature.

The reference repo has no attention at all (model.py is conv+linear);
this exists for the ViT extension config and the long-context path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


# Large-negative mask value: -inf would produce NaN through the
# online-softmax correction terms when a whole block is masked.
MASK_VALUE = -0.5 * jnp.finfo(jnp.float32).max


# Below this many key/query positions the dense path wins on TPU: the
# flash kernel pays per-grid-cell DMA/dispatch overhead that tiny
# blocks never amortize (measured on a v5e, 2026-07: ViT-Tiny at
# T=65 runs 2× FASTER dense; isolated attention crosses over between
# T=1024 and 2048, where flash reaches 2.8× by T=4096 and the O(T²)
# dense memory starts to matter anyway).
FLASH_MIN_LEN = 1024


def best_attention(*, causal: bool = False, block_q: int = 512,
                   block_k: int = 512):
    """Platform- and SIZE-resolved default attention.

    Returns a ``(q, k, v) -> out`` fn that picks per call (shapes are
    static at trace time): the compiled Pallas flash kernel on TPU for
    sequences of at least ``FLASH_MIN_LEN`` keys — fused
    forward+backward, O(T) memory (ops/flash.py) — and the dense XLA
    path otherwise (short sequences, where the kernel's per-block
    overhead loses to one fused einsum chain, and every non-TPU
    platform). The model factories (vit/lm/seq/moe) call this when no
    explicit ``attention_fn`` is given.
    """
    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu:
        return partial(dot_product_attention, causal=causal)

    from ddp_tpu.ops.flash import flash_attention

    def fn(q, k, v):
        if k.shape[1] >= FLASH_MIN_LEN:
            return flash_attention(q, k, v, causal, block_q, block_k, False)
        return dot_product_attention(q, k, v, causal=causal)

    return fn


def gspmd_flash_attention(mesh, *, causal: bool = False, block_q: int = 512,
                          block_k: int = 512, interpret: bool = False):
    """Size-dispatched attention usable INSIDE a GSPMD-jitted step.

    The GSPMD step (parallel/spmd.py) partitions by annotation, but a
    compiled Mosaic custom call has no partitioning rule, so the flash
    kernel can't ride plain propagation there. This wrapper routes the
    flash case through a ``shard_map`` island instead: batch over the
    data-parallel axes (the same set as ``spmd.batch_spec``), heads
    over ``model`` when tensor parallelism is on (the Megatron layout
    already shards attention heads there, so the island's specs match
    the activations' natural placement — no resharding), sequence and
    head_dim whole per shard. Below ``FLASH_MIN_LEN`` keys it returns
    the dense path exactly like ``best_attention`` (and always does on
    non-TPU platforms unless ``interpret`` forces the kernel for
    tests), so short-sequence models are untouched.
    """
    from ddp_tpu.runtime.mesh import data_axes

    on_tpu = jax.devices()[0].platform == "tpu"
    # Same axis set AND same size-1 filter as spmd.batch_spec, so the
    # island's specs always match the GSPMD step's activation layout.
    batch_axes = tuple(
        a for a in data_axes(mesh) if mesh.shape.get(a, 1) > 1
    )
    tp = mesh.shape.get("model", 1)

    def fn(q, k, v):
        if (not on_tpu and not interpret) or k.shape[1] < FLASH_MIN_LEN:
            return dot_product_attention(q, k, v, causal=causal)
        from jax.sharding import PartitionSpec as P

        from ddp_tpu.ops.flash import flash_attention

        head_ax = "model" if tp > 1 and q.shape[2] % tp == 0 else None
        spec = P(batch_axes if batch_axes else None, None, head_ax, None)
        island = jax.shard_map(
            lambda qq, kk, vv: flash_attention(
                qq, kk, vv, causal, block_q, block_k, interpret
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
        return island(q, k, v)

    return fn


def dot_product_attention(q, k, v, *, causal: bool = False, q_offset=None):
    """Plain softmax attention, fp32 accumulation.

    [B, T, H, D] in/out. Softmax runs in fp32 regardless of input dtype
    (bf16-safe); the two matmuls stay in the input dtype for the MXU.
    ``causal=True`` masks strictly-future keys, END-anchored when
    T != S (query t sees keys up to t + S − T — the KV-cache/chunked
    convention, and exactly the flash kernel's mask, so the size
    dispatch in ``best_attention`` can never change the attention
    pattern); for square T == S this is the ordinary lower triangle.

    ``q_offset`` (optional, may be a TRACED scalar) overrides the end
    anchor: query t attends keys up to ``q_offset + t``. This is the
    masked partial-prefill primitive the serving engine's chunked
    prefill runs — the chunk's T queries start at absolute position
    ``q_offset`` inside an S = total_len key lane, so the banded mask
    depends on a runtime value while the compiled shape stays fixed
    (one program per chunk width, any chunk position).
    """
    dtype = q.dtype
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * scale
    if causal:
        T, S = logits.shape[-2:]
        offset = (S - T) if q_offset is None else q_offset
        mask = (
            jnp.arange(T)[:, None] + offset >= jnp.arange(S)[None, :]
        )
        logits = jnp.where(mask, logits, MASK_VALUE)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", weights.astype(dtype), v)


def blockwise_attention(q, k, v, *, block_size: int = 512):
    """Memory-bounded attention: online-softmax over key/value blocks.

    Flash-attention's recurrence expressed with ``lax.scan`` — O(T)
    memory in the key length instead of O(T²), XLA fuses the inner
    block math onto the MXU. Exact (not approximate): matches
    ``dot_product_attention`` to fp32 tolerance for any block size.
    Also the building block of ring attention (each ring hop feeds one
    remote KV block through the same accumulator).
    """
    B, T, H, D = q.shape
    S = k.shape[1]
    if S % block_size:
        # Fall back to one block rather than padding with masks.
        block_size = S
    n_blocks = S // block_size
    qf = q.astype(jnp.float32)
    kf = k.reshape(B, n_blocks, block_size, H, D).transpose(1, 0, 2, 3, 4)
    vf = v.reshape(B, n_blocks, block_size, H, D).transpose(1, 0, 2, 3, 4)
    scale = D**-0.5

    def step(carry, kv):
        acc, row_max, row_sum = carry
        kb, vb = kv
        logits = (
            jnp.einsum("bthd,bshd->bhts", qf, kb.astype(jnp.float32)) * scale
        )  # [B, H, T, block]
        new_max = jnp.maximum(row_max, logits.max(axis=-1))
        correction = jnp.exp(row_max - new_max)
        p = jnp.exp(logits - new_max[..., None])
        acc = acc * correction[..., None] + jnp.einsum(
            "bhts,bshd->bthd", p, vb.astype(jnp.float32)
        ).transpose(0, 2, 1, 3)
        row_sum = row_sum * correction + p.sum(axis=-1)
        return (acc, new_max, row_sum), None

    acc0 = jnp.zeros((B, H, T, D), jnp.float32)
    max0 = jnp.full((B, H, T), -jnp.inf, jnp.float32)
    sum0 = jnp.zeros((B, H, T), jnp.float32)
    (acc, _, row_sum), _ = lax.scan(step, (acc0, max0, sum0), (kf, vf))
    out = acc / row_sum[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)

"""Attention kernels on [B, T, H, D] arrays.

The framework's attention contract: ``fn(q, k, v) -> out`` with all
four arrays shaped [batch, tokens, heads, head_dim]. Everything above
(the ViT family) is kernel-agnostic; everything below (dense reference,
blockwise/flash-style, the sequence-parallel ring in
ddp_tpu.parallel.ring) implements this one signature.

The reference repo has no attention at all (model.py is conv+linear);
this exists for the ViT extension config and the long-context path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


# Large-negative mask value: -inf would produce NaN through the
# online-softmax correction terms when a whole block is masked.
MASK_VALUE = -0.5 * jnp.finfo(jnp.float32).max


def best_attention(*, causal: bool = False, block_q: int = 512,
                   block_k: int = 512):
    """Platform-resolved default attention: flash kernel on TPU.

    On TPU this returns the compiled Pallas flash kernel (fused
    forward + backward, O(T) memory — ops/flash.py); elsewhere the
    dense XLA path, which is faster than interpreting the kernel on
    CPU dev boxes. The model factories (vit/lm/seq/moe) call this when
    no explicit ``attention_fn`` is given, so models are flash-by-
    default on the hardware that has the kernel. Resolution happens at
    model-construction time (the platform is fixed per process).
    """
    from ddp_tpu.ops.flash import make_flash_attention

    if jax.devices()[0].platform == "tpu":
        return make_flash_attention(
            causal=causal, block_q=block_q, block_k=block_k, interpret=False
        )
    return partial(dot_product_attention, causal=causal)


def dot_product_attention(q, k, v, *, causal: bool = False):
    """Plain softmax attention, fp32 accumulation.

    [B, T, H, D] in/out. Softmax runs in fp32 regardless of input dtype
    (bf16-safe); the two matmuls stay in the input dtype for the MXU.
    ``causal=True`` masks position t from keys s > t (q and k must
    cover the same positions).
    """
    dtype = q.dtype
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * scale
    if causal:
        T, S = logits.shape[-2:]
        mask = jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]
        logits = jnp.where(mask, logits, MASK_VALUE)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", weights.astype(dtype), v)


def blockwise_attention(q, k, v, *, block_size: int = 512):
    """Memory-bounded attention: online-softmax over key/value blocks.

    Flash-attention's recurrence expressed with ``lax.scan`` — O(T)
    memory in the key length instead of O(T²), XLA fuses the inner
    block math onto the MXU. Exact (not approximate): matches
    ``dot_product_attention`` to fp32 tolerance for any block size.
    Also the building block of ring attention (each ring hop feeds one
    remote KV block through the same accumulator).
    """
    B, T, H, D = q.shape
    S = k.shape[1]
    if S % block_size:
        # Fall back to one block rather than padding with masks.
        block_size = S
    n_blocks = S // block_size
    qf = q.astype(jnp.float32)
    kf = k.reshape(B, n_blocks, block_size, H, D).transpose(1, 0, 2, 3, 4)
    vf = v.reshape(B, n_blocks, block_size, H, D).transpose(1, 0, 2, 3, 4)
    scale = D**-0.5

    def step(carry, kv):
        acc, row_max, row_sum = carry
        kb, vb = kv
        logits = (
            jnp.einsum("bthd,bshd->bhts", qf, kb.astype(jnp.float32)) * scale
        )  # [B, H, T, block]
        new_max = jnp.maximum(row_max, logits.max(axis=-1))
        correction = jnp.exp(row_max - new_max)
        p = jnp.exp(logits - new_max[..., None])
        acc = acc * correction[..., None] + jnp.einsum(
            "bhts,bshd->bthd", p, vb.astype(jnp.float32)
        ).transpose(0, 2, 1, 3)
        row_sum = row_sum * correction + p.sum(axis=-1)
        return (acc, new_max, row_sum), None

    acc0 = jnp.zeros((B, H, T, D), jnp.float32)
    max0 = jnp.full((B, H, T), -jnp.inf, jnp.float32)
    sum0 = jnp.zeros((B, H, T), jnp.float32)
    (acc, _, row_sum), _ = lax.scan(step, (acc0, max0, sum0), (kf, vf))
    out = acc / row_sum[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)

"""Flash-decode: single-query attention over SlotCache key lanes.

The serving hot path (ROADMAP item 2): every engine step runs S
single-token queries against S cache lanes of up to ``total_len`` keys
— ``ops/flash.py`` only covers training shapes (many queries per
sequence), so until now decode paid a dense ``[S, H_kv, G, L]`` logits
tensor through XLA every step. This module is the decode-shaped
sibling:

- :func:`decode_attention_reference` — the jnp fallback, EXACTLY the
  einsum math ``models/generate.slot_decode_step`` always ran (same
  contraction strings, same fp32 casts, same ``-inf`` masking), pulled
  out so the kernel has a bit-identical baseline to pin against and
  non-TPU platforms keep the PR-3 numerics unchanged.
- :func:`flash_decode_attention` — a Pallas TPU kernel on a
  ``(S·H_kv, L/block_k)`` grid: each grid row owns one (slot, kv-head)
  pair's G grouped queries, KV blocks stream through VMEM under the
  online-softmax recurrence (fp32 scratch persisting across the
  innermost grid dim, flushed on its last iteration — the
  ``ops/flash.py`` scheme), and the **banded read honors per-slot
  positions**: key columns past ``pos[s]`` are masked, and whole
  blocks that start past ``pos[s]`` are ``pl.when``-skipped, so a
  young lane in a long cache pays O(pos) compute, not O(total_len).
  No [T, S]-style score tensor ever exists; per-step HBM traffic is
  the K/V lanes once.
- **int8 KV dequantize-in-kernel**: when the cache stores int8 K/V
  with per-(position, head) scales (:func:`quantize_kv`), both paths
  dequantize at the compute site — the kernel widens int8 blocks in
  VMEM, so HBM reads stay half-width (the whole point of quantizing:
  decode is cache-bandwidth bound).
- :func:`shard_decode_attention` — mesh composition: the compiled
  Mosaic call has no partitioning rule (same wall as
  ``ops/attention.gspmd_flash_attention``), so TP serving routes the
  kernel through a ``shard_map`` island over the ``model`` axis —
  whole kv-head groups per shard, matching the Megatron head layout
  the qkv kernels already use.

Decode is a forward-only surface: no custom VJP here (generation
never differentiates), which keeps the kernel a single
``pallas_call``.

``interpret=True`` (automatic off-TPU) runs the same program through
the Pallas interpreter — how the CPU test suite pins token identity
against the reference across every prefill bucket edge
(tests/test_flash_decode.py); online-softmax reassociation can move
logits by ~1 ulp, so the pins are engine-level token streams plus
elementwise tolerance, the same contract ops/flash.py tests use.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on CPU-only builds of pallas
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

# Per-row stats ride broadcast across the minor 128-lane dim (the
# ops/flash.py layout convention — [.., 1] would be lane-padded in
# VMEM anyway and 2-D one-row blocks are not tileable).
LANES = 128

# int8 quantization range: symmetric, NaN-free at zero rows (the amax
# floor below keeps the scale strictly positive).
_INT8_MAX = 127.0
_AMAX_FLOOR = 1e-8


# ---- int8 KV quantization -------------------------------------------


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[..., H_kv, Dh] float K/V → (int8 rows, per-head fp32 scales).

    Symmetric per-(position, head) scaling: ``scale = amax/127`` over
    the head_dim so each head row dequantizes as ``int8 · scale``.
    Scale shape is the input's without its trailing dim. The amax
    floor keeps all-zero rows (unwritten cache lines) exact zeros
    after round-trip rather than NaN.
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, _AMAX_FLOOR) / _INT8_MAX
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]),
        -_INT8_MAX,
        _INT8_MAX,
    ).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_kv(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_kv` → fp32 rows."""
    return q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)


def _maybe_dequant(x, scale):
    if x.dtype == jnp.int8:
        return dequantize_kv(x, scale)
    return x


# ---- jnp reference (the PR-3 decode math, verbatim) ------------------


def decode_attention_reference(q, k, v, pos, k_scale=None, v_scale=None):
    """Single-query banded attention → [S, H, Dh] fp32.

    ``q``: [S, H, Dh] (one query per lane); ``k``/``v``: [S, L, H_kv,
    Dh] cache lanes (fp32/bf16, or int8 with ``k_scale``/``v_scale``
    [S, L, H_kv]); ``pos``: [S] int32 — lane s attends keys at
    positions ``<= pos[s]``. GQA grouping, contraction order, fp32
    casts and the ``-inf`` mask are EXACTLY ``slot_decode_step``'s
    original inline math, so the fp32 path is bit-identical to the
    PR-3 engine (the token-identity baseline the kernel pins against).
    """
    S, H, Dh = q.shape
    L, H_kv = k.shape[1], k.shape[2]
    G = H // H_kv
    kf = _maybe_dequant(k, k_scale)
    vf = _maybe_dequant(v, v_scale)
    qg = q.reshape(S, H_kv, G, Dh)
    logits = (
        jnp.einsum(
            "bkgd,blkd->bkgl",
            qg.astype(jnp.float32),
            kf.astype(jnp.float32),
        )
        * Dh**-0.5
    )  # [S, H_kv, G, L]
    live = (jnp.arange(L)[None, :] <= pos[:, None])[:, None, None, :]
    logits = jnp.where(live, logits, -jnp.inf)
    w = jax.nn.softmax(logits, axis=-1)
    attn = jnp.einsum("bkgl,blkd->bkgd", w, vf.astype(jnp.float32))
    return attn.reshape(S, H, Dh)


# ---- the Pallas kernel ----------------------------------------------


def pick_block_k(L: int, block_k: int) -> int:
    """Effective KV block for a length-``L`` lane.

    The grid needs ``block_k | L``. When the requested size doesn't
    divide, fall back to the **largest divisor of L ≤ requested** —
    never to ``L`` itself (a single full-length block would defeat the
    ``pl.when`` dead-block skip that makes young lanes O(pos)). Worst
    case (prime ``L``) degrades to 1-wide blocks, which is still
    banded; the tuner and the xprof ledger surface the effective value
    so a pathological ``L`` is visible, not silent.
    """
    block_k = min(block_k, L)
    while L % block_k:
        block_k -= 1
    return block_k


# Pre-rename private spelling; kept so external callers (and the
# tuner's site-version hash) have one canonical name to import.
_pick_block_k = pick_block_k


def flash_decode_attention(
    q,
    k,
    v,
    pos,
    k_scale=None,
    v_scale=None,
    *,
    block_k: int = 128,
    interpret: bool | None = None,
):
    """Pallas flash-decode → [S, H, Dh] fp32 (the reference's contract).

    Same signature/semantics as :func:`decode_attention_reference`;
    ``interpret=None`` auto-detects (compiled Mosaic on TPU, the
    interpreter elsewhere so one engine config runs anywhere).
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    S, H, Dh = q.shape
    L, H_kv = k.shape[1], k.shape[2]
    G = H // H_kv
    block_k = pick_block_k(L, block_k)
    quantized = k.dtype == jnp.int8
    # One grid row per (slot, kv-head): q regrouped kv-head-major
    # (exactly the engine's qg = q.reshape(S, H_kv, G, Dh) grouping),
    # K/V lanes transposed so each row streams [L, Dh] blocks.
    qt = q.reshape(S * H_kv, G, Dh)
    kt = k.transpose(0, 2, 1, 3).reshape(S * H_kv, L, Dh)
    vt = v.transpose(0, 2, 1, 3).reshape(S * H_kv, L, Dh)
    # Per-row lane position, broadcast across the minor 128 lanes
    # (the ops/flash.py per-row-stat layout).
    pos_l = jnp.broadcast_to(
        jnp.repeat(pos.astype(jnp.int32), H_kv)[:, None, None],
        (S * H_kv, 1, LANES),
    )
    kw = {} if _VMEM is None or interpret else {"memory_space": _VMEM}
    qmap = lambda b, j: (b, 0, 0)
    kmap = lambda b, j: (b, j, 0)
    in_specs = [
        pl.BlockSpec((1, G, Dh), qmap, **kw),
        pl.BlockSpec((1, block_k, Dh), kmap, **kw),
        pl.BlockSpec((1, block_k, Dh), kmap, **kw),
    ]
    args = [qt, kt, vt]
    if quantized:
        ksc = k_scale.transpose(0, 2, 1).reshape(S * H_kv, L, 1)
        vsc = v_scale.transpose(0, 2, 1).reshape(S * H_kv, L, 1)
        in_specs += [
            pl.BlockSpec((1, block_k, 1), kmap, **kw),
            pl.BlockSpec((1, block_k, 1), kmap, **kw),
        ]
        args += [ksc.astype(jnp.float32), vsc.astype(jnp.float32)]
    in_specs.append(pl.BlockSpec((1, 1, LANES), qmap, **kw))
    args.append(pos_l)

    def scratch(shape):
        if pltpu is None:  # pragma: no cover
            # No pallas.tpu module → no VMEM scratch spec to build.
            # `auto` never routes here off-TPU; a forced `flash` on
            # such a build gets a clear error, not a Mosaic crash.
            raise RuntimeError(
                "flash_decode_attention needs jax.experimental"
                ".pallas.tpu for its scratch buffers; this jax build "
                "lacks it — use impl='reference'"
            )
        return pltpu.VMEM(shape, jnp.float32)

    kernel = (
        _quantized_kernel if quantized else _plain_kernel
    )
    out = pl.pallas_call(
        functools.partial(
            kernel, scale=Dh**-0.5, block_k=block_k,
        ),
        grid=(S * H_kv, L // block_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, G, Dh), qmap, **kw),
        out_shape=jax.ShapeDtypeStruct((S * H_kv, G, Dh), jnp.float32),
        scratch_shapes=[
            scratch((G, Dh)),
            scratch((G, LANES)),
            scratch((G, LANES)),
        ],
        interpret=interpret,
    )(*args)
    return out.reshape(S, H, Dh)


def _plain_kernel(
    q_ref, k_ref, v_ref, pos_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale, block_k,
):
    _decode_body(
        q_ref, k_ref, v_ref, None, None, pos_ref, o_ref,
        acc_ref, m_ref, l_ref, scale=scale, block_k=block_k,
    )


def _quantized_kernel(
    q_ref, k_ref, v_ref, ksc_ref, vsc_ref, pos_ref, o_ref,
    acc_ref, m_ref, l_ref, *, scale, block_k,
):
    _decode_body(
        q_ref, k_ref, v_ref, ksc_ref, vsc_ref, pos_ref, o_ref,
        acc_ref, m_ref, l_ref, scale=scale, block_k=block_k,
    )


def _decode_body(
    q_ref, k_ref, v_ref, ksc_ref, vsc_ref, pos_ref, o_ref,
    acc_ref, m_ref, l_ref, *, scale, block_k,
):
    """Shared online-softmax body (see :func:`_decode_kernel` docs)."""
    j = pl.program_id(1)
    n_kb = pl.num_programs(1)
    pos = pos_ref[0, 0, 0]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Banded read: a block whose first key is past the lane position
    # is dead in full — skip its MXU work entirely (block 0 is always
    # live since pos >= 0, so the denominator can never be empty).
    @pl.when(j * block_k <= pos)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # [G, Dh]
        kb = k_ref[0].astype(jnp.float32)  # [block_k, Dh]
        vb = v_ref[0].astype(jnp.float32)
        if ksc_ref is not None:
            # int8 rows widen at the compute site: HBM traffic for
            # the lane read stays half-width.
            kb = kb * ksc_ref[0][:, :1]
            vb = vb * vsc_ref[0][:, :1]
        s = lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, block_k]
        cols = j * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols <= pos, s, -jnp.inf)
        m = m_ref[...][:, :1]
        l = l_ref[...][:, :1]
        new_m = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        shift = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(s - shift)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - shift), 0.0)
        acc_ref[...] = acc_ref[...] * corr + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        m_ref[...] = jnp.broadcast_to(new_m, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == n_kb - 1)
    def _flush():
        l = l_ref[...][:, :1]
        o_ref[0] = (
            acc_ref[...] / jnp.maximum(l, 1e-30)
        ).astype(o_ref.dtype)


# ---- paged KV: gather lane views through int32 page tables ----------
#
# The paged cache (models/generate.PagedSlotCache, PR 12) stores K/V
# as a POOL of page_size-token blocks shared across lanes; a lane's
# logical [L, H_kv, Dh] view is its page table's gather. Keeping the
# gather here (rather than inline in generate.py) gives both decode
# paths one definition: the jnp reference runs the EXACT fixed-lane
# einsum math over the gathered view (bit-identical off-TPU — the
# token-identity pin), and the flash path streams the gathered lanes
# through the same Pallas kernel with block_k = page_size. The gather
# itself is one XLA dynamic-gather over int32 ids — static shape
# arithmetic, no host sync (lint TN fixture ddp002_tn.py pins the
# pattern).
#
# Honest cost note: the gather MATERIALIZES the per-lane views before
# the kernel runs, so on this path the kernel's dead-block skip saves
# compute only — the gather already paid O(total_len) HBM traffic per
# layer per step, the bandwidth the fixed-lane banded read avoids.
# The O(pos) paged hot path needs IN-KERNEL table indexing (a
# scalar-prefetch BlockSpec index_map resolving page ids per grid
# row, the vLLM/TPU paged-attention shape) — the wired on-chip
# follow-up; until then an on-chip capture of paged+flash measures
# gather + kernel, and bench.py's serve_decode paged_kv sub-record
# should be read accordingly.


def gather_paged_kv(pages: jax.Array, table: jax.Array) -> jax.Array:
    """[num_pages, page_size, ...] pool + [S, n] int32 table →
    [S, n·page_size, ...] per-lane views (works for K/V rows AND their
    int8 per-head scale planes — anything page-major)."""
    g = jnp.take(pages, table, axis=0)  # [S, n, page_size, ...]
    S, n, ps = g.shape[:3]
    return g.reshape(S, n * ps, *g.shape[3:])


def paged_decode_attention(
    q, k_pages, v_pages, table, pos, k_scale=None, v_scale=None, *,
    impl: str = "reference", interpret: bool | None = None,
):
    """Single-query banded attention over paged lanes → [S, H, Dh].

    ``k_pages``/``v_pages``: one layer's page pool ([num_pages,
    page_size, H_kv, Dh]); ``table``: [S, n_lane_pages] int32 page ids
    (0 = the engine's scratch page); ``pos``: [S] as in
    :func:`decode_attention_reference`. Semantics are EXACTLY the
    fixed-lane call over the table's gathered view — positions past
    ``pos[s]`` (including every scratch-page line) are masked, so a
    stale or zero table entry above the live region can never leak
    into the softmax. ``block_k = page_size`` aligns the flash
    kernel's dead-block skip with page boundaries (compute-side only
    here — see the module's cost note: the gather materializes the
    full lane views first; in-kernel table indexing is the on-chip
    follow-up).
    """
    k = gather_paged_kv(k_pages, table)
    v = gather_paged_kv(v_pages, table)
    ks = gather_paged_kv(k_scale, table) if k_scale is not None else None
    vs = gather_paged_kv(v_scale, table) if v_scale is not None else None
    return decode_attention(
        q, k, v, pos, ks, vs,
        impl=impl, block_k=int(k_pages.shape[1]), interpret=interpret,
    )


# ---- runtime selection + mesh composition ---------------------------


def decode_attention(
    q, k, v, pos, k_scale=None, v_scale=None, *,
    impl: str = "reference", block_k: int = 128,
    interpret: bool | None = None,
):
    """The engine-facing entry: ``impl`` picks the path at trace time.

    ``reference`` — the jnp einsum math (bit-identical to the PR-3
    engine on fp32 caches); ``flash`` — the Pallas kernel (compiled
    Mosaic on TPU, interpreter elsewhere); ``auto`` — flash on TPU,
    reference everywhere else (the serving default: off-TPU nothing
    beats XLA's fused einsums, and the PR-3 numerics stay untouched).
    """
    if impl == "auto":
        impl = (
            "flash" if jax.devices()[0].platform == "tpu" else "reference"
        )
    if impl == "flash":
        return flash_decode_attention(
            q, k, v, pos, k_scale, v_scale,
            block_k=block_k, interpret=interpret,
        )
    if impl != "reference":
        raise ValueError(
            f"unknown decode attention impl {impl!r}: expected "
            "'auto', 'flash' or 'reference'"
        )
    return decode_attention_reference(q, k, v, pos, k_scale, v_scale)


def shard_decode_attention(
    mesh, *, impl: str = "auto", block_k: int = 128,
    interpret: bool | None = None,
):
    """Mesh-composable flash-decode: shard_map over the ``model`` axis.

    The compiled Mosaic custom call has no GSPMD partitioning rule
    (the ``ops/attention.gspmd_flash_attention`` wall), so a
    tensor-parallel serving step routes the kernel through a
    ``shard_map`` island: kv heads shard over ``model`` (whole GQA
    groups per shard — the Megatron layout the qkv kernels already
    use, so no resharding at the island boundary), slots/positions
    replicate along it. Falls back to a plain call when the mesh has
    no ``model`` axis > 1 or the kv heads do not divide.

    Returns ``fn(q, k, v, pos, k_scale=None, v_scale=None)``.
    """
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape.get("model", 1)

    def fn(q, k, v, pos, k_scale=None, v_scale=None):
        H_kv = k.shape[2]
        if tp <= 1 or H_kv % tp:
            return decode_attention(
                q, k, v, pos, k_scale, v_scale,
                impl=impl, block_k=block_k, interpret=interpret,
            )
        qspec = P(None, "model", None)
        kvspec = P(None, None, "model", None)
        scspec = P(None, None, "model")
        has_scales = k_scale is not None
        in_specs = (qspec, kvspec, kvspec) + (
            (scspec, scspec) if has_scales else ()
        ) + (P(),)
        args = (q, k, v) + (
            (k_scale, v_scale) if has_scales else ()
        ) + (pos,)

        def island(*a):
            if has_scales:
                qq, kk, vv, ks, vs, pp = a
            else:
                qq, kk, vv, pp = a
                ks = vs = None
            return decode_attention(
                qq, kk, vv, pp, ks, vs,
                impl=impl, block_k=block_k, interpret=interpret,
            )

        return jax.shard_map(
            island,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=qspec,
            check_vma=False,
        )(*args)

    return fn

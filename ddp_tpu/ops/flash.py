"""Pallas TPU flash attention: fused forward AND backward kernels.

The hot op of the attention path, written for the hardware
(/opt/skills/guides/pallas_guide.md): Q and K/V blocks stream through
VMEM on a (batch·head, q-block, kv-block) grid, the online-softmax
recurrence lives in fp32 VMEM scratch that persists across the
innermost grid dimension, every matmul hits the MXU with
``preferred_element_type=jnp.float32``, and HBM traffic is O(T·D) —
the [T, S] score matrix never exists. This is the TPU-native answer to
the fused ATen attention kernels the reference inherits invisibly from
torch's C++ core (/root/reference/train_ddp.py:199, SURVEY.md §2b N5) —
there the fusion lives in cuDNN/ATen; here it is an explicit trio of
Pallas kernels.

Differentiation is flash end to end: the forward kernel also emits the
per-row log-sum-exp (LSE), and the backward runs two Pallas kernels —
one gridded over Q blocks producing dQ, one gridded over K/V blocks
producing dK/dV — each recomputing P = exp(S − LSE) blockwise from the
saved residuals. Peak memory of the whole VJP is O(T·D); the round-1
version recomputed backward through a dense O(T²) reference
(VERDICT.md "What's missing" #1).

Causal masking skips FLOPs: strictly-future (q-block, kv-block) cells
are ``pl.when``-gated off in all three kernels, so ~half the MXU work
disappears at large T.

``flash_attention_with_lse`` additionally returns the LSE rows, which
makes the kernel composable as the per-hop block primitive of ring
attention (parallel/ring.py): partial results from different KV blocks
merge by the standard (out, lse) log-space combine, and the custom VJP
routes the lse cotangent through the same blockwise backward (the
``delta − dlse`` fold below).

Layout notes (Mosaic constraints): per-row statistics (LSE, delta)
travel as [B·H, T, LANES] fp32 broadcast across a 128-lane minor
dimension — a [.., T, 1] layout would be lane-padded to 128 in VMEM
anyway, and 2-D [B·H, T] blocks of one row are not tileable. Scratch
accumulators persist across the innermost grid dimension and flush on
its last iteration (``pl.when``), the same scheme as
jax.experimental.pallas.ops.tpu.flash_attention.

``interpret=True`` runs the kernels on CPU for tests — the same
program the TPU compiles, minus Mosaic.

Validated on a real TPU chip (2026-07, v5e): forward+backward compile
through Mosaic and run at T up to 32768 (causal, bf16), gradients
finite, forward matching the fp32 dense reference to ≤2e-3 and the
backward matching dense-attention gradients to fp32 tolerance.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on CPU-only builds of pallas
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

# Minor-most lanes of a TPU vector register; per-row stats are carried
# broadcast across this many lanes (see module docstring).
LANES = 128


def _row_stat(ref):
    """Read a [block, LANES] lane-broadcast stat as a [block, 1] column."""
    return ref[0][:, :1]


def _causal_mask(s, q_start, k_start, block_q, block_k, S_total, T_total):
    """End-anchored causal mask: query t sees keys up to t + S − T
    (the dense reference's tril(k=S−T); KV-cache convention for T≠S)."""
    rows = q_start + (S_total - T_total) + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    cols = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return jnp.where(rows >= cols, s, -jnp.inf)


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale, causal, block_q, block_k, T_total, S_total,
):
    """Grid (B·H, T/bq, S/bk): online softmax over streamed KV blocks."""
    j = pl.program_id(2)
    n_kb = pl.num_programs(2)
    q_start = pl.program_id(1) * block_q

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    if causal:
        # Fully-masked (strictly future) block: skip all compute.
        live = q_start + block_q - 1 + (S_total - T_total) >= j * block_k
    else:
        live = True

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # [block_q, D]
        kb = k_ref[0].astype(jnp.float32)  # [block_k, D]
        vb = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            s = _causal_mask(
                s, q_start, j * block_k, block_q, block_k, S_total, T_total
            )
        m = m_ref[...][:, :1]
        l = l_ref[...][:, :1]
        new_m = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        # A fully-masked ROW has new_m = -inf; exp(-inf − -inf) would
        # be NaN. Guard the shift.
        shift = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(s - shift)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - shift), 0.0)
        acc_ref[...] = acc_ref[...] * corr + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        m_ref[...] = jnp.broadcast_to(new_m, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == n_kb - 1)
    def _flush():
        m = m_ref[...][:, :1]
        l = l_ref[...][:, :1]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse = jnp.where(
            l > 0.0,
            jnp.where(jnp.isfinite(m), m, 0.0)
            + jnp.log(jnp.maximum(l, 1e-30)),
            -jnp.inf,
        )
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, dq_acc,
    *, scale, causal, block_q, block_k, T_total, S_total,
):
    """Grid (B·H, T/bq, S/bk): dQ accumulates over streamed KV blocks.

    ``dl_ref`` holds delta' = rowsum(dO ∘ O) − dLSE; with P recomputed
    as exp(S − LSE), dS = P ∘ (dO·Vᵀ − delta') and dQ = scale · dS·K.
    """
    j = pl.program_id(2)
    n_kb = pl.num_programs(2)
    q_start = pl.program_id(1) * block_q

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    if causal:
        live = q_start + block_q - 1 + (S_total - T_total) >= j * block_k
    else:
        live = True

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = _row_stat(lse_ref)
        lse = jnp.where(
            jnp.isfinite(lse), lse, 0.5 * jnp.finfo(jnp.float32).max
        )
        dl = _row_stat(dl_ref)
        s = scale * lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            s = _causal_mask(
                s, q_start, j * block_k, block_q, block_k, S_total, T_total
            )
        p = jnp.exp(s - lse)  # masked: exp(-inf) = 0
        dp = lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dl)
        dq_acc[...] = dq_acc[...] + lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_kb - 1)
    def _flush():
        dq_ref[0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, dl_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale, causal, block_q, block_k, T_total, S_total,
):
    """Grid (B·H, S/bk, T/bq): dK/dV accumulate over streamed Q blocks."""
    i = pl.program_id(2)
    n_qb = pl.num_programs(2)
    k_start = pl.program_id(1) * block_k

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    if causal:
        # Last query row of this Q block must see the first key of
        # this K block: (i+1)·bq − 1 + S − T >= k_start.
        live = (i + 1) * block_q - 1 + (S_total - T_total) >= k_start
    else:
        live = True

    @pl.when(live)
    def _compute():
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        qb = q_ref[0].astype(jnp.float32)
        dob = do_ref[0].astype(jnp.float32)
        lse = _row_stat(lse_ref)
        lse = jnp.where(
            jnp.isfinite(lse), lse, 0.5 * jnp.finfo(jnp.float32).max
        )
        dl = _row_stat(dl_ref)
        s = scale * lax.dot_general(
            qb, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            s = _causal_mask(
                s, i * block_q, k_start, block_q, block_k, S_total, T_total
            )
        p = jnp.exp(s - lse)
        dv_acc[...] = dv_acc[...] + lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            dob, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dl)
        dk_acc[...] = dk_acc[...] + lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == n_qb - 1)
    def _flush():
        dk_ref[0] = (dk_acc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _pick_blocks(T, S, block_q, block_k):
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    if T % block_q:
        block_q = T
    if S % block_k:
        block_k = S
    return block_q, block_k


def _to_bh(x):
    """[B, T, H, D] → [B·H, T, D]: one grid row per (batch, head)."""
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _scratch(shape):
    if pltpu is not None:
        return pltpu.VMEM(shape, jnp.float32)
    return pl.ANY(shape, jnp.float32)  # pragma: no cover


def _flash_forward(
    q, k, v, *, causal: bool, block_q: int, block_k: int, interpret: bool
):
    """Returns (out [B,T,H,D], lse [B,T,H] fp32)."""
    B, T, H, D = q.shape
    S = k.shape[1]
    block_q, block_k = _pick_blocks(T, S, block_q, block_k)
    scale = D**-0.5
    qt, kt, vt = _to_bh(q), _to_bh(k), _to_bh(v)

    kw = {} if _VMEM is None or interpret else {"memory_space": _VMEM}
    qmap = lambda b, i, j: (b, i, 0)
    kmap = lambda b, i, j: (b, j, 0)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, T_total=T, S_total=S,
        ),
        grid=(B * H, T // block_q, S // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), qmap, **kw),
            pl.BlockSpec((1, block_k, D), kmap, **kw),
            pl.BlockSpec((1, block_k, D), kmap, **kw),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), qmap, **kw),
            pl.BlockSpec((1, block_q, LANES), qmap, **kw),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, LANES), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, D)),
            _scratch((block_q, LANES)),
            _scratch((block_q, LANES)),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out = out.reshape(B, H, T, D).transpose(0, 2, 1, 3)
    lse = lse[:, :, 0].reshape(B, H, T).transpose(0, 2, 1)  # [B, T, H]
    return out, lse


def _to_lanes(x_bth):
    """[B, T, H] per-row stat → [B·H, T, LANES] lane-broadcast fp32."""
    B, T, H = x_bth.shape
    flat = x_bth.astype(jnp.float32).transpose(0, 2, 1).reshape(B * H, T, 1)
    return jnp.broadcast_to(flat, (B * H, T, LANES))


def _flash_backward(
    q, k, v, out, lse, g, dlse, *, causal, block_q, block_k, interpret
):
    """Blockwise VJP: (dq, dk, dv) with O(T·D) peak memory.

    ``dlse`` is the cotangent of the LSE output (zeros when the caller
    only differentiates the attention output): dS picks up an extra
    +P·dLSE term, folded in as delta' = rowsum(dO ∘ O) − dLSE.
    """
    B, T, H, D = q.shape
    S = k.shape[1]
    block_q, block_k = _pick_blocks(T, S, block_q, block_k)
    scale = D**-0.5
    delta = (g.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    dl_l = _to_lanes(delta - dlse.astype(jnp.float32))
    lse_l = _to_lanes(lse)
    qt, kt, vt, gt = _to_bh(q), _to_bh(k), _to_bh(v), _to_bh(g)

    kw = {} if _VMEM is None or interpret else {"memory_space": _VMEM}
    common = dict(
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        T_total=T, S_total=S,
    )
    qmap = lambda b, i, j: (b, i, 0)
    kmap = lambda b, i, j: (b, j, 0)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid=(B * H, T // block_q, S // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), qmap, **kw),
            pl.BlockSpec((1, block_k, D), kmap, **kw),
            pl.BlockSpec((1, block_k, D), kmap, **kw),
            pl.BlockSpec((1, block_q, D), qmap, **kw),
            pl.BlockSpec((1, block_q, LANES), qmap, **kw),
            pl.BlockSpec((1, block_q, LANES), qmap, **kw),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), qmap, **kw),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        scratch_shapes=[_scratch((block_q, D))],
        interpret=interpret,
    )(qt, kt, vt, gt, lse_l, dl_l)

    # For dK/dV the K block is the OUTER streamed dim, Q the inner.
    kvmap = lambda b, jk, i: (b, jk, 0)
    qmap2 = lambda b, jk, i: (b, i, 0)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **common),
        grid=(B * H, S // block_k, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_k, D), kvmap, **kw),
            pl.BlockSpec((1, block_k, D), kvmap, **kw),
            pl.BlockSpec((1, block_q, D), qmap2, **kw),
            pl.BlockSpec((1, block_q, D), qmap2, **kw),
            pl.BlockSpec((1, block_q, LANES), qmap2, **kw),
            pl.BlockSpec((1, block_q, LANES), qmap2, **kw),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), kvmap, **kw),
            pl.BlockSpec((1, block_k, D), kvmap, **kw),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
        ],
        scratch_shapes=[_scratch((block_k, D)), _scratch((block_k, D))],
        interpret=interpret,
    )(kt, vt, qt, gt, lse_l, dl_l)

    back = lambda x, T_: x.reshape(B, H, T_, D).transpose(0, 2, 1, 3)
    return back(dq, T), back(dk, S), back(dv, S)


def _reference(q, k, v, causal: bool):
    """Dense XLA attention — the math the kernels implement, for tests
    and the non-Pallas fallback. fp32 accumulation throughout."""
    dtype = q.dtype
    scale = q.shape[-1] ** -0.5
    logits = (
        jnp.einsum(
            "bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32)
        )
        * scale
    )
    if causal:
        T, S = logits.shape[-2:]
        mask = jnp.tril(jnp.ones((T, S), bool), k=S - T)
        logits = jnp.where(mask, logits, -jnp.inf)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", weights, v.astype(jnp.float32))
    return out.astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q,
    k,
    v,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
):
    """Flash attention on [B, T, H, D]; Pallas forward AND backward.

    ``interpret=True`` for CPU (tests); on TPU the kernels compile via
    Mosaic. Use keyword-style through ``make_flash_attention`` for the
    model-facing ``(q, k, v) -> out`` contract.
    """
    out, _ = _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v, out, lse = residuals
    return _flash_backward(
        q, k, v, out, lse, g, jnp.zeros_like(lse), causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(
    q,
    k,
    v,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
):
    """Like ``flash_attention`` but returns ``(out, lse)``.

    ``lse`` is [B, T, H] fp32 = logsumexp of the scaled logits per
    query row. Partial attention outputs over different KV blocks
    combine exactly from (out, lse) pairs — this is the per-hop
    primitive of ring attention (parallel/ring.py). Differentiable in
    both outputs.
    """
    return _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def _fal_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return (out, lse), (q, k, v, out, lse)


def _fal_bwd(causal, block_q, block_k, interpret, residuals, cotangents):
    q, k, v, out, lse = residuals
    g, dlse = cotangents
    return _flash_backward(
        q, k, v, out, lse, g, dlse, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )


flash_attention_with_lse.defvjp(_fal_fwd, _fal_bwd)


def make_flash_attention(
    *, causal: bool = False, block_q: int = 512, block_k: int = 512,
    interpret: bool | None = None,
):
    """Bind options → the framework's ``(q, k, v) -> out`` attention fn.

    ``interpret=None`` auto-detects: compiled kernel on TPU, interpreter
    elsewhere (CPU dev boxes), so the same model config runs anywhere.
    """

    def fn(q, k, v):
        interp = interpret
        if interp is None:
            interp = jax.devices()[0].platform != "tpu"
        return flash_attention(q, k, v, causal, block_q, block_k, interp)

    return fn

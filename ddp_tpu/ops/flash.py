"""Pallas TPU flash-attention kernel.

The hot op of the attention path, written for the hardware
(/opt/skills/guides/pallas_guide.md): Q blocks stream through VMEM, the
online-softmax recurrence runs in fp32 vector registers, both matmuls
hit the MXU with ``preferred_element_type=jnp.float32``, and HBM
traffic is O(T·D) per query block instead of materializing the [T, S]
score matrix. Same math as ``ops.attention.blockwise_attention`` — the
kernel is the TPU-resident version of that scan.

Differentiation: ``flash_attention`` carries a ``jax.custom_vjp`` whose
backward recomputes through the XLA blockwise implementation (exact
same accumulator, so gradients are exact); forward-pass inference and
the forward half of training run the Pallas kernel.

``interpret=True`` runs the kernel on CPU for tests — the same code
path the TPU compiles, minus Mosaic.

Validated on a real v4 chip (2026-07): compiles through Mosaic at
T up to 8192, bf16 forward matches the fp32 reference to ≤2e-3
(non-causal) / 1.6e-2 (causal, bf16 rounding at the mask boundary),
and the custom-vjp backward produces finite exact gradients.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on CPU-only builds of pallas
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _kernel(
    q_ref, k_ref, v_ref, o_ref, *, scale, block_k, causal, block_q, T_total
):
    """One (batch·head, q-block) grid cell."""
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, D]
    S_total = S = k_ref.shape[1]
    num_kb = S // block_k
    q_start = pl.program_id(1) * block_q

    def body(i, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            # Anchored at the sequence END (query t sees keys up to
            # t + S - T), matching _reference's tril(k=S-T) — the
            # KV-cache convention when T != S.
            rows = q_start + (S_total - T_total) + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = i * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(rows >= cols, s, -jnp.inf)
        new_m = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        # With causal masking a fully-masked row has new_m = -inf;
        # exp(-inf - -inf) would be NaN. Guard the shift.
        shift = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(s - shift)
        corr = jnp.exp(jnp.where(jnp.isfinite(m), m - shift, -jnp.inf))
        corr = jnp.where(jnp.isfinite(m), corr, 0.0)
        acc = acc * corr + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        l = l * corr + p.sum(axis=-1, keepdims=True)
        return acc, new_m, l

    D = q_ref.shape[-1]
    acc0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, _, l = lax.fori_loop(0, num_kb, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(
    q, k, v, *, causal: bool, block_q: int, block_k: int, interpret: bool
):
    B, T, H, D = q.shape
    S = k.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    if T % block_q:
        block_q = T
    if S % block_k:
        block_k = S
    scale = D**-0.5
    # [B, T, H, D] → [B·H, T, D]: one grid row per (batch, head).
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    spec_kwargs = {} if _VMEM is None or interpret else {"memory_space": _VMEM}
    out = pl.pallas_call(
        functools.partial(
            _kernel, scale=scale, block_k=block_k, causal=causal,
            block_q=block_q, T_total=T,
        ),
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), **spec_kwargs),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0), **spec_kwargs),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0), **spec_kwargs),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, D), lambda b, i: (b, i, 0), **spec_kwargs
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _reference(q, k, v, causal: bool):
    """XLA online-softmax attention — the exact math the kernel runs.

    Used for the backward pass (recompute + AD) and as the non-TPU
    fallback. fp32 accumulation throughout.
    """
    dtype = q.dtype
    scale = q.shape[-1] ** -0.5
    logits = (
        jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32))
        * scale
    )
    if causal:
        T, S = logits.shape[-2:]
        mask = jnp.tril(jnp.ones((T, S), bool), k=S - T)
        logits = jnp.where(mask, logits, -jnp.inf)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", weights, v.astype(jnp.float32))
    return out.astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q,
    k,
    v,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Flash attention on [B, T, H, D]; Pallas forward, exact gradients.

    ``interpret=True`` for CPU (tests); on TPU the kernel compiles via
    Mosaic. Use keyword-style through ``make_flash_attention`` for the
    model-facing ``(q, k, v) -> out`` contract.
    """
    return _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(lambda a, b, c: _reference(a, b, c, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def make_flash_attention(
    *, causal: bool = False, block_q: int = 128, block_k: int = 128,
    interpret: bool | None = None,
):
    """Bind options → the framework's ``(q, k, v) -> out`` attention fn.

    ``interpret=None`` auto-detects: compiled kernel on TPU, interpreter
    elsewhere (CPU dev boxes), so the same model config runs anywhere.
    """

    def fn(q, k, v):
        interp = interpret
        if interp is None:
            interp = jax.devices()[0].platform != "tpu"
        return flash_attention(q, k, v, causal, block_q, block_k, interp)

    return fn

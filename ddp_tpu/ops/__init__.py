"""Custom TPU ops (Pallas kernels).

The reference's kernel layer is ATen C++ (SURVEY.md §2b N5); on TPU the
XLA compiler covers it, and this package holds Pallas kernels for ops
where hand-tiling beats XLA's schedule.
"""

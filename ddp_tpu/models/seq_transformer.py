"""Long-context transformer with model-level sequence parallelism.

SURVEY.md §5 lists long-context/sequence parallelism as absent from the
reference; parallel/ring.py supplies the collective attention kernels,
and this module puts a whole model on top of them: tokens shard over
the ``seq`` mesh axis end to end — embedding, position slices,
attention (ring or Ulysses), MLPs, pooling — so sequences larger than
one chip's HBM train without ever materializing [B, T_global, C] on a
device.

Design: the per-token ops (Dense, LayerNorm, MLP) are embarrassingly
token-parallel, so the module body runs unchanged on a local token
shard; the two places that need the global sequence are pluggable —
``attention_fn`` (ring/Ulysses collectives from parallel/ring.py) and
``pool_fn`` (a psum-mean for the classification head). Position
embeddings are a global-length parameter sliced per shard by offset.
Gradients for the replicated parameters come out correct by
construction: ``jax.grad`` through ``shard_map`` transposes the
replicated-in broadcast into a psum over the mesh.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.models.vit import EncoderBlock
from ddp_tpu.parallel.ddp import StepMetrics
from ddp_tpu.parallel.ring import sequence_sharded_attention


class LongContextTransformer(nn.Module):
    """Encoder over [B, T_local, d_in] feature sequences.

    ``total_len`` sizes the global position table; ``pos_offset`` says
    where this shard's tokens start. With the defaults (dense attention,
    local mean-pool, offset 0) it is an ordinary single-device model —
    the sequence-parallel wrapper below swaps the two pluggable fns.
    """

    num_classes: int
    total_len: int
    d_model: int = 64
    depth: int = 2
    num_heads: int = 4
    mlp_ratio: int = 4
    # None → best_attention(): size-dispatched (flash on TPU past
    # FLASH_MIN_LEN, dense XLA otherwise).
    attention_fn: Optional[Callable] = None
    pool_fn: Callable = lambda x: x.mean(axis=1)
    # jax.checkpoint each block — the natural pairing with sequence
    # parallelism: long contexts are exactly where activations dominate
    # HBM (see models/vit.py ViT.remat).
    remat: bool = False
    # Megatron TP over ``model`` (parallel/tp.py): blocks shard heads
    # + MLP hidden; embed/head/LNs/pos stay replicated.
    tp_axis: Optional[str] = None
    tp_size: int = 1

    @nn.compact
    def __call__(self, x, pos_offset=0):
        B, T_local, _ = x.shape
        x = nn.Dense(self.d_model, name="embed")(x)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, self.total_len, self.d_model),
        )
        x = x + lax.dynamic_slice_in_dim(
            pos.astype(x.dtype), pos_offset, T_local, axis=1
        )
        block_cls = nn.remat(EncoderBlock) if self.remat else EncoderBlock
        for i in range(self.depth):
            x = block_cls(
                num_heads=self.num_heads,
                mlp_dim=self.d_model * self.mlp_ratio,
                attention_fn=self.attention_fn,
                tp_axis=self.tp_axis,
                tp_size=self.tp_size,
                name=f"block{i + 1}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        pooled = self.pool_fn(x)
        return nn.Dense(self.num_classes, name="head", dtype=jnp.float32)(
            pooled
        )


class SeqTransformerSpec(NamedTuple):
    num_classes: int
    total_len: int
    d_in: int
    d_model: int = 64
    depth: int = 2
    num_heads: int = 4
    strategy: str = "ring"  # or "ulysses"
    remat: bool = False  # jax.checkpoint each block


def _dense_model(spec: SeqTransformerSpec) -> LongContextTransformer:
    return LongContextTransformer(
        num_classes=spec.num_classes,
        total_len=spec.total_len,
        d_model=spec.d_model,
        depth=spec.depth,
        num_heads=spec.num_heads,
        remat=spec.remat,
    )


def _sharded_model(
    spec: SeqTransformerSpec, *, tp_size: int = 1
) -> LongContextTransformer:
    def attention(q, k, v):
        return sequence_sharded_attention(
            q, k, v, axis_name="seq", strategy=spec.strategy
        )

    def pool(x):
        total = lax.psum(jnp.asarray(x.shape[1], jnp.float32), "seq")
        return lax.psum(x.sum(axis=1), "seq") / total

    return LongContextTransformer(
        num_classes=spec.num_classes,
        total_len=spec.total_len,
        d_model=spec.d_model,
        depth=spec.depth,
        num_heads=spec.num_heads,
        attention_fn=attention,
        pool_fn=pool,
        remat=spec.remat,
        tp_axis="model" if tp_size > 1 else None,
        tp_size=tp_size,
    )


def init_seq_transformer(spec: SeqTransformerSpec, *, seed: int = 0):
    """Initialize params without touching the full sequence.

    Every parameter shape is independent of the input length — the
    position table is sized by the ``total_len`` attribute, not by the
    sample — so init runs on a short stub sequence. This keeps init
    O(short²) in attention cost where a full-length init would
    materialize the [H, T_global, T_global] score tensor on one device
    and defeat the module's whole point at long context.
    """
    model = _dense_model(spec)
    stub_len = min(spec.total_len, 128)
    return model.init(
        jax.random.key(seed), jnp.zeros((1, stub_len, spec.d_in))
    )["params"]


def dense_apply(spec: SeqTransformerSpec, params, x):
    """Single-device reference forward over the full sequence."""
    return _dense_model(spec).apply({"params": params}, x)


def _batch_axes(mesh: Mesh):
    """Mesh axes the batch shards over — runtime/mesh.py ``data_axes``
    (fsdp and expert are data axes: each group member sees different
    rows), filtered to the axes this mesh actually splits."""
    from ddp_tpu.runtime.mesh import data_axes

    axes = tuple(a for a in data_axes(mesh) if mesh.shape[a] > 1)
    return axes if axes else None


def make_seq_parallel_apply(
    spec: SeqTransformerSpec, mesh: Mesh, *, compute_dtype=jnp.float32
):
    """Jitted ``apply(params, x) -> logits`` with tokens on ``seq``.

    ``x``: [B, T_global, d_in] global array — batch shards over the
    data axes (``data`` and, when present, ``fsdp``), tokens over
    ``seq``; logits come back sharded over the data axes only
    (identical on every seq member). Params may rest fsdp-sharded
    (parallel/seq_fsdp.py) — they are all-gathered inside the shard
    and their gradients psum_scatter back automatically.
    ``compute_dtype=jnp.bfloat16`` runs the blocks (and the ring
    collectives' payloads) in bf16 — LayerNorms and the head stay fp32
    by module dtype; master params remain fp32 outside.
    """
    from ddp_tpu.parallel.tp import (
        gather_sharded,
        seq_param_specs,
        tp_size as mesh_tp_size,
    )

    model = _sharded_model(spec, tp_size=mesh_tp_size(mesh))
    baxes = _batch_axes(mesh)
    bspec = P(baxes)
    xspec = P(baxes, "seq")

    def apply_fn(params, x):
        pspecs = seq_param_specs(params, mesh)

        def per_shard(params, x_shard):
            params = gather_sharded(params, pspecs)
            t_local = x_shard.shape[1]
            offset = lax.axis_index("seq") * t_local
            if compute_dtype != jnp.float32:
                params = jax.tree.map(
                    lambda p: p.astype(compute_dtype), params
                )
                x_shard = x_shard.astype(compute_dtype)
            return model.apply({"params": params}, x_shard, pos_offset=offset)

        return jax.shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(pspecs, xspec),
            out_specs=bspec,
            check_vma=False,
        )(params, x)

    return jax.jit(apply_fn)


class SeqTrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def replicated_train_state(
    params, optimizer: optax.GradientTransformation, mesh: Mesh
) -> SeqTrainState:
    """Replicate EVERY leaf (params, optimizer state, the step scalar)
    over the mesh. Shared by the sequence-model families; uniform
    shardings matter — a restore templated on this state must not mix
    single-device scalars with mesh-replicated tensors.
    """
    rep = NamedSharding(mesh, P())
    put = lambda t: jax.tree.map(lambda x: jax.device_put(x, rep), t)
    params = put(params)
    return SeqTrainState(
        step=put(jnp.zeros((), jnp.int32)),
        params=params,
        opt_state=put(optimizer.init(params)),
    )


def sharded_or_replicated_state(
    params, optimizer: optax.GradientTransformation, mesh: Mesh
) -> SeqTrainState:
    """Sharded state when the mesh has ``fsdp`` or ``model`` > 1, else
    replicated. Sharded path: params rest per parallel/tp.py
    ``seq_param_specs`` (fsdp dim-0 + Megatron model dims) and
    ``optimizer.init`` on them makes the moments inherit the same
    placement (``zeros_like`` preserves shardings), so Adam memory
    shards too; unshardable leaves and scalars replicate.
    """
    from ddp_tpu.parallel.seq_fsdp import fsdp_size
    from ddp_tpu.parallel.tp import ep_size, shard_seq_params, tp_size

    if (
        fsdp_size(mesh) <= 1
        and tp_size(mesh) <= 1
        and ep_size(mesh) <= 1
    ):
        return replicated_train_state(params, optimizer, mesh)
    rep = NamedSharding(mesh, P())
    params = shard_seq_params(params, mesh)
    opt_state = optimizer.init(params)
    # Scalars (Adam's count, schedule steps) came out uncommitted —
    # pin them replicated so the state's shardings are deterministic.
    opt_state = jax.tree.map(
        lambda x: jax.device_put(x, rep) if jnp.ndim(x) == 0 else x,
        opt_state,
    )
    return SeqTrainState(
        step=jax.device_put(jnp.zeros((), jnp.int32), rep),
        params=params,
        opt_state=opt_state,
    )


def make_seq_parallel_train_step(
    spec: SeqTransformerSpec,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    donate: bool = True,
    compute_dtype=jnp.float32,
    grad_accum_steps: int = 1,
    label_smoothing: float = 0.0,
    health: bool = False,
    health_inject: tuple[str, int] | None = None,
):
    """Full dp×sp[×fsdp] train step through the collective forward.

    Gradients arrive correctly psum'd over the mesh by the shard_map
    transpose (scatter-reduced for fsdp-sharded params). Batch shards
    over the data axes, tokens over ``seq``.
    ``compute_dtype=jnp.bfloat16`` = mixed precision (fp32 master
    params, bf16 blocks/collectives, fp32 grads out of the cast's
    transpose). ``grad_accum_steps=k``: strided microbatches through
    one ``lax.scan`` (parallel/spmd.py rationale);
    ``label_smoothing=ε``: optax smoothed cross-entropy.
    """
    apply_fn = make_seq_parallel_apply(spec, mesh, compute_dtype=compute_dtype)
    lspec = P(_batch_axes(mesh))

    def loss_and_correct(params, x, labels):
        from ddp_tpu.parallel.common import xent

        logits = apply_fn(params, x).astype(jnp.float32)
        loss = xent(logits, labels, label_smoothing).mean()
        correct = (jnp.argmax(logits, -1) == labels).sum().astype(jnp.float32)
        return loss, correct

    def step(state: SeqTrainState, x, labels):
        labels = lax.with_sharding_constraint(
            labels, NamedSharding(mesh, lspec)
        )
        if grad_accum_steps == 1:
            (loss, correct), grads = jax.value_and_grad(
                loss_and_correct, has_aux=True
            )(state.params, x, labels)
        else:
            from ddp_tpu.parallel.common import check_accum_divisible

            mb = check_accum_divisible(x.shape[0], grad_accum_steps)
            xm = lax.with_sharding_constraint(
                x.reshape(mb, grad_accum_steps, *x.shape[1:]).swapaxes(0, 1),
                NamedSharding(mesh, P(None, *P(_batch_axes(mesh), "seq"))),
            )
            lm_ = lax.with_sharding_constraint(
                labels.reshape(mb, grad_accum_steps).swapaxes(0, 1),
                NamedSharding(mesh, P(None, *lspec)),
            )

            def micro(carry, xy):
                g_acc, loss_acc, correct_acc = carry
                xi, yi = xy
                (loss, correct), g = jax.value_and_grad(
                    loss_and_correct, has_aux=True
                )(state.params, xi, yi)
                return (
                    jax.tree.map(jnp.add, g_acc, g),
                    loss_acc + loss,
                    correct_acc + correct,
                ), None

            zero_g = jax.tree.map(jnp.zeros_like, state.params)
            (g_sum, loss_sum, correct), _ = lax.scan(
                micro,
                (zero_g, jnp.float32(0.0), jnp.float32(0.0)),
                (xm, lm_),
            )
            grads = jax.tree.map(lambda g: g / grad_accum_steps, g_sum)
            loss = loss_sum / grad_accum_steps
        if health_inject is not None:
            from ddp_tpu.obs.health import inject_nan

            grads = inject_nan(grads, state.step, health_inject)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        accuracy = correct / x.shape[0]
        if health:
            from ddp_tpu.obs.health import health_stats

            hstats = health_stats(grads, state.params, updates)
        else:
            hstats = None
        # _replace keeps the caller's state type: SeqTrainState from
        # this module's API, or the trainer's TrainState (which adds a
        # model_state field this model never uses).
        return (
            state._replace(
                step=state.step + 1, params=params, opt_state=opt_state
            ),
            StepMetrics(
                loss=loss, accuracy=accuracy,
                grad_norm=optax.global_norm(grads),
                health=hstats,
            ),
        )

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_seq_parallel_eval_step(
    spec: SeqTransformerSpec, mesh: Mesh, *, compute_dtype=jnp.float32
):
    """Trainer-compatible eval step over the dp×sp mesh.

    Signature matches the image eval steps —
    ``(params, model_state, x, labels, weights) → (correct, loss_sum)``
    (``model_state`` ignored; the model is stateless) — so
    ``Trainer.evaluate`` drives it unchanged. ``weights`` mask the
    wraparound padding of the final partial batch.
    """
    apply_fn = make_seq_parallel_apply(spec, mesh, compute_dtype=compute_dtype)

    def step(params, model_state, x, labels, weights):
        del model_state
        logits = apply_fn(params, x).astype(jnp.float32)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        correct = ((jnp.argmax(logits, -1) == labels) * weights).sum()
        return correct, (loss * weights).sum()

    return jax.jit(step)


def create_seq_train_state(
    spec: SeqTransformerSpec,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    seed: int = 0,
) -> SeqTrainState:
    return sharded_or_replicated_state(
        init_seq_transformer(spec, seed=seed), optimizer, mesh
    )

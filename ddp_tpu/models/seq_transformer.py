"""Long-context transformer with model-level sequence parallelism.

SURVEY.md §5 lists long-context/sequence parallelism as absent from the
reference; parallel/ring.py supplies the collective attention kernels,
and this module puts a whole model on top of them: tokens shard over
the ``seq`` mesh axis end to end — embedding, position slices,
attention (ring or Ulysses), MLPs, pooling — so sequences larger than
one chip's HBM train without ever materializing [B, T_global, C] on a
device.

Design: the per-token ops (Dense, LayerNorm, MLP) are embarrassingly
token-parallel, so the module body runs unchanged on a local token
shard; the two places that need the global sequence are pluggable —
``attention_fn`` (ring/Ulysses collectives from parallel/ring.py) and
``pool_fn`` (a psum-mean for the classification head). Position
embeddings are a global-length parameter sliced per shard by offset.
Gradients for the replicated parameters come out correct by
construction: ``jax.grad`` through ``shard_map`` transposes the
replicated-in broadcast into a psum over the mesh.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.models.vit import EncoderBlock
from ddp_tpu.ops.attention import dot_product_attention
from ddp_tpu.parallel.ddp import StepMetrics
from ddp_tpu.parallel.ring import sequence_sharded_attention


class LongContextTransformer(nn.Module):
    """Encoder over [B, T_local, d_in] feature sequences.

    ``total_len`` sizes the global position table; ``pos_offset`` says
    where this shard's tokens start. With the defaults (dense attention,
    local mean-pool, offset 0) it is an ordinary single-device model —
    the sequence-parallel wrapper below swaps the two pluggable fns.
    """

    num_classes: int
    total_len: int
    d_model: int = 64
    depth: int = 2
    num_heads: int = 4
    mlp_ratio: int = 4
    attention_fn: Callable = dot_product_attention
    pool_fn: Callable = lambda x: x.mean(axis=1)
    # jax.checkpoint each block — the natural pairing with sequence
    # parallelism: long contexts are exactly where activations dominate
    # HBM (see models/vit.py ViT.remat).
    remat: bool = False

    @nn.compact
    def __call__(self, x, pos_offset=0):
        B, T_local, _ = x.shape
        x = nn.Dense(self.d_model, name="embed")(x)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, self.total_len, self.d_model),
        )
        x = x + lax.dynamic_slice_in_dim(
            pos.astype(x.dtype), pos_offset, T_local, axis=1
        )
        block_cls = nn.remat(EncoderBlock) if self.remat else EncoderBlock
        for i in range(self.depth):
            x = block_cls(
                num_heads=self.num_heads,
                mlp_dim=self.d_model * self.mlp_ratio,
                attention_fn=self.attention_fn,
                name=f"block{i + 1}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        pooled = self.pool_fn(x)
        return nn.Dense(self.num_classes, name="head", dtype=jnp.float32)(
            pooled
        )


class SeqTransformerSpec(NamedTuple):
    num_classes: int
    total_len: int
    d_in: int
    d_model: int = 64
    depth: int = 2
    num_heads: int = 4
    strategy: str = "ring"  # or "ulysses"
    remat: bool = False  # jax.checkpoint each block


def _dense_model(spec: SeqTransformerSpec) -> LongContextTransformer:
    return LongContextTransformer(
        num_classes=spec.num_classes,
        total_len=spec.total_len,
        d_model=spec.d_model,
        depth=spec.depth,
        num_heads=spec.num_heads,
        remat=spec.remat,
    )


def _sharded_model(spec: SeqTransformerSpec) -> LongContextTransformer:
    def attention(q, k, v):
        return sequence_sharded_attention(
            q, k, v, axis_name="seq", strategy=spec.strategy
        )

    def pool(x):
        total = lax.psum(jnp.asarray(x.shape[1], jnp.float32), "seq")
        return lax.psum(x.sum(axis=1), "seq") / total

    return LongContextTransformer(
        num_classes=spec.num_classes,
        total_len=spec.total_len,
        d_model=spec.d_model,
        depth=spec.depth,
        num_heads=spec.num_heads,
        attention_fn=attention,
        pool_fn=pool,
        remat=spec.remat,
    )


def init_seq_transformer(spec: SeqTransformerSpec, *, seed: int = 0):
    """Initialize params without touching the full sequence.

    Every parameter shape is independent of the input length — the
    position table is sized by the ``total_len`` attribute, not by the
    sample — so init runs on a short stub sequence. This keeps init
    O(short²) in attention cost where a full-length init would
    materialize the [H, T_global, T_global] score tensor on one device
    and defeat the module's whole point at long context.
    """
    model = _dense_model(spec)
    stub_len = min(spec.total_len, 128)
    return model.init(
        jax.random.key(seed), jnp.zeros((1, stub_len, spec.d_in))
    )["params"]


def dense_apply(spec: SeqTransformerSpec, params, x):
    """Single-device reference forward over the full sequence."""
    return _dense_model(spec).apply({"params": params}, x)


def make_seq_parallel_apply(
    spec: SeqTransformerSpec, mesh: Mesh, *, compute_dtype=jnp.float32
):
    """Jitted ``apply(params, x) -> logits`` with tokens on ``seq``.

    ``x``: [B, T_global, d_in] global array — batch shards over
    ``data``, tokens over ``seq``; logits come back sharded over
    ``data`` only (identical on every seq member).
    ``compute_dtype=jnp.bfloat16`` runs the blocks (and the ring
    collectives' payloads) in bf16 — LayerNorms and the head stay fp32
    by module dtype; master params remain fp32 outside.
    """
    model = _sharded_model(spec)
    has_data = mesh.shape.get("data", 1) > 1
    bspec = P("data") if has_data else P(None)
    xspec = P(bspec[0], "seq")

    def per_shard(params, x_shard):
        t_local = x_shard.shape[1]
        offset = lax.axis_index("seq") * t_local
        if compute_dtype != jnp.float32:
            params = jax.tree.map(
                lambda p: p.astype(compute_dtype), params
            )
            x_shard = x_shard.astype(compute_dtype)
        return model.apply({"params": params}, x_shard, pos_offset=offset)

    sharded = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), xspec),
        out_specs=bspec,
        check_vma=False,
    )
    return jax.jit(sharded)


class SeqTrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def replicated_train_state(
    params, optimizer: optax.GradientTransformation, mesh: Mesh
) -> SeqTrainState:
    """Replicate EVERY leaf (params, optimizer state, the step scalar)
    over the mesh. Shared by the sequence-model families; uniform
    shardings matter — a restore templated on this state must not mix
    single-device scalars with mesh-replicated tensors.
    """
    rep = NamedSharding(mesh, P())
    put = lambda t: jax.tree.map(lambda x: jax.device_put(x, rep), t)
    params = put(params)
    return SeqTrainState(
        step=put(jnp.zeros((), jnp.int32)),
        params=params,
        opt_state=put(optimizer.init(params)),
    )


def make_seq_parallel_train_step(
    spec: SeqTransformerSpec,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    donate: bool = True,
    compute_dtype=jnp.float32,
):
    """Full dp×sp train step: loss/grad through the collective forward.

    Params replicate; their gradients arrive correctly psum'd over both
    axes by the shard_map transpose. Batch shards over ``data``, tokens
    over ``seq``. ``compute_dtype=jnp.bfloat16`` = mixed precision
    (fp32 master params, bf16 blocks/collectives, fp32 grads out of
    the cast's transpose).
    """
    apply_fn = make_seq_parallel_apply(spec, mesh, compute_dtype=compute_dtype)
    has_data = mesh.shape.get("data", 1) > 1
    lspec = P("data") if has_data else P(None)

    def step(state: SeqTrainState, x, labels):
        labels = lax.with_sharding_constraint(
            labels, NamedSharding(mesh, lspec)
        )

        def loss_fn(params):
            logits = apply_fn(params, x)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels
            ).mean()
            return loss, logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        correct = (jnp.argmax(logits.astype(jnp.float32), -1) == labels).mean()
        # _replace keeps the caller's state type: SeqTrainState from
        # this module's API, or the trainer's TrainState (which adds a
        # model_state field this model never uses).
        return (
            state._replace(
                step=state.step + 1, params=params, opt_state=opt_state
            ),
            StepMetrics(
                loss=loss, accuracy=correct,
                grad_norm=optax.global_norm(grads),
            ),
        )

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_seq_parallel_eval_step(
    spec: SeqTransformerSpec, mesh: Mesh, *, compute_dtype=jnp.float32
):
    """Trainer-compatible eval step over the dp×sp mesh.

    Signature matches the image eval steps —
    ``(params, model_state, x, labels, weights) → (correct, loss_sum)``
    (``model_state`` ignored; the model is stateless) — so
    ``Trainer.evaluate`` drives it unchanged. ``weights`` mask the
    wraparound padding of the final partial batch.
    """
    apply_fn = make_seq_parallel_apply(spec, mesh, compute_dtype=compute_dtype)

    def step(params, model_state, x, labels, weights):
        del model_state
        logits = apply_fn(params, x).astype(jnp.float32)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        correct = ((jnp.argmax(logits, -1) == labels) * weights).sum()
        return correct, (loss * weights).sum()

    return jax.jit(step)


def create_seq_train_state(
    spec: SeqTransformerSpec,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    seed: int = 0,
) -> SeqTrainState:
    return replicated_train_state(
        init_seq_transformer(spec, seed=seed), optimizer, mesh
    )

"""Pipeline-parallel ViT: a real model through the GPipe schedule.

The reference has no pipeline parallelism (SURVEY.md §2c); the
framework's schedule (parallel/pipeline.py) needs *same-shaped* stage
programs, which transformers provide naturally: the patch-embed front
and the LN+head back run data-parallel outside the pipeline, and the
uniform encoder-block stack is cut into S stages of ``depth_per_stage``
blocks each, parameters stacked on a leading stage dim sharded over
``pipe``. Composes with ``data``: the batch shards across the data
axis while activations ride the pipe ring, and the whole train step —
embed → pipeline → head → loss → grad → update — is one jitted,
differentiable program (the backward schedule is the scan/ppermute
transpose, derived by AD).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.models.vit import AttentionFn, EncoderBlock
from ddp_tpu.parallel.ddp import StepMetrics
from ddp_tpu.parallel.common import _preprocess, xent
from ddp_tpu.parallel.pipeline import spmd_pipeline, stack_stage_params

# Stage-sharding machinery shared with the pipelined LM — see
# parallel/pipe_common.py (FSDP_MIN_SIZE and friends live there). The
# private aliases keep this module's call sites stable.
from ddp_tpu.parallel.pipe_common import (
    gather_stages as _gather_stages,
    merge_microbatch_stream as _merge_microbatch_stream,
    pipe_batch_axes as _pipe_batch_axes,
    scatter_stage_grads as _scatter_stage_grads,
    split_microbatch_labels as _split_microbatch_labels,
    split_microbatch_stream as _split_microbatch_stream,
    stage_specs_megatron as _stage_specs_megatron,
)


def _reject_expert_mesh(mesh):
    """The pipelined ViT has no MoE, but ``pipe_batch_axes`` would
    still shard its batch over an ``expert`` axis — and the
    hand-scheduled steps only reduce stage grads over ``data``, so an
    expert axis would silently diverge params across expert groups.
    PP×EP is the pipelined LM's (models/pipeline_lm.py); refuse here
    at build time."""
    if mesh.shape.get("expert", 1) > 1:
        raise ValueError(
            "the pipelined ViT takes no expert mesh axis (it has no "
            "MoE blocks); PP×EP is the pipelined MoE-LM's — "
            "models/pipeline_lm.py"
        )


class PipeViTConfig(NamedTuple):
    num_classes: int = 10
    patch_size: int = 4
    embed_dim: int = 64
    num_heads: int = 4
    mlp_ratio: int = 4
    num_stages: int = 4
    depth_per_stage: int = 1
    num_microbatches: int = 4
    attention_fn: Optional[AttentionFn] = None
    remat: bool = False  # jax.checkpoint each stage's blocks
    # Interleaved schedule only: v model chunks per device (total
    # depth num_stages × virtual_stages × depth_per_stage blocks),
    # placed round-robin — parallel/interleaved.py.
    virtual_stages: int = 1
    # Megatron TP over the ``model`` mesh axis inside each stage's
    # blocks (PP×TP) — same machinery as the pipelined LM.
    tp_size: int = 1


class PatchEmbed(nn.Module):
    """Patch projection + learned position embedding (no cls token —
    the pipeline keeps stages shape-uniform; the head mean-pools)."""

    embed_dim: int
    patch_size: int

    @nn.compact
    def __call__(self, x):
        p = self.patch_size
        x = nn.Conv(
            self.embed_dim, (p, p), strides=(p, p), padding="VALID",
            name="proj",
        )(x)
        B = x.shape[0]
        x = x.reshape(B, -1, self.embed_dim)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], self.embed_dim),
        )
        return x + pos.astype(x.dtype)


class StageBlocks(nn.Module):
    """One pipeline stage: ``depth`` encoder blocks, shape-preserving.

    ``tp_axis``/``tp_size``: Megatron TP inside each block (PP×TP —
    used by the pipelined LM; see models/vit.py EncoderBlock).

    ``num_experts > 0``: every ``moe_every``-th block WITHIN the stage
    is a routed MoE block (models/moe.py MoEEncoderBlock). Stages must
    stay structure-uniform for parameter stacking, so the pattern is
    per-stage; with ``depth % moe_every == 0`` it equals the global
    every-Nth pattern the seq-family CausalLM uses. The GShard
    load-balance aux loss is ``is_mutable_collection``-guarded and the
    pipeline kernels apply stages purely, so routing works but the
    balance loss is NOT collected on the pipe path (callers document
    this).

    ``ep_axis``/``ep_size`` (PP×EP, round 5): expert weights shard
    their leading dim over the ``expert`` mesh axis INSIDE the stage's
    pipeline island — each member holds ``num_experts/ep_size``
    experts and a different token shard, and MoEMLP's explicit
    ``lax.all_to_all`` pair carries dispatched slots to each expert's
    owner and back, exactly the flat EP family's exchange riding
    within each pipeline stage."""

    depth: int
    num_heads: int
    mlp_dim: int
    attention_fn: Optional[AttentionFn] = None
    remat: bool = False  # jax.checkpoint each block (see models/vit.py)
    tp_axis: Optional[str] = None
    tp_size: int = 1
    tp_inner_vjp: bool = False  # Megatron f/g — see models/vit.py
    num_kv_heads: int = 0  # GQA — see models/vit.py MultiHeadAttention
    num_experts: int = 0  # MoE MLPs — see models/moe.py
    moe_every: int = 2
    moe_top_k: int = 2  # routing config — threaded from PipeLMConfig
    moe_normalize_gates: bool = True
    ep_axis: Optional[str] = None  # expert parallelism (see MoEMLP)
    ep_size: int = 1

    @nn.compact
    def __call__(self, x):
        from ddp_tpu.models.moe import MoEEncoderBlock, is_moe_block

        # In-module guard: the HAND-SCHEDULED kernels' in-island vjp
        # needs Megatron f/g plumbing that does not extend into routed
        # blocks — a caller combining them must hear it HERE, not get
        # silently-wrong gradients. The AD path (GPipe — tp_inner_vjp
        # False) composes MoE×TP exactly like the flat CausalLM: the
        # shard_map transpose owns the cross-member sums, and the
        # routed block's attention takes the same column/row wiring.
        if self.num_experts and self.tp_size > 1 and self.tp_inner_vjp:
            raise ValueError(
                "StageBlocks: MoE blocks do not compose with tp under "
                "the hand-scheduled schedules (their in-island vjp's "
                "Megatron f/g plumbing does not extend into routed "
                "blocks) — use the GPipe schedule or the flat "
                "causal_lm for TP×MoE"
            )
        block_cls = nn.remat(EncoderBlock) if self.remat else EncoderBlock
        moe_cls = (
            nn.remat(MoEEncoderBlock) if self.remat else MoEEncoderBlock
        )
        for i in range(self.depth):
            if is_moe_block(i, self.num_experts, self.moe_every):
                x = moe_cls(
                    num_heads=self.num_heads,
                    mlp_dim=self.mlp_dim,
                    num_experts=self.num_experts,
                    top_k=self.moe_top_k,
                    normalize_gates=self.moe_normalize_gates,
                    attention_fn=self.attention_fn,
                    ep_axis=self.ep_axis,
                    ep_size=self.ep_size,
                    num_kv_heads=self.num_kv_heads,
                    tp_axis=self.tp_axis,
                    tp_size=self.tp_size,
                    name=f"block{i + 1}",
                )(x)
            else:
                x = block_cls(
                    num_heads=self.num_heads,
                    mlp_dim=self.mlp_dim,
                    attention_fn=self.attention_fn,
                    tp_axis=self.tp_axis,
                    tp_size=self.tp_size,
                    tp_inner_vjp=self.tp_inner_vjp,
                    num_kv_heads=self.num_kv_heads,
                    name=f"block{i + 1}",
                )(x)
        return x


class PipeHead(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=jnp.float32, name="ln")(x)
        return nn.Dense(self.num_classes, name="head", dtype=jnp.float32)(
            x.mean(axis=1)
        )


class PipeViTParams(NamedTuple):
    embed: Any
    stages: Any  # stacked: leading dim num_stages, sharded on 'pipe'
    head: Any


class PipeViTState(NamedTuple):
    step: jax.Array
    params: PipeViTParams
    opt_state: Any


def _modules(cfg: PipeViTConfig, *, tp: bool = False, inner_vjp: bool = False):
    """``tp=False`` builds the GLOBAL-shape stage (init, sequential
    forward); ``tp=True`` the Megatron variant whose local param
    shapes match each ``model`` member's shard; ``inner_vjp=True``
    adds the f/g plumbing the hand-scheduled kernels' in-body vjp
    needs (models/pipeline_lm.py has the full story)."""
    embed = PatchEmbed(embed_dim=cfg.embed_dim, patch_size=cfg.patch_size)
    stage = StageBlocks(
        depth=cfg.depth_per_stage,
        num_heads=cfg.num_heads,
        mlp_dim=cfg.embed_dim * cfg.mlp_ratio,
        attention_fn=cfg.attention_fn,
        remat=cfg.remat,
        tp_axis="model" if tp else None,
        tp_size=cfg.tp_size if tp else 1,
        tp_inner_vjp=inner_vjp,
    )
    head = PipeHead(num_classes=cfg.num_classes)
    return embed, stage, head


def _vit_stage_specs(cfg: PipeViTConfig, stages, mesh, *, lead: int):
    return _stage_specs_megatron(
        stages, mesh, lead=lead, tp_size=cfg.tp_size
    )


def init_pipe_vit(
    cfg: PipeViTConfig, sample_input, *, seed: int = 0
) -> PipeViTParams:
    """Initialize all segments; stage s seeded by fold_in(seed, s)."""
    embed, stage, head = _modules(cfg)
    k = jax.random.key(seed)
    embed_p = embed.init(k, sample_input)["params"]
    feats = embed.apply({"params": embed_p}, sample_input)
    stage_ps = [
        stage.init(jax.random.fold_in(k, 1 + s), feats)["params"]
        for s in range(cfg.num_stages)
    ]
    head_p = head.init(jax.random.fold_in(k, 0), feats)["params"]
    return PipeViTParams(embed_p, stack_stage_params(stage_ps), head_p)


def sequential_apply(cfg: PipeViTConfig, params: PipeViTParams, images):
    """Reference forward without the pipeline — same math, one device."""
    embed, stage, head = _modules(cfg)
    x = embed.apply({"params": params.embed}, images)
    for s in range(cfg.num_stages):
        stage_p = jax.tree.map(lambda p: p[s], params.stages)
        x = stage.apply({"params": stage_p}, x)
    return head.apply({"params": params.head}, x)


def init_pipe_vit_interleaved(
    cfg: PipeViTConfig, sample_input, *, seed: int = 0
) -> PipeViTParams:
    """Interleaved layout: C = S·v chunks stacked as [v, S, …].

    Chunk c = k·S + d sits at stages[k, d] — sharding dim 1 over
    ``pipe`` places it on device c mod S, the round-robin placement
    the interleaved schedule requires (consecutive chunks on
    consecutive devices; a flat [C] array sharded over pipe would
    place them BLOCKED, which is just a deeper plain pipeline). Chunk
    c is seeded fold_in(seed, 1+c), matching ``init_pipe_vit``'s
    per-stage seeding so v=1 interleaved == the plain layout.
    """
    embed, stage, head = _modules(cfg)
    C = cfg.num_stages * cfg.virtual_stages
    k = jax.random.key(seed)
    embed_p = embed.init(k, sample_input)["params"]
    feats = embed.apply({"params": embed_p}, sample_input)
    chunk_ps = [
        stage.init(jax.random.fold_in(k, 1 + c), feats)["params"]
        for c in range(C)
    ]
    head_p = head.init(jax.random.fold_in(k, 0), feats)["params"]
    flat = stack_stage_params(chunk_ps)  # [C, ...] in chunk order
    stages = jax.tree.map(
        lambda p: p.reshape(cfg.virtual_stages, cfg.num_stages, *p.shape[1:]),
        flat,
    )
    return PipeViTParams(embed_p, stages, head_p)


def sequential_apply_interleaved(
    cfg: PipeViTConfig, params: PipeViTParams, images
):
    """Reference forward over the [v, S, …] chunk layout — same math
    as the interleaved pipeline, one device. Also serves as the eval
    forward (jitted, XLA gathers each chunk's params as it goes).

    Flattens [k, d] → chunk c = k·S + d and delegates to
    ``sequential_apply`` (StageBlocks is num_stages-agnostic), so
    there is exactly one copy of the reference forward loop."""
    C = cfg.num_stages * cfg.virtual_stages
    flat = jax.tree.map(
        lambda p: p.reshape(C, *p.shape[2:]), params.stages
    )
    return sequential_apply(
        cfg._replace(num_stages=C), params._replace(stages=flat), images
    )


def make_pipe_vit_apply(cfg: PipeViTConfig, mesh: Mesh):
    """Jitted pipelined ``apply(params, images) -> logits``.

    The WHOLE model rides the pipeline: the patch-embed front runs
    inside stage 0 (``first_fn``) and the norm+head back inside stage
    S-1 (``last_fn``) — non-uniform stages with raw-pixel inputs,
    token activations, and logit outputs all of different shapes
    (round-1 version ran embed/head outside, data-parallel). The
    microbatch stream is sharded over ``pipe`` (microbatch m rests on
    device m mod S; per-device buffers O(M/S) — parallel/pipeline.py).
    Batch additionally shards over the mesh's ``data`` axis.
    Differentiable end to end. GPipe bubble: ``bubble_fraction(S, M)``.
    """
    # AD path: TP blocks WITHOUT the f/g ops (the shard_map transpose
    # owns the cross-member sums here — see models/pipeline_lm.py).
    embed, stage, head = _modules(cfg, tp=cfg.tp_size > 1)
    _reject_expert_mesh(mesh)
    baxes = _pipe_batch_axes(mesh)
    bspec = P(baxes) if baxes else P()
    mbspec = P(None, "pipe", baxes) if baxes else P(None, "pipe")

    def stage_fn(p, x):
        return stage.apply({"params": p}, x)

    def first_fn(p, raw):
        return embed.apply({"params": p}, raw)

    def last_fn(p, x):
        return head.apply({"params": p}, x)

    S = mesh.shape["pipe"]

    def apply_fn(params: PipeViTParams, images):
        images = lax.with_sharding_constraint(
            images, NamedSharding(mesh, bspec)
        )
        mb = _split_microbatch_stream(images, cfg.num_microbatches, S)
        sspecs = _vit_stage_specs(cfg, params.stages, mesh, lead=1)

        pipelined = jax.shard_map(
            lambda sp, ep, hp, m: spmd_pipeline(
                stage_fn, _gather_stages(sp, sspecs), m, axis_name="pipe",
                first_fn=first_fn, first_params=ep,
                last_fn=last_fn, last_params=hp,
            ),
            mesh=mesh,
            in_specs=(sspecs, P(), P(), mbspec),
            out_specs=mbspec,
            check_vma=False,
        )
        out = pipelined(params.stages, params.embed, params.head, mb)
        return _merge_microbatch_stream(out)

    return apply_fn


def _maybe_augment(augment_fn, seed, step_no, x):
    """Train-time augmentation before the pipeline (data/augment.py):
    per-step rng keyed on the step counter, applied to the GLOBAL
    batch before microbatching — same placement contract as the DDP
    step families (inside jit, after the uint8→float conversion)."""
    if augment_fn is None:
        return x
    rng = jax.random.fold_in(jax.random.key(seed), step_no)
    return augment_fn(rng, x).astype(x.dtype)


def make_pipe_vit_train_step(
    cfg: PipeViTConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    compute_dtype=jnp.float32,
    label_smoothing: float = 0.0,
    donate: bool = True,
    augment_fn=None,
    seed: int = 0,
    jit: bool = True,
):
    """``step(state, images, labels) -> (state, metrics)`` over dp×pp.

    Stage params (and their optimizer state, by GSPMD propagation
    through the constrained update) stay sharded on ``pipe``; embed and
    head replicate, their gradients all-reduced over ``data`` by XLA.
    """
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {label_smoothing}"
        )
    apply_fn = make_pipe_vit_apply(cfg, mesh)

    def constrain(params: PipeViTParams) -> PipeViTParams:
        sspecs = _vit_stage_specs(cfg, params.stages, mesh, lead=1)
        return params._replace(
            stages=jax.tree.map(
                lambda x, s: lax.with_sharding_constraint(
                    x, NamedSharding(mesh, s)
                ),
                params.stages,
                sspecs,
            )
        )

    def step(state: PipeViTState, images, labels):
        def loss_fn(params):
            logits = apply_fn(
                params,
                _maybe_augment(
                    augment_fn, seed, state.step,
                    _preprocess(images, compute_dtype),
                ),
            )
            loss = xent(
                logits.astype(jnp.float32), labels, label_smoothing
            ).mean()
            return loss, logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        grads = constrain(jax.tree.map(lambda g: g.astype(jnp.float32), grads))
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = constrain(optax.apply_updates(state.params, updates))
        correct = (jnp.argmax(logits.astype(jnp.float32), -1) == labels).mean()
        return (
            PipeViTState(state.step + 1, params, opt_state),
            StepMetrics(loss=loss, accuracy=correct),
        )

    if not jit:
        return step  # raw: the compiled-epoch runner scans it
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_pipe_vit_1f1b_train_step(
    cfg: PipeViTConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    compute_dtype=jnp.float32,
    label_smoothing: float = 0.0,
    donate: bool = True,
    augment_fn=None,
    seed: int = 0,
    jit: bool = True,
):
    """``step(state, images, labels)`` under the 1F1B schedule.

    Same contract and (to numerics) same result as
    ``make_pipe_vit_train_step``, but the backward is hand-scheduled
    (parallel/one_f1b.py): the loss runs inside the last stage, the
    activation stash is O(S) instead of the AD-GPipe path's O(M), and
    gradients come straight out of the schedule — no ``jax.grad``
    around the pipeline. Pinned equal to the GPipe step by
    tests/test_one_f1b.py / test_pipeline_vit.py.
    """
    from ddp_tpu.parallel.one_f1b import schedule_1f1b, spmd_pipeline_1f1b

    S = mesh.shape["pipe"]
    M = cfg.num_microbatches
    if M % S:
        raise ValueError(f"{M} microbatches not divisible by {S} stages")
    return _make_handsched_step(
        cfg, optimizer, mesh, spmd_pipeline_1f1b, schedule_1f1b(S, M),
        lead=1, compute_dtype=compute_dtype,
        label_smoothing=label_smoothing, donate=donate,
        augment_fn=augment_fn, seed=seed, jit=jit,
    )


def _make_handsched_step(
    cfg: PipeViTConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    pipeline_fn,
    sched,
    *,
    lead: int,
    compute_dtype,
    label_smoothing: float,
    donate: bool,
    augment_fn=None,
    seed: int = 0,
    jit: bool = True,
):
    """Shared machinery of the hand-scheduled (no-jax.grad) pipe steps.

    ``pipeline_fn`` is the schedule kernel (spmd_pipeline_1f1b or
    spmd_pipeline_interleaved — same call contract) and ``lead`` the
    number of leading stacked dims in the stage layout (1 for [S, …],
    2 for the interleaved [v, S, …]). Everything else — the island
    specs, the fsdp gather/scatter pair, the batch-axis reductions,
    and the mean-gradient update — is identical across schedules and
    lives only here.
    """
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {label_smoothing}"
        )
    # Hand-scheduled paths vjp INSIDE the island: the TP blocks need
    # Megatron's f/g custom-VJP pair (models/pipeline_lm.py rationale).
    embed, stage, head = _modules(
        cfg, tp=cfg.tp_size > 1, inner_vjp=cfg.tp_size > 1
    )
    S = mesh.shape["pipe"]
    M = cfg.num_microbatches
    _reject_expert_mesh(mesh)
    baxes = _pipe_batch_axes(mesh)
    has_fsdp = mesh.shape.get("fsdp", 1) > 1
    bspec = P(baxes) if baxes else P()
    mbspec = P(None, "pipe", baxes) if baxes else P(None, "pipe")
    lblspec = P(None, baxes) if baxes else P()

    def stage_fn(p, x):
        return stage.apply({"params": p}, x)

    def first_fn(p, raw):
        return embed.apply({"params": p}, raw)

    def last_fn(p, x):
        return head.apply({"params": p}, x)

    def loss_fn(logits, lbl):
        logits = logits.astype(jnp.float32)
        loss = xent(logits, lbl, label_smoothing).sum()
        correct = (jnp.argmax(logits, -1) == lbl).sum().astype(jnp.float32)
        return loss, correct

    def make_run(sspecs):
        def inner(sp, ep, hp, m, l):
            loss, aux, gs, gf, gl = pipeline_fn(
                stage_fn, _gather_stages(sp, sspecs), m, l, loss_fn,
                sched, axis_name="pipe",
                first_fn=first_fn, first_params=ep,
                last_fn=last_fn, last_params=hp,
            )
            if baxes:
                loss = lax.psum(loss, baxes)
                aux = lax.psum(aux, baxes)
                gf = jax.tree.map(lambda g: lax.psum(g, baxes), gf)
                gl = jax.tree.map(lambda g: lax.psum(g, baxes), gl)
            if "data" in baxes:
                gs = jax.tree.map(lambda g: lax.psum(g, "data"), gs)
            if has_fsdp:
                # Sum over the fsdp batch replicas AND re-shard the
                # resting leaves — the explicit twin of the gather's
                # AD transpose on the GPipe path.
                gs = _scatter_stage_grads(gs, sspecs)
            return loss, aux, gs, gf, gl

        return jax.shard_map(
            inner,
            mesh=mesh,
            in_specs=(sspecs, P(), P(), mbspec, lblspec),
            out_specs=(P(), P(), sspecs, P(), P()),
            check_vma=False,
        )

    def constrain(params: PipeViTParams) -> PipeViTParams:
        sspecs = _vit_stage_specs(cfg, params.stages, mesh, lead=lead)
        return params._replace(
            stages=jax.tree.map(
                lambda x, s: lax.with_sharding_constraint(
                    x, NamedSharding(mesh, s)
                ),
                params.stages,
                sspecs,
            )
        )

    def step(state: PipeViTState, images, labels):
        images = lax.with_sharding_constraint(
            _maybe_augment(
                augment_fn, seed, state.step,
                _preprocess(images, compute_dtype),
            ),
            NamedSharding(mesh, bspec),
        )
        B = images.shape[0]
        mbs = _split_microbatch_stream(images, M, S)
        lbl_mb = _split_microbatch_labels(labels, M)
        run = make_run(_vit_stage_specs(cfg, state.params.stages, mesh, lead=lead))
        loss_sum, correct, gs, gf, gl = run(
            state.params.stages, state.params.embed, state.params.head,
            mbs, lbl_mb,
        )
        # The schedule accumulates per-example SUMS; the optimizer
        # contract (like every other step) is the batch MEAN.
        grads = jax.tree.map(
            lambda g: (g / B).astype(jnp.float32),
            PipeViTParams(embed=gf, stages=gs, head=gl),
        )
        grads = constrain(grads)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = constrain(optax.apply_updates(state.params, updates))
        return (
            PipeViTState(state.step + 1, params, opt_state),
            StepMetrics(loss=loss_sum / B, accuracy=correct / B),
        )

    if not jit:
        return step  # raw: the compiled-epoch runner scans it
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_pipe_vit_interleaved_train_step(
    cfg: PipeViTConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    compute_dtype=jnp.float32,
    label_smoothing: float = 0.0,
    donate: bool = True,
    augment_fn=None,
    seed: int = 0,
    jit: bool = True,
):
    """``step(state, images, labels)`` under the interleaved-1F1B
    schedule (v = cfg.virtual_stages model chunks per device).

    Same contract as the other pipe steps; the model is
    S·v·depth_per_stage blocks deep, chunk weights rest at
    stages[k, d] sharded P(None, pipe) (round-robin placement). The
    bubble shrinks to (S−1)/(v·M+S−1) — parallel/interleaved.py.
    Gradient parity vs the single-device reference step is pinned by
    tests/test_interleaved.py.
    """
    from ddp_tpu.parallel.interleaved import (
        schedule_interleaved,
        spmd_pipeline_interleaved,
    )

    S = mesh.shape["pipe"]
    if S != cfg.num_stages:
        raise ValueError(
            f"mesh pipe axis {S} != cfg.num_stages {cfg.num_stages}"
        )
    sched = schedule_interleaved(
        S, cfg.num_microbatches, cfg.virtual_stages
    )
    return _make_handsched_step(
        cfg, optimizer, mesh, spmd_pipeline_interleaved, sched,
        lead=2, compute_dtype=compute_dtype,
        label_smoothing=label_smoothing, donate=donate,
        augment_fn=augment_fn, seed=seed, jit=jit,
    )


def _create_state(
    cfg: PipeViTConfig,
    optimizer: optax.GradientTransformation,
    sample_input,
    mesh: Mesh,
    seed: int,
    init_fn,
    lead: int,
) -> PipeViTState:
    params = init_fn(cfg, sample_input, seed=seed)
    # TP-aware: Megatron kernels REST sharded over ``model`` (the
    # placements are the checkpoint contract, like pipe/fsdp).
    sspecs = _vit_stage_specs(cfg, params.stages, mesh, lead=lead)
    rep = NamedSharding(mesh, P())
    params = PipeViTParams(
        embed=jax.tree.map(lambda x: jax.device_put(x, rep), params.embed),
        stages=jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params.stages,
            sspecs,
        ),
        head=jax.tree.map(lambda x: jax.device_put(x, rep), params.head),
    )
    opt_state = optimizer.init(params)
    # Scalars (Adam's count, schedule steps) come out uncommitted —
    # pin them (and the step counter) replicated on THIS mesh, so a
    # restore templated on this state places everything mesh-wide
    # (a single-device step scalar would clash with the sharded
    # params at the first jitted step after resume).
    opt_state = jax.tree.map(
        lambda x: jax.device_put(x, rep) if jnp.ndim(x) == 0 else x,
        opt_state,
    )
    return PipeViTState(
        step=jax.device_put(jnp.zeros((), jnp.int32), rep),
        params=params,
        opt_state=opt_state,
    )


def create_pipe_vit_state(
    cfg: PipeViTConfig,
    optimizer: optax.GradientTransformation,
    sample_input,
    mesh: Mesh,
    *,
    seed: int = 0,
) -> PipeViTState:
    return _create_state(
        cfg, optimizer, sample_input, mesh, seed, init_pipe_vit, 1
    )


def create_pipe_vit_state_interleaved(
    cfg: PipeViTConfig,
    optimizer: optax.GradientTransformation,
    sample_input,
    mesh: Mesh,
    *,
    seed: int = 0,
) -> PipeViTState:
    """Like ``create_pipe_vit_state`` but with the [v, S, …]
    round-robin chunk layout resting sharded P(None, pipe)."""
    return _create_state(
        cfg, optimizer, sample_input, mesh, seed,
        init_pipe_vit_interleaved, 2,
    )

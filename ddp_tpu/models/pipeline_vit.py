"""Pipeline-parallel ViT: a real model through the GPipe schedule.

The reference has no pipeline parallelism (SURVEY.md §2c); the
framework's schedule (parallel/pipeline.py) needs *same-shaped* stage
programs, which transformers provide naturally: the patch-embed front
and the LN+head back run data-parallel outside the pipeline, and the
uniform encoder-block stack is cut into S stages of ``depth_per_stage``
blocks each, parameters stacked on a leading stage dim sharded over
``pipe``. Composes with ``data``: the batch shards across the data
axis while activations ride the pipe ring, and the whole train step —
embed → pipeline → head → loss → grad → update — is one jitted,
differentiable program (the backward schedule is the scan/ppermute
transpose, derived by AD).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.models.vit import AttentionFn, EncoderBlock
from ddp_tpu.parallel.ddp import StepMetrics
from ddp_tpu.parallel.common import _preprocess
from ddp_tpu.parallel.pipeline import spmd_pipeline, stack_stage_params


class PipeViTConfig(NamedTuple):
    num_classes: int = 10
    patch_size: int = 4
    embed_dim: int = 64
    num_heads: int = 4
    mlp_ratio: int = 4
    num_stages: int = 4
    depth_per_stage: int = 1
    num_microbatches: int = 4
    attention_fn: Optional[AttentionFn] = None
    remat: bool = False  # jax.checkpoint each stage's blocks


class PatchEmbed(nn.Module):
    """Patch projection + learned position embedding (no cls token —
    the pipeline keeps stages shape-uniform; the head mean-pools)."""

    embed_dim: int
    patch_size: int

    @nn.compact
    def __call__(self, x):
        p = self.patch_size
        x = nn.Conv(
            self.embed_dim, (p, p), strides=(p, p), padding="VALID",
            name="proj",
        )(x)
        B = x.shape[0]
        x = x.reshape(B, -1, self.embed_dim)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], self.embed_dim),
        )
        return x + pos.astype(x.dtype)


class StageBlocks(nn.Module):
    """One pipeline stage: ``depth`` encoder blocks, shape-preserving."""

    depth: int
    num_heads: int
    mlp_dim: int
    attention_fn: Optional[AttentionFn] = None
    remat: bool = False  # jax.checkpoint each block (see models/vit.py)

    @nn.compact
    def __call__(self, x):
        block_cls = nn.remat(EncoderBlock) if self.remat else EncoderBlock
        for i in range(self.depth):
            x = block_cls(
                num_heads=self.num_heads,
                mlp_dim=self.mlp_dim,
                attention_fn=self.attention_fn,
                name=f"block{i + 1}",
            )(x)
        return x


class PipeHead(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=jnp.float32, name="ln")(x)
        return nn.Dense(self.num_classes, name="head", dtype=jnp.float32)(
            x.mean(axis=1)
        )


class PipeViTParams(NamedTuple):
    embed: Any
    stages: Any  # stacked: leading dim num_stages, sharded on 'pipe'
    head: Any


class PipeViTState(NamedTuple):
    step: jax.Array
    params: PipeViTParams
    opt_state: Any


def _modules(cfg: PipeViTConfig):
    embed = PatchEmbed(embed_dim=cfg.embed_dim, patch_size=cfg.patch_size)
    stage = StageBlocks(
        depth=cfg.depth_per_stage,
        num_heads=cfg.num_heads,
        mlp_dim=cfg.embed_dim * cfg.mlp_ratio,
        attention_fn=cfg.attention_fn,
        remat=cfg.remat,
    )
    head = PipeHead(num_classes=cfg.num_classes)
    return embed, stage, head


def init_pipe_vit(
    cfg: PipeViTConfig, sample_input, *, seed: int = 0
) -> PipeViTParams:
    """Initialize all segments; stage s seeded by fold_in(seed, s)."""
    embed, stage, head = _modules(cfg)
    k = jax.random.key(seed)
    embed_p = embed.init(k, sample_input)["params"]
    feats = embed.apply({"params": embed_p}, sample_input)
    stage_ps = [
        stage.init(jax.random.fold_in(k, 1 + s), feats)["params"]
        for s in range(cfg.num_stages)
    ]
    head_p = head.init(jax.random.fold_in(k, 0), feats)["params"]
    return PipeViTParams(embed_p, stack_stage_params(stage_ps), head_p)


def sequential_apply(cfg: PipeViTConfig, params: PipeViTParams, images):
    """Reference forward without the pipeline — same math, one device."""
    embed, stage, head = _modules(cfg)
    x = embed.apply({"params": params.embed}, images)
    for s in range(cfg.num_stages):
        stage_p = jax.tree.map(lambda p: p[s], params.stages)
        x = stage.apply({"params": stage_p}, x)
    return head.apply({"params": params.head}, x)


def make_pipe_vit_apply(cfg: PipeViTConfig, mesh: Mesh):
    """Jitted pipelined ``apply(params, images) -> logits``.

    Batch shards over the mesh's ``data`` axis (if present) and
    microbatches stream over ``pipe``. Differentiable end to end.
    """
    embed, stage, head = _modules(cfg)
    has_data = mesh.shape.get("data", 1) > 1
    bspec = P("data") if has_data else P()
    mbspec = P(None, "data") if has_data else P()

    def stage_fn(p, x):
        return stage.apply({"params": p}, x)

    def apply_fn(params: PipeViTParams, images):
        images = lax.with_sharding_constraint(
            images, NamedSharding(mesh, bspec)
        )
        feats = embed.apply({"params": params.embed}, images)
        B = feats.shape[0]
        M = cfg.num_microbatches
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        mb = feats.reshape(M, B // M, *feats.shape[1:])

        pipelined = jax.shard_map(
            lambda p, m: spmd_pipeline(stage_fn, p, m, axis_name="pipe"),
            mesh=mesh,
            in_specs=(P("pipe"), mbspec),
            out_specs=mbspec,
            check_vma=False,
        )
        out = pipelined(params.stages, mb)
        out = out.reshape(B, *out.shape[2:])
        return head.apply({"params": params.head}, out)

    return apply_fn


def make_pipe_vit_train_step(
    cfg: PipeViTConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    compute_dtype=jnp.float32,
    donate: bool = True,
):
    """``step(state, images, labels) -> (state, metrics)`` over dp×pp.

    Stage params (and their optimizer state, by GSPMD propagation
    through the constrained update) stay sharded on ``pipe``; embed and
    head replicate, their gradients all-reduced over ``data`` by XLA.
    """
    apply_fn = make_pipe_vit_apply(cfg, mesh)
    stage_sharding = NamedSharding(mesh, P("pipe"))

    def constrain(params: PipeViTParams) -> PipeViTParams:
        return params._replace(
            stages=jax.tree.map(
                lambda x: lax.with_sharding_constraint(x, stage_sharding),
                params.stages,
            )
        )

    def step(state: PipeViTState, images, labels):
        def loss_fn(params):
            logits = apply_fn(params, _preprocess(images, compute_dtype))
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels
            ).mean()
            return loss, logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        grads = constrain(jax.tree.map(lambda g: g.astype(jnp.float32), grads))
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = constrain(optax.apply_updates(state.params, updates))
        correct = (jnp.argmax(logits.astype(jnp.float32), -1) == labels).mean()
        return (
            PipeViTState(state.step + 1, params, opt_state),
            StepMetrics(loss=loss, accuracy=correct),
        )

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def create_pipe_vit_state(
    cfg: PipeViTConfig,
    optimizer: optax.GradientTransformation,
    sample_input,
    mesh: Mesh,
    *,
    seed: int = 0,
) -> PipeViTState:
    params = init_pipe_vit(cfg, sample_input, seed=seed)
    stage_sharding = NamedSharding(mesh, P("pipe"))
    rep = NamedSharding(mesh, P())
    params = PipeViTParams(
        embed=jax.tree.map(lambda x: jax.device_put(x, rep), params.embed),
        stages=jax.tree.map(
            lambda x: jax.device_put(x, stage_sharding), params.stages
        ),
        head=jax.tree.map(lambda x: jax.device_put(x, rep), params.head),
    )
    return PipeViTState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=optimizer.init(params),
    )

"""Model zoo.

The reference ships one model (SimpleCNN, ``model.py``); the driver's
extension configs (BASELINE.json) add ResNet-18/CIFAR-10, ViT-Tiny/
CIFAR-100 (bf16 attention path) and ResNet-50/ImageNet. All are defined
here in Flax with NHWC layout and registered by name so the CLI can
select them.
"""

from __future__ import annotations

from typing import Callable

from ddp_tpu.models.cnn import SimpleCNN

_REGISTRY: dict[str, Callable] = {}


def register(name: str):
    def deco(ctor):
        _REGISTRY[name] = ctor
        return ctor

    return deco


def get_model(name: str, **kwargs):
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


def available() -> list[str]:
    return sorted(_REGISTRY)


def _register_all():
    from ddp_tpu.models.moe import MoEViTTiny
    from ddp_tpu.models.resnet import ResNet18, ResNet34, ResNet50
    from ddp_tpu.models.vit import ViTTiny

    register("simple_cnn")(SimpleCNN)
    # BASELINE.json config 3: CIFAR-10 ResNet-18
    register("resnet18")(ResNet18)
    register("resnet34")(ResNet34)
    # BASELINE.json config 5: ImageNet-1k ResNet-50
    register("resnet50")(ResNet50)
    # BASELINE.json config 4: ViT-Tiny / CIFAR-100 (attention path)
    register("vit_tiny")(ViTTiny)
    # Expert-parallel family (no reference counterpart — SURVEY.md §2c)
    register("vit_moe_tiny")(MoEViTTiny)

    # Micro configs: smoke tests / CI / CLI dry runs. Same code paths
    # as the tiny family at a fraction of the compile+step cost.
    from ddp_tpu.models.moe import MoEViT
    from ddp_tpu.models.vit import ViT

    register("vit_micro")(
        lambda num_classes=10, depth=2, **kw: ViT(
            num_classes=num_classes, patch_size=7, embed_dim=32,
            depth=depth, num_heads=4, **kw,
        )
    )
    register("vit_moe_micro")(
        lambda num_classes=10, depth=2, num_experts=4, **kw: MoEViT(
            num_classes=num_classes, patch_size=7, embed_dim=32,
            depth=depth, num_heads=4, num_experts=num_experts,
            moe_every=2, **kw,
        )
    )


_register_all()

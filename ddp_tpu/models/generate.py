"""KV-cache incremental decoding + sampling for the causal LM.

The reference ends at training (no eval, no inference — SURVEY.md §5);
round 1 added a decoder-only LM but no way to decode from it
(VERDICT.md "What's missing" #4). This module closes that gap the
TPU-friendly way: a single jitted ``lax.scan`` over decode steps, a
static-shape K/V cache updated in place with ``dynamic_update_slice``
(donated through the scan carry, so XLA keeps one buffer), and O(T)
attention per step against the cache.

It is a *functional* twin of ``models.lm.CausalLM``: the same
parameter tree (embed / pos_embed / blockN{ln1, attn{qkv, proj}, ln2,
mlp1, mlp2} / ln_final, tied head) driven step-by-step. Exactness is
pinned by tests/test_generate.py: per-position cached logits equal the
dense full-sequence forward to fp32 tolerance, which is also why the
numerics mirror Flax defaults exactly (LayerNorm eps 1e-6, tanh-GELU).

Sampling: greedy (``temperature=0``) or temperature-scaled categorical
with a per-step folded PRNG key.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ddp_tpu.models.lm import LMSpec
from ddp_tpu.ops.attention import best_attention, dot_product_attention
from ddp_tpu.ops.decode import (
    decode_attention,
    dequantize_kv,
    gather_paged_kv,
    paged_decode_attention,
    quantize_kv,
)


class DecodeCache(NamedTuple):
    """Static-shape per-layer K/V cache.

    ``k``/``v``: [depth, B, total_len, H_kv, Dh]; ``pos``: next write
    position (scalar int32). One stacked array per side keeps the scan
    carry flat and lets the per-layer update be a ``dynamic_update_slice``
    on a leading index. Under GQA (spec.num_kv_heads < num_heads) the
    cache stores the COMPACT kv heads — the whole point: per-step
    decode HBM reads shrink by the group factor.
    """

    k: jax.Array
    v: jax.Array
    pos: jax.Array


def _kv_heads(spec: LMSpec) -> int:
    return spec.num_kv_heads or spec.num_heads


def init_cache(spec: LMSpec, batch: int, dtype=jnp.float32) -> DecodeCache:
    head_dim = spec.d_model // spec.num_heads
    shape = (spec.depth, batch, spec.total_len, _kv_heads(spec), head_dim)
    return DecodeCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        pos=jnp.zeros((), jnp.int32),
    )


def _layer_norm(x, p):
    """Flax LayerNorm numerics: fp32, eps 1e-6, scale+bias."""
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = ((x32 - mean) ** 2).mean(-1, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + 1e-6)
    return y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)


def _dense(x, p):
    return x @ p["kernel"] + p["bias"]


def _block_qkv(p, x, H, Dh, H_kv=None):
    """ln1 → qkv projection → (q [B,T,H,Dh], k/v [B,T,H_kv,Dh]).
    Shared by the incremental decode (T=1) and the parallel prefill
    (T=P) so the two paths cannot drift numerically."""
    H_kv = H_kv or H
    h = _layer_norm(x, p["ln1"]).astype(x.dtype)
    qkv = _dense(h, p["attn"]["qkv"])
    if H_kv != H:
        # GQA GROUP-MAJOR fused layout [kv-group: q·G | k | v] × H_kv,
        # mirroring models/vit.py MultiHeadAttention's GQA path (whole
        # kv groups per TP column shard). q head j = g·G + i comes out
        # in natural 0..H-1 order, matching the grouped decode einsums.
        G = H // H_kv
        qkv = qkv.reshape(*x.shape[:2], H_kv, G + 2, Dh)
        q = qkv[..., :G, :].reshape(*x.shape[:2], H, Dh)
        return q, qkv[..., G, :], qkv[..., G + 1, :]
    # HEAD-MAJOR fused layout, mirroring models/vit.py
    # MultiHeadAttention: columns ordered [head, (q|k|v), head_dim] so
    # TP shards of the kernel are whole heads.
    qkv = qkv.reshape(*x.shape[:2], H, 3, Dh)
    return qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]


def _moe_mlp(p, x, *, top_k: int = 2, normalize_gates: bool = True):
    """Routed expert MLP for serving (round 5 — MoE-LM decode).

    models/moe.py MoEMLP numerics WITHOUT the capacity mechanism:
    each token's top-k experts are selected by the same iterative
    argmax, gates normalized the same way, and the combine runs as a
    dense weighting over all E expert FFNs — so the output equals the
    training forward EXACTLY while no token overflows capacity (the
    no-drop regime; capacity competition depends on the batch a layer
    sees, so a skewed router drops differently at train vs serve —
    the same caveat as any batch-size-dependent GShard eval). Dense
    E-way compute is the right serving shape here: decode batches are
    small and the capacity/dispatch einsums exist for training-scale
    token counts. ``top_k``/``normalize_gates`` come from the LMSpec
    (round-5 ADVICE fix: decode no longer hardcodes the MoEMLP
    defaults — a checkpoint trained at top_k=1 or with raw gates now
    serves with its own routing)."""
    B, T, d = x.shape
    toks = x.reshape(B * T, d)
    gates = jax.nn.softmax(
        toks.astype(jnp.float32) @ p["router"]["kernel"]
        + p["router"]["bias"],
        axis=-1,
    )  # [n, E] fp32 — the router runs fp32 in training too
    E = gates.shape[-1]
    remaining = gates
    comb = jnp.zeros_like(gates)
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        comb = comb + remaining * mask
        remaining = remaining * (1.0 - mask)
    if normalize_gates:
        comb = comb / jnp.maximum(comb.sum(-1, keepdims=True), 1e-9)
    wi, wo = p["wi"].astype(x.dtype), p["wo"].astype(x.dtype)
    h = jax.nn.gelu(
        jnp.einsum("nd,edf->enf", toks, wi) + p["bi"].astype(x.dtype)
    )
    y = jnp.einsum("enf,efd->end", h, wo) + p["bo"].astype(x.dtype)
    out = jnp.einsum("ne,end->nd", comb.astype(x.dtype), y)
    return out.reshape(B, T, d)


def _block_finish(spec: LMSpec, p, x, attn_vec):
    """Output projection residual + MLP residual (the block's back
    half). Routed blocks (``moe`` in the tree) take the expert path —
    every decode surface (decode_step, prefill, beam_search,
    cached_logits) flows through here, so the MoE-LM serves through
    the whole stack. Routing config (top_k, gate normalization) comes
    from the spec, not the MoEMLP defaults."""
    x = x + _dense(attn_vec, p["attn"]["proj"])
    h = _layer_norm(x, p["ln2"]).astype(x.dtype)
    if "moe" in p:
        return x + _moe_mlp(
            p["moe"], h,
            top_k=spec.moe_top_k,
            normalize_gates=spec.moe_normalize_gates,
        )
    h = _dense(h, p["mlp1"])
    h = jax.nn.gelu(h)  # tanh approximation — Flax's default
    return x + _dense(h, p["mlp2"])


def decode_step(
    spec: LMSpec, params: Any, cache: DecodeCache, token: jax.Array
) -> tuple[jax.Array, DecodeCache]:
    """Feed ONE token per sequence → (logits [B, vocab], new cache).

    ``token``: [B] int32 at position ``cache.pos``. Attention runs the
    new query against the full static cache with positions > pos masked
    — O(total_len·d) per step, no [T, T] tensor.
    """
    embed = params["embed"]
    B = token.shape[0]
    H = spec.num_heads
    Dh = spec.d_model // H
    H_kv = _kv_heads(spec)
    G = H // H_kv  # 1 for MHA; the grouped einsums reduce to plain MHA
    pos = cache.pos
    x = embed[token][:, None, :]  # [B, 1, d]
    x = x + lax.dynamic_slice_in_dim(
        params["pos_embed"].astype(x.dtype), pos, 1, axis=1
    )
    # Keys at positions > pos are cache zeros — mask them out.
    live = (jnp.arange(spec.total_len) <= pos)[None, None, None, :]
    ck, cv = cache.k, cache.v
    for i in range(spec.depth):
        p = params[f"block{i + 1}"]
        q, k, v = _block_qkv(p, x, H, Dh, H_kv)
        ck = lax.dynamic_update_slice(ck, k[None], (i, 0, pos, 0, 0))
        cv = lax.dynamic_update_slice(cv, v[None], (i, 0, pos, 0, 0))
        # q head h attends through kv head h // G (h = k·G + g, the
        # same grouping jnp.repeat gives the training path).
        qg = q[:, 0].reshape(B, H_kv, G, Dh)
        logits = (
            jnp.einsum(
                "bkgd,blkd->bkgl",
                qg.astype(jnp.float32),
                ck[i].astype(jnp.float32),
            )
            * Dh**-0.5
        )  # [B, H_kv, G, L]
        logits = jnp.where(live, logits, -jnp.inf)
        w = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("bkgl,blkd->bkgd", w, cv[i].astype(jnp.float32))
        attn = attn.reshape(B, 1, spec.d_model).astype(x.dtype)
        x = _block_finish(spec, p, x, attn)
    x = _layer_norm(x, params["ln_final"])
    out_logits = (x[:, 0] @ embed.T.astype(jnp.float32)).astype(jnp.float32)
    return out_logits, DecodeCache(k=ck, v=cv, pos=pos + 1)


def prefill(
    spec: LMSpec, params: Any, prompt: jax.Array
) -> tuple[jax.Array, DecodeCache]:
    """Warm the cache from the prompt in ONE parallel forward.

    ``prompt``: [B, P] int32, P ≥ 1. The standard two-phase decode
    architecture: prefill processes all prompt positions at once
    (dense causal attention, MXU-shaped [B, P, ...] matmuls) and
    writes every position's K/V into the cache; generation then
    proceeds token-by-token. Returns (last position's logits, cache
    with pos = P). Pinned equal to sequential ``decode_step`` feeding
    by tests/test_generate.py.
    """
    B, P = prompt.shape
    H = spec.num_heads
    Dh = spec.d_model // H
    H_kv = _kv_heads(spec)
    G = H // H_kv
    cache = init_cache(spec, B)
    embed = params["embed"]
    x = embed[prompt]  # [B, P, d]
    x = x + params["pos_embed"].astype(x.dtype)[:, :P]
    ck, cv = cache.k, cache.v
    # Size-dispatched (flash on TPU past FLASH_MIN_LEN, dense
    # otherwise) — prefill is a full causal attention over the
    # prompt. Resolved once, like CausalLM.
    attn_fn = best_attention(causal=True)
    for i in range(spec.depth):
        p = params[f"block{i + 1}"]
        q, k, v = _block_qkv(p, x, H, Dh, H_kv)
        ck = lax.dynamic_update_slice(ck, k[None], (i, 0, 0, 0, 0))
        cv = lax.dynamic_update_slice(cv, v[None], (i, 0, 0, 0, 0))
        # The cache keeps kv compact; compute expands to full heads
        # (same jnp.repeat grouping as the training path).
        attn = attn_fn(
            q.astype(jnp.float32),
            jnp.repeat(k, G, axis=2).astype(jnp.float32),
            jnp.repeat(v, G, axis=2).astype(jnp.float32),
        )
        attn = attn.reshape(B, P, spec.d_model).astype(x.dtype)
        x = _block_finish(spec, p, x, attn)
    x = _layer_norm(x[:, -1:], params["ln_final"])
    last_logits = (x[:, 0] @ embed.T.astype(jnp.float32)).astype(jnp.float32)
    return last_logits, DecodeCache(
        k=ck, v=cv, pos=jnp.asarray(P, jnp.int32)
    )


def filter_logits(logits, *, top_k: int = 0, top_p: float = 1.0):
    """Mask logits to the top-k and/or nucleus (top-p) candidate set.

    ``top_k > 0`` keeps the k highest logits per row — tie-inclusive:
    every logit equal to the kth value survives, so exact ties can
    leave more than k candidates (the standard shape-static choice;
    masking ``logits < kth`` keeps strictly-less out only).
    ``top_p < 1``
    keeps the smallest prefix of the probability-sorted vocabulary
    whose cumulative mass reaches p (the highest-probability token
    always survives, so the set is never empty). Masked entries become
    a large negative (not −inf: the downstream ``categorical`` is
    NaN-safe that way even if a row were fully masked). Static shapes
    throughout — jit/vmap/scan-safe.
    """
    logits = logits.astype(jnp.float32)
    neg = jnp.float32(jnp.finfo(jnp.float32).min / 2)
    if top_k and top_k < logits.shape[-1]:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep entries whose PRECEDING mass is < p (so the first token
        # is always kept); the threshold is the smallest kept logit.
        keep = jnp.concatenate(
            [jnp.ones_like(cum[..., :1], bool), cum[..., :-1] < top_p],
            axis=-1,
        )
        thresh = jnp.min(
            jnp.where(keep, sorted_logits, jnp.float32(jnp.inf)),
            axis=-1, keepdims=True,
        )
        logits = jnp.where(logits < thresh, neg, logits)
    return logits


def generate(
    spec: LMSpec,
    params: Any,
    prompt: jax.Array,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    seed: int = 0,
) -> jax.Array:
    """Sample continuations → [B, P + max_new_tokens] int32.

    Greedy when ``temperature == 0``; otherwise categorical over
    ``filter_logits(logits / temperature, top_k, top_p)`` — the
    conventional order: temperature first, so the nucleus is computed
    on the distribution actually being sampled (a hot distribution
    keeps a wider top-p set). ``top_k=0``/``top_p=1`` disable
    filtering; combining filters with ``temperature == 0`` is an
    error (greedy ignores them — refusing beats silently recording
    settings that had no effect). The whole loop (prefill + decode)
    is jittable; positions past ``spec.total_len`` are rejected up
    front since the position table ends there.
    """
    P = prompt.shape[1]
    if P + max_new_tokens > spec.total_len:
        raise ValueError(
            f"prompt ({P}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"total_len {spec.total_len}"
        )
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if temperature <= 0.0 and (top_k or top_p < 1.0):
        raise ValueError(
            "top_k/top_p only apply when sampling: set --temperature "
            "> 0 (greedy decoding ignores the filters)"
        )
    logits, cache = prefill(spec, params, prompt)
    key = jax.random.key(seed)

    def pick(logits, step_idx):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        k = jax.random.fold_in(key, step_idx)
        filtered = filter_logits(
            logits.astype(jnp.float32) / temperature,
            top_k=top_k, top_p=top_p,
        )
        return jax.random.categorical(k, filtered, axis=-1).astype(
            jnp.int32
        )

    def step(carry, step_idx):
        logits, cache = carry
        tok = pick(logits, step_idx)
        logits, cache = decode_step(spec, params, cache, tok)
        return (logits, cache), tok

    (_, _), new_tokens = lax.scan(
        step, (logits, cache), jnp.arange(max_new_tokens)
    )
    return jnp.concatenate([prompt, new_tokens.T], axis=1)


def beam_search(
    spec: LMSpec,
    params: Any,
    prompt: jax.Array,
    *,
    max_new_tokens: int,
    beam_width: int,
) -> tuple[jax.Array, jax.Array]:
    """Deterministic beam decode → (tokens [B, W, P+N], scores [B, W]).

    Standard length-synchronous beam search over the KV cache: every
    step scores all W·V continuations per sequence, keeps the top W,
    and reorders the cache rows and token history to follow their
    parent beams (one ``take`` along the cache's batch dim — the
    [depth, B·W, L, H_kv, Dh] layout makes beam bookkeeping a gather,
    not a copy loop). Beams are returned best-first with their total
    log-probabilities; ``beam_width=1`` IS greedy decoding (pinned by
    tests). All beams decode the full ``max_new_tokens`` (the LM has
    no reserved EOS token), so no length normalization is applied —
    scores are directly comparable sums.
    """
    B, P = prompt.shape
    W = beam_width
    if W < 1:
        raise ValueError(f"beam_width must be >= 1, got {W}")
    if max_new_tokens < 1:
        raise ValueError(
            f"beam search decodes at least one token, got "
            f"max_new_tokens={max_new_tokens}"
        )
    if P + max_new_tokens > spec.total_len:
        raise ValueError(
            f"prompt ({P}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"total_len {spec.total_len}"
        )
    V = spec.vocab_size
    if W > V:
        raise ValueError(f"beam_width {W} exceeds vocab_size {V}")
    logits, cache = prefill(spec, params, prompt)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    scores, tok0 = lax.top_k(logp, W)  # [B, W] first expansion

    def tile(x):  # [depth, B, ...] → [depth, B·W, ...], b-major
        return jnp.repeat(x, W, axis=1)

    cache = DecodeCache(tile(cache.k), tile(cache.v), cache.pos)
    seqs = jnp.zeros((B, W, max_new_tokens), jnp.int32)
    seqs = seqs.at[:, :, 0].set(tok0)

    def step(carry, i):
        scores, toks, cache, seqs = carry
        logits, cache = decode_step(spec, params, cache, toks)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        total = scores[..., None] + logp.reshape(B, W, V)
        scores, idx = lax.top_k(total.reshape(B, W * V), W)
        parent = idx // V  # [B, W] surviving beams' ancestors
        tok = (idx % V).astype(jnp.int32)
        flat = (jnp.arange(B)[:, None] * W + parent).reshape(-1)
        cache = DecodeCache(
            k=jnp.take(cache.k, flat, axis=1),
            v=jnp.take(cache.v, flat, axis=1),
            pos=cache.pos,
        )
        seqs = jnp.take_along_axis(seqs, parent[..., None], axis=1)
        seqs = seqs.at[:, :, i].set(tok)
        return (scores, tok.reshape(B * W), cache, seqs), None

    (scores, _, _, seqs), _ = lax.scan(
        step,
        (scores, tok0.reshape(B * W), cache, seqs),
        jnp.arange(1, max_new_tokens),
    )
    tiled_prompt = jnp.broadcast_to(prompt[:, None, :], (B, W, P))
    return jnp.concatenate([tiled_prompt, seqs], axis=2), scores


# --- slot-level primitives (ddp_tpu.serve continuous batching) -------
#
# The serving engine (serve/engine.py) keeps ONE static-shape decode
# batch of S slots alive forever; requests of different ages share it.
# That needs decode with a PER-SLOT position (DecodeCache.pos is one
# scalar for the whole batch) plus lane-level refill: prompts are
# ingested by ``prefill_chunk`` — fixed-width chunks written straight
# into a lane of the donated cache, co-scheduled with decode steps.
# Every primitive is shape-static — slot index, lengths, positions and
# sampling config are traced scalars/vectors — so a running engine's
# compiled-program set is bounded by its chunk-width buckets
# regardless of the request mix, and the decode loop is fully
# device-resident (``slot_decode_sample_step`` fuses sampling; the
# host sees [S] int32 tokens, never logits).


class SlotCache(NamedTuple):
    """Per-slot variant of DecodeCache for continuous batching.

    Same ``k``/``v`` layout ([depth, S, total_len, H_kv, Dh] — each
    slot is a lane of the batch dim), but ``pos`` is [S] int32: every
    slot decodes at its own position, so a mixed-age batch (one
    request 5 tokens in, another 200) advances in one step.

    ``k_scale``/``v_scale`` ([depth, S, total_len, H_kv] fp32) exist
    only for int8-quantized caches (``init_slot_cache(...,
    dtype=jnp.int8)`` — ops/decode.quantize_kv per-head scales,
    written alongside every K/V row); fp32/bf16 caches carry empty
    tuples there, so the plain cache's pytree (and every donation
    path over it) is unchanged. ``quantized()`` is a trace-time
    dispatch: dtype is static under jit.
    """

    k: jax.Array
    v: jax.Array
    pos: jax.Array
    k_scale: Any = ()
    v_scale: Any = ()

    def quantized(self) -> bool:
        return self.k.dtype == jnp.int8


def init_slot_cache(
    spec: LMSpec, slots: int, dtype=jnp.float32
) -> SlotCache:
    """``dtype=jnp.int8`` allocates the quantized variant: int8 K/V
    plus per-(position, head) fp32 scales — cache HBM per slot drops
    to ~(1 + 4/Dh)/8 of the fp32 layout, the ``slots``-per-chip
    capacity win `bench.py serve_decode` measures."""
    head_dim = spec.d_model // spec.num_heads
    shape = (spec.depth, slots, spec.total_len, _kv_heads(spec), head_dim)
    # Two DISTINCT buffers: the cache is donated through every engine
    # program, and aliased leaves ((x,) * 2) make XLA reject the
    # donation ("same buffer twice").
    scales = (
        (jnp.zeros(shape[:-1], jnp.float32),
         jnp.zeros(shape[:-1], jnp.float32))
        if dtype == jnp.int8
        else ((), ())
    )
    return SlotCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        pos=jnp.zeros((slots,), jnp.int32),
        k_scale=scales[0],
        v_scale=scales[1],
    )


class PagedSlotCache(NamedTuple):
    """Paged variant of :class:`SlotCache` (PR 12 — serve/pages.py).

    K/V live in a POOL of ``page_size``-token pages (``k``/``v``:
    [depth, num_pages, page_size, H_kv, Dh]) instead of per-slot
    lanes; each slot's logical [total_len] lane is spelled by its row
    of ``table`` ([S, lane_pages] int32 page ids, lane_pages =
    total_len // page_size), so two slots whose prompts share a
    prefix can map the SAME pages copy-free — the radix-index reuse
    the engine's PrefixCache hands out. ``pos`` is [S] exactly as in
    SlotCache; ``k_scale``/``v_scale`` ([depth, num_pages, page_size,
    H_kv] fp32) exist only for int8 pools, mirroring the fixed-lane
    convention (empty tuples otherwise, two distinct buffers for
    donation).

    Page id 0 is the engine's reserved SCRATCH page: all-zero table
    rows (idle lanes, warmup) read and write it, and any write whose
    position falls at/past the lane's table end is dropped outright
    (the scatter indices are pushed out of bounds — cleaner than the
    fixed-lane clamp-to-last-line, and required: a clamped write
    could land in a page another lane shares).

    The cache KIND is trace-time static (isinstance dispatch), like
    the int8 dtype: one engine compiles either the paged or the
    fixed-lane program set, never both.
    """

    k: jax.Array
    v: jax.Array
    pos: jax.Array
    table: jax.Array
    k_scale: Any = ()
    v_scale: Any = ()

    def quantized(self) -> bool:
        return self.k.dtype == jnp.int8

    @property
    def page_size(self) -> int:
        return int(self.k.shape[2])

    @property
    def num_pages(self) -> int:
        return int(self.k.shape[1])


def init_paged_slot_cache(
    spec: LMSpec,
    slots: int,
    *,
    num_pages: int,
    page_size: int,
    dtype=jnp.float32,
) -> PagedSlotCache:
    """Allocate the page pool + all-zero (scratch-mapped) tables.

    ``total_len`` must be a multiple of ``page_size`` (the engine
    validates and names the flags); the pool's HBM is ``num_pages ·
    page_size`` cache lines regardless of ``slots`` — the decoupling
    that turns int8's bytes/slot win into an effective-slots win.
    """
    if spec.total_len % page_size:
        raise ValueError(
            f"page_size {page_size} must divide total_len "
            f"{spec.total_len}"
        )
    head_dim = spec.d_model // spec.num_heads
    shape = (spec.depth, num_pages, page_size, _kv_heads(spec), head_dim)
    scales = (
        (jnp.zeros(shape[:-1], jnp.float32),
         jnp.zeros(shape[:-1], jnp.float32))
        if dtype == jnp.int8
        else ((), ())
    )
    return PagedSlotCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        pos=jnp.zeros((slots,), jnp.int32),
        table=jnp.zeros(
            (slots, spec.total_len // page_size), jnp.int32
        ),
        k_scale=scales[0],
        v_scale=scales[1],
    )


def _page_scatter_ids(
    table: jax.Array, posns: jax.Array, page_size: int, num_pages: int
):
    """Absolute positions → (page ids, in-page offsets) for writes.

    ``table``: [..., lane_pages] int32 rows; ``posns``: positions with
    the same leading batch dims (the decode/verify path passes the
    whole [S, lane_pages] table with [S, T] positions, a chunk passes
    one lane's [lane_pages] row with [C] positions). THE one
    definition of the out-of-lane convention: positions at/past the
    table's end map to page id ``num_pages`` — OUT of bounds, so the
    scatter drops them (jit's documented mode), the paged analogue of
    the fixed-lane position-ceiling clamp, minus the garbage line.
    """
    lane_pages = table.shape[-1]
    pidx = jnp.minimum(posns // page_size, lane_pages - 1)
    pids = jnp.take_along_axis(table, pidx, axis=-1)
    pids = jnp.where(
        posns < lane_pages * page_size, pids, jnp.int32(num_pages)
    )
    return pids, posns % page_size


def _paged_write_rows(cache: PagedSlotCache, layer: int, k, v, pos):
    """Paged twin of the fixed-lane row write: scatter each lane's T
    rows through its page table (quantize-on-write on int8 pools).
    ``k``/``v``: [S, T, H_kv, Dh]; row t of lane s lands at absolute
    position ``pos[s] + t``."""
    T = k.shape[1]
    posns = pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    pids, offs = _page_scatter_ids(
        cache.table, posns, cache.page_size, cache.num_pages
    )  # both [S, T]
    ck, cv, ksc, vsc = cache.k, cache.v, cache.k_scale, cache.v_scale
    if cache.quantized():
        qk, k_s = quantize_kv(k)
        qv, v_s = quantize_kv(v)
        ck = ck.at[layer, pids, offs].set(qk)
        cv = cv.at[layer, pids, offs].set(qv)
        ksc = ksc.at[layer, pids, offs].set(k_s)
        vsc = vsc.at[layer, pids, offs].set(v_s)
    else:
        ck = ck.at[layer, pids, offs].set(k.astype(ck.dtype))
        cv = cv.at[layer, pids, offs].set(v.astype(cv.dtype))
    return cache._replace(k=ck, v=cv, k_scale=ksc, v_scale=vsc)


def _full_kv(cache, layer: int):
    """All S lanes' [L, H_kv, Dh] float views for ``layer`` —
    dequantized if int8, gathered through the page tables if paged.
    The verify step's key/value source (decode steps go through
    ops/decode instead, where the flash path avoids materializing
    this)."""
    kf, vf = cache.k[layer], cache.v[layer]
    if isinstance(cache, PagedSlotCache):
        kf = gather_paged_kv(kf, cache.table)
        vf = gather_paged_kv(vf, cache.table)
        if cache.quantized():
            kf = dequantize_kv(
                kf, gather_paged_kv(cache.k_scale[layer], cache.table)
            )
            vf = dequantize_kv(
                vf, gather_paged_kv(cache.v_scale[layer], cache.table)
            )
        return kf, vf
    if cache.quantized():
        kf = dequantize_kv(kf, cache.k_scale[layer])
        vf = dequantize_kv(vf, cache.v_scale[layer])
    return kf, vf


def _write_kv_rows(cache, layer: int, k, v, pos):
    """Write per-lane K/V rows at each lane's position, in place.

    ``k``/``v``: [S, T, H_kv, Dh] float rows for positions
    ``pos[s]..pos[s]+T-1``. On a quantized cache the rows quantize on
    write (ops/decode.quantize_kv — int8 rows + per-head scales), so
    the cache never holds full-precision lines. Returns the updated
    cache. The vmapped ``dynamic_update_slice`` clamps per lane, so
    callers must pre-clamp ``pos`` when T > 1 (a clamp-shift would
    silently move the write over live lines). Paged caches take the
    scatter-through-the-table twin instead (same rows, same
    positions; out-of-lane writes dropped, not clamped).
    """
    if isinstance(cache, PagedSlotCache):
        return _paged_write_rows(cache, layer, k, v, pos)
    write = jax.vmap(
        lambda lane, row, p: lax.dynamic_update_slice(
            lane, row, (p, 0, 0)
        )
    )  # ([S, L, H_kv, Dh], [S, T, H_kv, Dh], [S]) → written lanes
    ck, cv, ksc, vsc = cache.k, cache.v, cache.k_scale, cache.v_scale
    if cache.quantized():
        write_sc = jax.vmap(
            lambda lane, row, p: lax.dynamic_update_slice(
                lane, row, (p, 0)
            )
        )  # ([S, L, H_kv], [S, T, H_kv], [S])
        qk, k_s = quantize_kv(k)
        qv, v_s = quantize_kv(v)
        ck = ck.at[layer].set(write(ck[layer], qk, pos))
        cv = cv.at[layer].set(write(cv[layer], qv, pos))
        ksc = ksc.at[layer].set(write_sc(ksc[layer], k_s, pos))
        vsc = vsc.at[layer].set(write_sc(vsc[layer], v_s, pos))
    else:
        ck = ck.at[layer].set(write(ck[layer], k.astype(ck.dtype), pos))
        cv = cv.at[layer].set(write(cv[layer], v.astype(cv.dtype), pos))
    return cache._replace(k=ck, v=cv, k_scale=ksc, v_scale=vsc)


def _lane_scales(cache: SlotCache, layer: int):
    if cache.quantized():
        return cache.k_scale[layer], cache.v_scale[layer]
    return None, None


def slot_decode_step(
    spec: LMSpec,
    params: Any,
    cache: SlotCache,
    tokens: jax.Array,
    *,
    attn_impl: str = "reference",
) -> tuple[jax.Array, SlotCache]:
    """decode_step with per-slot positions → (logits [S, V], cache).

    ``tokens``: [S] int32, slot s's token written at ``cache.pos[s]``.
    Numerics per lane are identical to ``decode_step`` (same einsums,
    same mask rule ``key_pos <= pos``) — only the position bookkeeping
    is vectorized: the K/V write is a vmapped ``dynamic_update_slice``
    over the slot dim (a scatter of S rows, not a full-cache rewrite),
    the position embedding a per-slot gather. Idle slots are decoded
    too (the batch shape never changes); their outputs are garbage the
    engine ignores, but never NaN — position 0 is always live, so the
    softmax normalizes over at least one (zero) logit. ``pos`` is
    clamped at ``total_len`` so an idle slot can sit in the batch
    indefinitely without indexing past the cache (writes at the clamp
    land on the last line, which a refill overwrites).

    ``attn_impl`` (Python-static — the engine compiles its choice in)
    picks the banded single-query attention: ``reference`` is the
    ops/decode jnp path, bit-identical to the math that used to live
    inline here; ``flash``/``auto`` route through the Pallas
    flash-decode kernel (ops/decode.py). On an int8 cache both paths
    dequantize at the compute site.
    """
    embed = params["embed"]
    S = tokens.shape[0]
    H = spec.num_heads
    Dh = spec.d_model // H
    H_kv = _kv_heads(spec)
    pos = cache.pos  # [S]
    x = embed[tokens][:, None, :]  # [S, 1, d]
    # Per-slot position embedding: row s reads pos_embed[pos[s]].
    pe = params["pos_embed"][0]  # [L, d]
    x = x + pe[jnp.minimum(pos, spec.total_len - 1)][:, None, :].astype(
        x.dtype
    )
    for i in range(spec.depth):
        p = params[f"block{i + 1}"]
        q, k, v = _block_qkv(p, x, H, Dh, H_kv)
        cache = _write_kv_rows(cache, i, k, v, pos)
        ksc, vsc = _lane_scales(cache, i)
        if isinstance(cache, PagedSlotCache):
            # Same banded math over the table's gathered view
            # (ops/decode.paged_decode_attention, block_k =
            # page_size) — scratch/stale entries sit past ``pos`` and
            # are masked, so the paged step is token-identical to the
            # fixed-lane one (pinned by tests/test_paged.py).
            attn = paged_decode_attention(
                q[:, 0], cache.k[i], cache.v[i], cache.table, pos,
                ksc, vsc, impl=attn_impl,
            )  # [S, H, Dh] fp32
        else:
            attn = decode_attention(
                q[:, 0], cache.k[i], cache.v[i], pos, ksc, vsc,
                impl=attn_impl,
            )  # [S, H, Dh] fp32
        attn = attn.reshape(S, 1, spec.d_model).astype(x.dtype)
        x = _block_finish(spec, p, x, attn)
    x = _layer_norm(x, params["ln_final"])
    out_logits = (x[:, 0] @ embed.T.astype(jnp.float32)).astype(jnp.float32)
    return out_logits, cache._replace(
        pos=jnp.minimum(pos + 1, spec.total_len)
    )


def nucleus_filter(logits: jax.Array, top_p: jax.Array) -> jax.Array:
    """``filter_logits``'s top-p branch with a TRACED threshold.

    1-D ``logits``; ``top_p`` a traced scalar, so one compiled program
    serves every per-request nucleus setting (the serving engine's
    requirement — the static-arg variant would recompile per value).
    Semantics are identical to ``filter_logits(..., top_p=p)`` for
    p < 1: keep the smallest probability-sorted prefix reaching p, the
    best token always survives, masked entries become a large negative.
    Callers that need exact parity with ``filter_logits`` at p == 1.0
    (no filtering at all) must select the unfiltered logits themselves
    — at p == 1.0 this function can drop zero-probability tail entries
    whose preceding cumulative mass already rounds to 1.0.
    """
    logits = logits.astype(jnp.float32)
    neg = jnp.float32(jnp.finfo(jnp.float32).min / 2)
    sorted_logits = jnp.sort(logits)[::-1]
    probs = jax.nn.softmax(sorted_logits)
    cum = jnp.cumsum(probs)
    keep = jnp.concatenate(
        [jnp.ones((1,), bool), cum[:-1] < top_p]
    )
    thresh = jnp.min(
        jnp.where(keep, sorted_logits, jnp.float32(jnp.inf))
    )
    return jnp.where(logits < thresh, neg, logits)


def sample_token(
    logits: jax.Array,
    seed: jax.Array,
    step: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
) -> jax.Array:
    """One on-device sampling decision → scalar int32 token.

    The fused-sampling half of the device-resident decode loop: the
    serving engine jits this INTO its decode/prefill programs so the
    per-step host transfer is tokens, not logits. Matches
    ``generate``'s ``pick`` decision-for-decision — greedy argmax at
    ``temperature <= 0``; otherwise ``categorical`` under the key
    ``fold_in(key(seed), step)`` over temperature-scaled,
    nucleus-filtered logits — so a seeded sampled stream is
    token-identical between the engine and per-request ``generate()``
    (pinned by tests/test_serve.py). All of seed/step/temperature/
    top_p are traced scalars: one compiled program covers any
    per-request sampling config. ``top_k`` is not supported here (its
    k is a SHAPE, so per-request values would recompile per mix);
    serve-side requests get temperature + top_p only.
    """
    logits = logits.astype(jnp.float32)

    def greedy(_):
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def drawn(_):
        key = jax.random.fold_in(jax.random.key(seed), step)
        scaled = logits / temperature  # > 0 inside this branch
        # filter_logits skips filtering entirely at top_p == 1.0;
        # branch (not blend) so p == 1.0 stays bit-identical to
        # generate AND skips the vocab sort at runtime.
        cand = lax.cond(
            top_p < 1.0,
            lambda s: nucleus_filter(s, top_p),
            lambda s: s,
            scaled,
        )
        return jax.random.categorical(key, cand, axis=-1).astype(
            jnp.int32
        )

    # Real branch skip (this is a scalar cond, not a vmapped one): a
    # greedy request pays one argmax, no key derivation, no sort.
    return lax.cond(temperature > 0.0, drawn, greedy, operand=None)


def sample_slot_tokens(
    logits: jax.Array,
    seeds: jax.Array,
    steps: jax.Array,
    temps: jax.Array,
    top_ps: jax.Array,
) -> jax.Array:
    """Per-slot on-device sampling over [S, V] logits → [S] int32.

    Vectorized ``sample_token``, with the expensive machinery gated at
    RUNTIME (``lax.cond`` on the batch's sampling config, traced — no
    recompilation): a pure-greedy batch runs one argmax and never
    touches key derivation, and the vocab sort of the nucleus filter
    only runs when some lane actually sets top_p < 1. Mostly-greedy
    serving traffic therefore pays (almost) nothing for the fused
    sampling path — the reason the old engine kept sampling on host.

    Exactly the K=1 specialization of ``sample_slot_tokens_block``
    (offset 0 folds in ``steps + 0``), and implemented as such: the
    speculative path's seeded-acceptance guarantee depends on the two
    key-derivation/gating paths staying bit-identical, so there is
    only one.
    """
    return sample_slot_tokens_block(
        logits[:, None, :], seeds, steps, temps, top_ps
    )[:, 0]


def slot_decode_sample_step(
    spec: LMSpec,
    params: Any,
    cache: SlotCache,
    tokens: jax.Array,
    seeds: jax.Array,
    steps: jax.Array,
    temps: jax.Array,
    top_ps: jax.Array,
    *,
    attn_impl: str = "reference",
) -> tuple[jax.Array, SlotCache, jax.Array]:
    """``slot_decode_step`` with sampling fused → ([S] int32, cache,
    advanced step counters).

    The serving engine's steady-state program: advance all S lanes one
    token AND pick each lane's next token on device, so the engine
    transfers [S] int32 per step instead of [S, vocab] logits and the
    per-slot host sampling loop disappears. ``seeds``/``steps``/
    ``temps``/``top_ps`` are [S] per-slot sampling state living as
    DEVICE-RESIDENT engine state (written by ``prefill_chunk`` at
    refill, never re-uploaded per step): ``steps`` is each lane's
    emitted-token index — the ``fold_in`` counter that keeps seeded
    streams identical to ``generate`` — and is returned advanced by
    one so the loop threads it like the cache. Idle lanes sample
    garbage the engine ignores — their logits are finite (position 0
    is always live), so no NaN can propagate.
    """
    logits, cache = slot_decode_step(
        spec, params, cache, tokens, attn_impl=attn_impl
    )
    toks = sample_slot_tokens(logits, seeds, steps, temps, top_ps)
    return toks, cache, steps + 1


def sample_slot_tokens_block(
    logits: jax.Array,
    seeds: jax.Array,
    steps: jax.Array,
    temps: jax.Array,
    top_ps: jax.Array,
) -> jax.Array:
    """Per-(slot, offset) sampling over [S, K, V] logits → [S, K] int32.

    The verify-step sibling of ``sample_slot_tokens``: offset j of
    lane s samples under ``fold_in(key(seeds[s]), steps[s] + j)`` —
    the EXACT key the non-speculative loop would use for that lane's
    (steps[s] + j)-th emitted token, which is what makes speculative
    acceptance exact for seeded sampling (the target's tokens are the
    same stream, just computed K at a time). Same runtime gating: a
    pure-greedy batch runs one argmax, the nucleus sort only runs
    when some lane set top_p < 1.
    """
    S, K, _V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    sampling = temps > 0.0

    def drawn(_):
        def lane_keys(s, st):
            return jax.vmap(
                lambda j: jax.random.fold_in(jax.random.key(s), st + j)
            )(jnp.arange(K))

        keys = jax.vmap(lane_keys)(seeds, steps)  # [S, K] keys
        safe_t = jnp.where(sampling, temps, jnp.float32(1.0))
        scaled = logits.astype(jnp.float32) / safe_t[:, None, None]

        def filtered(sc):
            return jax.vmap(
                lambda rows, p: jax.vmap(
                    lambda row: jnp.where(
                        p < 1.0, nucleus_filter(row, p), row
                    )
                )(rows)
            )(sc, top_ps)

        cand = lax.cond(
            jnp.any(sampling & (top_ps < 1.0)),
            filtered,
            lambda sc: sc,
            scaled,
        )
        return jax.vmap(
            jax.vmap(lambda k, c: jax.random.categorical(k, c, axis=-1))
        )(keys, cand).astype(jnp.int32)

    toks = lax.cond(
        jnp.any(sampling), drawn, lambda _: greedy, operand=None
    )
    return jnp.where(sampling[:, None], toks, greedy)


def slot_verify_step(
    spec: LMSpec,
    params: Any,
    cache: SlotCache,
    tokens: jax.Array,
    drafts: jax.Array,
    seeds: jax.Array,
    steps: jax.Array,
    temps: jax.Array,
    top_ps: jax.Array,
) -> tuple[jax.Array, SlotCache, jax.Array, jax.Array, jax.Array]:
    """Speculative-decoding verify: score K draft tokens per lane in
    ONE target-model step → ``(next_toks [S], cache, steps,
    target_toks [S, K], matched [S])``.

    ``tokens``: [S] — each lane's last accepted token (the decode
    loop's ``_toks``); ``drafts``: [S, K] — the draft model's K
    greedy proposals d_1..d_K. The target runs the K inputs
    ``[token, d_1..d_{K-1}]`` at positions ``pos[s]..pos[s]+K-1``
    under the banded per-lane mask (query j attends keys ``<=
    pos[s]+j``) — a K-wide chunked forward over the SAME cache lanes
    the decode step uses, K/V written (and on an int8 cache,
    quantized) before attending. Each of the K positions then samples
    the target's token with that position's own fold_in counter
    (``sample_slot_tokens_block``), so ``target_toks[s]`` is exactly
    the token stream the non-speculative loop would emit.

    Acceptance is prefix-exact: ``matched[s]`` = leading positions
    where draft == target. The lane emits ``n = min(matched + 1, K)``
    tokens — the matched drafts plus the target's correction token
    (or, on a full match, the K targets with no bonus: the K+1-th
    logit was never computed) — and ``next_toks``/``pos``/``steps``
    advance by exactly n per lane, so rejected positions' K/V rows
    sit above ``pos`` (never attendable) until the next round
    overwrites them — the engine's write-before-attend invariant.
    Output equivalence to the non-speculative stream is exact for
    greedy AND seeded sampling (tests/test_spec_decode.py).

    The write start is pre-clamped at ``total_len - K`` (the vmapped
    ``dynamic_update_slice`` would clamp-shift over live lines
    otherwise): the engine reserves K-1 positions at admission so a
    LIVE lane never triggers the clamp — it only guards idle lanes
    parked at the position ceiling.
    """
    embed = params["embed"]
    S, K = drafts.shape
    H = spec.num_heads
    Dh = spec.d_model // H
    H_kv = _kv_heads(spec)
    G = H // H_kv
    pos = cache.pos  # [S]
    inputs = jnp.concatenate([tokens[:, None], drafts[:, :-1]], axis=1)
    x = embed[inputs]  # [S, K, d]
    pe = params["pos_embed"][0]  # [L, d]
    offsets = jnp.arange(K, dtype=jnp.int32)
    q_pos = jnp.minimum(
        pos[:, None] + offsets[None, :], spec.total_len - 1
    )  # [S, K]
    x = x + pe[q_pos].astype(x.dtype)
    wstart = jnp.minimum(pos, spec.total_len - K)
    live = (
        jnp.arange(spec.total_len)[None, None, :]
        <= (pos[:, None] + offsets[None, :])[:, :, None]
    )[:, None, None, :, :]  # [S, 1, 1, K, L]
    for i in range(spec.depth):
        p = params[f"block{i + 1}"]
        q, k, v = _block_qkv(p, x, H, Dh, H_kv)
        cache = _write_kv_rows(cache, i, k, v, wstart)
        # Full [S, L] float views: dequantized if int8, gathered
        # through the page tables if paged (_full_kv) — the verify
        # math itself is cache-layout-blind.
        kf, vf = _full_kv(cache, i)
        qg = q.reshape(S, K, H_kv, G, Dh)
        logits = (
            jnp.einsum(
                "bqkgd,blkd->bkgql",
                qg.astype(jnp.float32),
                kf.astype(jnp.float32),
            )
            * Dh**-0.5
        )  # [S, H_kv, G, K, L]
        logits = jnp.where(live, logits, -jnp.inf)
        w = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum(
            "bkgql,blkd->bqkgd", w, vf.astype(jnp.float32)
        )
        attn = attn.reshape(S, K, spec.d_model).astype(x.dtype)
        x = _block_finish(spec, p, x, attn)
    x = _layer_norm(x, params["ln_final"])
    out_logits = (x @ embed.T.astype(jnp.float32)).astype(jnp.float32)
    target = sample_slot_tokens_block(
        out_logits, seeds, steps, temps, top_ps
    )  # [S, K]
    # Leading exact matches: cumprod turns the first mismatch into a
    # permanent zero, so the sum is the accepted-prefix length.
    matched = (
        jnp.cumprod((target == drafts).astype(jnp.int32), axis=1)
        .sum(axis=1)
        .astype(jnp.int32)
    )  # [S], 0..K
    n_emit = jnp.minimum(matched + 1, K)
    next_toks = jnp.take_along_axis(
        target, jnp.minimum(matched, K - 1)[:, None], axis=1
    )[:, 0]
    return (
        next_toks,
        cache._replace(pos=jnp.minimum(pos + n_emit, spec.total_len)),
        steps + n_emit,
        target,
        matched,
    )


def prefill_chunk(
    spec: LMSpec,
    params: Any,
    cache: SlotCache,
    toks: jax.Array,
    seeds: jax.Array,
    steps: jax.Array,
    temps: jax.Array,
    top_ps: jax.Array,
    slot: jax.Array,
    chunk: jax.Array,
    start: jax.Array,
    length: jax.Array,
    final: jax.Array,
    seed: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    *,
    lane_attend: bool = True,
) -> tuple[SlotCache, jax.Array, jax.Array, jax.Array, jax.Array,
           jax.Array, jax.Array]:
    """Ingest ONE chunk of a prompt into a cache lane, in place.

    The Sarathi-style stall-free replacement for monolithic
    ``prefill_slot`` + ``write_slot``: a long prompt is split into
    fixed-width chunks, each co-scheduled with decode steps so running
    lanes never wait behind a full-width prefill. Per chunk:

    - ``chunk``: [C] int32 — prompt tokens for absolute positions
      [start, start + length), arbitrary padding after ``length``. C is
      the compiled width (one program per bucketed width); ``start``/
      ``length`` are traced, so chunk position never recompiles.
    - K/V for all C positions are written into lane ``slot`` of the
      DONATED ``cache`` first; attention then runs the C queries
      against their causal prefix. ``lane_attend`` (PYTHON-static —
      the engine compiles both variants) picks the key source: True
      reads the full lane under the banded mask ``key <= start + i``
      (``dot_product_attention(..., q_offset=start)``) — write-then-
      attend, continuation chunks see earlier chunks' cache lines;
      False attends the chunk against ITSELF (plain causal square),
      correct exactly when ``start == 0`` and C ≥ the whole prompt —
      the single-chunk fast path that keeps short prompts at
      monolithic-prefill cost instead of total_len-wide reads. Pad
      positions (>= length) write garbage ABOVE the lane's live
      region; the decode loop overwrites each line before it becomes
      attendable (the same invariant ``write_slot`` relied on).
    - The lane's ``pos`` is set to ``start + length`` — which also
      repairs the spurious ``pos`` advance idle-shape decode steps
      apply to mid-prefill lanes between chunks.
    - The lane's SAMPLING state is installed on device: ``seeds``/
      ``temps``/``top_ps`` take the request's scalars at ``slot``, and
      ``steps`` becomes 1 on the final chunk (the next decode samples
      emitted-token index 1) — so the engine never re-uploads
      per-slot sampling arrays on the steady-state path.
    - When ``final`` (traced bool) the request's FIRST token is
      sampled on device (``sample_token`` at step 0) and spliced into
      ``toks`` at ``slot``, so the refilled lane joins the very next
      decode step without any host round-trip.

    Returns ``(cache, toks, seeds, steps, temps, top_ps, first_token)``
    — ``first_token`` is the sampled scalar (0 unless ``final``; the
    whole logits/sampling tail sits behind a ``final`` branch),
    exposed so the engine can fetch the value asynchronously for the
    completion record.
    """
    C = chunk.shape[0]
    H = spec.num_heads
    Dh = spec.d_model // H
    H_kv = _kv_heads(spec)
    G = H // H_kv
    embed = params["embed"]
    x = embed[chunk][None]  # [1, C, d]
    pe = lax.dynamic_slice_in_dim(
        params["pos_embed"], start, C, axis=1
    )
    x = x + pe.astype(x.dtype)
    quantized = cache.quantized()
    paged = isinstance(cache, PagedSlotCache)
    ck, cv = cache.k, cache.v
    ksc, vsc = cache.k_scale, cache.v_scale
    if paged:
        # One lane's table row + this chunk's scatter coordinates,
        # computed once outside the layer loop: positions
        # [start, start + C) map through the row to (page id, offset)
        # pairs. The engine's min_bucket clamp keeps start + C <=
        # total_len (the tail-chunk invariant), so the only
        # non-private targets are pad positions past the lane's
        # demand — those rows land in whatever the table says (their
        # page, or scratch page 0) above the live region, overwritten
        # before they become attendable exactly like fixed-lane pads.
        row = lax.dynamic_index_in_dim(
            cache.table, slot, 0, keepdims=False
        )  # [lane_pages] int32
        pids, offs = _page_scatter_ids(
            row, start + jnp.arange(C, dtype=jnp.int32),
            cache.page_size, cache.num_pages,
        )
    for i in range(spec.depth):
        p = params[f"block{i + 1}"]
        q, k, v = _block_qkv(p, x, H, Dh, H_kv)
        if quantized:
            # Quantize-on-write (ops/decode.quantize_kv): the cache
            # only ever holds int8 rows + per-head scales — chunked
            # prefill is the bulk write path, so this is where the
            # cache-bytes halving is actually earned.
            wk, k_s = quantize_kv(k)
            wv, v_s = quantize_kv(v)
            if paged:
                ksc = ksc.at[i, pids, offs].set(k_s[0])
                vsc = vsc.at[i, pids, offs].set(v_s[0])
            else:
                ksc = lax.dynamic_update_slice(
                    ksc, k_s[:, None], (i, slot, start, 0)
                )
                vsc = lax.dynamic_update_slice(
                    vsc, v_s[:, None], (i, slot, start, 0)
                )
        else:
            wk, wv = k.astype(ck.dtype), v.astype(cv.dtype)
        if paged:
            ck = ck.at[i, pids, offs].set(wk[0])
            cv = cv.at[i, pids, offs].set(wv[0])
        else:
            ck = lax.dynamic_update_slice(
                ck, wk[:, None], (i, slot, start, 0, 0)
            )
            cv = lax.dynamic_update_slice(
                cv, wv[:, None], (i, slot, start, 0, 0)
            )
        if lane_attend:
            if paged:
                # The lane's logical [L] view is its table row's
                # gather — write-then-attend, so a continuation chunk
                # sees both the matched PREFIX pages (the hit's whole
                # point: those tokens were never prefilled here) and
                # this chunk's freshly scattered rows.
                lane_k = jnp.take(ck[i], row, axis=0)
                lane_k = lane_k.reshape(-1, *lane_k.shape[2:])
                lane_v = jnp.take(cv[i], row, axis=0)
                lane_v = lane_v.reshape(-1, *lane_v.shape[2:])
                if quantized:
                    sck = jnp.take(ksc[i], row, axis=0)
                    scv = jnp.take(vsc[i], row, axis=0)
                    lane_k = dequantize_kv(
                        lane_k, sck.reshape(-1, sck.shape[2])
                    )
                    lane_v = dequantize_kv(
                        lane_v, scv.reshape(-1, scv.shape[2])
                    )
            else:
                lane_k = lax.dynamic_index_in_dim(
                    ck[i], slot, axis=0, keepdims=False
                )
                lane_v = lax.dynamic_index_in_dim(
                    cv[i], slot, axis=0, keepdims=False
                )
                if quantized:
                    lane_k = dequantize_kv(
                        lane_k,
                        lax.dynamic_index_in_dim(
                            ksc[i], slot, axis=0, keepdims=False
                        ),
                    )
                    lane_v = dequantize_kv(
                        lane_v,
                        lax.dynamic_index_in_dim(
                            vsc[i], slot, axis=0, keepdims=False
                        ),
                    )
            attn = dot_product_attention(
                q.astype(jnp.float32),
                jnp.repeat(lane_k, G, axis=1)[None].astype(jnp.float32),
                jnp.repeat(lane_v, G, axis=1)[None].astype(jnp.float32),
                causal=True,
                q_offset=start,
            )
        else:
            attn = dot_product_attention(
                q.astype(jnp.float32),
                jnp.repeat(k, G, axis=2).astype(jnp.float32),
                jnp.repeat(v, G, axis=2).astype(jnp.float32),
                causal=True,
            )
        attn = attn.reshape(1, C, spec.d_model).astype(x.dtype)
        x = _block_finish(spec, p, x, attn)
    def _sample_first(_):
        # Only the FINAL chunk owes a token: the last-position layer
        # norm, the [d]×[vocab] logits projection and the sampling
        # draw sit behind a real branch (scalar cond) so every
        # non-final chunk of a long prompt skips them entirely.
        xt = lax.dynamic_slice_in_dim(x, length - 1, 1, axis=1)
        xt = _layer_norm(xt, params["ln_final"])
        logits = (
            xt[0, 0] @ embed.T.astype(jnp.float32)
        ).astype(jnp.float32)
        tok = sample_token(
            logits, seed, jnp.int32(0), temperature, top_p
        )
        return lax.dynamic_update_slice(toks, tok[None], (slot,)), tok

    new_toks, first = lax.cond(
        final, _sample_first, lambda _: (toks, jnp.int32(0)),
        operand=None,
    )
    new_pos = lax.dynamic_update_slice(
        cache.pos, (start + length)[None].astype(jnp.int32), (slot,)
    )
    put = lax.dynamic_update_slice
    seeds = put(seeds, seed[None].astype(seeds.dtype), (slot,))
    steps = put(
        steps,
        jnp.where(final, jnp.int32(1), jnp.int32(0))[None],
        (slot,),
    )
    temps = put(temps, temperature[None].astype(temps.dtype), (slot,))
    top_ps = put(top_ps, top_p[None].astype(top_ps.dtype), (slot,))
    return (
        # _replace keeps the cache KIND: the paged pytree carries its
        # table through untouched (tables only change at the engine's
        # bind/retire host events, never inside a program).
        cache._replace(
            k=ck, v=cv, pos=new_pos, k_scale=ksc, v_scale=vsc
        ),
        new_toks, seeds, steps, temps, top_ps, first,
    )


def cached_logits(
    spec: LMSpec, params: Any, tokens: jax.Array
) -> jax.Array:
    """Per-position logits via the cache — [B, T, vocab].

    The parity probe: must equal ``dense_lm_apply(spec, params,
    tokens)`` (full-sequence forward) to fp32 tolerance.
    """
    cache = init_cache(spec, tokens.shape[0])

    def step(cache, tok):
        logits, cache = decode_step(spec, params, cache, tok)
        return cache, logits

    _, all_logits = lax.scan(step, cache, tokens.T)
    return all_logits.transpose(1, 0, 2)

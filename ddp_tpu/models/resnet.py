"""ResNet family (NHWC, Flax) — the driver's deeper-conv extension configs.

BASELINE.json configs 3 and 5: "CIFAR-10 ResNet-18 (swap model.py/data.py
— deeper conv stack)" and "ImageNet-1k ResNet-50 on v4-32 multi-host".
The reference itself ships only SimpleCNN (/root/reference/model.py:4-20);
these are the models its README-level 'tweaks' section imagines swapping
in, built TPU-first:

- NHWC layout throughout (TPU conv layout; torchvision is NCHW);
- BatchNorm running statistics live in the ``batch_stats`` collection
  and ride ``TrainState.model_state``; the DDP step averages them
  across replicas each step (SyncBN semantics — stricter than torch
  DDP's per-rank stats);
- the CIFAR variant uses the standard 3×3/stride-1 stem with no
  max-pool (32×32 inputs would otherwise collapse before stage 1);
- He-normal conv init, zero-init for the final BN scale in each
  residual branch (the standard "zero-gamma" trick), matching
  torchvision's defaults in function if not in RNG stream.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any

_conv = partial(
    nn.Conv, use_bias=False, kernel_init=nn.initializers.he_normal()
)


class BasicBlock(nn.Module):
    """2×(3×3 conv) residual block — ResNet-18/34."""

    features: int
    strides: int = 1
    norm: ModuleDef = None

    @nn.compact
    def __call__(self, x):
        residual = x
        y = _conv(self.features, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = _conv(self.features, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = _conv(
                self.features, (1, 1), strides=(self.strides, self.strides),
                name="downsample",
            )(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1×1 → 3×3 → 1×1 residual block (4× expansion) — ResNet-50/101/152."""

    features: int
    strides: int = 1
    norm: ModuleDef = None

    @nn.compact
    def __call__(self, x):
        residual = x
        out = self.features * 4
        y = _conv(self.features, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = _conv(self.features, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = _conv(out, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = _conv(
                out, (1, 1), strides=(self.strides, self.strides),
                name="downsample",
            )(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Stage-configurable ResNet with ImageNet or CIFAR stem."""

    stage_sizes: Sequence[int]
    block: Callable  # BasicBlock | BottleneckBlock
    num_classes: int = 1000
    width: int = 64
    cifar_stem: bool = False  # 3×3/1 stem, no pool (32×32 inputs)
    # jax.checkpoint each residual block: recompute activations in the
    # backward instead of storing them — HBM for FLOPs (see models/vit.py).
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=x.dtype,
        )
        if self.cifar_stem:
            x = _conv(self.width, (3, 3), name="stem_conv")(x)
        else:
            x = _conv(self.width, (7, 7), strides=(2, 2), name="stem_conv")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        if not self.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block_cls = nn.remat(self.block) if self.remat else self.block
        for stage, num_blocks in enumerate(self.stage_sizes):
            for block_idx in range(num_blocks):
                strides = 2 if stage > 0 and block_idx == 0 else 1
                x = block_cls(
                    features=self.width * 2**stage,
                    strides=strides,
                    norm=norm,
                    name=f"stage{stage + 1}_block{block_idx + 1}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, name="fc", dtype=jnp.float32)(x)
        return x


def ResNet18(
    num_classes: int = 10, cifar_stem: bool = True, remat: bool = False
) -> ResNet:
    return ResNet(
        stage_sizes=(2, 2, 2, 2),
        block=BasicBlock,
        num_classes=num_classes,
        cifar_stem=cifar_stem,
        remat=remat,
    )


def ResNet34(
    num_classes: int = 1000, cifar_stem: bool = False, remat: bool = False
) -> ResNet:
    return ResNet(
        stage_sizes=(3, 4, 6, 3),
        block=BasicBlock,
        num_classes=num_classes,
        cifar_stem=cifar_stem,
        remat=remat,
    )


def ResNet50(
    num_classes: int = 1000, cifar_stem: bool = False, remat: bool = False
) -> ResNet:
    return ResNet(
        stage_sizes=(3, 4, 6, 3),
        block=BottleneckBlock,
        num_classes=num_classes,
        cifar_stem=cifar_stem,
        remat=remat,
    )

"""Decoder-only causal language model with sequence parallelism.

The reference is a vision classifier (model.py:4-20); this is the
framework's demonstration that its long-context machinery carries a
*language-model* workload: causal ring/Ulysses attention
(parallel/ring.py, global triangular mask exact across shard
boundaries), tokens sharded over the ``seq`` mesh axis end to end, and
a next-token loss whose label shift happens on the global sequence
before sharding — so the shard-boundary token's label (the NEXT
shard's first token) is correct by construction.

Layout: token embedding → learned position embedding → pre-LN causal
blocks (models/vit.py EncoderBlock with a causal attention_fn) → final
LN → logits through the TIED embedding transpose (the standard
weight-tying trick; halves the embedding parameters).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.models.vit import EncoderBlock
from ddp_tpu.ops.attention import best_attention
from ddp_tpu.parallel.ddp import StepMetrics
from ddp_tpu.parallel.ring import sequence_sharded_attention


class CausalLM(nn.Module):
    """[B, T_local] int32 tokens → [B, T_local, vocab] fp32 logits.

    ``num_experts > 0`` makes every ``moe_every``-th block a routed
    MoE block (models/moe.py MoEEncoderBlock — GShard top-k with
    capacity); the load-balance aux losses land in the ``losses``
    collection when it is marked mutable. Under sequence parallelism
    each token shard routes independently (standard local routing —
    the router never sees remote tokens).
    """

    vocab_size: int
    total_len: int
    d_model: int = 64
    depth: int = 2
    num_heads: int = 4
    mlp_ratio: int = 4
    # None → ops.attention.best_attention(causal=True): size-
    # dispatched — flash kernel on TPU past FLASH_MIN_LEN, dense
    # XLA otherwise.
    attention_fn: Optional[Callable] = None
    num_experts: int = 0  # 0 = dense MLPs everywhere
    moe_every: int = 2
    # Routing config for the MoE blocks (models/moe.py MoEMLP): how
    # many experts each token visits, and whether the surviving gates
    # are renormalized to sum to 1. Threaded from LMSpec so the decode
    # path (models/generate.py) can reproduce the training routing
    # instead of assuming the defaults.
    moe_top_k: int = 2
    moe_normalize_gates: bool = True
    remat: bool = False
    # Megatron TP over the ``model`` mesh axis (shard_map-only):
    # attention heads + MLP hidden shard, embeddings/LNs/tied head
    # replicate (parallel/tp.py). Routed blocks shard their ATTENTION
    # over ``model`` too (round 5 — Megatron-MoE); their expert MLPs
    # replicate across ``model`` and shard over ``expert`` instead.
    tp_axis: Optional[str] = None
    tp_size: int = 1
    # Expert parallelism over the ``expert`` mesh axis (shard_map-only):
    # each member holds num_experts/ep_size experts, tokens all-to-all
    # to their expert's owner and back (models/moe.py MoEMLP).
    ep_axis: Optional[str] = None
    ep_size: int = 1
    num_kv_heads: int = 0  # GQA — see models/vit.py MultiHeadAttention

    @nn.compact
    def __call__(self, tokens, pos_offset=0):
        embed = self.param(
            "embed",
            nn.initializers.normal(stddev=0.02),
            (self.vocab_size, self.d_model),
        )
        x = embed[tokens]  # [B, T_local, d]
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, self.total_len, self.d_model),
        )
        x = x + lax.dynamic_slice_in_dim(
            pos.astype(x.dtype), pos_offset, x.shape[1], axis=1
        )
        from ddp_tpu.models.moe import MoEEncoderBlock, is_moe_block

        block_cls = nn.remat(EncoderBlock) if self.remat else EncoderBlock
        moe_cls = (
            nn.remat(MoEEncoderBlock) if self.remat else MoEEncoderBlock
        )
        attn_fn = self.attention_fn or best_attention(causal=True)
        for i in range(self.depth):
            if is_moe_block(i, self.num_experts, self.moe_every):
                x = moe_cls(
                    num_heads=self.num_heads,
                    mlp_dim=self.d_model * self.mlp_ratio,
                    num_experts=self.num_experts,
                    top_k=self.moe_top_k,
                    normalize_gates=self.moe_normalize_gates,
                    attention_fn=attn_fn,
                    ep_axis=self.ep_axis,
                    ep_size=self.ep_size,
                    num_kv_heads=self.num_kv_heads,
                    tp_axis=self.tp_axis,
                    tp_size=self.tp_size,
                    name=f"block{i + 1}",
                )(x)
            else:
                x = block_cls(
                    num_heads=self.num_heads,
                    mlp_dim=self.d_model * self.mlp_ratio,
                    attention_fn=attn_fn,
                    tp_axis=self.tp_axis,
                    tp_size=self.tp_size,
                    num_kv_heads=self.num_kv_heads,
                    name=f"block{i + 1}",
                )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        # Tied head: logits through the embedding transpose.
        return (x @ embed.T.astype(x.dtype)).astype(jnp.float32)


class LMSpec(NamedTuple):
    vocab_size: int
    total_len: int
    d_model: int = 64
    depth: int = 2
    num_heads: int = 4
    strategy: str = "ring"  # ring | ulysses
    remat: bool = False
    num_experts: int = 0  # >0: MoE MLPs every moe_every-th block
    moe_every: int = 2
    aux_loss_weight: float = 0.01  # GShard load-balance loss weight
    # MoE routing config (round-5 ADVICE: decode hardcoded top_k=2 and
    # always-normalized gates — now derived from the spec, and recorded
    # in the lm_spec.json checkpoint sidecar so serving recovers it).
    moe_top_k: int = 2
    moe_normalize_gates: bool = True
    # Grouped-query attention: 0 → num_heads (MHA). The generation
    # cache stores the COMPACT num_kv_heads (models/generate.py), so
    # decode HBM reads shrink by num_heads/num_kv_heads.
    num_kv_heads: int = 0
    mlp_ratio: int = 4


def derive_lm_spec(params: Any, *, num_heads: int, **overrides) -> LMSpec:
    """Recover an LMSpec from a restored parameter tree.

    vocab_size, total_len, d_model, depth and the GQA kv-head count
    are all visible in the shapes (embed [V, d], pos_embed [1, L, d],
    blockN count, qkv kernel columns (H + 2·H_kv)·Dh); only the head
    count is not, so it is an argument. ``overrides`` lets a
    checkpoint-sidecar config (train/checkpoint.py save_lm_spec) fill
    the fields shapes cannot carry — MoE routing (moe_top_k,
    moe_normalize_gates), strategy — and wins over the derivation.
    Raises ValueError when the tree is not a causal-LM tree or the
    head count does not explain the shapes.
    """
    try:
        vocab_size, d_model = (int(s) for s in params["embed"].shape)
        total_len = int(params["pos_embed"].shape[1])
        depth = sum(1 for k in params if str(k).startswith("block"))
        qkv_cols = int(params["block1"]["attn"]["qkv"]["kernel"].shape[-1])
    except (KeyError, TypeError, AttributeError) as e:
        raise ValueError(f"not a causal-LM parameter tree (missing {e})")
    if d_model % num_heads:
        raise ValueError(
            f"num_heads {num_heads} does not divide d_model {d_model}"
        )
    head_dim = d_model // num_heads
    num_kv_heads = (qkv_cols // head_dim - num_heads) // 2
    if (num_kv_heads * 2 + num_heads) * head_dim != qkv_cols:
        raise ValueError(
            f"qkv kernel has {qkv_cols} columns, which no kv-head "
            f"count explains at num_heads {num_heads} — wrong head "
            "count?"
        )
    fields = dict(
        vocab_size=vocab_size,
        total_len=total_len,
        d_model=d_model,
        depth=depth,
        num_heads=num_heads,
        num_kv_heads=0 if num_kv_heads == num_heads else num_kv_heads,
    )
    # Shape-derived fields win: the checkpoint is ground truth, a
    # sidecar can only add what shapes cannot see.
    fields.update(
        (k, v)
        for k, v in overrides.items()
        if k in LMSpec._fields and k not in fields
    )
    return LMSpec(**fields)


def _dense_lm(spec: LMSpec) -> CausalLM:
    return CausalLM(
        vocab_size=spec.vocab_size,
        total_len=spec.total_len,
        d_model=spec.d_model,
        depth=spec.depth,
        num_heads=spec.num_heads,
        num_experts=spec.num_experts,
        moe_every=spec.moe_every,
        moe_top_k=spec.moe_top_k,
        moe_normalize_gates=spec.moe_normalize_gates,
        remat=spec.remat,
        num_kv_heads=spec.num_kv_heads,
        mlp_ratio=spec.mlp_ratio,
    )


def _sharded_lm(
    spec: LMSpec, *, tp_size: int = 1, ep_size: int = 1
) -> CausalLM:
    def attention(q, k, v):
        return sequence_sharded_attention(
            q, k, v, axis_name="seq", strategy=spec.strategy, causal=True
        )

    return CausalLM(
        vocab_size=spec.vocab_size,
        total_len=spec.total_len,
        d_model=spec.d_model,
        depth=spec.depth,
        num_heads=spec.num_heads,
        attention_fn=attention,
        num_experts=spec.num_experts,
        moe_every=spec.moe_every,
        moe_top_k=spec.moe_top_k,
        moe_normalize_gates=spec.moe_normalize_gates,
        remat=spec.remat,
        tp_axis="model" if tp_size > 1 else None,
        tp_size=tp_size,
        ep_axis="expert" if ep_size > 1 else None,
        ep_size=ep_size,
        num_kv_heads=spec.num_kv_heads,
        mlp_ratio=spec.mlp_ratio,
    )


def init_lm(spec: LMSpec, *, seed: int = 0):
    """Params from a short stub — every shape is length-independent."""
    stub = min(spec.total_len, 128)
    return _dense_lm(spec).init(
        jax.random.key(seed), jnp.zeros((1, stub), jnp.int32)
    )["params"]


def dense_lm_apply(spec: LMSpec, params, tokens):
    """Single-device reference forward over the full sequence."""
    return _dense_lm(spec).apply({"params": params}, tokens)


def next_token_loss(logits, tokens, *, label_smoothing: float = 0.0):
    """Mean causal-LM loss: position t predicts token t+1.

    ``logits``/``tokens`` are GLOBAL ([B, T, V] / [B, T]); the final
    position has no target and is masked out. ``label_smoothing=ε``
    trains against (1−ε)·one-hot + ε·uniform, computed directly from
    log-probs (no [B, T, V] one-hot materialized).
    """
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1
    )
    weights = jnp.concatenate(
        [
            jnp.ones(tokens[:, 1:].shape, jnp.float32),
            jnp.zeros(tokens[:, :1].shape, jnp.float32),
        ],
        axis=1,
    )
    logits32 = logits.astype(jnp.float32)
    per_tok = _per_token_nll(logits32, targets, label_smoothing)
    return (per_tok * weights).sum() / weights.sum()


# One step/params/opt_state state shape serves every sequence-model
# family (models/seq_transformer.py defines it + the replication
# factory — uniform shardings on every leaf).
from ddp_tpu.models.seq_transformer import (  # noqa: E402
    SeqTrainState as LMTrainState,
    replicated_train_state,
)


def create_lm_train_state(
    spec: LMSpec,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    seed: int = 0,
    zero_layout=None,
    zero_gather_dtype=None,
) -> LMTrainState:
    """Replicated state, or fsdp-sharded at rest when the mesh has an
    ``fsdp`` axis > 1 (parallel/seq_fsdp.py — moments shard with the
    params, so optimizer memory drops by the axis size too).

    ``zero_layout`` (parallel/zero.py BucketLayout) is the ZeRO
    weight-update sharding variant: params replicate as usual but the
    optimizer state rests as flat fp32 buckets sharded 1/N over
    ``data`` — the layout ``make_lm_train_step(..., zero_layout=)``
    updates in place. ``zero_gather_dtype='bf16'`` adds the fp32
    master shards the half-width gather keeps exact (parallel/zero.py
    module docstring).
    """
    from ddp_tpu.models.seq_transformer import sharded_or_replicated_state

    if zero_layout is not None:
        from ddp_tpu.parallel.zero import create_zero_opt_state

        rep = NamedSharding(mesh, P())
        params = jax.tree.map(
            lambda x: jax.device_put(x, rep), init_lm(spec, seed=seed)
        )
        return LMTrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32), rep),
            params=params,
            opt_state=create_zero_opt_state(
                params, optimizer, mesh, zero_layout,
                gather_dtype=zero_gather_dtype or jnp.float32,
            ),
        )
    return sharded_or_replicated_state(
        init_lm(spec, seed=seed), optimizer, mesh
    )


def _make_sharded_forward(spec: LMSpec, mesh: Mesh, compute_dtype):
    from ddp_tpu.models.seq_transformer import _batch_axes
    from ddp_tpu.parallel.tp import (
        ep_size as mesh_ep_size,
        gather_sharded,
        seq_param_specs,
        tp_size as mesh_tp_size,
    )

    model = _sharded_lm(
        spec, tp_size=mesh_tp_size(mesh), ep_size=mesh_ep_size(mesh)
    )
    baxes = _batch_axes(mesh)
    xspec = P(baxes, "seq")

    def forward(params, tokens, want_aux: bool = True):
        """→ (logits sharded like the tokens, replicated MoE aux loss
        scalar — 0.0 for dense specs or ``want_aux=False``, which also
        skips the aux collection and its cross-device mean: eval has
        no use for the routing penalty)."""
        pspecs = seq_param_specs(params, mesh)
        collect_aux = bool(spec.num_experts) and want_aux

        def per_shard_forward(params, tok_shard):
            params = gather_sharded(params, pspecs)
            t_local = tok_shard.shape[1]
            offset = lax.axis_index("seq") * t_local
            if compute_dtype != jnp.float32:
                params = jax.tree.map(
                    lambda p: p.astype(compute_dtype), params
                )
            if collect_aux:
                logits, variables = model.apply(
                    {"params": params}, tok_shard, pos_offset=offset,
                    mutable=["losses"],
                )
                leaves = jax.tree.leaves(variables.get("losses", {}))
                aux = (
                    sum(leaves) / len(leaves) if leaves else jnp.float32(0.0)
                )
                # Replicate: each shard routed its own tokens; the
                # batch aux is the mean over every shard's groups.
                aux = lax.pmean(aux, mesh.axis_names)
            else:
                logits = model.apply(
                    {"params": params}, tok_shard, pos_offset=offset
                )
                aux = jnp.float32(0.0)
            return logits, aux

        return jax.shard_map(
            per_shard_forward,
            mesh=mesh,
            in_specs=(pspecs, xspec),
            out_specs=(xspec, P()),
            check_vma=False,
        )(params, tokens)

    return forward, xspec


def _per_token_nll(logits32, targets, label_smoothing: float):
    """[B, T] next-token NLL from fp32 logits (shared CE math)."""
    if label_smoothing:
        eps = label_smoothing
        logp = jax.nn.log_softmax(logits32, axis=-1)
        nll_target = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
        return (1.0 - eps) * nll_target - (
            eps / logits32.shape[-1]
        ) * logp.sum(-1)
    return optax.softmax_cross_entropy_with_integer_labels(logits32, targets)


def _make_sharded_token_metrics(
    spec: LMSpec, mesh: Mesh, *, label_smoothing: float = 0.0
):
    """Next-token (loss, correct-count) computed INSIDE shard_map.

    The train/eval steps used to run the CE + argmax on the GLOBAL
    [B, T, V] logits the forward shard_map returns, leaving the jit
    partitioner to reshard them. On jax 0.4.x CPU that miscompiles
    once the mesh has both ``model`` and ``seq`` axes (values half-
    wrong or NaN for ops fused downstream of the multi-axis shard_map
    — measured round 6). Keeping every consumer of the sharded logits
    inside shard_map sidesteps the partitioner entirely, and is the
    TPU-native shape anyway: no global reshard of train-scale logits,
    each shard reduces its own tokens, one psum carries scalars.

    The global label shift becomes a ring exchange: shard s's last
    local position targets shard s+1's first token (``ppermute``); the
    very last global position is weight-0, exactly as in
    ``next_token_loss``. Returns ``(mean loss, correct count)``
    replicated; weights sum to B·(T−1).
    """
    from ddp_tpu.models.seq_transformer import _batch_axes

    baxes = _batch_axes(mesh)
    xspec = P(baxes, "seq")
    n_seq = mesh.shape.get("seq", 1)
    red_axes = tuple(baxes or ()) + (("seq",) if n_seq > 1 else ())
    # model/expert members hold identical logits copies, so pmean over
    # them is an identity — but it is what lets the jax-0.4.x shard_map
    # transpose treat the P() scalar outputs as replicated (same reason
    # the forward's aux output pmeans over every mesh axis).
    rep_axes = tuple(a for a in mesh.axis_names if a not in red_axes)

    def body(logits, tok_shard):
        T_l = tok_shard.shape[1]
        if n_seq > 1:
            nxt = lax.ppermute(
                tok_shard[:, :1],
                "seq",
                perm=[(k, (k - 1) % n_seq) for k in range(n_seq)],
            )
            on_last_shard = lax.axis_index("seq") == n_seq - 1
        else:
            nxt = jnp.zeros_like(tok_shard[:, :1])
            on_last_shard = jnp.bool_(True)
        targets = jnp.concatenate([tok_shard[:, 1:], nxt], axis=1)
        weights = jnp.where(
            (jnp.arange(T_l) == T_l - 1) & on_last_shard, 0.0, 1.0
        )[None, :].astype(jnp.float32)  # [1, T_l], broadcasts over B
        logits32 = logits.astype(jnp.float32)
        per_tok = _per_token_nll(logits32, targets, label_smoothing)
        loss_sum = (per_tok * weights).sum()
        pred = jnp.argmax(logits32, -1)
        correct = ((pred == targets).astype(jnp.float32) * weights).sum()
        if red_axes:
            loss_sum, correct = lax.psum((loss_sum, correct), red_axes)
        # The weight total is static — B_global·(T_global−1) — so divide
        # by the Python constant: a TRACED w_sum would become a scalar
        # residual with a nonzero cotangent, which the jax-0.4.x
        # shard_map transpose cannot express (rank-0 aval with
        # all-axes out names → _SpecError).
        b_global = tok_shard.shape[0]
        for a in baxes or ():
            b_global *= mesh.shape[a]
        loss = loss_sum / (b_global * (T_l * n_seq - 1))
        if rep_axes:
            loss, correct = lax.pmean((loss, correct), rep_axes)
        return loss, correct

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(xspec, xspec),
        out_specs=(P(), P()),
        check_vma=False,
    )


def make_lm_eval_step(
    spec: LMSpec, mesh: Mesh, *, compute_dtype=jnp.float32
):
    """Trainer-compatible eval: next-token metrics over held-out tokens.

    Signature matches the classifier eval steps —
    ``(params, model_state, tokens, labels, weights) →
    (weighted Σ per-sequence token accuracy, weighted Σ per-sequence
    mean loss)`` — so ``Trainer.evaluate`` divides by n and reports
    average next-token accuracy where classifiers report top-1.
    ``labels`` is ignored (targets are the shifted tokens themselves).
    """
    sharded_forward, _ = _make_sharded_forward(spec, mesh, compute_dtype)
    seq_metrics = _make_sharded_seq_metrics(spec, mesh)

    def step(params, model_state, tokens, labels, weights):
        del model_state, labels
        logits, _ = sharded_forward(params, tokens, want_aux=False)
        return seq_metrics(logits, tokens, weights)

    return jax.jit(step)


def _make_sharded_seq_metrics(spec: LMSpec, mesh: Mesh):
    """Eval-side sibling of ``_make_sharded_token_metrics``: weighted
    Σ per-sequence accuracy and per-sequence mean loss, with the CE /
    argmax kept inside shard_map for the same jax-0.4.x partitioner
    reason. Per-sequence sums psum over ``seq``; the weighted batch
    sums psum over the batch axes."""
    from ddp_tpu.models.seq_transformer import _batch_axes

    baxes = _batch_axes(mesh)
    xspec = P(baxes, "seq")
    n_seq = mesh.shape.get("seq", 1)
    red_axes = tuple(baxes or ()) + (("seq",) if n_seq > 1 else ())
    rep_axes = tuple(a for a in mesh.axis_names if a not in red_axes)

    def body(logits, tok_shard, w_shard):
        T_l = tok_shard.shape[1]
        if n_seq > 1:
            nxt = lax.ppermute(
                tok_shard[:, :1],
                "seq",
                perm=[(k, (k - 1) % n_seq) for k in range(n_seq)],
            )
            on_last_shard = lax.axis_index("seq") == n_seq - 1
        else:
            nxt = jnp.zeros_like(tok_shard[:, :1])
            on_last_shard = jnp.bool_(True)
        targets = jnp.concatenate([tok_shard[:, 1:], nxt], axis=1)
        mask = jnp.where(
            (jnp.arange(T_l) == T_l - 1) & on_last_shard, 0.0, 1.0
        )[None, :].astype(jnp.float32)
        logits32 = logits.astype(jnp.float32)
        per_tok = _per_token_nll(logits32, targets, 0.0)
        pred = jnp.argmax(logits32, -1)
        seq_loss = (per_tok * mask).sum(axis=1)  # [B_l]
        seq_correct = ((pred == targets).astype(jnp.float32) * mask).sum(1)
        if n_seq > 1:
            seq_loss, seq_correct = lax.psum(
                (seq_loss, seq_correct), "seq"
            )
        denom = T_l * n_seq - 1  # targets per sequence
        acc_sum = (seq_correct / denom * w_shard).sum()
        loss_sum = (seq_loss / denom * w_shard).sum()
        if baxes:
            acc_sum, loss_sum = lax.psum((acc_sum, loss_sum), baxes)
        if rep_axes:
            acc_sum, loss_sum = lax.pmean((acc_sum, loss_sum), rep_axes)
        return acc_sum, loss_sum

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(xspec, xspec, P(baxes)),
        out_specs=(P(), P()),
        check_vma=False,
    )


def make_lm_train_step(
    spec: LMSpec,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    donate: bool = True,
    compute_dtype=jnp.float32,
    grad_accum_steps: int = 1,
    label_smoothing: float = 0.0,
    jit: bool = True,
    health: bool = False,
    health_inject: tuple[str, int] | None = None,
    zero_layout=None,
    zero_gather_dtype=None,
    zero_grad_clip_norm: float = 0.0,
):
    """dp×sp[×fsdp] causal-LM step: ``step(state, tokens)``.

    ``zero_layout`` swaps the replicated weight update for the ZeRO
    in-graph GSPMD expression (parallel/zero.py ``zero_gspmd_update``):
    gradients constrain into data-sharded flat buckets, the optimizer
    runs on 1/N shards with the moments resting sharded, and the SPMD
    partitioner derives the parameter all-gather — composing with
    populated ``model``/``seq`` axes, where the buckets shard over
    ``data`` and replicate over the model axes. Loss/metrics math is
    untouched — trajectories pin against the plain step.
    ``zero_gather_dtype='bf16'`` gathers the updated params half-width
    over fp32 master shards; ``zero_grad_clip_norm`` applies the
    global-norm clip inside the sharded update (the trainer builds the
    optimizer without the chained clip in zero mode).

    ``jit=False`` returns the raw (untraced) step for callers that
    embed it in a larger program — the compiled-epoch runner
    (train/fast.py make_lm_epoch_runner) scans it.

    ``tokens``: [B, T_global] int32. The loss/accuracy math runs
    INSIDE a second shard_map (``_make_sharded_token_metrics`` — label
    shift as a ring exchange, CE reduced per shard, one psum), so the
    jit partitioner never consumes the sharded logits; gradients
    arrive psum'd (and, for fsdp-sharded params, scatter-reduced —
    parallel/seq_fsdp.py) by the shard_map transpose. ``grad_accum_steps=k`` splits the
    batch into k STRIDED microbatches (rows i::k — contiguous splits
    would reshard the data-axis layout every step, parallel/spmd.py)
    and accumulates gradients through one ``lax.scan``. Metrics: loss
    is the mean next-token cross-entropy, accuracy the next-token
    top-1.
    """
    if zero_layout is not None and health:
        # The health stats pass reads the UPDATE tree, which the zero
        # expression only materializes as 1/N flat shards — same wall
        # the Trainer enforces at the flag level.
        raise ValueError(
            "health stats need the full update tree; the zero sharded "
            "update never materializes it — drop health or zero_layout"
        )
    sharded_forward, xspec = _make_sharded_forward(spec, mesh, compute_dtype)
    token_metrics = _make_sharded_token_metrics(
        spec, mesh, label_smoothing=label_smoothing
    )

    def loss_and_logits(params, tokens):
        logits, aux = sharded_forward(params, tokens)
        loss, correct = token_metrics(logits, tokens)
        if spec.num_experts:
            loss = loss + spec.aux_loss_weight * aux
        return loss, correct

    def step(state: LMTrainState, tokens):
        tokens = lax.with_sharding_constraint(
            tokens, NamedSharding(mesh, xspec)
        )
        if grad_accum_steps == 1:
            (loss, correct), grads = jax.value_and_grad(
                loss_and_logits, has_aux=True
            )(state.params, tokens)
        else:
            from ddp_tpu.parallel.common import check_accum_divisible

            mb = check_accum_divisible(tokens.shape[0], grad_accum_steps)
            micro_toks = lax.with_sharding_constraint(
                tokens.reshape(mb, grad_accum_steps, tokens.shape[1])
                .swapaxes(0, 1),
                NamedSharding(mesh, P(None, *xspec)),
            )

            def micro(carry, toks):
                g_acc, loss_acc, correct_acc = carry
                (loss, correct), g = jax.value_and_grad(
                    loss_and_logits, has_aux=True
                )(state.params, toks)
                return (
                    jax.tree.map(jnp.add, g_acc, g),
                    loss_acc + loss,
                    correct_acc + correct,
                ), None

            zero_g = jax.tree.map(jnp.zeros_like, state.params)
            (g_sum, loss_sum, correct), _ = lax.scan(
                micro, (zero_g, jnp.float32(0.0), jnp.float32(0.0)), micro_toks
            )
            grads = jax.tree.map(lambda g: g / grad_accum_steps, g_sum)
            loss = loss_sum / grad_accum_steps
        if health_inject is not None:
            from ddp_tpu.obs.health import inject_nan

            grads = inject_nan(grads, state.step, health_inject)
        if zero_layout is not None:
            from ddp_tpu.parallel.zero import zero_gspmd_update

            params, opt_state = zero_gspmd_update(
                optimizer, zero_layout, mesh, grads,
                state.opt_state, state.params,
                gather_dtype=zero_gather_dtype or jnp.float32,
                grad_clip_norm=zero_grad_clip_norm,
            )
        else:
            updates, opt_state = optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
        accuracy = correct / (tokens.shape[0] * (tokens.shape[1] - 1))
        if health:
            from ddp_tpu.obs.health import health_stats

            hstats = health_stats(grads, state.params, updates)
        else:
            hstats = None
        return (
            state._replace(
                step=state.step + 1, params=params, opt_state=opt_state
            ),
            StepMetrics(
                loss=loss, accuracy=accuracy,
                grad_norm=optax.global_norm(grads),
                health=hstats,
            ),
        )

    if not jit:
        return step
    return jax.jit(step, donate_argnums=(0,) if donate else ())

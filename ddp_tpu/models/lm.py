"""Decoder-only causal language model with sequence parallelism.

The reference is a vision classifier (model.py:4-20); this is the
framework's demonstration that its long-context machinery carries a
*language-model* workload: causal ring/Ulysses attention
(parallel/ring.py, global triangular mask exact across shard
boundaries), tokens sharded over the ``seq`` mesh axis end to end, and
a next-token loss whose label shift happens on the global sequence
before sharding — so the shard-boundary token's label (the NEXT
shard's first token) is correct by construction.

Layout: token embedding → learned position embedding → pre-LN causal
blocks (models/vit.py EncoderBlock with a causal attention_fn) → final
LN → logits through the TIED embedding transpose (the standard
weight-tying trick; halves the embedding parameters).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.models.vit import EncoderBlock
from ddp_tpu.ops.attention import dot_product_attention
from ddp_tpu.parallel.ddp import StepMetrics
from ddp_tpu.parallel.ring import sequence_sharded_attention


class CausalLM(nn.Module):
    """[B, T_local] int32 tokens → [B, T_local, vocab] fp32 logits."""

    vocab_size: int
    total_len: int
    d_model: int = 64
    depth: int = 2
    num_heads: int = 4
    mlp_ratio: int = 4
    attention_fn: Callable = partial(dot_product_attention, causal=True)
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, pos_offset=0):
        embed = self.param(
            "embed",
            nn.initializers.normal(stddev=0.02),
            (self.vocab_size, self.d_model),
        )
        x = embed[tokens]  # [B, T_local, d]
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, self.total_len, self.d_model),
        )
        x = x + lax.dynamic_slice_in_dim(
            pos.astype(x.dtype), pos_offset, x.shape[1], axis=1
        )
        block_cls = nn.remat(EncoderBlock) if self.remat else EncoderBlock
        for i in range(self.depth):
            x = block_cls(
                num_heads=self.num_heads,
                mlp_dim=self.d_model * self.mlp_ratio,
                attention_fn=self.attention_fn,
                name=f"block{i + 1}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        # Tied head: logits through the embedding transpose.
        return (x @ embed.T.astype(x.dtype)).astype(jnp.float32)


class LMSpec(NamedTuple):
    vocab_size: int
    total_len: int
    d_model: int = 64
    depth: int = 2
    num_heads: int = 4
    strategy: str = "ring"  # ring | ulysses
    remat: bool = False


def _dense_lm(spec: LMSpec) -> CausalLM:
    return CausalLM(
        vocab_size=spec.vocab_size,
        total_len=spec.total_len,
        d_model=spec.d_model,
        depth=spec.depth,
        num_heads=spec.num_heads,
        remat=spec.remat,
    )


def _sharded_lm(spec: LMSpec) -> CausalLM:
    def attention(q, k, v):
        return sequence_sharded_attention(
            q, k, v, axis_name="seq", strategy=spec.strategy, causal=True
        )

    return CausalLM(
        vocab_size=spec.vocab_size,
        total_len=spec.total_len,
        d_model=spec.d_model,
        depth=spec.depth,
        num_heads=spec.num_heads,
        attention_fn=attention,
        remat=spec.remat,
    )


def init_lm(spec: LMSpec, *, seed: int = 0):
    """Params from a short stub — every shape is length-independent."""
    stub = min(spec.total_len, 128)
    return _dense_lm(spec).init(
        jax.random.key(seed), jnp.zeros((1, stub), jnp.int32)
    )["params"]


def dense_lm_apply(spec: LMSpec, params, tokens):
    """Single-device reference forward over the full sequence."""
    return _dense_lm(spec).apply({"params": params}, tokens)


def next_token_loss(logits, tokens):
    """Mean causal-LM loss: position t predicts token t+1.

    ``logits``/``tokens`` are GLOBAL ([B, T, V] / [B, T]); the final
    position has no target and is masked out.
    """
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1
    )
    weights = jnp.concatenate(
        [
            jnp.ones(tokens[:, 1:].shape, jnp.float32),
            jnp.zeros(tokens[:, :1].shape, jnp.float32),
        ],
        axis=1,
    )
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    )
    return (per_tok * weights).sum() / weights.sum()


# One step/params/opt_state state shape serves every sequence-model
# family (models/seq_transformer.py defines it + the replication
# factory — uniform shardings on every leaf).
from ddp_tpu.models.seq_transformer import (  # noqa: E402
    SeqTrainState as LMTrainState,
    replicated_train_state,
)


def create_lm_train_state(
    spec: LMSpec,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    seed: int = 0,
) -> LMTrainState:
    return replicated_train_state(init_lm(spec, seed=seed), optimizer, mesh)


def _make_sharded_forward(spec: LMSpec, mesh: Mesh, compute_dtype):
    model = _sharded_lm(spec)
    has_data = mesh.shape.get("data", 1) > 1
    bspec = P("data") if has_data else P(None)
    xspec = P(bspec[0], "seq")

    def per_shard_forward(params, tok_shard):
        t_local = tok_shard.shape[1]
        offset = lax.axis_index("seq") * t_local
        if compute_dtype != jnp.float32:
            params = jax.tree.map(lambda p: p.astype(compute_dtype), params)
        return model.apply({"params": params}, tok_shard, pos_offset=offset)

    return (
        jax.shard_map(
            per_shard_forward,
            mesh=mesh,
            in_specs=(P(), xspec),
            out_specs=xspec,
            check_vma=False,
        ),
        xspec,
    )


def make_lm_eval_step(
    spec: LMSpec, mesh: Mesh, *, compute_dtype=jnp.float32
):
    """Trainer-compatible eval: next-token metrics over held-out tokens.

    Signature matches the classifier eval steps —
    ``(params, model_state, tokens, labels, weights) →
    (weighted Σ per-sequence token accuracy, weighted Σ per-sequence
    mean loss)`` — so ``Trainer.evaluate`` divides by n and reports
    average next-token accuracy where classifiers report top-1.
    ``labels`` is ignored (targets are the shifted tokens themselves).
    """
    sharded_forward, _ = _make_sharded_forward(spec, mesh, compute_dtype)

    def step(params, model_state, tokens, labels, weights):
        del model_state, labels
        logits = sharded_forward(params, tokens)
        targets = tokens[:, 1:]
        per_tok = optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1].astype(jnp.float32), targets
        )  # [B, T-1]
        seq_loss = per_tok.mean(axis=1)
        pred = jnp.argmax(logits[:, :-1].astype(jnp.float32), -1)
        seq_acc = (pred == targets).mean(axis=1)
        return (seq_acc * weights).sum(), (seq_loss * weights).sum()

    return jax.jit(step)


def make_lm_train_step(
    spec: LMSpec,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    donate: bool = True,
    compute_dtype=jnp.float32,
):
    """dp×sp causal-LM step: ``step(state, tokens) -> (state, metrics)``.

    ``tokens``: [B, T_global] int32. The label shift and loss masking
    happen on GLOBAL arrays before/after the sharded forward, so shard
    boundaries need no special cases; gradients for the replicated
    params arrive psum'd by the shard_map transpose. Metrics: loss is
    the mean next-token cross-entropy, accuracy the next-token top-1.
    """
    sharded_forward, xspec = _make_sharded_forward(spec, mesh, compute_dtype)

    def step(state: LMTrainState, tokens):
        tokens = lax.with_sharding_constraint(
            tokens, NamedSharding(mesh, xspec)
        )

        def loss_fn(params):
            logits = sharded_forward(params, tokens)
            return next_token_loss(logits, tokens), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        pred = jnp.argmax(logits[:, :-1].astype(jnp.float32), -1)
        accuracy = (pred == tokens[:, 1:]).mean()
        return (
            state._replace(
                step=state.step + 1, params=params, opt_state=opt_state
            ),
            StepMetrics(
                loss=loss, accuracy=accuracy,
                grad_norm=optax.global_norm(grads),
            ),
        )

    return jax.jit(step, donate_argnums=(0,) if donate else ())

"""Vision Transformer (ViT-Tiny and friends) — the attention-path config.

BASELINE.json config 4: "ViT-Tiny on CIFAR-100 (attention path, bf16
mixed precision)". The reference has no attention anywhere
(/root/reference/model.py:8-16 is conv+linear); this adds the family
TPU-first:

- attention runs through a pluggable callable (``attention_fn``) with
  the signature ``(q, k, v) -> out`` on [B, T, H, D] arrays, so the
  same module serves dense single-chip attention and the
  sequence-parallel ring attention in ``ddp_tpu.parallel.ring`` — the
  mesh decides, the model doesn't;
- pre-LN blocks, GELU MLP, learned position embeddings, class token;
- all matmul-heavy ops inherit the input dtype (bf16 under mixed
  precision) while LayerNorm and the head stay fp32-stable.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from ddp_tpu.ops.attention import best_attention

AttentionFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


class RowParallelDense(nn.Module):
    """Megatron row-parallel Dense for use inside ``shard_map``.

    The kernel's INPUT dim is sharded over ``axis_name`` — each mesh
    member holds [d_in/tp, features] and contributes a partial
    product, combined by one ``lax.psum``; the bias (replicated) is
    added once, after the sum. Param tree paths (``kernel``/``bias``
    under the module name) match ``nn.Dense`` exactly, so a densely
    initialized checkpoint shards onto this module without renaming
    (parallel/tp.py ``seq_param_specs``).
    """

    features: int
    axis_name: str
    # True → combine with Megatron's ``g`` (psum forward, identity
    # backward) instead of a bare psum: required when the block's
    # gradient comes from an explicit jax.vjp INSIDE the shard_map
    # body (hand-scheduled pipeline schedules) — see parallel/tp.py.
    inner_vjp: bool = False

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (x.shape[-1], self.features),
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        partial_y = x @ kernel.astype(x.dtype)
        if self.inner_vjp:
            from ddp_tpu.parallel.tp import megatron_g

            y = megatron_g(partial_y, self.axis_name)
        else:
            y = lax.psum(partial_y, self.axis_name)
        return y + bias.astype(y.dtype)


class MultiHeadAttention(nn.Module):
    """QKV projection + pluggable attention kernel + output projection.

    ``attention_fn=None`` (the default everywhere in the model zoo)
    resolves to ``ops.attention.best_attention()`` at call time: on
    TPU the Pallas flash kernel for sequences past FLASH_MIN_LEN and
    dense XLA below it (where the kernel's per-block overhead loses);
    dense everywhere else. Passing a callable overrides it
    (ring/Ulysses collectives, causal variants, tests).

    ``tp_axis``/``tp_size`` (shard_map-only): Megatron tensor
    parallelism — qkv goes column-parallel (this member computes
    ``num_heads/tp_size`` heads; the attention kernel sees only local
    heads, so TP composes freely with ring/Ulysses over ``seq``) and
    the output projection row-parallel with one psum (parallel/tp.py).
    """

    num_heads: int
    attention_fn: Optional[AttentionFn] = None
    tp_axis: Optional[str] = None
    tp_size: int = 1
    # True → Megatron f/g custom-VJP plumbing for contexts that take
    # the gradient with an explicit jax.vjp inside the shard_map body
    # (hand-scheduled pipeline schedules). See parallel/tp.py.
    tp_inner_vjp: bool = False
    # Grouped-query attention: 0 → num_heads (plain MHA). Fewer KV
    # heads shrink the qkv projection and — the real win — the
    # generation KV cache and its per-step HBM reads
    # (models/generate.py stores the COMPACT kv). KV is expanded to
    # the full head count before ``attention_fn``, so flash / ring /
    # Ulysses compose unchanged. Composes with TP when tp_size
    # divides num_kv_heads (whole kv groups per member — see the
    # group-major layout note in __call__). BREAKING vs the round-3
    # layout: the fused qkv columns moved from the [q·H | k·H_kv |
    # v·H_kv] block order to group-major (same shapes — a round-3 GQA
    # checkpoint restores shape-clean but mispermuted; retrain or
    # re-export).
    num_kv_heads: int = 0

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        B, T, C = x.shape
        assert C % self.num_heads == 0, (C, self.num_heads)
        assert self.num_heads % self.tp_size == 0, (
            self.num_heads, self.tp_size,
        )
        head_dim = C // self.num_heads
        fn = self.attention_fn or best_attention()
        H_kv = self.num_kv_heads or self.num_heads
        if H_kv != self.num_heads:
            # ValueError (not assert): library users bypass the trainer
            # guards, and asserts vanish under ``python -O``.
            if self.num_heads % H_kv != 0:
                raise ValueError(
                    f"num_heads={self.num_heads} must be a multiple of "
                    f"num_kv_heads={H_kv}"
                )
            if H_kv % self.tp_size != 0:
                raise ValueError(
                    f"GQA under TP shards whole kv groups: num_kv_heads="
                    f"{H_kv} not divisible by tp_size={self.tp_size}"
                )
            # GROUP-MAJOR fused layout: columns ordered [kv-group g:
            # q_{g,0..G-1} | k_g | v_g] × H_kv groups. A contiguous
            # shard of the output dim — what P(..., "model") hands each
            # TP member — is a whole number of kv GROUPS, each with its
            # G query heads and its complete k AND v (the GQA analogue
            # of the MHA head-major contract above). generate.py
            # mirrors this layout.
            if self.tp_size > 1 and self.tp_inner_vjp:
                from ddp_tpu.parallel.tp import megatron_f

                x = megatron_f(x, self.tp_axis)
            g = self.num_heads // H_kv
            kv_local = H_kv // self.tp_size
            qkv = nn.Dense(
                (self.num_heads + 2 * H_kv) * head_dim // self.tp_size,
                name="qkv",
            )(x)
            qkv = qkv.reshape(B, T, kv_local, g + 2, head_dim)
            q = qkv[..., :g, :].reshape(B, T, kv_local * g, head_dim)
            k = qkv[..., g, :]  # [B, T, kv_local, head_dim]
            v = qkv[..., g + 1, :]
            k = jnp.repeat(k, g, axis=2)
            v = jnp.repeat(v, g, axis=2)
        else:
            heads_local = self.num_heads // self.tp_size
            if self.tp_size > 1 and self.tp_inner_vjp:
                from ddp_tpu.parallel.tp import megatron_f

                x = megatron_f(x, self.tp_axis)
            # HEAD-MAJOR qkv layout: the fused kernel's output columns
            # are ordered [head, (q|k|v), head_dim], so a contiguous
            # shard of the output dim — what P(..., "model") hands each
            # TP member — is a whole number of heads with their
            # complete q, k, AND v. (A (q|k|v)-major layout would hand
            # member 0 "all of Q plus half of K" under TP.) generate.py
            # mirrors this layout.
            qkv = nn.Dense(3 * C // self.tp_size, name="qkv")(x)
            qkv = qkv.reshape(B, T, heads_local, 3, head_dim)
            q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
        out = fn(q, k, v)  # [B, T, H_local, D]
        out = out.reshape(B, T, C // self.tp_size)
        if self.tp_size > 1:
            return RowParallelDense(
                C, self.tp_axis, inner_vjp=self.tp_inner_vjp, name="proj"
            )(out)
        return nn.Dense(C, name="proj")(out)


class EncoderBlock(nn.Module):
    """Pre-LN block. ``deterministic`` is a module attribute (not a call
    kwarg) so ``nn.remat(EncoderBlock)`` traces only the activation —
    a traced bool would break Dropout/BatchNorm's Python branching.

    ``tp_axis``/``tp_size``: Megatron tensor parallelism inside a
    shard_map — attention heads and the MLP hidden dim shard over the
    ``model`` mesh axis, two psums per block (after attn/proj and
    mlp2); LayerNorms and the residual stream stay replicated
    (parallel/tp.py has the layout + gradient-exactness story)."""

    num_heads: int
    mlp_dim: int
    dropout_rate: float = 0.0
    attention_fn: Optional[AttentionFn] = None
    deterministic: bool = True
    tp_axis: Optional[str] = None
    tp_size: int = 1
    tp_inner_vjp: bool = False  # Megatron f/g — see MultiHeadAttention
    num_kv_heads: int = 0  # GQA — see MultiHeadAttention

    @nn.compact
    def __call__(self, x):
        assert self.mlp_dim % self.tp_size == 0, (self.mlp_dim, self.tp_size)
        y = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x).astype(x.dtype)
        y = MultiHeadAttention(
            self.num_heads,
            attention_fn=self.attention_fn,
            tp_axis=self.tp_axis,
            tp_size=self.tp_size,
            tp_inner_vjp=self.tp_inner_vjp,
            num_kv_heads=self.num_kv_heads,
            name="attn",
        )(y, deterministic=self.deterministic)
        y = nn.Dropout(self.dropout_rate, deterministic=self.deterministic)(y)
        x = x + y
        y = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x).astype(x.dtype)
        if self.tp_size > 1 and self.tp_inner_vjp:
            from ddp_tpu.parallel.tp import megatron_f

            y = megatron_f(y, self.tp_axis)
        y = nn.Dense(self.mlp_dim // self.tp_size, name="mlp1")(y)
        y = nn.gelu(y)
        if self.tp_size > 1:
            y = RowParallelDense(
                x.shape[-1], self.tp_axis, inner_vjp=self.tp_inner_vjp,
                name="mlp2",
            )(y)
        else:
            y = nn.Dense(x.shape[-1], name="mlp2")(y)
        y = nn.Dropout(self.dropout_rate, deterministic=self.deterministic)(y)
        return x + y


class ViT(nn.Module):
    """Patch-embed → [cls] + pos-embed → N pre-LN blocks → head."""

    num_classes: int = 100
    patch_size: int = 4
    embed_dim: int = 192
    depth: int = 12
    num_heads: int = 3
    mlp_ratio: int = 4
    dropout_rate: float = 0.0
    attention_fn: Optional[AttentionFn] = None
    use_cls_token: bool = True
    # Rematerialize each encoder block in the backward pass
    # (jax.checkpoint): activations are recomputed instead of stored,
    # trading ~1 extra forward of FLOPs for O(depth) less HBM — the
    # standard TPU memory lever for deep/long-sequence configs.
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        B = x.shape[0]
        p = self.patch_size
        x = nn.Conv(
            self.embed_dim, (p, p), strides=(p, p), padding="VALID",
            name="patch_embed",
        )(x)  # [B, H/p, W/p, C]
        x = x.reshape(B, -1, self.embed_dim)
        if self.use_cls_token:
            cls = self.param(
                "cls_token", nn.initializers.zeros, (1, 1, self.embed_dim)
            )
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (B, 1, self.embed_dim)).astype(x.dtype), x],
                axis=1,
            )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], self.embed_dim),
        )
        x = x + pos.astype(x.dtype)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        block_cls = nn.remat(EncoderBlock) if self.remat else EncoderBlock
        for i in range(self.depth):
            x = block_cls(
                num_heads=self.num_heads,
                mlp_dim=self.embed_dim * self.mlp_ratio,
                dropout_rate=self.dropout_rate,
                attention_fn=self.attention_fn,
                deterministic=not train,
                name=f"block{i + 1}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        x = x[:, 0] if self.use_cls_token else x.mean(axis=1)
        return nn.Dense(self.num_classes, name="head", dtype=jnp.float32)(x)


def ViTTiny(
    num_classes: int = 100,
    patch_size: int = 4,
    depth: int = 12,
    attention_fn: Optional[AttentionFn] = None,
    **kwargs,
) -> ViT:
    return ViT(
        num_classes=num_classes,
        patch_size=patch_size,
        embed_dim=192,
        depth=depth,
        num_heads=3,
        attention_fn=attention_fn,
        **kwargs,
    )

"""Vision Transformer (ViT-Tiny and friends) — the attention-path config.

BASELINE.json config 4: "ViT-Tiny on CIFAR-100 (attention path, bf16
mixed precision)". The reference has no attention anywhere
(/root/reference/model.py:8-16 is conv+linear); this adds the family
TPU-first:

- attention runs through a pluggable callable (``attention_fn``) with
  the signature ``(q, k, v) -> out`` on [B, T, H, D] arrays, so the
  same module serves dense single-chip attention and the
  sequence-parallel ring attention in ``ddp_tpu.parallel.ring`` — the
  mesh decides, the model doesn't;
- pre-LN blocks, GELU MLP, learned position embeddings, class token;
- all matmul-heavy ops inherit the input dtype (bf16 under mixed
  precision) while LayerNorm and the head stay fp32-stable.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from ddp_tpu.ops.attention import best_attention

AttentionFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


class MultiHeadAttention(nn.Module):
    """QKV projection + pluggable attention kernel + output projection.

    ``attention_fn=None`` (the default everywhere in the model zoo)
    resolves to ``ops.attention.best_attention()`` at call time: on
    TPU the Pallas flash kernel for sequences past FLASH_MIN_LEN and
    dense XLA below it (where the kernel's per-block overhead loses);
    dense everywhere else. Passing a callable overrides it
    (ring/Ulysses collectives, causal variants, tests).
    """

    num_heads: int
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        B, T, C = x.shape
        assert C % self.num_heads == 0, (C, self.num_heads)
        head_dim = C // self.num_heads
        qkv = nn.Dense(3 * C, name="qkv")(x)
        qkv = qkv.reshape(B, T, 3, self.num_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        fn = self.attention_fn or best_attention()
        out = fn(q, k, v)  # [B, T, H, D]
        out = out.reshape(B, T, C)
        return nn.Dense(C, name="proj")(out)


class EncoderBlock(nn.Module):
    """Pre-LN block. ``deterministic`` is a module attribute (not a call
    kwarg) so ``nn.remat(EncoderBlock)`` traces only the activation —
    a traced bool would break Dropout/BatchNorm's Python branching."""

    num_heads: int
    mlp_dim: int
    dropout_rate: float = 0.0
    attention_fn: Optional[AttentionFn] = None
    deterministic: bool = True

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x).astype(x.dtype)
        y = MultiHeadAttention(
            self.num_heads, attention_fn=self.attention_fn, name="attn"
        )(y, deterministic=self.deterministic)
        y = nn.Dropout(self.dropout_rate, deterministic=self.deterministic)(y)
        x = x + y
        y = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x).astype(x.dtype)
        y = nn.Dense(self.mlp_dim, name="mlp1")(y)
        y = nn.gelu(y)
        y = nn.Dense(x.shape[-1], name="mlp2")(y)
        y = nn.Dropout(self.dropout_rate, deterministic=self.deterministic)(y)
        return x + y


class ViT(nn.Module):
    """Patch-embed → [cls] + pos-embed → N pre-LN blocks → head."""

    num_classes: int = 100
    patch_size: int = 4
    embed_dim: int = 192
    depth: int = 12
    num_heads: int = 3
    mlp_ratio: int = 4
    dropout_rate: float = 0.0
    attention_fn: Optional[AttentionFn] = None
    use_cls_token: bool = True
    # Rematerialize each encoder block in the backward pass
    # (jax.checkpoint): activations are recomputed instead of stored,
    # trading ~1 extra forward of FLOPs for O(depth) less HBM — the
    # standard TPU memory lever for deep/long-sequence configs.
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        B = x.shape[0]
        p = self.patch_size
        x = nn.Conv(
            self.embed_dim, (p, p), strides=(p, p), padding="VALID",
            name="patch_embed",
        )(x)  # [B, H/p, W/p, C]
        x = x.reshape(B, -1, self.embed_dim)
        if self.use_cls_token:
            cls = self.param(
                "cls_token", nn.initializers.zeros, (1, 1, self.embed_dim)
            )
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (B, 1, self.embed_dim)).astype(x.dtype), x],
                axis=1,
            )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], self.embed_dim),
        )
        x = x + pos.astype(x.dtype)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        block_cls = nn.remat(EncoderBlock) if self.remat else EncoderBlock
        for i in range(self.depth):
            x = block_cls(
                num_heads=self.num_heads,
                mlp_dim=self.embed_dim * self.mlp_ratio,
                dropout_rate=self.dropout_rate,
                attention_fn=self.attention_fn,
                deterministic=not train,
                name=f"block{i + 1}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        x = x[:, 0] if self.use_cls_token else x.mean(axis=1)
        return nn.Dense(self.num_classes, name="head", dtype=jnp.float32)(x)


def ViTTiny(
    num_classes: int = 100,
    patch_size: int = 4,
    depth: int = 12,
    attention_fn: Optional[AttentionFn] = None,
    **kwargs,
) -> ViT:
    return ViT(
        num_classes=num_classes,
        patch_size=patch_size,
        embed_dim=192,
        depth=depth,
        num_heads=3,
        attention_fn=attention_fn,
        **kwargs,
    )

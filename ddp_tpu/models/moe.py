"""Mixture-of-Experts layers + MoE ViT — the expert-parallel family.

The reference has no MoE anywhere (SURVEY.md §2c lists expert
parallelism as absent); this module adds the capability TPU-first, the
GShard/GSPMD way: expert computation is expressed as *global* einsums
over a dispatch tensor, expert weights carry a leading ``num_experts``
dim sharded on the mesh's ``expert`` axis (parallel/spmd.py
ShardingRules), and XLA's partitioner derives the token all-to-alls
from the shardings alone — no hand-written routing collectives.

Routing is classic top-k with capacity (GShard): per-token softmax
gates, iterative top-k selection, position-in-expert by a global
cumsum with earlier choices taking priority, tokens past capacity
dropped. The load-balancing auxiliary loss is recorded in a ``losses``
variable collection (stable-structure `self.variable`, not `sow`, so
the train-state pytree never changes shape); the train steps in
parallel/{ddp,spmd}.py add it to the objective.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ddp_tpu.models.vit import AttentionFn, EncoderBlock, MultiHeadAttention


class MoEMLP(nn.Module):
    """Top-k routed expert MLP with capacity-based token dropping.

    Input/output: ``[B, T, d]``. Expert weights: ``wi [E, d, mlp_dim]``,
    ``wo [E, mlp_dim, d]`` (+ biases ``bi``/``bo``) — the leading dim is
    what the ``expert`` mesh axis shards.

    ``ep_axis``/``ep_size`` (shard_map-only — the GSPMD/image family
    gets EP by annotation instead, parallel/spmd.py): each mesh member
    holds ``num_experts/ep_size`` experts and a DIFFERENT token shard
    (``expert`` is a batch axis, runtime/mesh.py ``data_axes``). The
    member routes its own tokens over all E experts, one
    ``lax.all_to_all`` carries each expert's dispatched slots to the
    member that owns it, the FFN runs on local experts over everyone's
    slots, and the inverse all_to_all brings results home — the
    explicit form of the token exchange XLA derives for the annotated
    family. AD transposes each all_to_all into its inverse, so
    gradients route themselves.
    """

    num_experts: int
    mlp_dim: int
    top_k: int = 2
    capacity_factor: float = 2.0
    normalize_gates: bool = True
    ep_axis: Optional[str] = None
    ep_size: int = 1

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        B, T, d = x.shape
        E = self.num_experts
        assert E % self.ep_size == 0, (E, self.ep_size)
        n = B * T
        tokens = x.reshape(n, d)
        # Per-expert slot count; static (derived from traced shapes).
        capacity = max(1, int(round(self.capacity_factor * n * self.top_k / E)))  # ddp-lint: disable=DDP002 n/E are Python ints from x.shape — static at trace time

        # Router in fp32 for numerically stable softmax under bf16.
        gate_logits = nn.Dense(E, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32)
        )
        gates = jax.nn.softmax(gate_logits, axis=-1)  # [n, E]

        # Iterative top-k: pick, record, mask out, repeat.
        remaining = gates
        expert_masks, gate_vals = [], []
        for _ in range(self.top_k):
            idx = jnp.argmax(remaining, axis=-1)
            mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [n, E]
            gate_vals.append((remaining * mask).sum(-1))  # [n]
            expert_masks.append(mask)
            remaining = remaining * (1.0 - mask)

        # Position-in-expert via one global cumsum with k=0 assignments
        # taking priority over k=1 for the limited capacity slots.
        masks = jnp.stack(expert_masks)  # [K, n, E]
        flat = masks.reshape(self.top_k * n, E)
        pos = jnp.cumsum(flat, axis=0) - flat  # slot index per assignment
        pos = pos.reshape(self.top_k, n, E)
        in_cap = masks * (pos < capacity)  # [K, n, E]
        pos_in_expert = (pos * in_cap).sum(-1).astype(jnp.int32)  # [K, n]

        gate_stack = jnp.stack(gate_vals) * in_cap.sum(-1)  # zero dropped
        if self.normalize_gates:
            denom = gate_stack.sum(0, keepdims=True)
            gate_stack = gate_stack / jnp.maximum(denom, 1e-9)

        slot_onehot = jax.nn.one_hot(pos_in_expert, capacity)  # [K, n, C]
        # dispatch[n, e, c] = token n occupies slot c of expert e
        dispatch = jnp.einsum("kne,knc->nec", in_cap, slot_onehot)
        combine = jnp.einsum("kne,kn,knc->nec", in_cap, gate_stack, slot_onehot)

        # GShard load-balance aux loss: E * Σ_e mean-gate_e · frac-routed_e
        # (first-choice fractions). Recorded with stable pytree shape.
        frac_routed = expert_masks[0].mean(0)
        aux = E * jnp.sum(gates.mean(0) * frac_routed)
        if self.is_mutable_collection("losses"):
            self.variable(
                "losses", "moe_aux", lambda: jnp.zeros((), jnp.float32)
            ).value = aux

        dtype = x.dtype
        e_local = E // self.ep_size
        wi = self.param(
            "wi", nn.initializers.lecun_normal(), (e_local, d, self.mlp_dim)
        )
        bi = self.param(
            "bi", nn.initializers.zeros, (e_local, 1, self.mlp_dim)
        )
        wo = self.param(
            "wo", nn.initializers.lecun_normal(), (e_local, self.mlp_dim, d)
        )
        bo = self.param("bo", nn.initializers.zeros, (e_local, 1, d))

        # Dispatch → expert FFN → combine. Replicated experts: global
        # einsums (with tokens batch-sharded and wi/wo expert-sharded
        # under GSPMD, XLA inserts the token all-to-alls here). Expert-
        # parallel (shard_map): the all_to_alls are written out.
        xs = jnp.einsum("nec,nd->ecd", dispatch.astype(dtype), tokens)
        if self.ep_size > 1:
            # [E, C, d] → [E/ep, ep·C, d]: slots for MY experts from
            # every member, blocked by source (order is irrelevant —
            # the FFN is slot-wise and the inverse exchange restores it).
            xs = jax.lax.all_to_all(
                xs, self.ep_axis, split_axis=0, concat_axis=1, tiled=True
            )
        h = nn.gelu(
            jnp.einsum("ecd,edf->ecf", xs, wi.astype(dtype)) + bi.astype(dtype)
        )
        ys = jnp.einsum("ecf,efd->ecd", h, wo.astype(dtype)) + bo.astype(dtype)
        if self.ep_size > 1:
            ys = jax.lax.all_to_all(
                ys, self.ep_axis, split_axis=1, concat_axis=0, tiled=True
            )
        out = jnp.einsum("nec,ecd->nd", combine.astype(dtype), ys)
        return out.reshape(B, T, d)


def is_moe_block(i: int, num_experts: int, moe_every: int) -> bool:
    """THE block-interleave rule, shared by every MoE family (CausalLM,
    MoEViT, the pipelined StageBlocks): block index ``i`` (0-based)
    hosts a routed MLP iff experts are on and ``(i+1) % moe_every``
    lands. One definition so the three families cannot drift."""
    return bool(num_experts) and (i + 1) % moe_every == 0


class MoEEncoderBlock(nn.Module):
    """Pre-LN transformer block whose MLP is a routed expert layer.

    ``num_kv_heads`` (round 5): grouped-query attention in routed
    blocks too — GQA lives in the attention, routing in the MLP;
    orthogonal subsystems (the Mixtral-class composition). Same
    group-major fused-qkv layout as the dense EncoderBlock."""

    num_heads: int
    mlp_dim: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 2.0
    normalize_gates: bool = True
    dropout_rate: float = 0.0
    attention_fn: Optional[AttentionFn] = None
    deterministic: bool = True  # attribute, not call kwarg — remat-safe
    ep_axis: Optional[str] = None  # expert parallelism (see MoEMLP)
    ep_size: int = 1
    num_kv_heads: int = 0  # GQA — see models/vit.py MultiHeadAttention
    # Megatron TP for the ATTENTION half only (round 5 — the
    # Megatron-MoE layout): heads shard over ``model`` exactly as in
    # the dense EncoderBlock; the routed MLP stays replicated across
    # ``model`` members (experts shard over ``expert`` instead — EP
    # owns the MoE sharding story), so every member routes the same
    # replicated residual stream and computes identical expert
    # updates, which the shard_map AD transpose accounts for like any
    # replicated leaf (LNs, embeddings). Deliberately NO tp_inner_vjp:
    # the Megatron f/g path (hand-scheduled pipeline kernels) does not
    # extend into routed blocks — StageBlocks refuses MoE×TP when
    # built with tp_inner_vjp (1F1B/interleaved); the AD paths (flat
    # CausalLM, GPipe) compose MoE×TP via the shard_map transpose.
    tp_axis: Optional[str] = None
    tp_size: int = 1

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x).astype(x.dtype)
        y = MultiHeadAttention(
            self.num_heads, attention_fn=self.attention_fn,
            num_kv_heads=self.num_kv_heads,
            tp_axis=self.tp_axis, tp_size=self.tp_size, name="attn"
        )(y, deterministic=self.deterministic)
        y = nn.Dropout(self.dropout_rate, deterministic=self.deterministic)(y)
        x = x + y
        y = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x).astype(x.dtype)
        y = MoEMLP(
            num_experts=self.num_experts,
            mlp_dim=self.mlp_dim,
            top_k=self.top_k,
            capacity_factor=self.capacity_factor,
            normalize_gates=self.normalize_gates,
            ep_axis=self.ep_axis,
            ep_size=self.ep_size,
            name="moe",
        )(y, deterministic=self.deterministic)
        y = nn.Dropout(self.dropout_rate, deterministic=self.deterministic)(y)
        return x + y


class MoEViT(nn.Module):
    """ViT where every ``moe_every``-th block routes its MLP to experts.

    Same patch-embed/cls/pos front and head as models/vit.py ViT;
    interleaving dense and MoE blocks is the standard GShard/ST-MoE
    layout.
    """

    num_classes: int = 100
    patch_size: int = 4
    embed_dim: int = 192
    depth: int = 12
    num_heads: int = 3
    mlp_ratio: int = 4
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0
    moe_every: int = 2
    dropout_rate: float = 0.0
    attention_fn: Optional[AttentionFn] = None
    remat: bool = False  # jax.checkpoint each block (see models/vit.py)

    @nn.compact
    def __call__(self, x, train: bool = False):
        B = x.shape[0]
        p = self.patch_size
        x = nn.Conv(
            self.embed_dim, (p, p), strides=(p, p), padding="VALID",
            name="patch_embed",
        )(x)
        x = x.reshape(B, -1, self.embed_dim)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], self.embed_dim),
        )
        x = x + pos.astype(x.dtype)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        mlp_dim = self.embed_dim * self.mlp_ratio
        moe_cls = nn.remat(MoEEncoderBlock) if self.remat else MoEEncoderBlock
        dense_cls = nn.remat(EncoderBlock) if self.remat else EncoderBlock
        for i in range(self.depth):
            if is_moe_block(i, self.num_experts, self.moe_every):
                x = moe_cls(
                    num_heads=self.num_heads,
                    mlp_dim=mlp_dim,
                    num_experts=self.num_experts,
                    top_k=self.top_k,
                    capacity_factor=self.capacity_factor,
                    dropout_rate=self.dropout_rate,
                    attention_fn=self.attention_fn,
                    deterministic=not train,
                    name=f"block{i + 1}",
                )(x)
            else:
                x = dense_cls(
                    num_heads=self.num_heads,
                    mlp_dim=mlp_dim,
                    dropout_rate=self.dropout_rate,
                    attention_fn=self.attention_fn,
                    deterministic=not train,
                    name=f"block{i + 1}",
                )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        return nn.Dense(self.num_classes, name="head", dtype=jnp.float32)(
            x.mean(axis=1)
        )


def MoEViTTiny(
    num_classes: int = 100,
    num_experts: int = 8,
    depth: int = 12,
    attention_fn: Optional[AttentionFn] = None,
    **kwargs,
) -> MoEViT:
    return MoEViT(
        num_classes=num_classes,
        embed_dim=192,
        depth=depth,
        num_heads=3,
        num_experts=num_experts,
        attention_fn=attention_fn,
        **kwargs,
    )

"""Pipeline-parallel causal LM: the transformer LM through the pipe axis.

The round-3 verdict's biggest depth gap: pipeline parallelism only
carried the ViT family, while the canonical large-model layout — a
pipelined transformer LM — could not be expressed. This module cuts the
LM's uniform block stack through the SAME schedule kernels the ViT
family uses (parallel/pipeline.py GPipe, parallel/one_f1b.py 1F1B,
parallel/interleaved.py interleaved-1F1B):

- **stage 0** runs the token+position embedding (``first_fn``) before
  its blocks;
- **stage S−1** runs final-LN, the TIED embedding-transpose head, and
  the next-token loss (``loss_fn`` inside the last stage's backward for
  the hand-scheduled paths — logits never leave the device);
- the **tied embedding** lives once (in the front params) and is passed
  to both ends of the pipeline; its gradient is the SUM of the lookup
  contribution (stage 0) and the head contribution (stage S−1). The AD
  path gets this for free; the hand-scheduled paths add ``g_first.embed
  + g_last.embed`` explicitly.

Architecture matches models/lm.py CausalLM exactly (embed → pos →
pre-LN causal blocks → final LN → tied head), so loss parity against
the single-device LM step is testable block-for-block
(tests/test_pipeline_lm.py). The reference has neither pipeline
parallelism nor a language model (SURVEY.md §2c); this is framework
depth beyond it.

Composes with ``data`` (batch sharding), ``fsdp`` (ZeRO-sharded stage
params, parallel/pipe_common.py), and — via ``tp_size`` — ``model``
(Megatron column/row sharding INSIDE each stage's blocks, the PP×TP
composition).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.models.pipeline_vit import StageBlocks
from ddp_tpu.models.lm import next_token_loss
from ddp_tpu.ops.attention import best_attention
from ddp_tpu.parallel.ddp import StepMetrics
from ddp_tpu.parallel.pipe_common import (
    gather_stages,
    merge_microbatch_stream,
    pipe_batch_axes,
    scatter_stage_grads,
    stage_specs_megatron,
)
from ddp_tpu.parallel.pipeline import spmd_pipeline, stack_stage_params


class PipeLMConfig(NamedTuple):
    vocab_size: int
    seq_len: int  # tokens per sequence ([B, seq_len] step input)
    d_model: int = 64
    num_heads: int = 4
    mlp_ratio: int = 4
    num_stages: int = 4
    depth_per_stage: int = 1
    num_microbatches: int = 4
    attention_fn: Optional[Callable] = None  # None → causal best_attention
    remat: bool = False
    # Interleaved only: v chunks per device, round-robin placement —
    # total depth = num_stages × virtual_stages × depth_per_stage.
    virtual_stages: int = 1
    label_smoothing: float = 0.0
    # Megatron TP over the ``model`` mesh axis inside each stage's
    # blocks (PP×TP): attention heads + MLP hidden shard, everything
    # else replicates across ``model``.
    tp_size: int = 1
    # Grouped-query attention: 0 → num_heads (MHA). Same group-major
    # fused-qkv layout as the seq family (models/vit.py), so GQA
    # composes with the stage TP when tp_size divides num_kv_heads.
    num_kv_heads: int = 0
    # MoE: every moe_every-th block's MLP is GShard top-k routed
    # (models/moe.py). Any moe_every dividing depth_per_stage works
    # (1 = fully-routed; odd depths with k | D included): the global
    # every-k pattern is then chunk-periodic, which stacked SPMD
    # stages REQUIRE — one shard_map trace consumes one stacked param
    # tree, so every chunk must share its routed-block positions; a
    # flat model whose k does not divide D (per-chunk heterogeneous
    # structure) is inexpressible here and belongs to the seq-family
    # CausalLM. The
    # load-balance aux loss is NOT collected on the pipe path (the
    # kernels apply stages purely); routing + capacity dropping still
    # train. NOTE on routing semantics: GShard capacity/slot
    # competition is computed over whatever batch the layer sees —
    # per MICROBATCH in the pipelined step, per full batch in
    # ``sequential_apply``/eval — so the two forwards agree exactly
    # only while no token overflows capacity (always true for
    # near-uniform routers at capacity_factor 2.0; a skewed router
    # drops different tokens in the two views, like any
    # batch-size-dependent GShard eval). Composes with GQA (round 5 —
    # attention and routing are orthogonal) and with tp under the
    # GPipe schedule (the AD transpose owns the cross-member sums;
    # the hand-scheduled kernels' in-island vjp refuses MoE×TP — its
    # f/g plumbing does not extend into routed blocks).
    num_experts: int = 0
    moe_every: int = 2
    # Routing config for those MoE blocks (threaded into StageBlocks →
    # MoEEncoderBlock, same fields as LMSpec — the CLI's --moe_top_k /
    # --moe_raw_gates must not be silently ignored on this family).
    moe_top_k: int = 2
    moe_normalize_gates: bool = True
    # Expert parallelism over the ``expert`` mesh axis (PP×EP, round
    # 5): expert weights rest sharded 1/ep per member INSIDE each
    # stage, ``expert`` joins the batch axes (pipe_common.py
    # pipe_batch_axes), and MoEMLP's explicit lax.all_to_all pair runs
    # within the stage's pipeline island — the flat EP family's
    # exchange (models/moe.py, tests/test_ep_lm.py) riding per stage.
    ep_size: int = 1
    # Sequence/context parallelism over the ``seq`` mesh axis (PP×SP,
    # round 5): each microbatch's tokens shard over ``seq`` (the
    # stream spec gains a trailing seq dim), the stage blocks run
    # ring/Ulysses attention (parallel/ring.py — the lax.ppermute hops
    # ride INSIDE the schedule kernels exactly like the TP psums and
    # EP all-to-alls), stage 0 offsets its position embedding by the
    # shard index, and stage S−1 computes the next-token loss on its
    # LOCAL logits against the full (seq-replicated) token stream —
    # the shift crosses shard boundaries by slicing, never by
    # collective. ``seq`` reduces param grads like a batch axis.
    sp_size: int = 1
    sp_strategy: str = "ring"  # ring | ulysses


class PipeLMParams(NamedTuple):
    front: Any  # {"embed": [V, d], "pos_embed": [1, T, d]}
    stages: Any  # stacked blocks: leading [S, …] (or [v, S, …])
    back: Any  # {"ln": LayerNorm params}; head is the tied embed


class PipeLMState(NamedTuple):
    step: jax.Array
    params: PipeLMParams
    opt_state: Any


_LN = nn.LayerNorm(dtype=jnp.float32)  # the final LN (root module: no name)


def _attn(cfg: PipeLMConfig, *, sp: bool = False):
    """``sp=True`` (inside the pipeline island only): token-sharded
    attention over ``seq`` — ring or Ulysses per cfg.sp_strategy. The
    GLOBAL modules (init, sequential/eval) always take the dense
    path; shapes are identical either way."""
    if sp:
        if cfg.attention_fn is not None:
            raise ValueError(
                "attention_fn is not supported with sp_size > 1: the "
                "pipeline island must run the token-sharded "
                "ring/Ulysses exchange (a custom fn would silently "
                "diverge from the dense init/eval forward)"
            )
        from ddp_tpu.parallel.ring import sequence_sharded_attention

        def attn(q, k, v):
            return sequence_sharded_attention(
                q, k, v, axis_name="seq", strategy=cfg.sp_strategy,
                causal=True,
            )

        return attn
    return cfg.attention_fn or best_attention(causal=True)


def _stage_module(
    cfg: PipeLMConfig, *, tp: bool = False, inner_vjp: bool = False,
    ep: bool = False, sp: bool = False
):
    """The stage body. ``tp=False``/``ep=False`` builds the
    GLOBAL-shape module (init, sequential/eval forward); ``tp=True``
    the Megatron module whose local param shapes match each ``model``
    member's shard of the global arrays, ``ep=True`` the
    expert-parallel module whose expert weights are each ``expert``
    member's 1/ep slice (the seq-family convention, parallel/tp.py).
    ``inner_vjp=True`` adds the f/g custom-VJP plumbing the
    hand-scheduled kernels need (they vjp INSIDE the shard_map body,
    where the transpose's cross-member sums never run)."""
    if cfg.num_experts:
        if cfg.tp_size > 1 and inner_vjp:
            raise ValueError(
                "the pipelined MoE-LM composes with tp under the "
                "GPipe schedule only: the hand-scheduled kernels' "
                "in-island vjp needs Megatron f/g plumbing that does "
                "not extend into routed blocks — use --pipe_schedule "
                "gpipe (or the flat --model causal_lm)"
            )
        if cfg.depth_per_stage % cfg.moe_every:
            raise ValueError(
                f"depth_per_stage {cfg.depth_per_stage} must be a "
                f"multiple of moe_every {cfg.moe_every} (stages must "
                "be structure-uniform for parameter stacking)"
            )
        if cfg.num_experts % cfg.ep_size:
            raise ValueError(
                f"num_experts {cfg.num_experts} not divisible by "
                f"ep_size {cfg.ep_size}"
            )
    elif cfg.ep_size > 1:
        raise ValueError("ep_size > 1 needs num_experts > 0")
    if cfg.sp_size > 1 and cfg.seq_len % cfg.sp_size:
        raise ValueError(
            f"seq_len {cfg.seq_len} not divisible by sp_size "
            f"{cfg.sp_size}"
        )
    return StageBlocks(
        depth=cfg.depth_per_stage,
        num_heads=cfg.num_heads,
        mlp_dim=cfg.d_model * cfg.mlp_ratio,
        attention_fn=_attn(cfg, sp=sp),
        remat=cfg.remat,
        tp_axis="model" if tp else None,
        tp_size=cfg.tp_size if tp else 1,
        tp_inner_vjp=inner_vjp,
        num_kv_heads=cfg.num_kv_heads,
        num_experts=cfg.num_experts,
        moe_every=cfg.moe_every,
        moe_top_k=cfg.moe_top_k,
        moe_normalize_gates=cfg.moe_normalize_gates,
        ep_axis="expert" if ep else None,
        ep_size=cfg.ep_size if ep else 1,
    )


def _first_fn(fp, tokens):
    """Token + position embedding — runs inside stage 0."""
    x = fp["embed"][tokens]  # [mb, T, d]
    return x + fp["pos_embed"][:, : x.shape[1]].astype(x.dtype)


def _make_first_fn(cfg: PipeLMConfig):
    """Pipeline-island first_fn: under SP each member embeds its
    LOCAL token shard and slices the position table at its offset."""
    if cfg.sp_size <= 1:
        return _first_fn

    def first_fn(fp, tokens):
        x = fp["embed"][tokens]  # [mb, T_local, d]
        t_local = x.shape[1]
        off = lax.axis_index("seq") * t_local
        pos = lax.dynamic_slice_in_dim(
            fp["pos_embed"].astype(x.dtype), off, t_local, axis=1
        )
        return x + pos

    return first_fn


def _make_last_fn(cfg: PipeLMConfig):
    def last_fn(lp, x):
        """Final LN + tied head — runs inside stage S−1."""
        x = _LN.apply({"params": lp["ln"]}, x)
        return (x @ lp["embed"].T.astype(x.dtype)).astype(jnp.float32)

    return last_fn


def init_pipe_lm(
    cfg: PipeLMConfig, *, seed: int = 0, interleaved: bool = False
) -> PipeLMParams:
    """Initialize all segments; chunk c seeded fold_in(seed, 1+c).

    ``interleaved=True`` lays the C = S·v chunks out as [v, S, …]
    (chunk c = k·S + d at stages[k, d] — the round-robin placement the
    interleaved schedule requires); otherwise [S, …].
    """
    k = jax.random.key(seed)
    ke, kp = jax.random.split(jax.random.fold_in(k, 2**31))
    init = nn.initializers.normal(stddev=0.02)
    front = {
        "embed": init(ke, (cfg.vocab_size, cfg.d_model), jnp.float32),
        "pos_embed": init(kp, (1, cfg.seq_len, cfg.d_model), jnp.float32),
    }
    stage = _stage_module(cfg)
    feats = jnp.zeros((1, cfg.seq_len, cfg.d_model))
    C = cfg.num_stages * (cfg.virtual_stages if interleaved else 1)
    chunk_ps = [
        stage.init(jax.random.fold_in(k, 1 + c), feats)["params"]
        for c in range(C)
    ]
    stages = stack_stage_params(chunk_ps)
    if interleaved:
        stages = jax.tree.map(
            lambda p: p.reshape(
                cfg.virtual_stages, cfg.num_stages, *p.shape[1:]
            ),
            stages,
        )
    back = {"ln": _LN.init(jax.random.fold_in(k, 0), feats)["params"]}
    return PipeLMParams(front, stages, back)


def sequential_apply(cfg: PipeLMConfig, params: PipeLMParams, tokens):
    """Reference forward without the pipeline — same math, one device.

    Also the eval forward: jitted, XLA gathers each stage's params in
    turn. Handles both the [S, …] and interleaved [v, S, …] layouts
    (detected by leaf rank: the smallest block leaf — an LN bias — is
    1-D, so min rank 2 ⇒ one stacked dim, 3 ⇒ two).
    """
    stage = _stage_module(cfg)
    stages = params.stages
    min_rank = min(p.ndim for p in jax.tree.leaves(stages))
    if min_rank == 3:  # [v, S, …] → chunk-ordered [C, …] (c = k·S + d)
        stages = jax.tree.map(
            lambda p: p.reshape(-1, *p.shape[2:]), stages
        )
    C = jax.tree.leaves(stages)[0].shape[0]
    x = _first_fn(params.front, tokens)
    for c in range(C):
        sp = jax.tree.map(lambda p: p[c], stages)
        x = stage.apply({"params": sp}, x)
    lp = {"ln": params.back["ln"], "embed": params.front["embed"]}
    return _make_last_fn(cfg)(lp, x)


def _loss_fn_factory(cfg: PipeLMConfig):
    """Per-microbatch next-token loss SUM + correct count, computed
    inside the last stage (hand-scheduled paths).

    Under SP (cfg.sp_size > 1) the logits are this member's token
    shard while ``tok_mb`` is the full sequence, so the label shift
    crosses shard boundaries by SLICING tok_mb at the shard's offset;
    the final global position (no target) is masked out — summing the
    masked local losses over ``seq`` equals the dense ``[:, :-1]``
    reduction exactly."""

    def _per_tok(logits32, targets):
        if cfg.label_smoothing:
            eps = cfg.label_smoothing
            logp = jax.nn.log_softmax(logits32, axis=-1)
            nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
            return (1.0 - eps) * nll - (
                eps / logits32.shape[-1]
            ) * logp.sum(-1)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits32, targets
        )

    def loss_fn(logits, tok_mb):
        logits32 = logits[:, :-1].astype(jnp.float32)
        targets = tok_mb[:, 1:]
        per_tok = _per_tok(logits32, targets)
        correct = (
            (jnp.argmax(logits32, -1) == targets).sum().astype(jnp.float32)
        )
        return per_tok.sum(), correct

    if cfg.sp_size <= 1:
        return loss_fn

    def sp_loss_fn(logits, tok_mb):
        t_local = logits.shape[1]
        T = tok_mb.shape[1]
        off = lax.axis_index("seq") * t_local
        logits32 = logits.astype(jnp.float32)
        # Target for local position p is token off+p+1; pad one dummy
        # column so the slice stays in bounds on the last shard.
        padded = jnp.pad(tok_mb, ((0, 0), (0, 1)))
        targets = lax.dynamic_slice_in_dim(padded, off + 1, t_local, 1)
        valid = ((off + jnp.arange(t_local)) < T - 1).astype(jnp.float32)
        per_tok = _per_tok(logits32, targets) * valid
        correct = (
            ((jnp.argmax(logits32, -1) == targets) * valid)
            .sum()
            .astype(jnp.float32)
        )
        return per_tok.sum(), correct

    return sp_loss_fn


def _split_microbatches(cfg: PipeLMConfig, mesh: Mesh, tokens):
    """[B, T] int32 → ([M//S, S, mb, T] stream layout, [M, mb, T]),
    STRIDED (rows m::M) — parallel/pipe_common.py has the why."""
    from ddp_tpu.parallel.pipe_common import (
        split_microbatch_labels,
        split_microbatch_stream,
    )

    S = mesh.shape["pipe"]
    mbs = split_microbatch_stream(tokens, cfg.num_microbatches, S)
    lbl_mb = split_microbatch_labels(tokens, cfg.num_microbatches)
    return mbs, lbl_mb


def _check_sp_mesh(cfg: PipeLMConfig, mesh: Mesh):
    """cfg.sp_size and the mesh ``seq`` axis must agree: _specs and
    the grad reductions key off the MESH while attention/first_fn/loss
    key off the CFG — a mismatch (e.g. seq=2 mesh with sp_size=1)
    would shard tokens under dense per-shard attention and train
    silently wrong under GPipe."""
    mesh_sp = int(mesh.shape.get("seq", 1))
    if cfg.sp_size != mesh_sp and not (cfg.sp_size <= 1 and mesh_sp <= 1):
        raise ValueError(
            f"cfg.sp_size {cfg.sp_size} != mesh seq axis {mesh_sp} — "
            "set PipeLMConfig.sp_size to the mesh's seq size"
        )


def _specs(mesh: Mesh):
    baxes = pipe_batch_axes(mesh)
    ba = baxes if baxes else None
    sp = "seq" if mesh.shape.get("seq", 1) > 1 else None
    # Tokens [B, T]: batch over the batch axes, tokens over ``seq``.
    bspec = P(ba, sp)
    # Stream [R, S, mb, T]: microbatch rows over the batch axes,
    # tokens over ``seq``. Label stream [M, mb, T] keeps FULL
    # sequences per member (the in-stage loss slices its shard's
    # shifted targets out of it — pipe loss_fn).
    mbspec = P(None, "pipe", ba, sp)
    lblspec = P(None, ba)
    return baxes, bspec, mbspec, lblspec


def _tp_stage_fn(cfg: PipeLMConfig, mesh: Mesh, *, inner_vjp: bool = False):
    """stage_fn for the pipeline kernels, TP-aware.

    With ``tp_size == 1`` the stage applies its blocks directly. With
    TP the blocks are the Megatron variant (models/vit.py EncoderBlock
    column/row wiring): shard_map binds every mesh axis, so inside the
    pipeline island each ``model`` member holds its head/hidden shard
    of every stage (``_param_specs`` rests the kernels sharded over
    ``model``), activations stay full-size, and the row matmuls psum
    over ``model`` — two psums per block, exactly the seq-family TP.

    ``inner_vjp``: True for the hand-scheduled schedules (their
    explicit in-body jax.vjp needs Megatron's f/g ops to place the
    cross-member gradient sums the shard_map transpose would otherwise
    insert); False for the AD/GPipe path, where f/g would double-count.
    """
    del mesh
    stage = _stage_module(
        cfg, tp=cfg.tp_size > 1, inner_vjp=cfg.tp_size > 1 and inner_vjp,
        ep=cfg.ep_size > 1, sp=cfg.sp_size > 1,
    )

    def stage_fn(p, x):
        return stage.apply({"params": p}, x)

    return stage_fn


def make_pipe_lm_apply(cfg: PipeLMConfig, mesh: Mesh):
    """Jitted pipelined ``apply(params, tokens) -> logits`` (GPipe)."""
    _check_sp_mesh(cfg, mesh)
    stage_fn = _tp_stage_fn(cfg, mesh)
    first_fn = _make_first_fn(cfg)
    last_fn = _make_last_fn(cfg)
    baxes, bspec, mbspec, _ = _specs(mesh)

    def apply_fn(params: PipeLMParams, tokens):
        tokens = lax.with_sharding_constraint(
            tokens, NamedSharding(mesh, bspec)
        )
        mbs, _ = _split_microbatches(cfg, mesh, tokens)
        sspecs = _param_specs(cfg, params.stages, mesh, lead=1)

        pipelined = jax.shard_map(
            lambda sp, fp, lp, m: spmd_pipeline(
                stage_fn, gather_stages(sp, sspecs), m, axis_name="pipe",
                first_fn=first_fn, first_params=fp,
                last_fn=last_fn, last_params=lp,
            ),
            mesh=mesh,
            in_specs=(sspecs, P(), P(), mbspec),
            out_specs=mbspec,
            check_vma=False,
        )
        lp = {"ln": params.back["ln"], "embed": params.front["embed"]}
        out = pipelined(params.stages, params.front, lp, mbs)
        return merge_microbatch_stream(out)

    return apply_fn


def _param_specs(cfg: PipeLMConfig, stages, mesh: Mesh, *, lead: int):
    """Stage-tree specs; TP leaves take their Megatron dim on ``model``
    (parallel/pipe_common.py ``stage_specs_megatron`` — shared with
    the pipelined ViT)."""
    return stage_specs_megatron(
        stages, mesh, lead=lead, tp_size=cfg.tp_size, ep_size=cfg.ep_size
    )


def make_pipe_lm_train_step(
    cfg: PipeLMConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    compute_dtype=jnp.float32,
    donate: bool = True,
    jit: bool = True,
    health: bool = False,
    health_inject: tuple[str, int] | None = None,
):
    """GPipe (AD-derived backward) train step over dp×pp[×fsdp×tp].

    The tied embedding's two uses (lookup in stage 0, head in stage
    S−1) are both closed over ``params.front["embed"]`` — AD sums the
    two gradient contributions automatically.
    """
    apply_fn = make_pipe_lm_apply(cfg, mesh)

    def step(state: PipeLMState, tokens):
        def loss_f(params):
            cparams = _cast_params(params, compute_dtype)
            logits = apply_fn(cparams, tokens)
            loss = next_token_loss(
                logits, tokens, label_smoothing=cfg.label_smoothing
            )
            pred = jnp.argmax(logits[:, :-1].astype(jnp.float32), -1)
            correct = (pred == tokens[:, 1:]).sum().astype(jnp.float32)
            return loss, correct

        (loss, correct), grads = jax.value_and_grad(loss_f, has_aux=True)(
            state.params
        )
        return _apply_update(
            cfg, optimizer, mesh, state, grads, loss, correct,
            tokens.shape, lead=1, health=health,
            health_inject=health_inject,
        )

    if not jit:
        return step  # raw: the compiled-epoch runner scans it
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def _cast_params(params: PipeLMParams, compute_dtype) -> PipeLMParams:
    if compute_dtype == jnp.float32:
        return params
    return jax.tree.map(lambda p: p.astype(compute_dtype), params)


def _apply_update(
    cfg, optimizer, mesh, state, grads, loss, correct, tok_shape, *,
    lead, health=False, health_inject=None,
):
    grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
    grads = _constrain_tp(cfg, grads, mesh, lead)
    if health_inject is not None:
        from ddp_tpu.obs.health import inject_nan

        grads = inject_nan(grads, state.step, health_inject)
    updates, opt_state = optimizer.update(
        grads, state.opt_state, state.params
    )
    params = _constrain_tp(
        cfg, optax.apply_updates(state.params, updates), mesh, lead
    )
    if health:
        # Per-layer-group health vectors (obs/health.py); stage-
        # stacked leaves reduce under GSPMD like any sharded tree.
        from ddp_tpu.obs.health import health_stats

        hstats = health_stats(grads, state.params, updates)
    else:
        hstats = None
    B, T = tok_shape
    denom = B * (T - 1)
    return (
        PipeLMState(state.step + 1, params, opt_state),
        StepMetrics(
            loss=loss, accuracy=correct / denom,
            grad_norm=optax.global_norm(grads),
            health=hstats,
        ),
    )


def _constrain_tp(cfg, params: PipeLMParams, mesh: Mesh, lead: int):
    sspecs = _param_specs(cfg, params.stages, mesh, lead=lead)
    return params._replace(
        stages=jax.tree.map(
            lambda x, s: lax.with_sharding_constraint(
                x, NamedSharding(mesh, s)
            ),
            params.stages,
            sspecs,
        )
    )


def _make_handsched_lm_step(
    cfg: PipeLMConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    pipeline_fn,
    sched,
    *,
    lead: int,
    compute_dtype,
    donate: bool,
    jit: bool = True,
    health: bool = False,
    health_inject: tuple[str, int] | None = None,
):
    """Shared 1F1B/interleaved step: hand-scheduled backward, loss
    inside the last stage, tied-embed grads summed across both ends."""
    if cfg.sp_size > 1 and cfg.sp_strategy == "ring":
        # CONCRETE blocker, not a scope cut: lax.ppermute lowers to a
        # group-LESS CollectivePermute naming every device in the
        # assignment, and the hand-scheduled kernels run the stage
        # body inside lax.switch branches that DIVERGE across pipe
        # members (stage s does fwd while s' does bwd at the same
        # tick) — so a ring hop issued inside a branch can never
        # assemble its full participant set and the step deadlocks
        # (reproduced: XLA CPU rendezvous timeout, two members at the
        # fwd ring's CollectivePermute, two at the bwd's). AllReduce /
        # AllToAll carry replica GROUPS that stay within one stage,
        # which is why the TP psums, EP all-to-alls, and Ulysses
        # compose with these schedules while ring cannot.
        raise ValueError(
            "ring attention does not compose with the hand-scheduled "
            "pipeline schedules (1f1b/interleaved): its ppermute hops "
            "have no replica groups, and the schedules' fwd/bwd "
            "branches diverge across pipe stages — use "
            "sp_strategy='ulysses' here, or the GPipe schedule "
            "(unconditional stage body) for ring"
        )
    _check_sp_mesh(cfg, mesh)
    stage_fn = _tp_stage_fn(cfg, mesh, inner_vjp=True)
    first_fn = _make_first_fn(cfg)
    last_fn = _make_last_fn(cfg)
    loss_fn = _loss_fn_factory(cfg)
    baxes, bspec, mbspec, lblspec = _specs(mesh)
    has_fsdp = mesh.shape.get("fsdp", 1) > 1
    has_sp = mesh.shape.get("seq", 1) > 1

    def make_run(sspecs):
        def inner(sp, fp, lp, m, l):
            loss, correct, gs, gf, gl = pipeline_fn(
                stage_fn, gather_stages(sp, sspecs), m, l, loss_fn,
                sched, axis_name="pipe",
                first_fn=first_fn, first_params=fp,
                last_fn=last_fn, last_params=lp,
            )
            # ``seq`` shards tokens, not params: every param grad
            # sums over it like a batch axis (the in-stage collectives
            # already routed the ACTIVATION grads between shards) —
            # folded into ONE reduction with the batch axes.
            raxes = tuple(baxes) + (("seq",) if has_sp else ())
            if raxes:
                loss = lax.psum(loss, raxes)
                correct = lax.psum(correct, raxes)
                gf = jax.tree.map(lambda g: lax.psum(g, raxes), gf)
                gl = jax.tree.map(lambda g: lax.psum(g, raxes), gl)
            if has_sp:
                gs = jax.tree.map(lambda g: lax.psum(g, "seq"), gs)
            if "data" in baxes:
                gs = jax.tree.map(lambda g: lax.psum(g, "data"), gs)
            if "expert" in baxes:
                # Expert-sharded leaves (wi/bi/wo/bo) need NO expert
                # reduction: the all_to_all pair already routed every
                # member's slots through the owning expert, so each
                # member's backward computes the complete grad for its
                # own experts. Replicated-over-expert leaves (attn,
                # dense MLPs, router, LNs) saw different tokens per
                # member → sum like any batch axis.
                gs = jax.tree.map(
                    lambda g, s: g if "expert" in s
                    else lax.psum(g, "expert"),
                    gs, sspecs,
                )
            if has_fsdp:
                gs = scatter_stage_grads(gs, sspecs)
            # TP needs no extra reduction here: each ``model`` member
            # computes the full grad for its own kernel shard, and
            # identical grads for replicated leaves (the row matmuls
            # psum activations inside the forward, so every member's
            # backward sees the same residual stream).
            return loss, correct, gs, gf, gl

        return jax.shard_map(
            inner,
            mesh=mesh,
            in_specs=(sspecs, P(), P(), mbspec, lblspec),
            out_specs=(P(), P(), sspecs, P(), P()),
            check_vma=False,
        )

    def step(state: PipeLMState, tokens):
        tokens = lax.with_sharding_constraint(
            tokens, NamedSharding(mesh, bspec)
        )
        B, T = tokens.shape
        mbs, lbl_mb = _split_microbatches(cfg, mesh, tokens)
        cparams = _cast_params(state.params, compute_dtype)
        run = make_run(
            _param_specs(cfg, state.params.stages, mesh, lead=lead)
        )
        lp = {"ln": cparams.back["ln"], "embed": cparams.front["embed"]}
        loss_sum, correct, gs, gf, gl = run(
            cparams.stages, cparams.front, lp, mbs, lbl_mb
        )
        # Tied embedding: lookup grad (front) + head grad (back).
        gf = dict(gf)
        gf["embed"] = gf["embed"] + gl["embed"]
        grads = PipeLMParams(
            front=gf, stages=gs, back={"ln": gl["ln"]}
        )
        denom = jnp.float32(B * (T - 1))
        grads = jax.tree.map(lambda g: g / denom, grads)
        loss = loss_sum / denom
        return _apply_update(
            cfg, optimizer, mesh, state, grads, loss, correct,
            tokens.shape, lead=lead, health=health,
            health_inject=health_inject,
        )

    if not jit:
        return step  # raw: the compiled-epoch runner scans it
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_pipe_lm_1f1b_train_step(
    cfg: PipeLMConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    compute_dtype=jnp.float32,
    donate: bool = True,
    jit: bool = True,
    health: bool = False,
    health_inject: tuple[str, int] | None = None,
):
    """1F1B: O(S) activation stash, loss inside stage S−1."""
    from ddp_tpu.parallel.one_f1b import schedule_1f1b, spmd_pipeline_1f1b

    S = mesh.shape["pipe"]
    return _make_handsched_lm_step(
        cfg, optimizer, mesh, spmd_pipeline_1f1b,
        schedule_1f1b(S, cfg.num_microbatches),
        lead=1, compute_dtype=compute_dtype, donate=donate, jit=jit,
        health=health, health_inject=health_inject,
    )


def make_pipe_lm_interleaved_train_step(
    cfg: PipeLMConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    compute_dtype=jnp.float32,
    donate: bool = True,
    jit: bool = True,
    health: bool = False,
    health_inject: tuple[str, int] | None = None,
):
    """Interleaved-1F1B: v chunks per device, bubble (S−1)/(vM+S−1)."""
    from ddp_tpu.parallel.interleaved import (
        schedule_interleaved,
        spmd_pipeline_interleaved,
    )

    S = mesh.shape["pipe"]
    if S != cfg.num_stages:
        raise ValueError(
            f"mesh pipe axis {S} != cfg.num_stages {cfg.num_stages}"
        )
    sched = schedule_interleaved(
        S, cfg.num_microbatches, cfg.virtual_stages
    )
    return _make_handsched_lm_step(
        cfg, optimizer, mesh, spmd_pipeline_interleaved, sched,
        lead=2, compute_dtype=compute_dtype, donate=donate, jit=jit,
        health=health, health_inject=health_inject,
    )


def create_pipe_lm_state(
    cfg: PipeLMConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    seed: int = 0,
    interleaved: bool = False,
) -> PipeLMState:
    """Sharded-at-rest state: stages over ``pipe`` (and ``fsdp``/
    ``model`` when composed), front/back replicated."""
    lead = 2 if interleaved else 1
    params = init_pipe_lm(cfg, seed=seed, interleaved=interleaved)
    sspecs = _param_specs(cfg, params.stages, mesh, lead=lead)
    rep = NamedSharding(mesh, P())
    params = PipeLMParams(
        front=jax.tree.map(lambda x: jax.device_put(x, rep), params.front),
        stages=jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params.stages,
            sspecs,
        ),
        back=jax.tree.map(lambda x: jax.device_put(x, rep), params.back),
    )
    opt_state = optimizer.init(params)
    opt_state = jax.tree.map(
        lambda x: jax.device_put(x, rep) if jnp.ndim(x) == 0 else x,
        opt_state,
    )
    return PipeLMState(
        step=jax.device_put(jnp.zeros((), jnp.int32), rep),
        params=params,
        opt_state=opt_state,
    )


def to_dense_lm(cfg: PipeLMConfig, params: PipeLMParams):
    """Pipe-layout params → the dense CausalLM tree + its LMSpec.

    Train pipelined, serve dense: the returned tree is exactly what
    ``models/lm.py`` CausalLM builds (embed / pos_embed / blockN /
    ln_final), so the whole serving stack — ``dense_lm_apply``,
    models/generate.py prefill + KV-cache decode, scripts/predict.py —
    consumes a pipelined run's weights unchanged. Chunk c's block j
    becomes dense block c·depth_per_stage + j + 1 ([v, S] layouts
    flatten chunk-major, matching ``sequential_apply``).
    """
    from ddp_tpu.models.lm import LMSpec

    stages = params.stages
    if min(p.ndim for p in jax.tree.leaves(stages)) == 3:
        stages = jax.tree.map(lambda p: p.reshape(-1, *p.shape[2:]), stages)
    C = jax.tree.leaves(stages)[0].shape[0]
    dense = {
        "embed": params.front["embed"],
        "pos_embed": params.front["pos_embed"],
        "ln_final": params.back["ln"],
    }
    for c in range(C):
        chunk = jax.tree.map(lambda p: p[c], stages)
        for j in range(cfg.depth_per_stage):
            dense[f"block{c * cfg.depth_per_stage + j + 1}"] = chunk[
                f"block{j + 1}"
            ]
    spec = LMSpec(
        vocab_size=cfg.vocab_size,
        total_len=cfg.seq_len,
        d_model=cfg.d_model,
        depth=C * cfg.depth_per_stage,
        num_heads=cfg.num_heads,
        num_kv_heads=cfg.num_kv_heads,
        num_experts=cfg.num_experts,
        moe_every=cfg.moe_every,
        moe_top_k=cfg.moe_top_k,
        moe_normalize_gates=cfg.moe_normalize_gates,
        mlp_ratio=cfg.mlp_ratio,
    )
    return spec, dense


def make_pipe_lm_eval_step(
    cfg: PipeLMConfig, mesh: Mesh, *, compute_dtype=jnp.float32
):
    """Trainer-compatible eval over the sequential (non-pipelined)
    forward — same signature as models/lm.py make_lm_eval_step."""

    def step(params: PipeLMParams, model_state, tokens, labels, weights):
        del model_state, labels
        logits = sequential_apply(
            cfg, _cast_params(params, compute_dtype), tokens
        )
        targets = tokens[:, 1:]
        logits32 = logits[:, :-1].astype(jnp.float32)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(
            logits32, targets
        )
        seq_loss = per_tok.mean(axis=1)
        seq_acc = (jnp.argmax(logits32, -1) == targets).mean(axis=1)
        return (seq_acc * weights).sum(), (seq_loss * weights).sum()

    return jax.jit(step)

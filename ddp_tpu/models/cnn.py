"""SimpleCNN — the reference's flagship model, re-expressed for TPU.

Capability parity with ``model.py:4-20`` (``SimpleCNN(nn.Module)``):
Conv2d(1→32, 3×3, pad 1) → ReLU → Conv2d(32→64, 3×3, pad 1) → ReLU →
Flatten → Linear(64·28·28 → 10), 520,586 parameters. Differences are
deliberate TPU idiom, not behavior:

- NHWC layout (TPU-native; the reference is NCHW) — flatten order
  therefore differs, but the function class and parameter count are
  identical.
- Weights are initialized from an explicit PRNG key; running the same
  seed on every process replaces DDP's constructor-time rank-0
  parameter broadcast (train_ddp.py:34) with determinism by
  construction.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class SimpleCNN(nn.Module):
    """2-conv + linear MNIST classifier (model.py:4-20 parity).

    ``features`` defaults to the reference's (32, 64); tests shrink it
    to keep emulated-CPU runs cheap.
    """

    num_classes: int = 10
    features: tuple[int, int] = (32, 64)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # x: [B, 28, 28, 1] float. SAME padding preserves 28×28 like the
        # reference's padding=1 (model.py:9,12).
        x = nn.Conv(
            features=self.features[0], kernel_size=(3, 3), padding="SAME", name="conv1"
        )(x)
        x = nn.relu(x)
        x = nn.Conv(
            features=self.features[1], kernel_size=(3, 3), padding="SAME", name="conv2"
        )(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))  # Flatten (model.py:15)
        x = nn.Dense(features=self.num_classes, name="fc")(x)  # model.py:16
        return x

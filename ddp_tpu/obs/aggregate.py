"""Multi-process telemetry aggregation: N serve endpoints, one view.

ROADMAP item 1's multi-replica router needs exactly one input this
repo did not have: a single fleet-level view of per-process serving
telemetry — aggregate tokens/s, per-endpoint health, and which
endpoint is burning its SLO budget fastest. This module builds that
view two ways:

- **live**: scrape each endpoint's ``/statusz`` (JSON: engine stats,
  SLO state, build info) and ``/metricsz`` (Prometheus text, linted
  on the way in) over plain ``urllib`` — the exact interface a
  least-loaded dispatcher will poll;
- **offline**: read per-rank metrics JSONL streams
  (``serve_request``/``serve_step`` records from ``--metrics_file``)
  and reconstruct the same per-endpoint shape — post-hoc fleet
  analysis from artifacts alone, no live processes needed.

Latency summaries merge **exactly** through the existing
``StatSummary.merge`` (count/mean/min/max exact across the fold,
property-tested since PR 2): ``/statusz`` carries each summary's full
mergeable state (``summary_states``), not just the lossy snapshot, so
the fleet p50/p95 rides a combined reservoir instead of an average of
percentiles (which is not a percentile).

CLI: ``scripts/obs_aggregate.py``. Pure host-side stdlib — no jax.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from ddp_tpu.utils.metrics import StatSummary

# The summaries /statusz exports as mergeable states and the fleet
# view folds (engine.stats(include_states=True)).
MERGED_SUMMARIES = ("ttft_s", "tpot_s", "queue_s", "decode_tokens_per_s")


def classify_unreachable(exc: BaseException) -> str:
    """``'timeout'`` | ``'refused'`` | ``'unreachable'`` for a failed
    scrape/dispatch — the distinction the fleet router's circuit
    breaker needs: a TIMEOUT is a maybe-overloaded replica (count it
    toward the consecutive-failure threshold), a REFUSED connection is
    a dead one (nothing is listening — eject immediately instead of
    letting user requests time out against it). ``urllib``'s URLError
    wraps the underlying OSError in ``.reason``; unwrap before
    classifying."""
    import socket
    import urllib.error

    if isinstance(exc, urllib.error.URLError) and isinstance(
        exc.reason, BaseException
    ):
        exc = exc.reason
    if isinstance(exc, (socket.timeout, TimeoutError)):
        return "timeout"
    if isinstance(exc, ConnectionRefusedError):
        return "refused"
    return "unreachable"


def scrape_endpoint(url: str, *, timeout: float = 5.0) -> dict:
    """One endpoint's live view: /statusz JSON + linted /metricsz.

    Never raises on a dead endpoint — the fleet view must render with
    a hole where the sick replica is, not crash: failures come back
    as ``{"ok": False, "health": "timeout"|"refused"|..., "error":
    ...}`` rows. ``health`` distinguishes a scrape that TIMED OUT
    (endpoint alive but slow/overloaded) from one that was REFUSED
    (nothing listening) — the router treats the two differently.
    """
    import urllib.error
    import urllib.request

    from ddp_tpu.obs.promtext import validate_promtext

    url = url.rstrip("/")
    view: dict[str, Any] = {"endpoint": url, "ok": False}
    try:
        with urllib.request.urlopen(url + "/statusz", timeout=timeout) as r:
            view["statusz"] = json.loads(r.read().decode())
        with urllib.request.urlopen(url + "/metricsz", timeout=timeout) as r:
            text = r.read().decode()
        view["metricsz_samples"] = validate_promtext(text)
        view["ok"] = bool(view["statusz"].get("ok", False))
        view["health"] = "ok" if view["ok"] else "unhealthy"
    except ValueError as e:
        view["health"] = "bad_payload"
        view["error"] = f"{type(e).__name__}: {e}"
    except OSError as e:
        view["health"] = classify_unreachable(e)
        view["error"] = f"{type(e).__name__}: {e}"
    return view


def load_metrics_file(path: str) -> dict:
    """One per-rank metrics JSONL stream → the same endpoint shape.

    Rebuilds the latency summaries from ``serve_request`` records (so
    the offline fleet view merges through the identical
    ``StatSummary`` fold) and the token/step totals from
    ``serve_step`` records; torn tail lines are skipped, the
    health_report discipline.
    """
    # serve_request records carry the summaries under their exact
    # names — one source of truth, no field-mapping layer.
    summaries = {name: StatSummary() for name in MERGED_SUMMARIES}
    status_counts: dict[str, int] = {}
    tokens_total = 0
    steps = 0
    breaches: list[dict] = []
    t_first = t_last = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail of a live/killed run
            kind = rec.get("kind")
            if kind not in ("serve_request", "serve_step", "slo_breach"):
                continue
            t = rec.get("time")
            if isinstance(t, (int, float)):
                t_first = t if t_first is None else min(t_first, t)
                t_last = t if t_last is None else max(t_last, t)
            if kind == "serve_request":
                status = rec.get("status", "?")
                status_counts[status] = status_counts.get(status, 0) + 1
                for name in MERGED_SUMMARIES:
                    v = rec.get(name)
                    if v is not None:
                        summaries[name].add(float(v))
            elif kind == "serve_step":
                steps += 1
                tokens_total += int(rec.get("tokens") or 0)
            else:
                breaches.append(rec)
    wall_s = (
        (t_last - t_first) if t_first is not None and t_last > t_first
        else None
    )
    stats: dict[str, Any] = {
        "requests_by_status": status_counts,
        "tokens_total": tokens_total,
        "steps": steps,
        "summary_states": {
            n: s.to_state() for n, s in summaries.items() if s.count
        },
        **(
            {"goodput": {"wall_s": round(wall_s, 3)}}
            if wall_s
            else {}
        ),
    }
    view: dict[str, Any] = {
        "endpoint": path,
        "ok": True,
        "offline": True,
        "statusz": {"ok": True, "stats": stats},
    }
    if breaches:
        last = breaches[-1]
        view["statusz"]["slo_breaches"] = {
            "count": len(breaches),
            "last_objective": last.get("objective"),
            "last_burn_rate_fast": last.get("burn_rate_fast"),
        }
    return view


def _endpoint_row(view: dict) -> dict:
    """Flatten one scraped/offline view into a fleet-table row."""
    row: dict[str, Any] = {
        "endpoint": view.get("endpoint"),
        "ok": bool(view.get("ok")),
    }
    if "health" in view:
        row["health"] = view["health"]
    if "error" in view:
        row["error"] = view["error"]
        return row
    statusz = view.get("statusz") or {}
    stats = statusz.get("stats") or {}
    # Disaggregated fleets (PR 16): replicas advertise their serving
    # role on /statusz; classic replicas carry no key and the row
    # stays byte-identical.
    if "role" in statusz:
        row["role"] = statusz["role"]
    # Model-lifecycle replicas advertise their serving version in the
    # stats lifecycle block; versionless replicas carry no key and
    # the row stays byte-identical.
    version = (stats.get("lifecycle") or {}).get("model_version")
    if version is not None:
        row["model_version"] = version
    for key in ("active", "slots", "queue_depth", "tokens_total"):
        if key in stats:
            row[key] = stats[key]
    if "draining" in statusz:
        row["draining"] = statusz["draining"]
    bi = stats.get("build_info") or statusz.get("build_info")
    if bi:
        row["build"] = f"{bi.get('version')}/{bi.get('backend')}"
    wall = (stats.get("goodput") or {}).get("wall_s")
    if wall and stats.get("tokens_total") is not None:
        row["tokens_per_s"] = round(stats["tokens_total"] / wall, 2)
    by_status = stats.get("requests_by_status") or {}
    if by_status:
        row["requests"] = sum(by_status.values())
    slo = stats.get("slo")
    if slo:
        worst = max(
            slo.get("objectives", []),
            key=lambda o: o.get("burn_rate_fast", 0.0),
            default=None,
        )
        if worst is not None:
            row["slo_worst"] = {
                "objective": worst.get("name"),
                "burn_rate_fast": worst.get("burn_rate_fast"),
                "breached": worst.get("breached"),
            }
        row["slo_breached"] = bool(slo.get("breached"))
    elif "slo_breaches" in statusz:  # offline streams: breach records
        sb = statusz["slo_breaches"]
        row["slo_worst"] = {
            "objective": sb.get("last_objective"),
            "burn_rate_fast": sb.get("last_burn_rate_fast"),
            "breached": True,
        }
        row["slo_breached"] = True
    return row


def merge_fleet(views: list[dict]) -> dict:
    """N endpoint views → one fleet view (the router's input).

    Aggregate tokens/s is the sum of per-endpoint rates; request
    counts sum by status; latency summaries fold EXACTLY via
    ``StatSummary.merge`` over the states each view carries; the
    worst-SLO pointer names the endpoint to shed load from (or roll)
    first.
    """
    rows = [_endpoint_row(v) for v in views]
    merged = {name: None for name in MERGED_SUMMARIES}
    status_totals: dict[str, int] = {}
    tokens_per_s = 0.0
    tokens_total = 0
    for view in views:
        stats = (view.get("statusz") or {}).get("stats") or {}
        for status, n in (stats.get("requests_by_status") or {}).items():
            status_totals[status] = status_totals.get(status, 0) + int(n)
        tokens_total += int(stats.get("tokens_total") or 0)
        wall = (stats.get("goodput") or {}).get("wall_s")
        if wall and stats.get("tokens_total") is not None:
            tokens_per_s += stats["tokens_total"] / wall
        for name, state in (stats.get("summary_states") or {}).items():
            if name not in merged or not state.get("count"):
                continue
            incoming = StatSummary.from_state(state)
            if merged[name] is None:
                merged[name] = incoming
            else:
                merged[name].merge(incoming)
    worst = None
    for row in rows:
        w = row.get("slo_worst")
        if w is None or w.get("burn_rate_fast") is None:
            continue
        if worst is None or (
            w["burn_rate_fast"] > worst["burn_rate_fast"]
        ):
            worst = {**w, "endpoint": row["endpoint"]}
    # Per-role rollup, present only when some endpoint advertises a
    # role (disaggregated fleets) — classic fleet views stay
    # byte-identical. Dead endpoints scraped before their role was
    # known simply don't contribute; their holes still render in the
    # endpoint rows above.
    by_role: dict[str, dict[str, Any]] = {}
    for row in rows:
        role = row.get("role")
        if not role:
            continue
        g = by_role.setdefault(
            role,
            {"replicas": 0, "tokens_per_s": 0.0, "queue_depth": 0},
        )
        g["replicas"] += 1
        g["tokens_per_s"] = round(
            g["tokens_per_s"] + row.get("tokens_per_s", 0.0), 2
        )
        g["queue_depth"] += int(row.get("queue_depth") or 0)
    # Version rollup (lifecycle PR), present only when some endpoint
    # advertises one: the merged-fleet convergence observable — one
    # entry while converged, two mid-roll.
    model_versions: dict[str, int] = {}
    for row in rows:
        v = row.get("model_version")
        if v is not None:
            model_versions[v] = model_versions.get(v, 0) + 1
    return {
        "endpoints": rows,
        "healthy": sum(1 for r in rows if r["ok"]),
        "unhealthy": sum(1 for r in rows if not r["ok"]),
        "aggregate": {
            "requests_by_status": status_totals,
            "tokens_total": tokens_total,
            "tokens_per_s": round(tokens_per_s, 2),
            **{
                name: s.snapshot(ndigits=6)
                for name, s in merged.items()
                if s is not None
            },
        },
        **({"by_role": by_role} if by_role else {}),
        **(
            {"model_versions": dict(sorted(model_versions.items()))}
            if model_versions
            else {}
        ),
        **({"slo_worst": worst} if worst else {}),
    }


def render_fleet(fleet: dict) -> str:
    """Human one-screen rendering (scripts/obs_aggregate.py default)."""
    lines = ["ddp_tpu fleet view", "=" * 18]
    lines.append(
        f"endpoints     : {fleet['healthy']} healthy, "
        f"{fleet['unhealthy']} unhealthy"
    )
    agg = fleet["aggregate"]
    if agg.get("requests_by_status"):
        detail = ", ".join(
            f"{k}: {v}"
            for k, v in sorted(agg["requests_by_status"].items())
        )
        lines.append(
            f"requests      : {sum(agg['requests_by_status'].values())} "
            f"({detail})"
        )
    lines.append(
        f"tokens        : {agg.get('tokens_total', 0)} total, "
        f"{agg.get('tokens_per_s', 0.0)} tok/s aggregate"
    )
    for name, label in (
        ("ttft_s", "ttft"),
        ("tpot_s", "tpot"),
        ("queue_s", "queue wait"),
    ):
        snap = agg.get(name)
        if snap and snap.get("count"):
            lines.append(
                f"{label:<14}: p50 {snap.get('p50')}s  "
                f"p95 {snap.get('p95')}s  (n={snap['count']})"
            )
    for role, g in sorted((fleet.get("by_role") or {}).items()):
        lines.append(
            f"role {role:<9}: {g['replicas']} replica(s), "
            f"{g['tokens_per_s']} tok/s, queue={g['queue_depth']}"
        )
    worst = fleet.get("slo_worst")
    if worst:
        lines.append(
            f"slo worst     : {worst.get('objective')} burn "
            f"{worst.get('burn_rate_fast')} at {worst.get('endpoint')}"
            + (" [BREACHED]" if worst.get("breached") else "")
        )
    for row in fleet["endpoints"]:
        bits = [f"ok={1 if row['ok'] else 0}"]
        if row.get("role"):
            bits.append(f"role={row['role']}")
        if not row["ok"] and row.get("health"):
            # timeout (maybe-overloaded) vs refused (dead) — the two
            # demand different operator responses, so name which.
            bits.append(f"health={row['health']}")
        if "error" in row:
            bits.append(f"error={row['error']}")
        if row.get("draining"):
            bits.append("draining")
        if "active" in row and "slots" in row:
            bits.append(f"lanes={row['active']}/{row['slots']}")
        if "queue_depth" in row:
            bits.append(f"queue={row['queue_depth']}")
        if "tokens_per_s" in row:
            bits.append(f"tok/s={row['tokens_per_s']}")
        if row.get("slo_breached"):
            bits.append("SLO-BREACHED")
        if "build" in row:
            bits.append(f"build={row['build']}")
        lines.append(f"  {row['endpoint']}: " + " ".join(bits))
    return "\n".join(lines) + "\n"

"""Per-request distributed tracing for the serve path.

The span tracer (obs/tracer.py) answers "what was the ENGINE doing at
time T"; this module answers the complementary question an operator
triaging one slow completion actually asks: "where did request X spend
its 900 ms". Every request gets a **64-bit trace id at admission**
(derived deterministically from the scheduler's request id — the id
survives across the HTTP response, the metrics stream, the Perfetto
trace, and the /requestz endpoint, so one grep follows a request
through every telemetry plane), and the engine hangs lightweight event
records off the slot/lane bookkeeping it already keeps:

    admit -> queue -> prefill_chunk[i] (bucket, tokens)
          -> spec_round[j] (drafted/accepted) -> decode (steps, tokens)
          -> retire (reason)

Events are stamped ONLY at points where the engine already touches the
host (submit, slot bind, chunk/decode dispatch, the one-step-behind
retirement) — request tracing adds **zero device syncs** and the
steady-state decode loop stays provably transfer-free under
``--sanitize`` (pinned by tests/test_reqtrace.py re-running the
transfer-spy with tracing enabled).

Export rides the existing tracer as Perfetto **nestable async spans**
(ph ``b``/``e``/``n``, ``cat: "request"``, ``id`` = the hex trace id):
a merged multi-rank trace groups every request's lifecycle onto one
async track per id, and :func:`reconstruct_requests` +
:func:`validate_request_timeline` rebuild and causally check any
request's timeline from the merged document — what
``scripts/trace_merge.py`` runs over every merge.

Disabled mode (the default) is free: the engine skips every recording
call behind one ``is None`` check; no per-request objects exist.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Optional

# Span taxonomy (docs/OBSERVABILITY.md "Request tracing & SLOs").
REQUEST_SPAN = "request"  # the admit->retire umbrella
ADMIT = "req.admit"
QUEUE = "req.queue"
PREFILL_CHUNK = "req.prefill_chunk"
SPEC_ROUND = "req.spec_round"
DECODE = "req.decode"
RETIRE = "req.retire"

ASYNC_CAT = "request"

# Fleet hop taxonomy (docs/OBSERVABILITY.md "Fleet-wide tracing").
# Router-side spans live on their own async category: hop spans start
# BEFORE the replica's admit, so they cannot share the "request"
# category whose validator bounds every event inside admit..retire.
HOP_CAT = "hop"
HOP_DISPATCH = "hop.dispatch"
HOP_RETRY = "hop.retry"
HOP_HEDGE = "hop.hedge"
HOP_BREAKER_WAIT = "hop.breaker_wait"
HOP_HANDOFF = "hop.prefill_handoff"
HOP_MIGRATE = "hop.migrate"
HOP_MIGRATE_EXPORT = "hop.migrate_export"
HOP_MIGRATE_INSTALL = "hop.migrate_install"

# MPMD per-step spans (parallel/mpmd.py) — same mechanism, third cat.
STEP_CAT = "step"

# Bound on retired timelines kept for /requestz (per engine) — a
# week-long serving process must not grow a timeline per request
# forever, same discipline as the tracer ring.
DEFAULT_KEEP = 512


def splitmix64(x: int) -> int:
    """SplitMix64 finalizer: a cheap, well-mixed 64-bit hash."""
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


def derive_trace_id(seed: int, rid: int) -> int:
    """The request's 64-bit trace id, assigned at admission.

    Deterministic in (seed, rid) so tests can pin ids; a serving
    process seeds from os.urandom (scripts/serve.py) so two replicas'
    id spaces don't collide in a merged fleet trace. Never zero —
    0 is the "no id" sentinel everywhere downstream.
    """
    return splitmix64((int(seed) & 0xFFFFFFFFFFFFFFFF) ^ (int(rid) << 1)) or 1


def format_trace_id(trace_id: int) -> str:
    """Canonical hex form (the Perfetto ``id`` and /requestz key)."""
    return f"0x{int(trace_id) & 0xFFFFFFFFFFFFFFFF:016x}"


def derive_span_id(trace_id: int, salt: int) -> int:
    """A span id under ``trace_id`` (one per router attempt / hop).

    Deterministic in (trace_id, salt) for the same reason
    :func:`derive_trace_id` is; never zero (0 = "no parent")."""
    return splitmix64(
        (int(trace_id) & 0xFFFFFFFFFFFFFFFF) ^ ((int(salt) << 1) | 1)
    ) or 1


def encode_trace_context(
    trace_id: int, span_id: int, parent_span_id: int = 0
) -> str:
    """One-line traceparent-style context: ``00-<trace>-<span>-<parent>``.

    64-bit ids in fixed 16-hex (the house trace-id width), version
    pinned to ``00``. This single line is what rides the /generate and
    /pages/export JSON bodies, the DPKV migration header, and the ACTV
    p2p ``meta`` — the receiver's parent is this line's ``span`` field.
    """
    return "00-{:016x}-{:016x}-{:016x}".format(
        int(trace_id) & 0xFFFFFFFFFFFFFFFF,
        int(span_id) & 0xFFFFFFFFFFFFFFFF,
        int(parent_span_id) & 0xFFFFFFFFFFFFFFFF,
    )


def parse_trace_context(line) -> Optional[tuple]:
    """Parse a trace-context line into ``(trace_id, span_id, parent)``.

    Returns ``None`` on ANY malformation (wrong type, field count,
    version, width, non-hex, zero trace id) — never raises. A peer
    sending garbage must cost the receiver one counter bump
    (``trace_orphaned``), not a crash or a rejected request.
    """
    if not isinstance(line, str):
        return None
    parts = line.strip().split("-")
    if len(parts) != 4 or parts[0] != "00":
        return None
    if any(len(p) != 16 for p in parts[1:]):
        return None
    try:
        trace_id, span_id, parent = (int(p, 16) for p in parts[1:])
    except ValueError:
        return None
    if trace_id == 0:
        return None
    return trace_id, span_id, parent


class RequestTrace:
    """One request's event record, hung off the engine's bookkeeping.

    Pure host state: a list of (name, t0_perf, dur_s, args) tuples
    plus the aggregate decode counters the per-step loop bumps in
    place of per-step events (one span per request, not one per
    token — the record stays O(chunks + spec rounds)).
    """

    __slots__ = (
        "rid", "trace_id", "events", "admit_t", "bind_t", "retire_t",
        "decode_t0", "decode_end", "decode_steps", "decode_tokens",
        "chunks", "spec_rounds", "reason", "emitted", "parent",
    )

    def __init__(
        self, rid: int, trace_id: int, admit_t: float,
        parent: Optional[str] = None,
    ):
        self.rid = rid
        self.trace_id = int(trace_id)
        self.parent = parent  # hex span id of the router attempt
        self.admit_t = admit_t  # perf_counter domain
        self.bind_t: Optional[float] = None
        self.retire_t: Optional[float] = None
        self.decode_t0: Optional[float] = None
        self.decode_end: Optional[float] = None
        self.decode_steps = 0
        self.decode_tokens = 0
        self.chunks = 0
        self.spec_rounds = 0
        self.reason: Optional[str] = None
        self.emitted = False
        self.events: list[tuple] = [(ADMIT, admit_t, 0.0, {"rid": rid})]

    # ---- recording (called from the engine's host-touch points) -----

    def bind(self, t: float) -> None:
        """Queue head popped into a lane: the queue span closes."""
        self.bind_t = t
        self.events.append((QUEUE, self.admit_t, t - self.admit_t, None))

    def prefill_chunk(
        self, t0: float, dur_s: float, *, start: int, bucket: int,
        tokens: int, final: bool,
    ) -> None:
        self.events.append((
            PREFILL_CHUNK, t0, dur_s,
            {"i": self.chunks, "start": start, "bucket": bucket,
             "tokens": tokens, "final": final},
        ))
        self.chunks += 1

    def spec_round(
        self, t0: float, dur_s: float, *, drafted: int, accepted: int,
        emitted: int,
    ) -> None:
        self.events.append((
            SPEC_ROUND, t0, dur_s,
            {"j": self.spec_rounds, "drafted": drafted,
             "accepted": accepted, "emitted": emitted},
        ))
        self.spec_rounds += 1
        self._decode_step(t0, emitted)

    def decode_step(self, t0: float, tokens: int = 1) -> None:
        """One decode dispatch covering this lane (aggregate — the
        per-request decode record is ONE span, closed at retire)."""
        self._decode_step(t0, tokens)

    def _decode_step(self, t0: float, tokens: int) -> None:
        if self.decode_t0 is None:
            self.decode_t0 = t0
        self.decode_end = t0
        self.decode_steps += 1
        self.decode_tokens += tokens

    def retire(self, t: float, reason: str) -> None:
        self.retire_t = t
        self.reason = reason
        if self.decode_t0 is not None:
            self.events.append((
                DECODE, self.decode_t0, t - self.decode_t0,
                {"steps": self.decode_steps, "tokens": self.decode_tokens},
            ))
        self.events.append((RETIRE, t, 0.0, {"reason": reason}))

    # ---- views ------------------------------------------------------

    def summary(self) -> dict:
        """The per-completion digest (``Completion.trace``)."""
        end = self.retire_t if self.retire_t is not None else self.admit_t
        out: dict[str, Any] = {
            "trace_id": format_trace_id(self.trace_id),
            **({"parent": self.parent} if self.parent else {}),
            "queue_s": round(
                (self.bind_t if self.bind_t is not None else end)
                - self.admit_t, 6,
            ),
            "prefill_chunks": self.chunks,
            "decode_steps": self.decode_steps,
            "total_s": round(end - self.admit_t, 6),
        }
        chunk_events = [e for e in self.events if e[0] == PREFILL_CHUNK]
        if chunk_events:
            first = chunk_events[0]
            last = chunk_events[-1]
            out["prefill_s"] = round(last[1] + last[2] - first[1], 6)
        if self.decode_t0 is not None:
            out["decode_s"] = round(end - self.decode_t0, 6)
        if self.spec_rounds:
            drafted = sum(
                e[3]["drafted"] for e in self.events if e[0] == SPEC_ROUND
            )
            accepted = sum(
                e[3]["accepted"] for e in self.events if e[0] == SPEC_ROUND
            )
            out["spec"] = {
                "rounds": self.spec_rounds,
                "drafted": drafted,
                "accepted": accepted,
                "acceptance": (
                    round(accepted / drafted, 4) if drafted else None
                ),
            }
        if self.reason is not None:
            out["reason"] = self.reason
        return out

    def timeline(self) -> dict:
        """The full JSON-ready event list (the /requestz payload)."""
        return {
            "rid": self.rid,
            "trace_id": format_trace_id(self.trace_id),
            "events": [
                {
                    "name": name,
                    "t_s": round(t0 - self.admit_t, 6),
                    "dur_s": round(dur, 6),
                    **({"args": args} if args else {}),
                }
                for name, t0, dur, args in self.events
            ],
            "summary": self.summary(),
        }

    def emit(self, tracer) -> None:
        """Write the record into the span tracer as Perfetto nestable
        async events (id = the hex trace id) — called at retire (a
        point the engine already owns the host) or retroactively via
        ``ServeEngine.emit_request_spans()``; timestamps are the
        stamps recorded when the events happened, so emission cost
        never sits inside a measured window."""
        if self.emitted or not tracer.enabled:
            return
        aid = format_trace_id(self.trace_id)
        end = self.retire_t if self.retire_t is not None else self.admit_t

        def _args(a):
            # An adopted request stamps its router-attempt parent span
            # onto EVERY event so a merged fleet document can tell the
            # hedge winner's decode path from the cancelled loser's —
            # both hang off the same trace id. Absent when not adopted.
            if self.parent is None:
                return a
            return {**(a or {}), "parent": self.parent}

        tracer.async_complete(
            REQUEST_SPAN, self.admit_t, end - self.admit_t, aid,
            _args({"rid": self.rid, "reason": self.reason}),
        )
        for name, t0, dur, args in self.events:
            if dur > 0.0:
                tracer.async_complete(name, t0, dur, aid, _args(args))
            else:
                tracer.async_instant(name, t0, aid, _args(args))
        self.emitted = True


class RequestTracer:
    """The engine's request-trace registry: live traces keyed by rid,
    a bounded ring of retired ones for /requestz, and the trace-id ↔
    rid index. All host dict ops; the engine guards every call on the
    feature flag so disabled mode allocates nothing."""

    def __init__(self, *, keep: int = DEFAULT_KEEP, clock=time.perf_counter):
        self.keep = max(1, int(keep))
        self.clock = clock
        self._live: dict[int, RequestTrace] = {}
        self._retired: "OrderedDict[int, RequestTrace]" = OrderedDict()

    def admit(
        self, rid: int, trace_id: int, parent: Optional[str] = None
    ) -> RequestTrace:
        t = RequestTrace(rid, trace_id, self.clock(), parent=parent)
        self._live[rid] = t
        return t

    def get(self, rid: int) -> Optional[RequestTrace]:
        return self._live.get(rid)

    def retire(self, rid: int, reason: str, tracer=None) -> Optional[RequestTrace]:
        t = self._live.pop(rid, None)
        if t is None:
            return None
        t.retire(self.clock(), reason)
        if tracer is not None:
            t.emit(tracer)
        self._retired[rid] = t
        while len(self._retired) > self.keep:
            self._retired.popitem(last=False)
        return t

    def lookup(self, key) -> Optional[RequestTrace]:
        """By rid (int / decimal string) or hex trace id ("0x…")."""
        s = str(key)
        if s.lower().startswith("0x"):
            try:
                tid = int(s, 16)
            except ValueError:
                return None
            for t in self._live.values():
                if t.trace_id == tid:
                    return t
            for t in reversed(self._retired.values()):
                if t.trace_id == tid:
                    return t
            return None
        try:
            rid = int(s)
        except ValueError:
            return None
        return self._live.get(rid) or self._retired.get(rid)

    def recent(self, limit: int = 32) -> list[dict]:
        out = []
        for t in list(reversed(self._retired.values()))[:limit]:
            out.append({
                "rid": t.rid,
                "trace_id": format_trace_id(t.trace_id),
                "reason": t.reason,
            })
        return out

    def emit_all(self, tracer) -> int:
        """Retroactively emit every not-yet-emitted retired trace —
        the bench path: its timed window runs with the tracer's
        measuring mode off (span fidelity would destroy the overlap
        being measured), then exports the request spans after."""
        n = 0
        for t in self._retired.values():
            if not t.emitted:
                t.emit(tracer)
                n += 1
        return n

    @property
    def live_count(self) -> int:
        return len(self._live)

    @property
    def retired_count(self) -> int:
        return len(self._retired)


# ---- reconstruction from exported traces -----------------------------


def reconstruct_requests(
    events: list[dict], cat: str = ASYNC_CAT
) -> dict[str, list[dict]]:
    """Group a trace document's async request events by trace id.

    Input is ``traceEvents`` (one rank's file or a merged document);
    output maps hex trace id → that request's events as
    ``{"name", "ph", "ts", "dur"?, "args"?}`` sorted by (ts, begin-
    before-end). ``b``/``e`` pairs are folded into one entry carrying
    ``dur`` (matched per (pid, id, name) as a stack, the nestable-
    async contract — pid scopes the fold so two PROCESSES emitting
    the same span name under one trace id, a hedge winner and its
    cancelled loser, never cross-pair in a merged document);
    unmatched begins surface with ``dur: None`` so a torn ring still
    reconstructs partially instead of raising. ``cat`` selects the
    async category — "request" (default, the engine's lifecycle
    events), "hop" (router spans), or "step" (MPMD stages).
    """
    by_id: dict[str, list[dict]] = {}
    open_spans: dict[tuple, list[dict]] = {}
    order = {"b": 0, "n": 1, "e": 2}
    for ev in sorted(
        (e for e in events if e.get("cat") == cat
         and e.get("ph") in ("b", "e", "n")),
        key=lambda e: (e.get("ts", 0), order.get(e.get("ph"), 3)),
    ):
        aid = str(ev.get("id"))
        ph = ev["ph"]
        if ph == "n":
            by_id.setdefault(aid, []).append({
                "name": ev["name"], "ph": "n", "ts": ev["ts"],
                **({"args": ev["args"]} if ev.get("args") else {}),
            })
        elif ph == "b":
            entry = {
                "name": ev["name"], "ph": "X", "ts": ev["ts"],
                "dur": None,
                **({"args": ev["args"]} if ev.get("args") else {}),
            }
            by_id.setdefault(aid, []).append(entry)
            open_spans.setdefault(
                (ev.get("pid"), aid, ev["name"]), []
            ).append(entry)
        else:  # "e"
            stack = open_spans.get((ev.get("pid"), aid, ev["name"]))
            if stack:
                entry = stack.pop()
                entry["dur"] = round(ev["ts"] - entry["ts"], 3)
    for evs in by_id.values():
        evs.sort(key=lambda e: e["ts"])
    return by_id


def validate_request_timeline(timeline: list[dict]) -> dict:
    """Causal-ordering check for one reconstructed request.

    Raises ``ValueError`` naming the violated invariant; returns a
    summary on success. The invariants are exactly the engine's
    lifecycle contract:

    - one umbrella ``request`` span bounding everything;
    - ``req.admit`` first, ``req.retire`` last (by timestamp);
    - the queue span starts at admit and ends before any prefill
      chunk runs;
    - prefill chunks are sequential: indices 0..n-1 ascending, each
      chunk ends (ts+dur) before the next begins;
    - decode/spec activity starts only after the LAST chunk started
      (the final chunk's lane joins the decode batch the same step),
      and ends by retire.

    Timestamps are µs with 1e-3 rounding; comparisons use a 1 µs
    epsilon so rounding can never fail a genuinely ordered timeline.
    """
    eps = 1.0  # µs
    if not timeline:
        raise ValueError("empty timeline")
    named = {}
    for ev in timeline:
        named.setdefault(ev["name"], []).append(ev)
    for required in (REQUEST_SPAN, ADMIT, RETIRE):
        if required not in named:
            raise ValueError(f"missing {required} event")
    umbrella = named[REQUEST_SPAN][0]
    if umbrella["dur"] is None:
        raise ValueError("unclosed request umbrella span")
    t_admit = named[ADMIT][0]["ts"]
    t_retire = named[RETIRE][-1]["ts"]
    if t_retire + eps < t_admit:
        raise ValueError("retire precedes admit")
    for ev in timeline:
        if ev["ts"] + eps < t_admit:
            raise ValueError(f"{ev['name']} precedes admit")
        if ev["ts"] - eps > t_retire:
            raise ValueError(f"{ev['name']} follows retire")
    chunks = named.get(PREFILL_CHUNK, [])
    idxs = [c.get("args", {}).get("i") for c in chunks]
    if idxs != sorted(idxs) or len(set(idxs)) != len(idxs):
        raise ValueError(f"prefill chunk indices out of order: {idxs}")
    for a, b in zip(chunks, chunks[1:]):
        if a["dur"] is not None and a["ts"] + a["dur"] - eps > b["ts"]:
            raise ValueError(
                f"prefill chunks overlap: chunk {a.get('args')} runs "
                f"past chunk {b.get('args')}"
            )
    queue = named.get(QUEUE, [None])[0]
    if queue is not None and chunks:
        if queue["dur"] is not None and (
            queue["ts"] + queue["dur"] - eps > chunks[0]["ts"]
        ):
            raise ValueError("queue span runs past the first prefill chunk")
    decode = named.get(DECODE, [None])[0]
    if decode is not None:
        if chunks and decode["ts"] + eps < chunks[-1]["ts"]:
            raise ValueError("decode starts before the final prefill chunk")
        if decode["dur"] is not None and (
            decode["ts"] + decode["dur"] - eps > t_retire
        ):
            raise ValueError("decode span runs past retire")
    for r in named.get(SPEC_ROUND, []):
        if decode is None:
            raise ValueError("spec round outside any decode span")
        if r["ts"] + eps < decode["ts"]:
            raise ValueError("spec round precedes the decode span")
    retire_args = named[RETIRE][-1].get("args", {})
    return {
        "reason": retire_args.get("reason"),
        "chunks": len(chunks),
        "spec_rounds": len(named.get(SPEC_ROUND, [])),
        "queue_us": queue["dur"] if queue else None,
        "total_us": round(t_retire - t_admit, 3),
    }


# ---- fleet reconstruction (router hops + N replica timelines) --------


def reconstruct_fleet(events: list[dict]) -> dict[str, dict]:
    """Join router hop spans and replica request events per trace id.

    Input is a MERGED document's ``traceEvents`` (the router's trace
    dir plus every replica's); output maps hex trace id →
    ``{"hops": [...], "request": [...]}`` where each list is the
    :func:`reconstruct_requests` shape for that category. Ids with
    hops but no request events (an orphaned dispatch — the replica
    never adopted, or its ring was lost to a SIGKILL) still appear so
    the validator can name what is missing.
    """
    hops = reconstruct_requests(events, cat=HOP_CAT)
    reqs = reconstruct_requests(events, cat=ASYNC_CAT)
    return {
        aid: {"hops": hops[aid], "request": reqs.get(aid, [])}
        for aid in sorted(hops)
    }


def validate_fleet_timeline(fleet: dict) -> dict:
    """Causal check for ONE request's cross-process fleet timeline.

    ``fleet`` is one value of :func:`reconstruct_fleet`. Raises
    ``ValueError`` naming the violated invariant; returns a summary on
    success. The invariants are the router↔replica contract:

    - at least one ``hop.dispatch`` span, exactly ONE marked winner;
    - exactly one replica admit whose ``parent`` is the winning
      dispatch's span id (hedge losers and pre-replay attempts may
      add more admits under the same trace id — they must NOT win);
    - the winning dispatch begins before that admit (cross-process
      clocks: both sides anchor perf_counter to time.time, so a
      generous epsilon absorbs the anchoring jitter — the real gap is
      a full HTTP round trip);
    - every migration export ends before its paired install begins,
      and prefill handoff/migration staging precede the winning
      dispatch (router-local clock, tight epsilon);
    - the winning replica's own admit→retire timeline passes
      :func:`validate_request_timeline`.
    """
    eps_local = 1.0       # µs — one process's own clock
    eps_cross = 5000.0    # µs — router vs replica perf anchoring
    hops = fleet.get("hops") or []
    request = fleet.get("request") or []
    dispatches = [h for h in hops if h["name"] == HOP_DISPATCH]
    if not dispatches:
        raise ValueError("no hop.dispatch span")
    winners = [
        d for d in dispatches if (d.get("args") or {}).get("winner")
    ]
    if len(winners) != 1:
        raise ValueError(
            f"expected exactly one winning dispatch, saw {len(winners)}"
        )
    winner = winners[0]
    wspan = (winner.get("args") or {}).get("span")
    if not wspan:
        raise ValueError("winning dispatch carries no span id")
    admits = [e for e in request if e["name"] == ADMIT]
    if not admits:
        raise ValueError("no replica admit for this trace id")
    won_admits = [
        a for a in admits if (a.get("args") or {}).get("parent") == wspan
    ]
    if len(won_admits) != 1:
        raise ValueError(
            "expected exactly one admit adopted from the winning "
            f"dispatch, saw {len(won_admits)}"
        )
    if winner["ts"] - eps_cross > won_admits[0]["ts"]:
        raise ValueError("router dispatch follows replica admit")
    exports = [h for h in hops if h["name"] == HOP_MIGRATE_EXPORT]
    installs = [h for h in hops if h["name"] == HOP_MIGRATE_INSTALL]
    for ex, ins in zip(exports, installs):
        end = ex["ts"] + (ex["dur"] or 0.0)
        if end - eps_local > ins["ts"]:
            raise ValueError("migration install precedes its export")
    for h in hops:
        if h["name"] in (HOP_HANDOFF, HOP_MIGRATE):
            if h["ts"] - eps_local > winner["ts"]:
                raise ValueError(
                    f"{h['name']} follows the winning dispatch"
                )
    # Exactly one winning decode path: every event of the winning
    # attempt carries the winner's parent span id.
    winning = [
        e for e in request
        if (e.get("args") or {}).get("parent") == wspan
    ]
    req_summary = validate_request_timeline(winning)
    hop_seconds = {}
    for h in hops:
        if h.get("ph") == "X" and h.get("dur") is not None:
            hop_seconds[h["name"]] = round(
                hop_seconds.get(h["name"], 0.0) + h["dur"] / 1e6, 6
            )
    return {
        "winner_replica": (winner.get("args") or {}).get("replica"),
        "attempts": len(dispatches),
        "hedged": any(h["name"] == HOP_HEDGE for h in hops),
        "migrated": bool(exports),
        "hop_seconds": hop_seconds,
        "request": req_summary,
    }

"""Anomaly sentry: rolling-window detectors over per-step records.

Host-side (no device work), fed by the HealthMonitor's one-step-behind
ingestion. Four detectors, each against its own rolling baseline so a
slowly-drifting run never false-positives while a discontinuity fires
on the step that caused it:

- ``loss_spike``      — loss > mean + k·std of the window AND > 1.5×
                        the window mean (the second clause keeps a
                        flat-loss window's zero std from arming a
                        hair trigger);
- ``grad_explosion``  — global grad norm > k× the window median;
- ``straggler``       — host step interval > k× the rolling p50
                        (utils/metrics.StatSummary carries the
                        distribution — same machinery as serve TTFT);
- ``recompile_storm`` — more than N steps in the window paid an XLA
                        compile after the warmup grace (a shape leak:
                        steady-state training must compile nothing).

Detectors arm only after ``min_steps`` observations (the baselines
need mass) and re-emit at most once per ``cooldown`` steps — an
anomaly is one event, not one event per step until the window forgets.

What to DO about an event is the trainer's decision (``--health_action
warn | checkpoint | halt``); the sentry only detects and describes.
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass

from ddp_tpu.utils.metrics import StatSummary

ACTIONS = ("warn", "checkpoint", "halt")


@dataclass(frozen=True)
class SentryConfig:
    window: int = 32  # rolling-baseline length (steps)
    min_steps: int = 8  # observations before any detector arms
    loss_spike_sigma: float = 6.0
    grad_explode_factor: float = 10.0
    straggler_factor: float = 4.0
    recompile_limit: int = 3  # tolerated compiling steps per window
    cooldown: int = 32  # min steps between repeats per detector

    def __post_init__(self):
        if self.window < 2 or self.min_steps < 2:
            raise ValueError("sentry window/min_steps must be >= 2")


class AnomalySentry:
    """Stateful detector bank; ``observe`` one step → events list."""

    def __init__(self, config: SentryConfig | None = None):
        self.cfg = config or SentryConfig()
        w = self.cfg.window
        self._losses: deque[float] = deque(maxlen=w)
        self._gnorms: deque[float] = deque(maxlen=w)
        self._times = StatSummary(max_samples=max(64, 4 * w))
        self._compiling_steps: deque[int] = deque(maxlen=w)
        self._seen = 0
        self._last_emit: dict[str, int] = {}
        self.counts: dict[str, int] = {}

    # ---- internals ---------------------------------------------------

    def _emit(self, events: list, detector: str, step: int, **fields):
        last = self._last_emit.get(detector)
        if last is not None and step - last < self.cfg.cooldown:
            return
        self._last_emit[detector] = step
        self.counts[detector] = self.counts.get(detector, 0) + 1
        events.append({"detector": detector, "step": step, **fields})

    @property
    def _armed(self) -> bool:
        return self._seen >= self.cfg.min_steps

    # ---- the one entry point ----------------------------------------

    def observe(
        self,
        step: int,
        *,
        loss: float | None = None,
        grad_norm: float | None = None,
        step_time_s: float | None = None,
        recompiles: int = 0,
    ) -> list[dict]:
        """Feed one step's scalars; baselines update AFTER the checks
        so an anomalous value never dilutes the window it is judged
        against."""
        cfg = self.cfg
        events: list[dict] = []

        # Anomalous values never enter their own baseline (whether the
        # cooldown let them emit or not): a spike absorbed into the
        # window would raise the threshold and mask the NEXT spike.
        # A genuine regime shift then re-fires once per cooldown —
        # the honest reading of a baseline that no longer holds.
        if loss is not None and math.isfinite(loss):
            spiking = False
            if self._armed and len(self._losses) >= cfg.min_steps:
                mean = math.fsum(self._losses) / len(self._losses)
                var = math.fsum(
                    (v - mean) ** 2 for v in self._losses
                ) / len(self._losses)
                std = math.sqrt(var)
                spiking = (
                    loss > mean + cfg.loss_spike_sigma * std
                    and loss > 1.5 * mean + 1e-6
                )
                if spiking:
                    self._emit(
                        events, "loss_spike", step,
                        value=round(loss, 6),
                        baseline=round(mean, 6),
                    )
            if not spiking:
                self._losses.append(loss)

        if grad_norm is not None and math.isfinite(grad_norm):
            exploding = False
            if self._armed and len(self._gnorms) >= cfg.min_steps:
                med = sorted(self._gnorms)[len(self._gnorms) // 2]
                exploding = (
                    med > 0 and grad_norm > cfg.grad_explode_factor * med
                )
                if exploding:
                    self._emit(
                        events, "grad_explosion", step,
                        value=round(grad_norm, 6),
                        baseline=round(med, 6),
                    )
            if not exploding:
                self._gnorms.append(grad_norm)

        if step_time_s is not None and step_time_s >= 0:
            p50 = self._times.percentile(50)
            straggling = (
                self._armed
                and self._times.count >= cfg.min_steps
                and p50 is not None
                and p50 > 0
                and step_time_s > cfg.straggler_factor * p50
            )
            if straggling:
                self._emit(
                    events, "straggler", step,
                    value=round(step_time_s, 6),
                    baseline=round(p50, 6),
                )
            else:
                self._times.add(step_time_s)

        if recompiles > 0:
            # Record the OBSERVATION index, not the step number: a
            # resumed run's steps start wherever the checkpoint left
            # off, so a step-number grace would excuse nothing and the
            # fresh process's legitimate warmup compiles would read as
            # a storm (fatal under --health_action halt).
            self._compiling_steps.append(self._seen)
        if self._armed:
            in_window = sum(
                1
                for s in self._compiling_steps
                # Only observations past the warmup grace count: the
                # first min_steps observations legitimately compile
                # the program set.
                if s >= cfg.min_steps and self._seen - s < cfg.window
            )
            if in_window > cfg.recompile_limit:
                self._emit(
                    events, "recompile_storm", step,
                    value=in_window,
                    baseline=cfg.recompile_limit,
                )
                self._compiling_steps.clear()

        self._seen += 1
        return events

    def snapshot(self) -> dict:
        return {
            "observed_steps": self._seen,
            "events": dict(self.counts),
            "step_time_s": self._times.snapshot(ndigits=6),
        }

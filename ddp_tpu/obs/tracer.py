"""In-process span tracer → Perfetto/Chrome ``trace_event`` JSON.

``jax.profiler`` answers "which kernel is slow" but costs a heavyweight
capture and says nothing about the *host* side — input wait, scheduler
stalls, checkpoint flushes. This tracer is the complement: always-on-
capable host-level spans with bounded memory (a ring of the last N
events), thread-safe begin/end, and an export any Perfetto/
``chrome://tracing`` instance loads directly.

Design constraints, in priority order:

1. **Disabled mode is free.** ``span()`` on a disabled tracer returns
   one cached null context manager — the SAME object every call — and
   ``instant()`` returns immediately. No jax import, no jit, no growing
   allocation (pinned by tests/test_obs.py).
2. **Enabled mode is bounded.** Events live in a ``deque(maxlen=
   ring_events)``: a week-long serving process holds at most the ring.
   Per-span-name duration summaries (utils/metrics.StatSummary) are
   capped at ``MAX_SUMMARY_NAMES`` distinct names so a cardinality bug
   upstream cannot grow memory either.
3. **Export is crash-safe.** ``export()`` writes to a temp file in the
   target directory and ``os.replace``s it — a crash mid-export leaves
   the previous trace intact, never a half-written JSON. The launcher
   path (``install_from_env``) additionally registers an atexit export
   so a watchdog abort or uncaught exception still leaves a trace.

Timestamps are Unix-epoch microseconds (``perf_counter`` deltas pinned
to ``time.time`` at construction) so per-rank traces from different
processes merge onto one comparable timeline (scripts/trace_merge.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Optional

from ddp_tpu.utils.metrics import StatSummary

# Env vars the launcher/child processes use to switch tracing on
# without plumbing flags through every worker signature.
TRACE_DIR_ENV = "DDP_TPU_TRACE_DIR"
RING_EVENTS_ENV = "DDP_TPU_TRACE_RING_EVENTS"

DEFAULT_RING_EVENTS = 65536
MAX_SUMMARY_NAMES = 256

# Canonical per-rank trace filename (the launcher writes one per rank;
# trace_merge globs this pattern).
RANK_TRACE_FILENAME = "trace_rank{rank}.trace.json"


class _NullSpan:
    """The disabled-mode context manager: one shared immutable object."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """A live span: records duration on ``__exit__``."""

    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._t0 = 0.0

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self._tracer._end_span(self.name, self._t0, self.args)
        return False


class Tracer:
    """Thread-safe bounded span/instant recorder.

    ``enabled=False`` (the default) makes every method a constant-cost
    no-op. ``process_id`` becomes the Chrome ``pid`` so merged
    multi-rank traces show one track group per rank.
    """

    def __init__(
        self,
        *,
        enabled: bool = False,
        ring_events: int = DEFAULT_RING_EVENTS,
        process_id: int = 0,
    ):
        from collections import deque

        self.enabled = bool(enabled)
        self.process_id = int(process_id)
        self.ring_events = max(1, int(ring_events))
        self._events: Any = deque(maxlen=self.ring_events)
        self._lock = threading.Lock()
        self._summaries: dict[str, StatSummary] = {}
        self._dropped = 0
        # perf_counter→unix pin: exported ts are absolute µs, so traces
        # from different ranks/processes align on one timeline.
        self._unix_base = time.time() - time.perf_counter()

    # ---- recording --------------------------------------------------

    def span(self, name: str, args: Optional[dict] = None):
        """Context manager timing one span. ``args`` (a plain dict or
        None — not kwargs, to keep the disabled path allocation-free)
        lands in the event's Perfetto ``args`` pane."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        """A zero-duration marker event."""
        if not self.enabled:
            return
        now = time.perf_counter()
        self._record("i", name, now, 0.0, args)

    def counter(self, name: str, values: dict) -> None:
        """A Perfetto counter sample (``ph: "C"``): ``values`` maps
        series name → number and renders as a counter track (the HBM
        used/high-water track rides this). Free when disabled, like
        every recording path."""
        if not self.enabled:
            return
        self._record("C", name, time.perf_counter(), 0.0, values)

    def complete(
        self,
        name: str,
        start_perf: float,
        dur_s: float,
        args: Optional[dict] = None,
    ) -> None:
        """Record a span retroactively from stamps already in hand
        (``start_perf`` from ``time.perf_counter``) — the attribution
        path measures first and records after, so the recording cost
        never sits inside the measured window."""
        if not self.enabled:
            return
        self._record("X", name, start_perf, max(0.0, dur_s), args)

    def async_complete(
        self,
        name: str,
        start_perf: float,
        dur_s: float,
        aid: str,
        args: Optional[dict] = None,
        *,
        cat: str = "request",
    ) -> None:
        """A nestable async span (Perfetto ph ``b``/``e``) recorded
        retroactively. ``aid`` is the async id — events sharing
        (cat, id) land on one async track, which is how per-request
        lifecycle spans group across engine steps (obs/reqtrace.py).
        Free when disabled, like every recording path."""
        if not self.enabled:
            return
        self._record("b", name, start_perf, 0.0, args, aid=aid, cat=cat)
        self._record(
            "e", name, start_perf + max(0.0, dur_s), 0.0, None,
            aid=aid, cat=cat,
        )

    def async_instant(
        self,
        name: str,
        t_perf: float,
        aid: str,
        args: Optional[dict] = None,
        *,
        cat: str = "request",
    ) -> None:
        """A nestable async instant (ph ``n``) at an explicit stamp."""
        if not self.enabled:
            return
        self._record("n", name, t_perf, 0.0, args, aid=aid, cat=cat)

    def _end_span(self, name: str, t0: float, args: Optional[dict]) -> None:
        now = time.perf_counter()
        self._record("X", name, t0, now - t0, args)

    def _record(
        self, ph: str, name: str, t0: float, dur_s: float,
        args: Optional[dict],
        aid: Optional[str] = None,
        cat: Optional[str] = None,
    ) -> None:
        tid = threading.get_ident()
        with self._lock:
            if len(self._events) == self.ring_events:
                self._dropped += 1
            self._events.append((ph, name, t0, dur_s, tid, args, aid, cat))
            if ph == "X":
                summ = self._summaries.get(name)
                if summ is None:
                    if len(self._summaries) >= MAX_SUMMARY_NAMES:
                        return
                    summ = self._summaries[name] = StatSummary()
                summ.add(dur_s)

    # ---- export -----------------------------------------------------

    def _event_dicts(self, limit: Optional[int] = None) -> list[dict]:
        with self._lock:
            raw = list(self._events)
        if limit is not None:
            raw = raw[-limit:]
        out = []
        for ph, name, t0, dur_s, tid, args, aid, cat in raw:
            ev: dict[str, Any] = {
                "ph": ph,
                "name": name,
                "ts": round((self._unix_base + t0) * 1e6, 3),
                "pid": self.process_id,
                "tid": tid,
            }
            if ph == "X":
                ev["dur"] = round(dur_s * 1e6, 3)
            if ph == "i":
                ev["s"] = "t"  # thread-scoped instant
            if ph in ("b", "e", "n"):
                # Nestable async events: matched per (pid, cat, id) —
                # the per-request lifecycle tracks (obs/reqtrace.py).
                ev["cat"] = cat or "request"
                ev["id"] = aid
            if args:
                ev["args"] = args
            out.append(ev)
        return out

    def summaries(self) -> dict[str, dict]:
        """Per-span-name duration snapshots (seconds)."""
        with self._lock:
            names = list(self._summaries.items())
        return {n: s.snapshot(ndigits=6) for n, s in names}

    def summary_states(self) -> dict[str, dict]:
        """Mergeable per-name StatSummary states (trace_merge input)."""
        with self._lock:
            names = list(self._summaries.items())
        return {n: s.to_state() for n, s in names}

    def snapshot(self, *, limit: Optional[int] = 512) -> dict:
        """Live, JSON-ready view for the server's /statusz route."""
        return {
            "enabled": self.enabled,
            "traceEvents": self._event_dicts(limit),
            "dropped_events": self._dropped,
            "span_summaries": self.summaries(),
        }

    def trace_document(self) -> dict:
        """The full exportable Chrome/Perfetto trace object."""
        meta = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": self.process_id,
                "tid": 0,
                "args": {"name": f"ddp_tpu rank {self.process_id}"},
            }
        ]
        return {
            "traceEvents": meta + self._event_dicts(),
            "displayTimeUnit": "ms",
            "ddp_tpu": {
                "rank": self.process_id,
                "dropped_events": self._dropped,
                "span_summaries": self.summary_states(),
            },
        }

    def export(self, path: str) -> str:
        """Crash-safe write of the trace document to ``path``."""
        path = os.path.abspath(path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.trace_document(), f)
        os.replace(tmp, path)
        return path

    def export_to_dir(self, trace_dir: str) -> str:
        return self.export(
            os.path.join(
                trace_dir,
                RANK_TRACE_FILENAME.format(rank=self.process_id),
            )
        )


# ---- process-global tracer (launcher / env wiring) -------------------

_GLOBAL = Tracer()
_GLOBAL_LOCK = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer (disabled until someone installs one)."""
    return _GLOBAL


def install_from_env(
    process_id: int = 0, *, register_atexit: bool = True
) -> Tracer:
    """Enable the global tracer iff ``DDP_TPU_TRACE_DIR`` is set.

    Called by runtime/launch.py in every spawned child so worker
    functions get per-rank trace files without new plumbing. The
    atexit export makes the trace survive crashes and watchdog aborts
    (``os._exit`` skips atexit — the watchdog dumps stacks instead;
    everything softer than that still exports).
    """
    global _GLOBAL
    trace_dir = os.environ.get(TRACE_DIR_ENV)
    if not trace_dir:
        return _GLOBAL
    ring = int(os.environ.get(RING_EVENTS_ENV, DEFAULT_RING_EVENTS))
    with _GLOBAL_LOCK:
        tracer = Tracer(
            enabled=True, ring_events=ring, process_id=process_id
        )
        _GLOBAL = tracer
    if register_atexit:
        import atexit

        atexit.register(_export_quietly, tracer, trace_dir)
    return tracer


def _export_quietly(tracer: Tracer, trace_dir: str) -> None:
    try:
        tracer.export_to_dir(trace_dir)
    except OSError:
        pass  # interpreter teardown: never turn exit into a traceback


# ---- schema validation (shared by tests and trace_merge) -------------


def validate_trace_file(path: str) -> dict:
    """Load ``path`` and check the Chrome ``trace_event`` essentials.

    Raises ``ValueError`` with a precise reason on any violation —
    this is what the smoke tier runs against an emitted trace so an
    exporter regression fails tier-1 fast, and what trace_merge runs
    on every input before merging. Returns the parsed document.
    """
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: not valid JSON: {e}") from e
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: top level must be an object")
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError(f"{path}: traceEvents must be a list")
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"{path}: event {i} is not an object")
        ph = ev.get("ph")
        if not isinstance(ph, str) or not ph:
            raise ValueError(f"{path}: event {i} missing ph")
        if not isinstance(ev.get("name"), str):
            raise ValueError(f"{path}: event {i} missing name")
        if ph == "M":
            continue  # metadata events carry no timestamp
        if not isinstance(ev.get("ts"), (int, float)):
            raise ValueError(f"{path}: event {i} missing numeric ts")
        if not isinstance(ev.get("pid"), int) or not isinstance(
            ev.get("tid"), int
        ):
            raise ValueError(f"{path}: event {i} missing pid/tid")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(
                    f"{path}: complete event {i} needs dur >= 0"
                )
        if ph in ("b", "e", "n"):
            # Nestable async events (the per-request lifecycle spans):
            # Perfetto matches them per (pid, cat, id) — both fields
            # are load-bearing, so their absence is a schema error.
            if not isinstance(ev.get("id"), (str, int)):
                raise ValueError(
                    f"{path}: async event {i} missing id"
                )
            if not isinstance(ev.get("cat"), str):
                raise ValueError(
                    f"{path}: async event {i} missing cat"
                )
    return doc

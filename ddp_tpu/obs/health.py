"""Run health: in-graph gradient statistics and NaN/Inf provenance.

The trainer's only divergence signals used to be a single global
``grad_norm`` scalar and a dead ``np.isfinite`` gate on the FINAL
loss — a NaN born in one layer at step 400 surfaced hours later as a
useless end-of-run number. This module makes health a first-class,
per-layer observable:

- :func:`health_stats` is a jit-fused pass over the (grads, params,
  updates) trees computing per-layer-group L2 norms, max-abs,
  non-finite element counts, and the update/param ratio — all as
  ``[G]`` arrays where ``G`` is the number of layer groups, so the
  device→host cost is a few tiny vectors, never a tree of scalars.
- :class:`HealthMonitor` retires those vectors ONE STEP BEHIND the
  dispatch (the serve engine's device-resident pattern): reading step
  N's stats blocks only until step N finished, which it has by the
  time step N+1 is dispatched — no host sync beyond the existing
  one-step-behind metrics fetch.
- **NaN provenance**: the first step whose stats show a non-finite
  gradient (or loss) is recorded with the first offending layer-group
  path, so a dead run names its layer and step instead of a final NaN.
- :func:`inject_nan` is the fault-injection hook (tests and game-day
  drills): poison one layer group's gradients at one step, inside the
  compiled graph, and assert the provenance names it.

Disabled mode is pinned free, like the tracer: ``health=False`` step
builders trace the identical graph (the health pass is a Python-level
branch at trace time), and a disabled monitor returns one cached empty
tuple per call — no jit cache entries, no growing allocations
(tests/test_health.py).

Layer grouping: a leaf's group label is the first two components of
its parameter path (``block1/attn``, ``front/embed``,
``Conv_0/kernel``) — deterministic, sorted, identical between the
traced pass and the host-side :func:`group_layout` the trainer uses to
decode the ``[G]`` vectors. "First offending layer" means first in
this sorted order among the groups that went non-finite at the
earliest bad step.
"""

from __future__ import annotations

import math
import time
from typing import Any, NamedTuple, Optional

import numpy as np

# Cached empty result for the disabled monitor (same object every
# call — the allocation-free pin).
_NO_EVENTS: tuple = ()


class NonFiniteLossError(RuntimeError):
    """The run's final loss is non-finite.

    Raised by the trainer's end-of-run gate instead of silently
    writing a degraded final record. Carries the flight-recorder dump
    path (post-mortem) and, when health stats were on, the first
    offending (layer, step) the monitor attributed.
    """

    def __init__(
        self,
        loss: float,
        *,
        dump_path: Optional[str] = None,
        first_nonfinite: Optional[tuple] = None,
    ):
        where = (
            f"; first non-finite gradient at layer "
            f"{first_nonfinite[0]!r} step {first_nonfinite[1]}"
            if first_nonfinite
            else ""
        )
        post = f"; flight recorder dump: {dump_path}" if dump_path else ""
        super().__init__(
            f"final loss is non-finite ({loss!r}){where}{post} — the "
            "run diverged; see docs/OBSERVABILITY.md §Run health"
        )
        self.loss = loss
        self.dump_path = dump_path
        self.first_nonfinite = first_nonfinite


class HealthHaltError(RuntimeError):
    """``--health_action halt``: an anomaly detector fired."""

    def __init__(self, events: list, *, dump_path: Optional[str] = None):
        names = ", ".join(sorted({e.get("detector", "?") for e in events}))
        post = f"; flight recorder dump: {dump_path}" if dump_path else ""
        super().__init__(
            f"health sentry halt: {names} at step "
            f"{events[0].get('step')}{post}"
        )
        self.events = events
        self.dump_path = dump_path


class HealthStats(NamedTuple):
    """Per-layer-group stats, each ``[G]`` in ``group_layout`` order.

    Norms are NaN-propagating on purpose (a NaN group norm IS the
    signal); ``grad_nonfinite`` counts non-finite elements exactly.
    """

    grad_norm: Any  # [G] f32 — L2 norm of the group's gradients
    grad_maxabs: Any  # [G] f32 — max |g| in the group
    grad_nonfinite: Any  # [G] int32 — non-finite element count
    param_norm: Any  # [G] f32
    update_norm: Any  # [G] f32
    update_ratio: Any  # [G] f32 — ||update|| / (||param|| + eps)


def _key_str(k) -> str:
    """One path component → plain string (DictKey/GetAttrKey/…)."""
    for attr in ("key", "name", "idx"):
        v = getattr(k, attr, None)
        if v is not None:
            return str(v)
    return str(k)


def leaf_labels(tree) -> list[str]:
    """Per-leaf group label, in ``jax.tree.leaves`` order."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [
        "/".join(_key_str(k) for k in path[:2]) or "<root>"
        for path, _ in flat
    ]


def group_layout(tree) -> tuple[tuple[str, ...], list[int]]:
    """→ (sorted group paths, per-leaf group index).

    The single source of truth for the ``[G]`` vector layout: the
    traced :func:`health_stats` and the host-side decoder both call
    this, so the index→path mapping cannot drift.
    """
    labels = leaf_labels(tree)
    paths = tuple(sorted(set(labels)))
    idx = {p: i for i, p in enumerate(paths)}
    return paths, [idx[l] for l in labels]


def health_stats(grads, params, updates) -> HealthStats:
    """The jit-fused health pass (call inside a train step).

    Per-leaf partial reductions followed by segment-reductions into
    ``[G]`` — O(leaves) tiny ops that XLA fuses into the step; the
    only new outputs are six ``[G]`` vectors.
    """
    import jax
    import jax.numpy as jnp

    paths, gidx = group_layout(grads)
    G = len(paths)
    # Device-resident segment ids (self-lint DDP002): the layout is
    # trace-time static either way, but a host-numpy constant inside
    # the traced stats pass materializes on host first — jnp pins it
    # directly as an on-device constant.
    seg = jnp.asarray(gidx, jnp.int32)

    def seg_sqnorm(tree):
        parts = jnp.stack(
            [
                jnp.sum(jnp.square(l.astype(jnp.float32)))
                for l in jax.tree.leaves(tree)
            ]
        )
        return jnp.sqrt(jax.ops.segment_sum(parts, seg, num_segments=G))

    g_leaves = jax.tree.leaves(grads)
    maxabs = jax.ops.segment_max(
        jnp.stack(
            [jnp.max(jnp.abs(l.astype(jnp.float32))) for l in g_leaves]
        ),
        seg,
        num_segments=G,
    )
    nonfinite = jax.ops.segment_sum(
        jnp.stack(
            [
                (jnp.int32(l.size) - jnp.isfinite(l).sum().astype(jnp.int32))
                for l in g_leaves
            ]
        ),
        seg,
        num_segments=G,
    )
    gnorm = seg_sqnorm(grads)
    pnorm = seg_sqnorm(params)
    unorm = seg_sqnorm(updates)
    return HealthStats(
        grad_norm=gnorm,
        grad_maxabs=maxabs,
        grad_nonfinite=nonfinite,
        param_norm=pnorm,
        update_norm=unorm,
        update_ratio=unorm / (pnorm + 1e-12),
    )


# ---- fault injection -------------------------------------------------


def parse_inject(spec: Optional[str]) -> Optional[tuple[str, int]]:
    """``"layer/group@step"`` → ``(label, step)``; None passes through."""
    if not spec:
        return None
    label, sep, step = spec.rpartition("@")
    if not sep or not label:
        raise ValueError(
            f"--health_inject_nan wants 'layer/group@step', got {spec!r}"
        )
    return label, int(step)


def inject_nan(grads, step, spec: tuple[str, int]):
    """Poison one layer group's gradients at one step, in-graph.

    Adds a step-gated NaN to every leaf of group ``spec[0]`` when
    ``step == spec[1]`` (broadcast: the whole leaf goes NaN, exactly
    like a real overflow would propagate) and +0.0 otherwise — same
    graph shape at every step, so no recompilation per step. Unknown
    labels fail at TRACE time, naming the valid groups.
    """
    import jax
    import jax.numpy as jnp

    label, at_step = spec
    labels = leaf_labels(grads)
    if label not in labels:
        raise ValueError(
            f"health_inject_nan: no layer group {label!r}; groups are "
            f"{sorted(set(labels))}"
        )
    flat, treedef = jax.tree_util.tree_flatten(grads)
    out = []
    for leaf, lbl in zip(flat, labels):
        if lbl == label:
            poison = jnp.where(
                step == at_step, jnp.float32(jnp.nan), jnp.float32(0.0)
            ).astype(leaf.dtype)
            leaf = leaf + poison
        out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


# ---- host-side monitor -----------------------------------------------


class HealthMonitor:
    """One-step-behind retirement of the step's health vectors.

    ``on_step(step_no, metrics)`` enqueues the just-dispatched step's
    ``(loss, health)`` refs and ingests the PREVIOUS step's — reading
    values that are already (or nearly) computed, so the monitor never
    stalls the dispatch pipeline by more than the one-step lag. Call
    ``drain()`` at epoch end to ingest the final pending step.

    Events (provenance + sentry detections) are returned to the caller
    (the trainer applies the configured action) and simultaneously
    written to the metrics JSONL (kind ``"health"``), the trace ring
    (instant events), and the flight recorder.
    """

    def __init__(
        self,
        *,
        enabled: bool = False,
        paths: tuple[str, ...] = (),
        sentry=None,
        metrics=None,
        tracer=None,
        recorder=None,
    ):
        self.enabled = bool(enabled)
        self.paths = tuple(paths)
        self.sentry = sentry
        self.metrics = metrics
        self.tracer = tracer
        self.recorder = recorder
        self._pending: Optional[tuple] = None
        self._last_t: Optional[float] = None
        # (layer label | None, step) of the FIRST non-finite observation.
        self.first_nonfinite: Optional[tuple[Optional[str], int]] = None
        self.events_total: dict[str, int] = {}
        self.last_loss: Optional[float] = None
        self.last_grad_norm: Optional[float] = None
        if self.enabled:
            from ddp_tpu.obs.steptime import CompileCounter

            CompileCounter.install()
            self._compiles = CompileCounter.count
            self._c_prev = self._compiles()

    def on_step(self, step_no: int, metrics) -> tuple | list:
        """Enqueue this step, ingest the previous one → its events."""
        if not self.enabled:
            return _NO_EVENTS
        now = time.perf_counter()
        dt = None if self._last_t is None else now - self._last_t
        self._last_t = now
        c = self._compiles()
        recompiles, self._c_prev = c - self._c_prev, c
        prev = self._pending
        self._pending = (
            step_no,
            metrics.loss,
            getattr(metrics, "health", None),
            dt,
            recompiles,
        )
        if prev is None:
            return _NO_EVENTS
        return self._ingest(*prev)

    def drain(self) -> tuple | list:
        """Ingest the final pending step (epoch/run end)."""
        if not self.enabled:
            return _NO_EVENTS
        # Reset the interval clock: the gap to the next epoch's first
        # step spans eval + checkpoint + epoch bookkeeping, which must
        # never reach the straggler detector as a step time.
        self._last_t = None
        if self._pending is None:
            return _NO_EVENTS
        prev, self._pending = self._pending, None
        return self._ingest(*prev)

    def _ingest(self, step_no, loss_ref, stats_ref, dt, recompiles):
        loss = float(np.asarray(loss_ref))
        self.last_loss = loss
        events: list[dict] = []
        grad_norm = None
        bad = np.array([], dtype=np.int64)
        if stats_ref is not None:
            nonfinite = np.asarray(stats_ref.grad_nonfinite)
            gnorms = np.asarray(stats_ref.grad_norm, dtype=np.float64)
            # Global norm from the group norms (NaN-propagating).
            grad_norm = float(np.sqrt(np.sum(np.square(gnorms))))
            self.last_grad_norm = grad_norm
            bad = np.flatnonzero(nonfinite > 0)
        if (len(bad) or not math.isfinite(loss)) and (
            self.first_nonfinite is None
        ):
            layer = self.paths[int(bad[0])] if len(bad) else None
            self.first_nonfinite = (layer, step_no)
            events.append(
                {
                    "detector": "nonfinite",
                    "step": step_no,
                    "layer": layer,
                    "layers": [self.paths[int(i)] for i in bad],
                    "loss": loss,
                }
            )
        if self.sentry is not None:
            events.extend(
                self.sentry.observe(
                    step_no,
                    loss=loss,
                    grad_norm=grad_norm,
                    step_time_s=dt,
                    recompiles=recompiles,
                )
            )
        for ev in events:
            d = ev.get("detector", "?")
            self.events_total[d] = self.events_total.get(d, 0) + 1
            if self.metrics is not None:
                self.metrics.write("health", **ev)
            if self.tracer is not None:
                self.tracer.instant(f"health.{d}", dict(ev))
            if self.recorder is not None:
                self.recorder.record("health", **ev)
        return events

    def snapshot(self) -> dict:
        """JSON-ready summary (the /metricsz train exposition input)."""
        out: dict[str, Any] = {"events": dict(self.events_total)}
        if self.first_nonfinite is not None:
            out["nonfinite_layer"] = self.first_nonfinite[0]
            out["nonfinite_step"] = self.first_nonfinite[1]
        if self.last_loss is not None:
            out["loss"] = self.last_loss
        if self.last_grad_norm is not None:
            out["grad_norm"] = self.last_grad_norm
        return out

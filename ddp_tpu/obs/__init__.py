"""Observability subsystem: spans, step-time attribution, goodput/MFU.

Three zero-dependency layers, all off by default and pinned always-cheap
when off (tests/test_obs.py: disabled mode triggers no jit compilation
and no growing per-step allocations):

- ``tracer``  — in-process span tracer with a bounded ring buffer and
  crash-safe export to Perfetto/Chrome ``trace_event`` JSON;
- ``steptime`` — splits each training step into host-input-wait /
  dispatch / device-compute and flags recompiles via a process-wide
  XLA compile-event counter;
- ``goodput`` — per-model FLOPs estimators (CNN, ResNet, ViT, LM/MoE),
  MFU arithmetic against per-chip peak, and a restart-aware goodput
  accountant persisted in a sidecar next to the checkpoints.

Wiring: ``--trace_dir`` on train.py (train/trainer.py), the serve
engine/server (spans + ``/statusz``), runtime/launch.py (per-rank
trace files, merged by scripts/trace_merge.py) and bench.py (``mfu``
and ``trace`` fields per record). docs/OBSERVABILITY.md has the full
story.
"""

from ddp_tpu.obs.goodput import (
    GoodputAccountant,
    peak_flops_per_chip,
    train_flops_per_example,
)
from ddp_tpu.obs.steptime import CompileCounter, StepAttributor, StepTiming
from ddp_tpu.obs.tracer import (
    Tracer,
    get_tracer,
    install_from_env,
    validate_trace_file,
)

__all__ = [
    "CompileCounter",
    "GoodputAccountant",
    "StepAttributor",
    "StepTiming",
    "Tracer",
    "get_tracer",
    "install_from_env",
    "peak_flops_per_chip",
    "train_flops_per_example",
    "validate_trace_file",
]

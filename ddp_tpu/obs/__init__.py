"""Observability subsystem: spans, attribution, goodput, run health.

Layers, all off by default and pinned always-cheap when off
(tests/test_obs.py + tests/test_health.py: disabled mode triggers no
jit compilation and no growing per-step allocations):

- ``tracer``  — in-process span tracer with a bounded ring buffer and
  crash-safe export to Perfetto/Chrome ``trace_event`` JSON;
- ``steptime`` — splits each training step into host-input-wait /
  dispatch / device-compute and flags recompiles via a process-wide
  XLA compile-event counter;
- ``goodput`` — per-model FLOPs estimators (CNN, ResNet, ViT, LM/MoE),
  MFU arithmetic against per-chip peak, and a restart-aware goodput
  accountant persisted in a sidecar next to the checkpoints;
- ``health`` — jit-fused per-layer-group gradient stats with NaN/Inf
  provenance (first offending layer path + step) and the one-step-
  behind HealthMonitor;
- ``sentry`` — rolling-window anomaly detectors (loss spike, grad
  explosion, straggler, recompile storm) with warn/checkpoint/halt
  actions;
- ``recorder`` — the flight recorder: a bounded ring of step records
  dumped crash-safely on exception, SIGTERM, and watchdog kill;
- ``promtext`` — Prometheus text exposition of the live counters,
  served at ``/metricsz`` (serve frontend + trainer metrics port),
  with a matching lint;
- ``xprof`` — compiled-program introspection: per-executable compile
  ledger (label, arg-shape signature, compile wall-time, XLA-measured
  FLOPs/bytes, memory breakdown, HLO collective payloads) plus the
  device-memory high-water/headroom sampler, cross-checking the
  analytic estimators and the zero strategy's hand-priced
  ``comm_bytes`` against what XLA actually built;
- ``reqtrace`` — per-request distributed tracing for the serve path:
  a 64-bit trace id at admission, lifecycle events (admit → queue →
  prefill chunks → spec rounds → decode → retire) hung off the
  engine's existing slot bookkeeping, exported as Perfetto async
  spans and reconstructable/causally-validated from merged traces;
- ``slo`` — declarative serving objectives
  (``ttft_p99<0.5s,availability>0.999``) evaluated over rolling
  windows with multi-window (5 m / 1 h) burn-rate alerting;
- ``aggregate`` — the multi-process telemetry aggregator: scrape N
  ``/statusz`` + ``/metricsz`` endpoints (or read per-rank metrics
  files offline), merge StatSummaries exactly, render one fleet view
  — the interface the multi-replica router will consume.

Wiring: ``--trace_dir`` / ``--health`` / ``--metrics_port`` on
train.py (train/trainer.py), the serve engine/server (spans +
``/statusz`` + ``/metricsz``), runtime/launch.py (per-rank trace
files, merged by scripts/trace_merge.py), bench.py, and
scripts/health_report.py (JSONL → triage report).
docs/OBSERVABILITY.md has the full story.
"""

from ddp_tpu.obs.goodput import (
    GoodputAccountant,
    peak_flops_per_chip,
    train_flops_per_example,
)
from ddp_tpu.obs.health import (
    HealthMonitor,
    HealthStats,
    NonFiniteLossError,
    group_layout,
    health_stats,
)
from ddp_tpu.obs.promtext import (
    PromBuilder,
    render_serve,
    render_train,
    validate_promtext,
)
from ddp_tpu.obs.aggregate import (
    load_metrics_file,
    merge_fleet,
    render_fleet,
    scrape_endpoint,
)
from ddp_tpu.obs.recorder import FlightRecorder, build_info
from ddp_tpu.obs.reqtrace import (
    RequestTrace,
    RequestTracer,
    derive_trace_id,
    format_trace_id,
    reconstruct_requests,
    validate_request_timeline,
)
from ddp_tpu.obs.slo import Objective, SLOEngine, parse_slo
from ddp_tpu.obs.sentry import AnomalySentry, SentryConfig
from ddp_tpu.obs.steptime import CompileCounter, StepAttributor, StepTiming
from ddp_tpu.obs.tracer import (
    Tracer,
    get_tracer,
    install_from_env,
    validate_trace_file,
)
from ddp_tpu.obs.xprof import (
    DeviceMemorySampler,
    Xprof,
    parse_hlo_collectives,
    ring_collective_traffic,
)

__all__ = [
    "AnomalySentry",
    "CompileCounter",
    "DeviceMemorySampler",
    "FlightRecorder",
    "GoodputAccountant",
    "HealthMonitor",
    "HealthStats",
    "NonFiniteLossError",
    "Objective",
    "PromBuilder",
    "RequestTrace",
    "RequestTracer",
    "SLOEngine",
    "SentryConfig",
    "StepAttributor",
    "StepTiming",
    "Tracer",
    "Xprof",
    "build_info",
    "derive_trace_id",
    "format_trace_id",
    "get_tracer",
    "group_layout",
    "health_stats",
    "install_from_env",
    "load_metrics_file",
    "merge_fleet",
    "parse_hlo_collectives",
    "parse_slo",
    "peak_flops_per_chip",
    "reconstruct_requests",
    "render_fleet",
    "render_serve",
    "render_train",
    "ring_collective_traffic",
    "scrape_endpoint",
    "train_flops_per_example",
    "validate_promtext",
    "validate_request_timeline",
    "validate_trace_file",
]

"""FLOPs estimators, MFU arithmetic, and restart-aware goodput.

MFU (model FLOPs utilization) is the production TPU efficiency metric
("Scalable Training of Language Models using JAX pjit and TPUv4"
reports it as the headline number): analytic model FLOPs actually
trained per second, divided by the chip's peak. It needs two inputs
this module owns — a per-model **train-FLOPs-per-example estimator**
(matmul/conv arithmetic only, the community convention; fwd ≈ the
model's matmuls, train ≈ 3× fwd for fwd+bwd) and a **per-chip peak**.

Peaks come from public spec sheets for TPU generations. Off-TPU there
is no honest peak, so a nominal ``FALLBACK_PEAK_FLOPS`` (1e12) keeps
the field populated as a *trend line* — CPU MFU values are comparable
run-to-run, never a hardware-efficiency claim (the record's
``platform`` field disambiguates, as bench.py's always has).

Goodput is the restart-aware companion: productive training seconds
divided by wall seconds since the FIRST launch, persisted in a
``goodput.json`` sidecar next to the checkpoints so preemptions and
auto-resumes (train/trainer.py) accumulate instead of resetting —
a run that crash-loops shows its true cost.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Callable, Optional

# ---- per-chip peak ---------------------------------------------------

# bf16 peak FLOP/s per chip by device kind (public spec sheets) —
# shared with bench.py's MFU estimates.
TPU_BF16_PEAK = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# Nominal off-TPU peak: keeps MFU a stable run-to-run trend line on
# dev boxes/CI where no spec-sheet number exists. Deliberately high so
# fallback MFU can never exceed a real machine's (mfu <= 1 stays true).
FALLBACK_PEAK_FLOPS = 1e12


def peak_flops_per_chip(device=None) -> float:
    """Per-chip peak for MFU. ``device`` defaults to jax.devices()[0].

    TPU kinds use the bf16 spec-sheet peak (the compute dtype every
    perf config here runs); unknown kinds and CPU/GPU fall back to the
    nominal constant.
    """
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for prefix, peak in TPU_BF16_PEAK.items():
        if kind.startswith(prefix):
            return peak
    return FALLBACK_PEAK_FLOPS


def mfu(
    examples_per_sec: float,
    flops_per_example: Optional[float],
    peak: Optional[float],
) -> Optional[float]:
    """Fraction of peak, or None when either input is unknown."""
    if not flops_per_example or not peak or peak <= 0:
        return None
    if not math.isfinite(examples_per_sec) or examples_per_sec < 0:
        return None
    return examples_per_sec * flops_per_example / peak


# ---- analytic FLOPs estimators ---------------------------------------
#
# All return TRAIN flops per example (3× forward: fwd + ~2× bwd), with
# forward = the matmul/conv terms only. Elementwise/norm/softmax work
# is excluded by convention — MFU compares against the MXU peak, which
# only the contractions can use.
#
# Cross-checked against the compiler, not just golden-pinned: the
# xprof layer (obs/xprof.py) reads XLA's own op count off the compiled
# train step, and tests/test_xprof.py pins measured/analytic within a
# per-family tolerance band (near 1 for the conv nets, above 1 for
# tiny transformers where the excluded elementwise work is a visible
# share). An estimator edit that drifts from the real program now
# fails there, not in a quiet MFU skew.


def conv_flops(h_out: int, w_out: int, k: int, c_in: int, c_out: int) -> float:
    return 2.0 * h_out * w_out * k * k * c_in * c_out


def cnn_train_flops(
    image_shape=(28, 28, 1),
    num_classes: int = 10,
    *,
    features=(32, 64),
    depth=None,  # registry-uniform signature; SimpleCNN has no depth knob
) -> float:
    """models/cnn.py SimpleCNN: two SAME 3×3 convs + flatten + fc."""
    h, w, c = image_shape
    f0, f1 = features
    fwd = (
        conv_flops(h, w, 3, c, f0)
        + conv_flops(h, w, 3, f0, f1)
        + 2.0 * (h * w * f1) * num_classes
    )
    return 3.0 * fwd


def resnet_train_flops(
    image_shape=(32, 32, 3),
    num_classes: int = 10,
    *,
    stage_sizes=(2, 2, 2, 2),
    bottleneck: bool = False,
    width: int = 64,
    cifar_stem: bool = True,
    depth=None,  # structure comes from stage_sizes here
) -> float:
    """models/resnet.py: walks the exact stage/stride structure."""
    h, _, c = image_shape
    fwd = 0.0
    if cifar_stem:
        fwd += conv_flops(h, h, 3, c, width)
    else:
        h = -(-h // 2)
        fwd += conv_flops(h, h, 7, c, width)
        h = -(-h // 2)  # 3×3/2 max pool, SAME
    c = width
    for stage, num_blocks in enumerate(stage_sizes):
        f = width * 2**stage
        out = f * 4 if bottleneck else f
        for block_idx in range(num_blocks):
            strides = 2 if stage > 0 and block_idx == 0 else 1
            h_out = -(-h // strides)
            if bottleneck:
                fwd += conv_flops(h, h, 1, c, f)  # 1×1 reduce (pre-stride)
                fwd += conv_flops(h_out, h_out, 3, f, f)
                fwd += conv_flops(h_out, h_out, 1, f, out)
            else:
                fwd += conv_flops(h_out, h_out, 3, c, f)
                fwd += conv_flops(h_out, h_out, 3, f, f)
            if c != out or strides != 1:
                fwd += conv_flops(h_out, h_out, 1, c, out)  # downsample
            h, c = h_out, out
    fwd += 2.0 * c * num_classes
    return 3.0 * fwd


def transformer_block_fwd_flops_per_token(
    d: int,
    total_len: int,
    *,
    num_heads: int = 1,
    num_kv_heads: int = 0,
    mlp_ratio: int = 4,
    causal: bool = False,
    moe: bool = False,
    num_experts: int = 0,
    top_k: int = 2,
) -> float:
    """One pre-LN encoder/decoder block, per token.

    qkv + output projections, the two attention matmuls (QK^T and
    attn·V — halved for causal masking), and the MLP (top_k experts'
    worth plus the router when ``moe``).
    """
    h_kv = num_kv_heads or num_heads
    qkv = 2.0 * d * d * (num_heads + 2 * h_kv) / num_heads
    proj = 2.0 * d * d
    keys = total_len / 2 if causal else total_len
    attn = 2.0 * 2.0 * keys * d
    if moe:
        mlp = top_k * 2.0 * 2.0 * mlp_ratio * d * d + 2.0 * d * num_experts
    else:
        mlp = 2.0 * 2.0 * mlp_ratio * d * d
    return qkv + proj + attn + mlp


def vit_train_flops(
    image_shape=(32, 32, 3),
    num_classes: int = 100,
    *,
    patch_size: int = 4,
    embed_dim: int = 192,
    depth: int = 12,
    num_heads: int = 3,
    mlp_ratio: int = 4,
    use_cls_token: bool = True,
    num_experts: int = 0,
    moe_every: int = 2,
    top_k: int = 2,
) -> float:
    """models/vit.py ViT (and moe.py MoEViT when num_experts > 0)."""
    from ddp_tpu.models.moe import is_moe_block

    h, _, c = image_shape
    T = (h // patch_size) ** 2 + (1 if use_cls_token else 0)
    d = embed_dim
    fwd = 2.0 * T * patch_size * patch_size * c * d  # patch embed
    for i in range(depth):
        is_moe = is_moe_block(i, num_experts, moe_every)
        fwd += T * transformer_block_fwd_flops_per_token(
            d, T, num_heads=num_heads, mlp_ratio=mlp_ratio,
            moe=is_moe, num_experts=num_experts, top_k=top_k,
        )
    fwd += 2.0 * d * num_classes  # head
    return 3.0 * fwd


def lm_train_flops_per_token(
    *,
    vocab_size: int,
    total_len: int,
    d_model: int,
    depth: int,
    num_heads: int = 4,
    num_kv_heads: int = 0,
    mlp_ratio: int = 4,
    num_experts: int = 0,
    moe_every: int = 2,
    moe_top_k: int = 2,
) -> float:
    """models/lm.py CausalLM: blocks + tied embedding head, per token.

    The PaLM-style 6N-per-token accounting expressed structurally so
    GQA (smaller kv projections) and MoE (top-k active experts +
    router) report their *active* FLOPs, not total parameters.
    """
    from ddp_tpu.models.moe import is_moe_block

    fwd = 0.0
    for i in range(depth):
        fwd += transformer_block_fwd_flops_per_token(
            d_model, total_len,
            num_heads=num_heads, num_kv_heads=num_kv_heads,
            mlp_ratio=mlp_ratio, causal=True,
            moe=is_moe_block(i, num_experts, moe_every),
            num_experts=num_experts, top_k=moe_top_k,
        )
    fwd += 2.0 * d_model * vocab_size  # tied logits matmul
    return 3.0 * fwd


def lm_train_flops_per_sequence(spec) -> float:
    """Per-SEQUENCE train FLOPs for an LMSpec-shaped object (the
    trainer's examples are sequences; throughput is sequences/sec)."""
    return spec.total_len * lm_train_flops_per_token(
        vocab_size=spec.vocab_size,
        total_len=spec.total_len,
        d_model=spec.d_model,
        depth=spec.depth,
        num_heads=spec.num_heads,
        num_kv_heads=getattr(spec, "num_kv_heads", 0),
        mlp_ratio=getattr(spec, "mlp_ratio", 4),
        num_experts=getattr(spec, "num_experts", 0),
        moe_every=getattr(spec, "moe_every", 2),
        moe_top_k=getattr(spec, "moe_top_k", 2),
    )


def seq_classifier_train_flops(spec) -> float:
    """models/seq_transformer.py long-context classifier, per sequence."""
    T, d = spec.total_len, spec.d_model
    fwd = 2.0 * T * spec.d_in * d  # input projection
    fwd += T * spec.depth * transformer_block_fwd_flops_per_token(
        d, T, num_heads=spec.num_heads,
    )
    fwd += 2.0 * d * spec.num_classes
    return 3.0 * fwd


# ---- registry (keyed by models/__init__ registry names) --------------

FLOPS_ESTIMATORS: dict[str, Callable[..., float]] = {}


def register_flops(name: str):
    def deco(fn):
        FLOPS_ESTIMATORS[name] = fn
        return fn

    return deco


register_flops("simple_cnn")(cnn_train_flops)
register_flops("resnet18")(
    lambda image_shape, num_classes, depth=None: resnet_train_flops(
        image_shape, num_classes, stage_sizes=(2, 2, 2, 2),
    )
)
register_flops("resnet34")(
    lambda image_shape, num_classes, depth=None: resnet_train_flops(
        image_shape, num_classes, stage_sizes=(3, 4, 6, 3),
        cifar_stem=False,
    )
)
register_flops("resnet50")(
    lambda image_shape, num_classes, depth=None: resnet_train_flops(
        image_shape, num_classes, stage_sizes=(3, 4, 6, 3),
        bottleneck=True, cifar_stem=False,
    )
)
register_flops("vit_tiny")(
    lambda image_shape, num_classes, depth=None: vit_train_flops(
        image_shape, num_classes, patch_size=4, embed_dim=192,
        depth=depth or 12, num_heads=3,
    )
)
register_flops("vit_micro")(
    lambda image_shape, num_classes, depth=None: vit_train_flops(
        image_shape, num_classes, patch_size=7, embed_dim=32,
        depth=depth or 2, num_heads=4,
    )
)
register_flops("vit_moe_tiny")(
    lambda image_shape, num_classes, depth=None: vit_train_flops(
        image_shape, num_classes, patch_size=4, embed_dim=192,
        depth=depth or 12, num_heads=3, num_experts=8,
    )
)
register_flops("vit_moe_micro")(
    lambda image_shape, num_classes, depth=None: vit_train_flops(
        image_shape, num_classes, patch_size=7, embed_dim=32,
        depth=depth or 2, num_heads=4, num_experts=4,
    )
)


def train_flops_per_example(
    model: str,
    *,
    image_shape=None,
    num_classes: int = 10,
    depth: Optional[int] = None,
) -> Optional[float]:
    """Registry-model estimate, or None for unknown models.

    None (not 0) on unknown: a missing estimator must make MFU absent,
    never silently 0 — an unmeasured run and a broken run are
    different facts.
    """
    fn = FLOPS_ESTIMATORS.get(model)
    if fn is None:
        return None
    return fn(tuple(image_shape or (28, 28, 1)), num_classes, depth=depth)


# ---- restart-aware goodput -------------------------------------------


class GoodputAccountant:
    """Productive seconds ÷ wall seconds since FIRST launch.

    State lives in a JSON sidecar (next to the checkpoints, like the
    tokenizer and lm_spec sidecars) so auto-resume accumulates across
    process restarts::

        {"first_launch_unix": ..., "productive_s": ..., "restarts": N,
         "world_size": W, "last_flush_unix": ...,
         "restart_downtime_s": ..., "resize_downtime_s": ...,
         "resizes": M}

    ``start_run()`` loads-or-initializes (counting a restart when a
    previous run's sidecar exists), ``add_productive()`` accrues step/
    epoch seconds, ``flush()`` writes atomically — called per epoch so
    a kill between epochs loses at most one epoch of accounting.
    ``enabled=False`` (non-main ranks) makes everything a no-op.

    Restart vs RESIZE downtime: each relaunch's downtime — the wall
    time between the dead generation's last flush and this
    generation's ``start_run()``, i.e. the unproductive tail of the
    killed epoch plus reap/backoff/re-init — is attributed by whether
    the world CHANGED SIZE across the boundary. Same size: ordinary
    restart downtime (a crash loop). Different size: resize downtime
    (an elastic scale-down/up, runtime/launch.py ``elastic=True``).
    The split is what lets capacity planning separate "our jobs crash"
    from "our fleet gets preempted and reshapes" — accounted downtime,
    not a mystery gap. Callers pass the live ``world_size`` to
    ``start_run``; ``prev_world`` then holds the size the previous
    generation recorded (None on first launch / legacy sidecars).
    """

    def __init__(
        self,
        sidecar_path: Optional[str],
        *,
        enabled: bool = True,
        clock: Callable[[], float] = time.time,
    ):
        self.path = sidecar_path
        self.enabled = bool(enabled and sidecar_path)
        self.clock = clock
        self.first_launch: float | None = None
        self.productive_s = 0.0
        self.restarts = 0
        self.world_size: int | None = None
        self.prev_world: int | None = None
        self.restart_downtime_s = 0.0
        self.resize_downtime_s = 0.0
        self.resizes = 0

    def start_run(self, world_size: int | None = None) -> None:
        self.world_size = world_size
        if not self.enabled:
            return
        state = None
        try:
            with open(self.path) as f:
                state = json.load(f)
        except (OSError, json.JSONDecodeError):
            state = None
        if isinstance(state, dict) and "first_launch_unix" in state:
            self.first_launch = float(state["first_launch_unix"])
            self.productive_s = float(state.get("productive_s", 0.0))
            self.restarts = int(state.get("restarts", 0)) + 1
            self.restart_downtime_s = float(
                state.get("restart_downtime_s", 0.0)
            )
            self.resize_downtime_s = float(
                state.get("resize_downtime_s", 0.0)
            )
            self.resizes = int(state.get("resizes", 0))
            prev = state.get("world_size")
            self.prev_world = int(prev) if prev else None
            # Downtime of the boundary just crossed: last durable
            # flush of the dead generation → now. Legacy sidecars
            # without the flush stamp contribute 0 (unknowable, not
            # invented).
            down = max(
                0.0, self.clock() - float(state.get("last_flush_unix", self.clock()))
            )
            if (
                world_size is not None
                and self.prev_world is not None
                and world_size != self.prev_world
            ):
                self.resizes += 1
                self.resize_downtime_s += down
            else:
                self.restart_downtime_s += down
        else:
            self.first_launch = self.clock()
            self.productive_s = 0.0
            self.restarts = 0
            self.prev_world = None

    def add_productive(self, seconds: float) -> None:
        if self.enabled and math.isfinite(seconds) and seconds > 0:
            self.productive_s += seconds

    def snapshot(self) -> dict:
        if not self.enabled or self.first_launch is None:
            return {}
        wall = max(1e-9, self.clock() - self.first_launch)
        out = {
            "goodput": round(self.productive_s / wall, 6),
            "productive_s": round(self.productive_s, 3),
            "wall_s": round(wall, 3),
            "restarts": self.restarts,
            "first_launch_unix": round(self.first_launch, 3),
        }
        if self.restarts or self.resizes:
            out["restart_downtime_s"] = round(self.restart_downtime_s, 3)
            out["resize_downtime_s"] = round(self.resize_downtime_s, 3)
            out["resizes"] = self.resizes
        return out

    def flush(self) -> None:
        if not self.enabled or self.first_launch is None:
            return
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "first_launch_unix": self.first_launch,
                    "productive_s": self.productive_s,
                    "restarts": self.restarts,
                    "world_size": self.world_size,
                    "last_flush_unix": self.clock(),
                    "restart_downtime_s": self.restart_downtime_s,
                    "resize_downtime_s": self.resize_downtime_s,
                    "resizes": self.resizes,
                },
                f,
            )
        os.replace(tmp, self.path)

"""Declarative serving SLOs with multi-window burn-rate alerting.

The serve path's user-facing objectives, stated the way an SRE would
write them and evaluated live inside the serving process:

    --slo "ttft_p99<0.5s,tpot_p50<80ms,availability>0.999"

Each objective is an SLI over the per-request observations the engine
already retires (TTFT, TPOT = decode seconds per output token, queue
wait, request success), evaluated over **two rolling windows** in the
SRE multi-window style: a fast window (default 5 m) that reacts, and a
slow window (default 1 h) that keeps a transient blip from paging.
For a percentile objective ``ttft_p99<0.5s`` the error budget is the
percentile's complement (1% of requests may exceed 0.5 s); the **burn
rate** is the fraction of budget-violating requests in a window over
that budget — burn 1.0 consumes exactly the budget, burn 14.4 on a 5 m
window is the classic "page now" threshold. An objective **breaches**
when its current windowed value violates the target; it **alerts**
when BOTH windows burn past the alert threshold, and the False→True
transition fires the breach hook exactly once (the engine routes it to
the metrics stream and the PR-4 flight recorder).

Surfaced as: ``/statusz`` state (``stats.slo``), linted
``ddp_tpu_slo_{target,current,burn_rate,breached}`` gauges on
``/metricsz`` (obs/promtext.py), an ``slo`` sub-record in
``bench.py serve_decode``, and the aggregator's worst-endpoint view
(obs/aggregate.py). Pure host-side Python, clock-injectable; memory is
bounded by a ring of observations.
"""

from __future__ import annotations

import re
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

FAST_WINDOW_S = 300.0  # the SRE fast window: 5 minutes
SLOW_WINDOW_S = 3600.0  # the slow window: 1 hour

# Latency metrics an objective may target, mapped to the observation
# field; "availability" is the success-fraction special case.
_METRICS = ("ttft", "tpot", "queue")
_UNITS = {"s": 1.0, "ms": 1e-3}

_OBJ_RE = re.compile(
    r"^(?P<metric>[a-z]+)(?:_p(?P<pct>[0-9]+(?:\.[0-9]+)?))?"
    r"(?P<op>[<>])(?P<value>[0-9]*\.?[0-9]+)(?P<unit>ms|s)?$"
)

# Bounded observation ring: at serving rates the slow window can hold
# more requests than a process should keep — the burn estimate then
# rides the most recent N, which is the end that matters.
MAX_OBSERVATIONS = 65536


@dataclass(frozen=True)
class Objective:
    """One parsed objective, e.g. ttft_p99<0.5s."""

    name: str  # "ttft_p99" | "availability" | ...
    metric: str  # ttft|tpot|queue|availability
    percentile: Optional[float]  # None for availability
    op: str  # "<" (latency) or ">" (availability)
    target: float  # seconds, or a fraction for availability
    raw: str  # the exact spec text, for display

    @property
    def budget(self) -> float:
        """Error budget: the fraction of requests ALLOWED to violate."""
        if self.metric == "availability":
            return max(1e-9, 1.0 - self.target)
        return max(1e-9, 1.0 - self.percentile / 100.0)


def parse_slo(spec: str) -> list[Objective]:
    """``"ttft_p99<0.5s,availability>0.999"`` → objectives.

    Raises ``ValueError`` naming the offending clause — a mistyped
    objective must fail at the CLI, not render an empty gauge set.
    """
    objectives: list[Objective] = []
    seen: set[str] = set()
    for clause in str(spec).split(","):
        clause = clause.strip()
        if not clause:
            continue
        m = _OBJ_RE.match(clause)
        if not m:
            raise ValueError(
                f"bad SLO clause {clause!r} (want e.g. ttft_p99<0.5s, "
                f"tpot_p50<80ms, availability>0.999)"
            )
        metric = m.group("metric")
        pct = m.group("pct")
        op = m.group("op")
        value = float(m.group("value"))
        unit = m.group("unit")
        if metric == "availability":
            if pct is not None or unit is not None or op != ">":
                raise ValueError(
                    f"{clause!r}: availability objectives are "
                    f"availability>FRACTION (no percentile, no unit)"
                )
            if not 0.0 < value < 1.0:
                raise ValueError(
                    f"{clause!r}: availability target must be in (0, 1)"
                )
            name = "availability"
            target, percentile = value, None
        else:
            if metric not in _METRICS:
                raise ValueError(
                    f"{clause!r}: unknown metric {metric!r} "
                    f"(one of {', '.join(_METRICS)}, availability)"
                )
            if pct is None or op != "<":
                raise ValueError(
                    f"{clause!r}: latency objectives are "
                    f"METRIC_pNN<BOUND[s|ms]"
                )
            percentile = float(pct)
            if not 0.0 < percentile < 100.0:
                raise ValueError(
                    f"{clause!r}: percentile must be in (0, 100)"
                )
            target = value * _UNITS[unit or "s"]
            if target <= 0.0:
                raise ValueError(f"{clause!r}: bound must be positive")
            pname = pct
            if "." in pname:  # 99.0 -> 99, 99.9 stays (50 stays 50)
                pname = pname.rstrip("0").rstrip(".")
            name = f"{metric}_p{pname}"
        if name in seen:
            raise ValueError(f"duplicate objective {name!r}")
        seen.add(name)
        objectives.append(
            Objective(
                name=name, metric=metric, percentile=percentile,
                op=op, target=target, raw=clause,
            )
        )
    if not objectives:
        raise ValueError(f"empty SLO spec {spec!r}")
    return objectives


def parse_model_slos(spec: str) -> dict:
    """Multi-model SLO spec → {model_name_or_None: clause string}.

    ``;``-separated groups, each optionally prefixed ``name:`` —
    e.g. ``"ttft_p99<0.5s;draft:ttft_p99<0.2s,availability>0.99"``
    gives the default model its own objectives and the registered
    model ``draft`` another set (per-model SLO engines, per-model burn
    gauges). The bare form (no ``;``, no prefix) parses to
    ``{None: spec}`` — every pre-lifecycle ``--slo`` value is
    unchanged. Each group's clause string is validated by
    ``parse_slo`` here, so a typo in any group fails at the CLI.
    """
    out: dict = {}
    for group in str(spec).split(";"):
        group = group.strip()
        if not group:
            continue
        name: Optional[str] = None
        head, sep, tail = group.partition(":")
        # A ":" only introduces a model name when the head looks like
        # one (an objective clause can't contain ":").
        if sep and re.fullmatch(r"[A-Za-z0-9_.-]+", head.strip()):
            name = head.strip()
            group = tail.strip()
        if name in out:
            raise ValueError(
                f"duplicate SLO group for "
                f"{'the default model' if name is None else name!r}"
            )
        parse_slo(group)  # validate now, fail at the CLI
        out[name] = group
    if not out:
        raise ValueError(f"empty SLO spec {spec!r}")
    return out


def _percentile(values: list[float], q: float) -> Optional[float]:
    if not values:
        return None
    s = sorted(values)
    rank = max(0, min(len(s) - 1, round(q / 100.0 * (len(s) - 1))))
    return s[rank]


class SLOEngine:
    """Rolling-window evaluator + breach latch for a set of objectives.

    ``observe()`` is called once per retired request (host floats
    only); evaluation is throttled to ``min_eval_interval_s`` so
    neither a high request rate nor a hot scrape target pays a
    percentile sort per call — ``state()`` inside the interval serves
    the last evaluation. ``on_breach`` fires once per
    False→True alert transition per objective (multi-window burn:
    both the fast and slow window burning past ``burn_alert``), and
    re-arms when the objective stops alerting.
    """

    def __init__(
        self,
        objectives: "list[Objective] | str",
        *,
        fast_window_s: float = FAST_WINDOW_S,
        slow_window_s: float = SLOW_WINDOW_S,
        burn_alert: float = 1.0,
        min_eval_interval_s: float = 1.0,
        max_observations: int = MAX_OBSERVATIONS,
        clock: Callable[[], float] = time.monotonic,
        on_breach: Optional[Callable[[dict], None]] = None,
    ):
        if isinstance(objectives, str):
            objectives = parse_slo(objectives)
        if not objectives:
            raise ValueError("SLOEngine needs at least one objective")
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError(
                f"windows must satisfy 0 < fast ({fast_window_s}) <= "
                f"slow ({slow_window_s})"
            )
        self.objectives = list(objectives)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_alert = float(burn_alert)
        self.min_eval_interval_s = float(min_eval_interval_s)
        self.clock = clock
        self.on_breach = on_breach
        # (t, ttft, tpot, queue, ok) — latency fields None when the
        # request never produced them (queue timeouts etc.).
        self._obs: deque = deque(maxlen=max(1, int(max_observations)))
        self._alerting: dict[str, bool] = {
            o.name: False for o in self.objectives
        }
        self.breach_counts: dict[str, int] = {
            o.name: 0 for o in self.objectives
        }
        self._last_eval = -float("inf")
        self._last_states: list[dict] = self._evaluate(self.clock())

    @property
    def spec(self) -> str:
        return ",".join(o.raw for o in self.objectives)

    # ---- feeding ----------------------------------------------------

    def observe(
        self,
        *,
        ttft_s: Optional[float] = None,
        tpot_s: Optional[float] = None,
        queue_s: Optional[float] = None,
        ok: bool = True,
    ) -> None:
        """One retired request's SLI fields. Cheap: an append plus a
        throttled evaluation (the breach hook must fire from live
        traffic, not wait for the next scrape)."""
        now = self.clock()
        self._obs.append((now, ttft_s, tpot_s, queue_s, bool(ok)))
        if now - self._last_eval >= self.min_eval_interval_s:
            self._evaluate(now)

    # ---- evaluation -------------------------------------------------

    def _window(self, now: float, horizon_s: float) -> list[tuple]:
        cutoff = now - horizon_s
        return [o for o in self._obs if o[0] > cutoff]

    def _evaluate(self, now: float) -> list[dict]:
        self._last_eval = now
        fast = self._window(now, self.fast_window_s)
        slow = self._window(now, self.slow_window_s)
        field = {"ttft": 1, "tpot": 2, "queue": 3}
        states: list[dict] = []
        for obj in self.objectives:
            if obj.metric == "availability":
                f_vals = [o[4] for o in fast]
                s_vals = [o[4] for o in slow]
                current = (
                    sum(f_vals) / len(f_vals) if f_vals else None
                )
                bad_fast = (
                    (len(f_vals) - sum(f_vals)) / len(f_vals)
                    if f_vals else 0.0
                )
                bad_slow = (
                    (len(s_vals) - sum(s_vals)) / len(s_vals)
                    if s_vals else 0.0
                )
                breached = current is not None and current < obj.target
            else:
                i = field[obj.metric]
                f_vals = [o[i] for o in fast if o[i] is not None]
                s_vals = [o[i] for o in slow if o[i] is not None]
                current = _percentile(f_vals, obj.percentile)
                bad_fast = (
                    sum(1 for v in f_vals if v >= obj.target) / len(f_vals)
                    if f_vals else 0.0
                )
                bad_slow = (
                    sum(1 for v in s_vals if v >= obj.target) / len(s_vals)
                    if s_vals else 0.0
                )
                breached = current is not None and current >= obj.target
            burn_fast = bad_fast / obj.budget
            burn_slow = bad_slow / obj.budget
            alerting = (
                burn_fast >= self.burn_alert
                and burn_slow >= self.burn_alert
                and bool(f_vals)
            )
            state = {
                "name": obj.name,
                "objective": obj.raw,
                "metric": obj.metric,
                "target": obj.target,
                "current": (
                    round(current, 6) if current is not None else None
                ),
                "burn_rate_fast": round(burn_fast, 4),
                "burn_rate_slow": round(burn_slow, 4),
                "breached": bool(breached),
                "alerting": bool(alerting),
                "window_n": len(f_vals),
                "breaches": self.breach_counts[obj.name],
            }
            if alerting and not self._alerting[obj.name]:
                self.breach_counts[obj.name] += 1
                state["breaches"] = self.breach_counts[obj.name]
                if self.on_breach is not None:
                    self.on_breach(dict(state))
            self._alerting[obj.name] = alerting
            states.append(state)
        self._last_states = states
        return states

    def state(self) -> dict:
        """JSON-ready snapshot (the /statusz and stats() view).

        Rides the same ``min_eval_interval_s`` throttle as
        ``observe()``: a scrape inside the interval serves the cached
        states instead of paying window scans + percentile sorts over
        the observation ring under the server lock — a hot Prometheus
        target must not stall the admission path.
        """
        now = self.clock()
        if now - self._last_eval >= self.min_eval_interval_s:
            states = self._evaluate(now)
        else:
            states = self._last_states
        return {
            "spec": self.spec,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "burn_alert": self.burn_alert,
            "observations": len(self._obs),
            "objectives": states,
            "breached": any(s["breached"] for s in states),
            "alerting": any(s["alerting"] for s in states),
        }
